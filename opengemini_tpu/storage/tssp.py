"""TSSP-like immutable columnar file format with per-segment pre-aggregation.

Role of the reference's engine/immutable/ TSSP format (magic 53ac2021,
table.go:26-61): per-series chunks → per-column segments, chunk metas, a meta
index, a series-id bloom filter and a trailer. Pre-aggregation per column
segment (count/min/max/sum + min/max time — pre_aggregation.go:38) lets
aggregate queries skip decoding entirely.

TPU-first deviations:
- Segments are fixed-size row blocks (SEGMENT_SIZE rows, last segment ragged)
  so decoded columns concatenate into padded device blocks without
  re-chunking; SEGMENT_SIZE is the device block size.
- A per-segment "regular" flag (const-delta time codec) marks data eligible
  for the dense reshape kernel path.
- Chunk metas serialize with a compact struct codec and zstd (role of
  lib/codec); readers mmap the file and decode lazily via the meta index.

Layout:
    [magic u32][version u32]
    data section: encoded column blocks (+validity blocks), back to back
    chunk meta section: zstd([ChunkMeta...])
    meta index: [(sid_min, sid_max, offset, size) per meta group]
    bloom: series-id bloom filter bits
    trailer: fixed struct with section offsets + file stats
    [trailer size u32][magic u32]
"""

from __future__ import annotations

import mmap
import itertools
import os
import struct
from dataclasses import dataclass, field

import numpy as np

from ..encoding import blocks as enc
from ..record import ColVal, DataType, Field, Record, Schema
from ..utils import failpoint, fileops, knobs
from .. import native as _native

MAGIC = 0x54505553  # "SUPT" — distinct from reference's 53ac2021


def encode_workers() -> int:
    """Worker count for the flush encode pool (OG_ENCODE_WORKERS;
    unset = auto = min(4, cores), ``1`` pins the serial pre-PR-20
    behavior). The pool keeps file bytes identical (encode stage is
    pure; appends stay ordered on the caller's thread). The PR-3
    measurement that pinned the default to serial — a GIL handoff
    storm of many small numpy ops making 2-8 threads 2-4× SLOWER —
    predates the probe-driven encode menu: with codec pre-selection
    emitting DFOR from shape probes, provably-futile simple8b trials
    skipped, and the greedy packer vectorized, the same TSBS flush
    shape now measures NEUTRAL under threads, and native-codec-heavy
    schemas (zstd/LZ4 string blocks, gorilla) that release the GIL
    see real overlap. Auto therefore scales with cores (a 1-core
    container stays serial); small flushes (≤ one submit batch) stay
    serial regardless — see write_series_stream."""
    raw = knobs.get_raw("OG_ENCODE_WORKERS") or ""
    try:
        n = int(raw)
    except ValueError:
        n = -1
    if n >= 0:
        return n
    return min(4, os.cpu_count() or 1)
VERSION = 3                  # v2: PreAgg carries reproducible-sum limbs
#                              v3: trailer carries a CRC32 over the
#                              meta/index/bloom sections, verified at
#                              open (crash-consistency round: a torn
#                              or bit-flipped metadata region is
#                              caught before it mis-routes reads)
SEGMENT_SIZE = 4096          # rows per column segment == device block rows
META_GROUP_SERIES = 256      # series per meta-index group

_TRAILER_FMT = "<QQQQQQQqqQ"  # data_end, meta_off, meta_size, idx_off,
#                               idx_size, bloom_off, bloom_size,
#                               min_time, max_time, series_count
_TRAILER_FMT_V3 = _TRAILER_FMT + "I"   # + meta_crc (crc32 of
#                               [meta_off, bloom_off + bloom_size))


@dataclass
class PreAgg:
    """Per-segment pre-aggregation (reference pre_aggregation.go:38).
    v2 adds the reproducible-sum limb state (ops/exactsum.py): the exact
    integer decomposition of the segment's sum, so sum/mean queries keep
    the zero-decode metadata path under the bit-identical guarantee —
    no counterpart in the reference, which stores only the f64 sum."""
    count: int = 0
    sum: float = 0.0          # float64 for FLOAT, int value for INTEGER
    min: float = 0.0
    max: float = 0.0
    min_time: int = 0
    max_time: int = 0
    limbs: tuple | None = None    # K_LIMBS int limb sums
    scale: int = 0                # limb scale E (multiple of LIMB_BITS)
    exact: bool = False           # every value decomposed residual-free

    def pack(self) -> bytes:
        head = struct.pack("<qdddqq", self.count, float(self.sum),
                           float(self.min), float(self.max),
                           self.min_time, self.max_time)
        if self.limbs is None:
            return head + struct.pack("<?", False)
        return head + struct.pack("<?i?6q", True, self.scale,
                                  self.exact, *self.limbs)

    @classmethod
    def unpack_from(cls, buf, pos: int, version: int):
        c, s, mn, mx, mnt, mxt = struct.unpack_from("<qdddqq", buf, pos)
        pos += _PREAGG_HEAD
        pa = cls(c, s, mn, mx, mnt, mxt)
        if version < 2:
            return pa, pos
        (has_limbs,) = struct.unpack_from("<?", buf, pos)
        pos += 1
        if has_limbs:
            vals = struct.unpack_from("<i?6q", buf, pos)
            pos += struct.calcsize("<i?6q")
            pa.scale, pa.exact = vals[0], vals[1]
            pa.limbs = tuple(vals[2:])
        return pa, pos

_PREAGG_HEAD = struct.calcsize("<qdddqq")


@dataclass
class Segment:
    """One encoded column block (reference tssp_file_meta.go:51)."""
    offset: int
    size: int
    rows: int
    valid_offset: int
    valid_size: int
    preagg: PreAgg | None = None


@dataclass
class ColumnMeta:
    """(reference tssp_file_meta.go:136)"""
    name: str
    type: DataType
    segments: list[Segment] = field(default_factory=list)


@dataclass
class ChunkMeta:
    """Per-series chunk meta (reference tssp_file_meta.go:368)."""
    sid: int
    min_time: int
    max_time: int
    rows: int
    columns: list[ColumnMeta] = field(default_factory=list)
    regular: bool = False     # every time segment is const-delta

    def column(self, name: str) -> ColumnMeta | None:
        for c in self.columns:
            if c.name == name:
                return c
        return None


# ------------------------------------------------------------ serialization

def _pack_chunk_meta(cm: ChunkMeta) -> bytes:
    out = [struct.pack("<QqqqH?", cm.sid, cm.min_time, cm.max_time, cm.rows,
                       len(cm.columns), cm.regular)]
    for col in cm.columns:
        nb = col.name.encode()
        out.append(struct.pack("<HBH", len(nb), int(col.type),
                               len(col.segments)))
        out.append(nb)
        for s in col.segments:
            out.append(struct.pack("<QIIQI?", s.offset, s.size, s.rows,
                                   s.valid_offset, s.valid_size,
                                   s.preagg is not None))
            if s.preagg is not None:
                out.append(s.preagg.pack())
    return b"".join(out)


def _unpack_chunk_meta(buf, pos: int,
                       version: int = VERSION) -> tuple[ChunkMeta, int]:
    sid, mnt, mxt, rows, ncols, regular = struct.unpack_from("<QqqqH?", buf,
                                                             pos)
    pos += struct.calcsize("<QqqqH?")
    cm = ChunkMeta(sid, mnt, mxt, rows, [], regular)
    for _ in range(ncols):
        nlen, ty, nsegs = struct.unpack_from("<HBH", buf, pos)
        pos += struct.calcsize("<HBH")
        name = bytes(buf[pos:pos + nlen]).decode()
        pos += nlen
        col = ColumnMeta(name, DataType(ty))
        for _ in range(nsegs):
            off, size, rws, voff, vsize, has_pa = struct.unpack_from(
                "<QIIQI?", buf, pos)
            pos += struct.calcsize("<QIIQI?")
            pa = None
            if has_pa:
                pa, pos = PreAgg.unpack_from(buf, pos, version)
            col.segments.append(Segment(off, size, rws, voff, vsize, pa))
        cm.columns.append(col)
    return cm, pos


# ------------------------------------------------------------------- bloom

class SeriesBloom:
    """Series-id bloom filter (reference trailer bloom, table.go:54-61).
    k=4 hashes from two splitmix64 mixes; ~10 bits/key → <1% fp."""

    def __init__(self, bits: np.ndarray):
        self.bits = bits  # uint8 array, len power of two

    @classmethod
    def build(cls, sids: np.ndarray, bits_per_key: int = 10) -> "SeriesBloom":
        n = max(len(sids), 1)
        m = 1 << max(int(np.ceil(np.log2(n * bits_per_key))), 6)
        bits = np.zeros(m // 8, dtype=np.uint8)
        for h in cls._hashes(np.asarray(sids, dtype=np.uint64), m):
            np.bitwise_or.at(bits, h // 8, (1 << (h % 8)).astype(np.uint8))
        return cls(bits)

    @staticmethod
    def _hashes(sids: np.ndarray, m: int):
        with np.errstate(over="ignore"):
            x = sids.copy()
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            h1 = x ^ (x >> np.uint64(31))
            y = sids + np.uint64(0x9E3779B97F4A7C15)
            y = (y ^ (y >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            h2 = y ^ (y >> np.uint64(27))
            for k in range(4):
                yield ((h1 + np.uint64(k) * h2) % np.uint64(m)).astype(
                    np.int64)

    def may_contain(self, sid: int) -> bool:
        m = len(self.bits) * 8
        s = np.array([sid], dtype=np.uint64)
        for h in self._hashes(s, m):
            if not (self.bits[h[0] // 8] >> (h[0] % 8)) & 1:
                return False
        return True

    def may_contain_many(self, sids: np.ndarray) -> np.ndarray:
        """Vectorized probe: (N,) sids → (N,) bool (ONE numpy pass —
        the per-sid Python loop cost ~10µs each, which dominated scan
        planning at 10^5+ series)."""
        m = len(self.bits) * 8
        out = np.ones(len(sids), dtype=bool)
        s = np.asarray(sids, dtype=np.uint64)
        for h in self._hashes(s, m):
            out &= ((self.bits[h // 8] >> (h % 8).astype(np.uint8))
                    & 1).astype(bool)
        return out


# ------------------------------------------------------------------ writer

def _compute_preagg(col: ColVal, times: np.ndarray, lo: int,
                    hi: int) -> PreAgg | None:
    if col.values is None or col.type not in (DataType.FLOAT,
                                              DataType.INTEGER,
                                              DataType.TIME):
        return None
    v = col.values[lo:hi]
    m = col.valid[lo:hi]
    t = times[lo:hi]
    cnt = int(np.count_nonzero(m))
    if cnt == 0:
        return PreAgg(0, 0.0, 0.0, 0.0, 0, 0)
    vm = v[m]
    tm = t[m]
    pa = PreAgg(cnt, float(vm.sum(dtype=np.float64)), float(vm.min()),
                float(vm.max()), int(tm.min()), int(tm.max()))
    if col.type in (DataType.FLOAT, DataType.INTEGER):
        # reproducible-sum limb state (v2): exact unless the segment's
        # dynamic range exceeds the 108-bit limb span
        from ..ops import exactsum
        vf = np.ascontiguousarray(vm, dtype=np.float64)
        mx = float(np.max(np.abs(vf)))
        if np.isfinite(mx):
            E = exactsum.pick_scale(mx)
            # fused native pass (og_limb_sums — GIL-releasing, one
            # walk) when built; limb sums are exact integers, so the
            # span-order accumulation equals numpy's pairwise sum
            ns = _native.limb_sums(
                vf, np.zeros(1, dtype=np.int64),
                np.array([len(vf)], dtype=np.int64),
                np.array([E], dtype=np.int64),
                exactsum.K_LIMBS, exactsum.LIMB_BITS)
            if ns is not None:
                pa.limbs = tuple(int(x) for x in ns[0][0])
                pa.scale = E
                pa.exact = bool(ns[1][0])
            else:
                limbs, res = exactsum.decompose(vf, E)
                pa.limbs = tuple(int(x) for x in
                                 limbs.sum(axis=0, dtype=np.float64))
                pa.scale = E
                pa.exact = bool(np.all(res == 0.0))
    return pa


# compaction-transcode loser memo: segments whose decode + full
# encode-menu probe showed DFOR cannot beat their legacy codec are
# remembered by content fingerprint, so stream compaction pays the
# probe ONCE per distinct segment content instead of on every later
# compaction of the same bytes (segments copy verbatim across
# compactions, so the fingerprint recurs). A fingerprint collision
# merely SKIPS a probe — the segment keeps its legacy codec, never a
# correctness effect. Bounded FIFO; process-local (a restart re-pays
# one probe per segment, which is the pre-memo behavior once).
_DFOR_LOSERS: "dict[tuple, None]" = {}
_DFOR_LOSERS_CAP = 1 << 16


def _dfor_probe_key(seg_bytes, rows: int) -> tuple:
    import zlib
    head = bytes(seg_bytes[:64])
    return (len(seg_bytes), rows, zlib.crc32(head))


def _dfor_probe_lost(seg_bytes, rows: int) -> bool:
    return _dfor_probe_key(seg_bytes, rows) in _DFOR_LOSERS


def _dfor_probe_remember(seg_bytes, rows: int) -> None:
    if len(_DFOR_LOSERS) >= _DFOR_LOSERS_CAP:
        _DFOR_LOSERS.pop(next(iter(_DFOR_LOSERS)))
    _DFOR_LOSERS[_dfor_probe_key(seg_bytes, rows)] = None


class TSSPWriter:
    """Append-only writer: call write_series per series id (ascending,
    each series once), then finalize(). Analog of immutable/msbuilder.go."""

    def __init__(self, path: str, segment_size: int = SEGMENT_SIZE):
        self.path = path
        self.segment_size = segment_size
        self._f = open(path + ".tmp", "wb")
        self._f.write(struct.pack("<II", MAGIC, VERSION))
        self._pos = 8
        self._metas: list[ChunkMeta] = []
        self._last_sid = -1
        self._min_time = None
        self._max_time = None

    def _append(self, b: bytes) -> tuple[int, int]:
        off = self._pos
        self._f.write(b)
        self._pos += len(b)
        return off, len(b)

    def write_series(self, sid: int, rec: Record) -> None:
        self._append_encoded(sid, self._encode_series(rec))

    def _encode_series(self, rec: Record):
        """Pure encode stage of write_series: record → per-column
        segment payloads + pre-agg, NO writer state touched — safe to
        run on the encode worker pool (the native gorilla/LZ4/zstd
        codecs release the GIL inside their C calls)."""
        rec = rec.sort_by_time()
        times = rec.times
        n = rec.num_rows
        if n == 0:
            return None
        ss = self.segment_size
        cols_enc = []
        for f, col in zip(rec.schema, rec.cols):
            segs = []
            for lo in range(0, n, ss):
                hi = min(lo + ss, n)
                time_regular = True
                if f.type == DataType.TIME:
                    data = enc.encode_time_block(col.values[lo:hi])
                    time_regular = data[0] == enc.CONST_DELTA
                elif f.type == DataType.INTEGER:
                    data = enc.encode_integer_block(col.values[lo:hi])
                elif f.type == DataType.FLOAT:
                    data = enc.encode_float_block(col.values[lo:hi])
                elif f.type == DataType.BOOLEAN:
                    data = enc.encode_boolean_block(col.values[lo:hi])
                else:
                    sub = col.slice(lo, hi)
                    data = enc.encode_string_block(sub.offsets,
                                                   sub.data)
                segs.append((data,
                             enc.encode_validity(col.valid[lo:hi]),
                             hi - lo,
                             _compute_preagg(col, times, lo, hi),
                             time_regular))
            cols_enc.append((f.name, f.type, segs))
        return (int(times[0]), int(times[-1]), n, cols_enc)

    def _append_encoded(self, sid: int, encoded) -> None:
        """Ordered append stage of write_series (file offsets + chunk
        meta) — runs on the writer's thread only."""
        if sid <= self._last_sid:
            raise ValueError("series ids must be written in ascending order")
        self._last_sid = sid
        if encoded is None:
            return
        t0, t1, n, cols_enc = encoded
        cm = ChunkMeta(sid, t0, t1, n, regular=True)
        self._min_time = (t0 if self._min_time is None
                          else min(self._min_time, t0))
        self._max_time = (t1 if self._max_time is None
                          else max(self._max_time, t1))
        for name, ftype, segs in cols_enc:
            colmeta = ColumnMeta(name, ftype)
            for data, vdata, rows, preagg, time_regular in segs:
                if not time_regular:
                    cm.regular = False
                off, size = self._append(data)
                voff, vsize = self._append(vdata)
                colmeta.segments.append(
                    Segment(off, size, rows, voff, vsize, preagg))
            cm.columns.append(colmeta)
        self._metas.append(("one", sid, _pack_chunk_meta(cm)))

    def write_series_stream(self, pairs) -> None:
        """Encode-parallel write of many (sid, Record) pairs (ascending
        sids): OG_ENCODE_WORKERS threads run the pure encode stage
        while THIS thread appends results strictly in submission order
        — the file bytes are identical to serial write_series calls.
        The in-flight window is bounded (4 per worker) so a 69M-row
        flush never holds more than a few dozen encoded series in
        memory. The flush path uses this for the bench's 16k-series
        ingest; 0/1 workers = the serial loop, and a flush that fits
        in one submit batch (≤ 32 series) stays serial too — pool
        startup would dominate the overlap it buys."""
        w = encode_workers()
        head = None
        if w > 1:
            import itertools
            cutoff = max(0, int(knobs.get("OG_ENCODE_SERIAL_CUTOFF")))
            pairs = iter(pairs)
            head = list(itertools.islice(pairs, cutoff + 1))
            if len(head) <= cutoff:
                pairs, head = iter(head), None
            else:
                pairs = itertools.chain(head, pairs)
        if w <= 1 or head is None:
            for sid, rec in pairs:
                self.write_series(sid, rec)
            return
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        def encode_batch(batch):
            return [(sid, self._encode_series(rec))
                    for sid, rec in batch]

        pending: deque = deque()
        batch: list = []

        def drain_one():
            # crash boundary: worker-encoded series are being
            # committed to the (still .tmp) file in submission order
            # — a kill here must leave only an orphan .tmp that the
            # restart sweeps (C4), with every row still in the WAL
            failpoint.inject("tssp.parallel_flush.crash")
            for psid, encoded in pending.popleft().result():
                self._append_encoded(psid, encoded)

        with ThreadPoolExecutor(max_workers=w,
                                thread_name_prefix="og-encode") as pool:
            for pair in pairs:
                batch.append(pair)
                if len(batch) >= 32:   # amortize future overhead
                    pending.append(pool.submit(encode_batch, batch))
                    batch = []
                    if len(pending) >= 2 * w:
                        drain_one()
            if batch:
                pending.append(pool.submit(encode_batch, batch))
            while pending:
                drain_one()

    def write_series_raw(self, sid: int, holders: list) -> bool:
        """STREAM-COMPACTION path (role of the reference's
        engine/immutable/stream_compact.go + merge_tool.go self-merge):
        copy a series' already-encoded segments verbatim — no decode,
        no re-encode — rewriting only the byte offsets in the chunk
        meta. ``holders`` is [(ChunkMeta, TSSPReader)] oldest→newest;
        more than one holder streams as a CONCATENATION, which is only
        correct when the holders' time ranges are strictly disjoint in
        order and their column sets match — returns False (write
        nothing) when those conditions fail and the caller must take
        the decode-merge path."""
        if sid <= self._last_sid:
            raise ValueError("series ids must be written in ascending "
                             "order")
        if not holders:
            return False
        cms = [cm for cm, _r in holders]
        for a, b in zip(cms, cms[1:]):
            if a.max_time >= b.min_time:
                return False              # overlap: decode-merge
        sig0 = sorted((c.name, c.type) for c in cms[0].columns)
        if any(sorted((c.name, c.type) for c in cm.columns) != sig0
               for cm in cms[1:]):
            return False                  # ragged schema: decode-merge
        out = ChunkMeta(sid, cms[0].min_time, cms[-1].max_time,
                        sum(cm.rows for cm in cms),
                        regular=all(cm.regular for cm in cms))
        transcode = enc._device_layout_on()
        for colm0 in cms[0].columns:
            nc = ColumnMeta(colm0.name, colm0.type)
            for cm, r in holders:
                colm = cm.column(colm0.name)
                mm = r._mm
                for s in colm.segments:
                    seg_bytes = mm[s.offset:s.offset + s.size]
                    if (transcode and s.rows
                            and colm0.type == DataType.FLOAT
                            and seg_bytes[0] in (enc.ZSTD, enc.RAW,
                                                 enc.GORILLA)
                            and not _dfor_probe_lost(seg_bytes,
                                                     s.rows)):
                        # ONE-TIME transcode of legacy byte-codec
                        # float segments into the device layout as
                        # compaction rewrites them anyway
                        # (OG_WRITE_DEVICE_LAYOUT). The rewrite is
                        # kept ONLY when the menu actually picked
                        # DFOR: data the device layout can't beat
                        # stays on its ORIGINAL codec bytes (a
                        # gorilla segment must not degrade to
                        # zstd-of-raw). Winners leave the trigger set
                        # (DFOR is not in it); losers are remembered
                        # by content fingerprint so the decode +
                        # full-menu probe is not re-paid on every
                        # later compaction of the same bytes.
                        # Byte-identical decoded values — enforced by
                        # the round-trip oracle in tests/test_encoding
                        # — and the pre-agg (incl. limb state) is
                        # value-derived, so it carries over unchanged
                        vals = enc.decode_float_block(seg_bytes,
                                                      s.rows)
                        re_enc = enc.encode_float_block(vals)
                        if re_enc[0] == enc.DFOR:
                            seg_bytes = re_enc
                        else:
                            _dfor_probe_remember(seg_bytes, s.rows)
                    off, size = self._append(seg_bytes)
                    voff, vsize = self._append(
                        mm[s.valid_offset:s.valid_offset
                           + s.valid_size])
                    nc.segments.append(Segment(off, size, s.rows,
                                               voff, vsize, s.preagg))
            out.columns.append(nc)
        self._min_time = (out.min_time if self._min_time is None
                          else min(self._min_time, out.min_time))
        self._max_time = (out.max_time if self._max_time is None
                          else max(self._max_time, out.max_time))
        self._metas.append(("one", sid, _pack_chunk_meta(out)))
        self._last_sid = sid
        return True

    def write_series_bulk(self, sids: np.ndarray, offsets: np.ndarray,
                          times_cat: np.ndarray,
                          cols: dict[str, np.ndarray]) -> None:
        """Vectorized many-tiny-series write (the high-cardinality
        flush path — reference's >1M-series claim, README.md:40-42).
        All columns float64, all rows valid, series i owns rows
        [offsets[i], offsets[i+1]), sids ascending. Data encodes RAW
        (+CONST_DELTA times) in ONE buffer write per (run, rows)
        group, pre-aggregation (incl. exact limb sums) computes with
        reduceat spans, and chunk metas pack as fixed-size records in
        a numpy matrix — no per-series Python objects. Series the
        vector form can't express (non-uniform timestamps, non-finite
        values, rows > segment_size) fall back to write_series inline,
        preserving sid order."""
        from ..ops import exactsum
        S = len(sids)
        if S == 0:
            return
        names = sorted(cols)
        starts = offsets[:-1].astype(np.int64)
        ends = offsets[1:].astype(np.int64)
        r_all = ends - starts
        total = int(offsets[-1])
        t0 = times_cat[starts]
        t_last = times_cat[ends - 1]
        d = np.diff(times_cat)
        step = np.where(
            r_all > 1,
            d[np.minimum(starts, max(total - 2, 0))] if total > 1
            else 0, 0)
        within = (np.arange(total, dtype=np.int64)
                  - np.repeat(starts, r_all))
        predicted = (np.repeat(t0, r_all)
                     + np.repeat(step, r_all) * within)
        ok = (np.logical_and.reduceat(times_cat == predicted, starts)
              & (r_all <= self.segment_size) & (step >= 0))
        for k in names:
            ok &= np.logical_and.reduceat(np.isfinite(cols[k]), starts)

        def spans_reduce(ufunc, arr, st, en):
            idx = np.empty(2 * len(st), dtype=np.int64)
            idx[0::2] = st
            idx[1::2] = en
            if idx[-1] >= len(arr):
                idx = idx[:-1]
            out = ufunc.reduceat(arr, idx)[0::2]
            return out

        i = 0
        while i < S:
            if not ok[i]:
                lo, hi = int(starts[i]), int(ends[i])
                # canonical schema shape: fields sorted, time LAST
                fields = ([Field(k, DataType.FLOAT) for k in names]
                          + [Field("time", DataType.TIME)])
                rcols = ([ColVal(DataType.FLOAT, cols[k][lo:hi])
                          for k in names]
                         + [ColVal(DataType.TIME, times_cat[lo:hi])])
                self.write_series(int(sids[i]),
                                  Record(Schema(fields), rcols))
                i += 1
                continue
            j = i
            while j < S and ok[j]:
                j += 1
            self._write_bulk_run(
                sids[i:j], starts[i:j], ends[i:j], r_all[i:j],
                t0[i:j], t_last[i:j], step[i:j], times_cat, cols,
                names, spans_reduce, exactsum)
            i = j

    def _write_bulk_run(self, sids, starts, ends, r_run, t0, t_last,
                        step, times_cat, cols, names, spans_reduce,
                        exactsum) -> None:
        Sr = len(sids)
        F = len(names)
        if self._last_sid >= int(sids[0]):
            raise ValueError("series ids must be written in ascending "
                             "order")
        self._last_sid = int(sids[-1])
        # ---- data: one buffer write per rows-group ----
        data_off = np.empty(Sr, dtype=np.int64)
        u8 = np.uint8
        for r in np.unique(r_run):
            g = np.nonzero(r_run == r)[0]
            r = int(r)
            stride = 18 + F * (2 + 8 * r)
            M = np.zeros((len(g), stride), dtype=u8)
            M[:, 0] = enc.CONST_DELTA
            M[:, 1:9] = t0[g].astype("<i8").view(u8).reshape(-1, 8)
            M[:, 9:17] = step[g].astype("<i8").view(u8).reshape(-1, 8)
            M[:, 17] = enc.CONST          # validity: all-valid marker
            row_idx = (starts[g][:, None]
                       + np.arange(r, dtype=np.int64)[None, :])
            cb = 18
            for k in names:
                M[:, cb] = enc.RAW
                M[:, cb + 1:cb + 1 + 8 * r] = (
                    cols[k][row_idx].astype("<f8").view(u8)
                    .reshape(-1, 8 * r))
                M[:, cb + 1 + 8 * r] = enc.CONST
                cb += 2 + 8 * r
            base = self._pos
            self._f.write(M.tobytes())
            self._pos += len(g) * stride
            data_off[g] = base + np.arange(len(g),
                                           dtype=np.int64) * stride
        # ---- per-field preagg stats (vectorized spans) ----
        stats = {}
        for k in names:
            v = cols[k]
            ssum = spans_reduce(np.add, v, starts, ends)
            smin = spans_reduce(np.minimum, v, starts, ends)
            smax = spans_reduce(np.maximum, v, starts, ends)
            mx = np.maximum(np.abs(smin), np.abs(smax))
            # vectorized pick_scale (mirrors exactsum.pick_scale)
            with np.errstate(divide="ignore"):
                e = np.where(mx > 0,
                             np.ceil(np.log2(np.maximum(mx, 1e-300)))
                             + 1, 0)
            E = (np.ceil(e / exactsum.LIMB_BITS)
                 * exactsum.LIMB_BITS).astype(np.int64)
            E[mx <= 0] = 0
            ns = _native.limb_sums(v, starts, ends, E,
                                   exactsum.K_LIMBS, exactsum.LIMB_BITS)
            if ns is not None:
                stats[k] = (ssum, smin, smax, E, ns[0], ns[1])
                continue
            limbs = np.zeros((Sr, exactsum.K_LIMBS))
            exact = np.zeros(Sr, dtype=bool)
            for Ev in np.unique(E):
                gi = np.nonzero(E == Ev)[0]
                # absolute row indices of the member series (starts/
                # ends index the FULL concatenated array, not the run)
                reps = r_run[gi]
                lstarts = np.zeros(len(gi), dtype=np.int64)
                np.cumsum(reps[:-1], out=lstarts[1:])
                within = (np.arange(int(reps.sum()), dtype=np.int64)
                          - np.repeat(lstarts, reps))
                rows = np.repeat(starts[gi], reps) + within
                lb, res = exactsum.decompose(v[rows], int(Ev))
                lends = lstarts + reps
                for kk in range(exactsum.K_LIMBS):
                    limbs[gi, kk] = spans_reduce(np.add, lb[:, kk],
                                                 lstarts, lends)
                exact[gi] = spans_reduce(np.logical_and, res == 0.0,
                                         lstarts, lends)
            stats[k] = (ssum, smin, smax, E, limbs, exact)
        # ---- meta records: one constant template row + a single
        # record-major native scatter of the variable fields (the
        # per-field strided form pays ~30 cache-hostile passes over the
        # whole matrix; fallback below keeps it as exact behavior) ----
        REC_T = 5 + 4 + 29 + 49          # time column block
        REC_F = {k: 5 + len(k.encode()) + 29 + 102 for k in names}
        recsize = 35 + REC_T + sum(REC_F.values())
        tmpl = np.zeros(recsize, dtype=u8)
        spec: list = []                  # (record offset, (Sr, w) u8)

        def putc(off, b: bytes):
            tmpl[off:off + len(b)] = np.frombuffer(b, dtype=u8)

        def put(off, arr, dt):
            a = np.asarray(arr).astype(dt)
            spec.append((off, a.view(u8).reshape(Sr, -1)))

        put(0, sids, "<u8")
        put(8, t0, "<i8")
        put(16, t_last, "<i8")
        put(24, r_run, "<i8")
        putc(32, struct.pack("<H", F + 1))
        putc(34, b"\x01")                # regular (const-delta times)
        p = 35
        # time column meta
        putc(p, struct.pack("<HBH", 4, int(DataType.TIME), 1))
        putc(p + 5, b"time")
        p += 9
        put(p, data_off, "<u8")
        putc(p + 8, struct.pack("<I", 17))
        put(p + 12, r_run, "<u4")
        put(p + 16, data_off + 17, "<u8")
        putc(p + 24, struct.pack("<I", 1))
        putc(p + 28, b"\x01")            # has preagg
        p += 29
        # time preagg (no limbs)
        put(p, r_run, "<i8")
        tsum = spans_reduce(np.add, times_cat.astype(np.float64),
                            starts, ends)
        put(p + 8, tsum, "<f8")
        put(p + 16, t0.astype(np.float64), "<f8")
        put(p + 24, t_last.astype(np.float64), "<f8")
        put(p + 32, t0, "<i8")
        put(p + 40, t_last, "<i8")
        # has_limbs byte stays 0
        p += 49
        fb = 18                          # per-series field data base
        for k in names:
            kb = k.encode()
            ssum, smin, smax, E, limbs, exact = stats[k]
            putc(p, struct.pack("<HBH", len(kb), int(DataType.FLOAT), 1))
            putc(p + 5, kb)
            p += 5 + len(kb)
            vsize = 1 + 8 * r_run
            put(p, data_off + fb, "<u8")
            put(p + 8, vsize, "<u4")
            put(p + 12, r_run, "<u4")
            put(p + 16, data_off + fb + vsize, "<u8")
            putc(p + 24, struct.pack("<I", 1))
            putc(p + 28, b"\x01")
            p += 29
            put(p, r_run, "<i8")
            put(p + 8, ssum, "<f8")
            put(p + 16, smin, "<f8")
            put(p + 24, smax, "<f8")
            put(p + 32, t0, "<i8")
            put(p + 40, t_last, "<i8")
            putc(p + 48, b"\x01")        # has_limbs
            put(p + 49, E, "<i4")
            put(p + 53, exact, u8)
            put(p + 54, limbs.astype("<i8"), "<i8")   # (Sr, 6) block
            p += 102
            fb += 2 + 8 * r_run          # varies per series
        M = np.empty((Sr, recsize), dtype=u8)
        M[:] = tmpl
        if not _native.scatter_fields(M, spec):
            for off, mat in spec:
                M[:, off:off + mat.shape[1]] = mat
        self._metas.append(("grpb", np.asarray(sids, dtype=np.int64),
                            M.tobytes(), recsize))
        mn, mx = int(t0.min()), int(t_last.max())
        self._min_time = mn if self._min_time is None \
            else min(self._min_time, mn)
        self._max_time = mx if self._max_time is None \
            else max(self._max_time, mx)

    def _meta_groups(self):
        """Iterate ((first_sid, last_sid, count), blob_bytes) meta
        groups across singles and vectorized bulk entries (entries are
        sid-ordered, non-overlapping by construction). Consecutive
        singles batch up to META_GROUP_SERIES as the object-based
        finalize always did — one index entry and one zstd blob per
        group, not per series."""
        run_sids: list[int] = []
        run_blobs: list[bytes] = []

        def flush_run(final: bool):
            while len(run_sids) >= META_GROUP_SERIES or (final
                                                        and run_sids):
                n = min(META_GROUP_SERIES, len(run_sids))
                yield ((run_sids[0], run_sids[n - 1], n),
                       b"".join(run_blobs[:n]))
                del run_sids[:n], run_blobs[:n]

        for ent in self._metas:
            if ent[0] == "one":
                run_sids.append(ent[1])
                run_blobs.append(ent[2])
                yield from flush_run(False)
                continue
            # sid order is global: drain any partial single-run before
            # a bulk entry's sid range starts
            yield from flush_run(True)
            _k, sids, blob, rs = ent
            for g in range(0, len(sids), META_GROUP_SERIES):
                hi = min(g + META_GROUP_SERIES, len(sids))
                yield ((int(sids[g]), int(sids[hi - 1]), hi - g),
                       blob[g * rs:hi * rs])
        yield from flush_run(True)

    def _all_sids(self) -> np.ndarray:
        parts = []
        for ent in self._metas:
            if ent[0] == "one":
                parts.append(np.array([ent[1]], dtype=np.uint64))
            else:
                parts.append(ent[1].astype(np.uint64))
        return (np.concatenate(parts) if parts
                else np.zeros(0, dtype=np.uint64))

    def finalize(self) -> None:
        # fault injection: die before the trailer/rename — the .tmp is
        # orphaned and the durable file set is untouched (torn-flush
        # crash semantics)
        failpoint.inject("tssp.write.err")
        import zlib as _zlib
        data_end = self._pos
        # chunk metas in sid order, grouped for the meta index; the
        # running CRC over everything after the data section is the
        # v3 open-time verification
        meta_crc = 0
        idx_entries = []
        meta_off = self._pos
        for (s0, s1, cnt), raw in self._meta_groups():
            blob = enc._zstd_c(raw)
            off, size = self._append(blob)
            meta_crc = _zlib.crc32(blob, meta_crc)
            idx_entries.append((s0, s1, off, size, cnt))
        meta_size = self._pos - meta_off
        idx_off = self._pos
        b = struct.pack("<I", len(idx_entries))
        self._append(b)
        meta_crc = _zlib.crc32(b, meta_crc)
        for e in idx_entries:
            b = struct.pack("<QQQII", *e)
            self._append(b)
            meta_crc = _zlib.crc32(b, meta_crc)
        idx_size = self._pos - idx_off
        bloom = SeriesBloom.build(self._all_sids())
        bb = bloom.bits.tobytes()
        bloom_off, bloom_size = self._append(bb)
        meta_crc = _zlib.crc32(bb, meta_crc)
        trailer = struct.pack(
            _TRAILER_FMT_V3, data_end, meta_off, meta_size, idx_off,
            idx_size, bloom_off, bloom_size,
            self._min_time if self._min_time is not None else 0,
            self._max_time if self._max_time is not None else 0,
            len(self._all_sids()), meta_crc)
        self._append(trailer)
        self._append(struct.pack("<II", len(trailer), MAGIC))
        # crash points bracket each durability boundary of the atomic
        # publish: pre_sync → a torn .tmp (swept at restart, durable
        # set untouched); pre_rename → a COMPLETE .tmp that was never
        # published (also swept: publication is the rename, nothing
        # else); post_rename → published and durable, restart serves it
        failpoint.inject("tssp.finalize.crash_pre_sync")
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        failpoint.inject("tssp.finalize.crash_pre_rename")
        fileops.durable_replace(self.path + ".tmp", self.path)
        failpoint.inject("tssp.finalize.crash_post_rename")

    def abort(self) -> None:
        self._f.close()
        os.unlink(self.path + ".tmp")


# ------------------------------------------------------------------ reader

class TSSPReader:
    """mmap-backed reader with lazy chunk-meta decode via the meta index
    (analogs: immutable/reader.go, file_iterator.go, location_cursor.go)."""

    _SERIALS = itertools.count(1)

    def __init__(self, path: str, source=None):
        """path: local file (mmap) — or, with ``source`` (a byte-slice
        provider, e.g. obs.DetachedSource), a detached object-store read
        path (reference detached_lazy_load_index_reader.go); ``path`` is
        then only the cache identity."""
        # fault injection: unreadable file (media fault at open — the
        # query path surfaces it as a store-side error, never a hang)
        failpoint.inject("tssp.read.err")
        self.path = path
        # process-unique identity for content-addressed caches (id()
        # recycles after GC; serials never do)
        self.serial = next(TSSPReader._SERIALS)
        self.detached = source is not None
        if source is None:
            self._file = open(path, "rb")
            self._mm = mmap.mmap(self._file.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        else:
            self._file = None
            self._mm = source
        mm = self._mm
        if len(mm) < 16:
            raise ValueError(f"{path}: truncated TSSP file")
        magic, version = struct.unpack("<II", mm[0:8])
        tsize, tail_magic = struct.unpack("<II", mm[len(mm) - 8:len(mm)])
        if magic != MAGIC or tail_magic != MAGIC:
            raise ValueError(f"{path}: bad TSSP magic")
        if version not in (1, 2, VERSION):
            raise ValueError(f"{path}: unsupported version {version}")
        self.version = version
        fmt = _TRAILER_FMT_V3 if version >= 3 else _TRAILER_FMT
        if tsize != struct.calcsize(fmt) or len(mm) < 16 + tsize:
            raise ValueError(f"{path}: truncated TSSP trailer")
        tr = struct.unpack(fmt, mm[len(mm) - 8 - tsize:len(mm) - 8])
        (self.data_end, self.meta_off, self.meta_size, self.idx_off,
         self.idx_size, self.bloom_off, self.bloom_size,
         self.min_time, self.max_time, self.series_count) = tr[:10]
        # open-time verification (crash-consistency contract): the
        # trailer's section layout must be internally consistent and
        # inside the file, and — v3 — the metadata bytes must match
        # their recorded CRC. A failure raises ValueError; the shard
        # loader quarantines the file and keeps serving the rest.
        end = len(mm) - 8 - tsize
        if not (8 <= self.data_end <= self.meta_off
                and self.meta_off + self.meta_size == self.idx_off
                and self.idx_off + self.idx_size == self.bloom_off
                and self.bloom_off + self.bloom_size <= end):
            raise ValueError(f"{path}: inconsistent TSSP trailer "
                             "section layout")
        if version >= 3 and source is None:
            # local files verify the metadata CRC at open; detached
            # sources stay lazy (integrity there is the object store's
            # contract — forcing the whole meta section through ranged
            # GETs at open would defeat detached_lazy_load)
            import zlib as _zlib
            got = _zlib.crc32(
                mm[self.meta_off:self.bloom_off + self.bloom_size])
            if got != tr[10]:
                raise ValueError(
                    f"{path}: TSSP metadata checksum mismatch "
                    f"(crc {got:#x} != recorded {tr[10]:#x})")
        # copy (not view) so the mmap can close while the bloom lives on
        self.bloom = SeriesBloom(np.frombuffer(
            mm[self.bloom_off:self.bloom_off + self.bloom_size],
            dtype=np.uint8).copy())
        # meta index (one fetch: contiguous section)
        idx_blob = mm[self.idx_off:self.idx_off + self.idx_size]
        (n_groups,) = struct.unpack_from("<I", idx_blob, 0)
        pos = 4
        self._index = []
        for _ in range(n_groups):
            self._index.append(struct.unpack_from("<QQQII", idx_blob, pos))
            pos += struct.calcsize("<QQQII")
        self._meta_cache: dict[int, dict[int, ChunkMeta]] = {}

    def close(self) -> None:
        try:
            self._mm.close()
        except BufferError:
            # zero-staging hands out transient views over the mmap
            # (payload_view / _decode_segment / blockagg word views);
            # an exception traceback cycle (device-decode fault paths)
            # can pin a dead frame holding one until the cycle
            # collector runs — collect and retry before surfacing
            import gc
            gc.collect()
            self._mm.close()
        if self._file is not None:
            self._file.close()

    def __del__(self):  # deferred close for compacted-away files
        try:
            if not self._mm.closed:
                self.close()
        except Exception:
            pass

    # ---- meta access ----------------------------------------------------

    def _load_group(self, gi: int) -> dict[int, ChunkMeta]:
        cached = self._meta_cache.get(gi)
        if cached is not None:
            return cached
        _, _, off, size, count = self._index[gi]
        blob = enc._zstd_d(self._window(off, size))
        metas: dict[int, ChunkMeta] = {}
        pos = 0
        for _ in range(count):
            cm, pos = _unpack_chunk_meta(blob, pos, self.version)
            metas[cm.sid] = cm
        self._meta_cache[gi] = metas
        return metas

    def chunk_meta(self, sid: int) -> ChunkMeta | None:
        if not self.bloom.may_contain(sid):
            return None
        for gi, (lo, hi, *_rest) in enumerate(self._index):
            if lo <= sid <= hi:
                return self._load_group(gi).get(sid)
        return None

    def chunk_metas_many(self, sids: np.ndarray) -> dict:
        """Batched chunk-meta lookup: ONE bloom pass + grouped meta-
        index loads → {sid: ChunkMeta} for the sids present."""
        sids = np.asarray(sids, dtype=np.int64)
        if len(sids) == 0 or not self._index:
            return {}
        maybe = sids[self.bloom.may_contain_many(sids)]
        if len(maybe) == 0:
            return {}
        los = np.array([e[0] for e in self._index], dtype=np.int64)
        his = np.array([e[1] for e in self._index], dtype=np.int64)
        gi = np.searchsorted(los, maybe, side="right") - 1
        ok = (gi >= 0) & (maybe <= his[np.clip(gi, 0, len(his) - 1)])
        out = {}
        for g in np.unique(gi[ok]):
            grp = self._load_group(int(g))
            for sid in maybe[ok & (gi == g)].tolist():
                cm = grp.get(sid)
                if cm is not None:
                    out[sid] = cm
        return out

    def series_ids(self) -> list[int]:
        out = []
        for gi in range(len(self._index)):
            out.extend(self._load_group(gi).keys())
        return sorted(out)

    # ---- data access ----------------------------------------------------

    def read_segment(self, col: ColumnMeta, seg: Segment) -> ColVal:
        from . import readcache
        if readcache.enabled():
            key = (self.path, seg.offset)
            hit = readcache.global_cache().get(key)
            if hit is not None:
                return hit
            out = self._decode_segment(col, seg)
            nb = 0
            if out.values is not None:
                nb += out.values.nbytes
            if out.valid is not None:
                nb += out.valid.nbytes
            if out.data is not None:
                nb += len(out.data)
            readcache.global_cache().put(key, out, nb + 64)
            return out
        return self._decode_segment(col, seg)

    def payload_view(self, seg: Segment) -> memoryview:
        """ZERO-STAGING handoff: the segment's encoded payload as a
        memoryview straight over the file mmap — no staging copy. The
        view is transient scan-side state: every block decoder accepts
        a memoryview and returns freshly-allocated arrays (RAW/ZSTD
        ``.copy()``, gorilla/dfor ``bytes()`` their payload words), so
        nothing decoded aliases the mmap and ``close()`` stays safe.
        Callers must not hold the view past the reader's lifetime."""
        return self._window(seg.offset, seg.size)

    def _window(self, off: int, size: int) -> memoryview:
        """[off, off+size) as a memoryview. mmap-backed readers get a
        zero-copy window over the map; detached (object-store) readers
        slice through DetachedSource.__getitem__, which range-GETs and
        caches blocks — there the bytes ARE the staging, unavoidably."""
        if self.detached:
            return memoryview(self._mm[off:off + size])
        return memoryview(self._mm)[off:off + size]

    def _decode_segment(self, col: ColumnMeta, seg: Segment) -> ColVal:
        # zero-staging: decoders consume memoryviews of the mmap
        # directly (no bytes() staging copy of the encoded payload);
        # see payload_view for the aliasing contract
        raw = self._window(seg.offset, seg.size)
        valid = enc.decode_validity(
            self._window(seg.valid_offset, seg.valid_size), seg.rows)
        t = col.type
        if t == DataType.TIME:
            return ColVal(t, enc.decode_time_block(raw, seg.rows), valid)
        if t == DataType.INTEGER:
            return ColVal(t, enc.decode_integer_block(raw, seg.rows), valid)
        if t == DataType.FLOAT:
            return ColVal(t, enc.decode_float_block(raw, seg.rows), valid)
        if t == DataType.BOOLEAN:
            return ColVal(t, enc.decode_boolean_block(raw, seg.rows), valid)
        offsets, data = enc.decode_string_block(raw)
        return ColVal(t, valid=valid, offsets=offsets, data=data)

    def read_series(self, sid: int, columns: list[str] | None = None,
                    t_min: int | None = None,
                    t_max: int | None = None) -> Record | None:
        """Decode one series' columns (optionally a subset / time range)
        into a Record. Segment-level time pruning via column meta preagg."""
        cm = self.chunk_meta(sid)
        if cm is None:
            return None
        if t_min is not None and cm.max_time < t_min:
            return None
        if t_max is not None and cm.min_time > t_max:
            return None
        time_meta = cm.column("time")
        if time_meta is None:
            return None
        names = ([c for c in columns if c != "time"] if columns is not None
                 else [c.name for c in cm.columns if c.name != "time"])
        fields = []
        cols = []
        # segment selection by time range using the time column's segments
        nsegs = len(time_meta.segments)
        keep = []
        for si in range(nsegs):
            tcol = time_meta.segments[si]
            pa = tcol.preagg
            if pa is not None:
                if t_min is not None and pa.max_time < t_min:
                    continue
                if t_max is not None and pa.min_time > t_max:
                    continue
            keep.append(si)
        if not keep:
            return None
        for name in names:
            colm = cm.column(name)
            if colm is None:
                continue
            parts = [self.read_segment(colm, colm.segments[si])
                     for si in keep]
            col = parts[0]
            for p in parts[1:]:
                col.append(p)
            fields.append(Field(name, colm.type))
            cols.append(col)
        tparts = [self.read_segment(time_meta, time_meta.segments[si])
                  for si in keep]
        tcol = tparts[0]
        for p in tparts[1:]:
            tcol.append(p)
        fields.append(Field("time", DataType.TIME))
        cols.append(tcol)
        rec = Record(Schema(fields), cols)
        if t_min is not None or t_max is not None:
            lo = t_min if t_min is not None else rec.min_time
            hi = t_max if t_max is not None else rec.max_time
            rec = rec.time_slice(lo, hi)
        return rec if rec.num_rows else None
