"""Compaction: merge many small TSSP files into one (role of reference
engine/immutable/compact.go LevelCompact :119, merge_out_of_order.go,
merge_tool.go).

Level policy: files are grouped by size tier (level = log2(size/base)); when
a measurement accumulates >= `fanout` files in one level, they merge into
one file at the next level. Out-of-order data merges via the same per-series
ordered merge used by the read path (last-write-wins, null-preserving), so
compaction output is exactly what reads would have produced.
"""

from __future__ import annotations

import os

from ..utils import failpoint, get_logger
from .tssp import TSSPReader, TSSPWriter

log = get_logger(__name__)

# cumulative metrics for the statistics pusher (statistics/compact.go)
from ..utils.stats import register_counters

COMPACT_STATS = register_counters("compaction", {
    "merges": 0, "files_merged": 0, "series_merged": 0,
    "series_streamed": 0, "series_decoded": 0})

BASE_SIZE = 1 << 20       # 1 MiB → level 0
DEFAULT_FANOUT = 4
MAX_LEVEL = 6


def merge_series(readers, sid: int):
    """One series' merged Record across `readers` (oldest→newest, the
    read path's last-write-wins semantics) — the single definition of
    the decode-merge fold shared by compaction, the stream-compaction
    fallback, and downsampling."""
    from .shard import _merge_parts
    rec = None
    for r in readers:
        part = r.read_series(sid)
        if part is not None:
            rec = part if rec is None else _merge_parts(rec, part)
    return rec


def iter_merged_series(readers):
    """Yield (sid, merged Record) over the union of series in `readers`.
    Shared by compaction and downsampling."""
    sids = sorted({sid for r in readers for sid in r.series_ids()})
    for sid in sids:
        rec = merge_series(readers, sid)
        if rec is not None and rec.num_rows:
            yield sid, rec


def remove_reader_files(readers) -> None:
    """Unlink replaced TSSP inputs but do NOT close them: in-flight
    queries may still hold the readers (POSIX keeps the mapped data alive
    after unlink); the mmap closes when the last reference drops
    (TSSPReader.__del__). Detached inputs: drop the marker AND the
    object-store copy, or a restart would resurrect the pre-merge data
    through the stale marker. Shared by compaction/downsample swaps and
    DROP MEASUREMENT."""
    for r in readers:
        if r.detached:
            try:
                os.unlink(r.path + ".detached")
            except OSError:
                pass
            try:
                r._mm.store.delete(r._mm.key)
            except Exception as e:
                log.error("failed to delete cold object for %s: %s",
                          r.path, e)
            continue
        try:
            os.unlink(r.path)
        except OSError as e:
            log.error("failed to remove %s: %s", r.path, e)


def merge_and_swap(shard, mst: str, readers, transform=None) -> str | None:
    """Merge `readers` (a CONTIGUOUS, oldest→newest slice of the shard's
    file list for `mst`) into one new TSSP file — optionally rewriting
    each merged record through `transform(rec, sid)` — then atomically swap it
    into the file list at the position of the oldest input and unlink the
    inputs. Shared by compaction and downsampling; the shard's table_lock
    serializes all such whole-table rewrites so two services can never
    merge overlapping file sets (one would resurrect data the other
    replaced).

    Returns the new file's path, or None when the merge produced no rows
    (inputs are still removed — they contributed nothing).
    """
    # fault injection BEFORE the lock/plan: a failed merge leaves the
    # input files exactly as they were (compaction retries next round)
    failpoint.inject("compact.merge.err")
    from ..utils.stats import bump as _bump
    _bump(COMPACT_STATS, "merges")
    _bump(COMPACT_STATS, "files_merged", len(readers))
    with shard.table_lock:
        # re-snapshot under the lock: a concurrent rewrite may have
        # replaced some of the planned inputs
        with shard._lock:
            current = set(id(r) for r in shard._files.get(mst, ()))
            readers = [r for r in readers if id(r) in current]
            if not readers:
                return None
            shard._file_seq += 1
            out_path = os.path.join(shard.path, "tssp",
                                    f"{mst}_{shard._file_seq:06d}.tssp")
        w = TSSPWriter(out_path, segment_size=shard.segment_size)
        wrote = False
        if transform is None:
            # STREAM COMPACTION (reference stream_compact.go +
            # merge_tool.go): series whose inputs don't overlap in time
            # copy their encoded segments verbatim — no decode, no
            # re-encode; only genuinely overlapping series take the
            # ordered decode-merge. Typical level merges are
            # time-disjoint flushes, so most bytes stream through.
            sids = sorted({sid for r in readers
                           for sid in r.series_ids()})
            for sid in sids:
                holders = [(cm, r) for r in readers
                           for cm in (r.chunk_meta(sid),)
                           if cm is not None]
                holders.sort(key=lambda h: h[0].min_time)
                if w.write_series_raw(sid, holders):
                    _bump(COMPACT_STATS, "series_streamed")
                    wrote = True
                    continue
                rec = merge_series(readers, sid)
                if rec is not None and rec.num_rows:
                    _bump(COMPACT_STATS, "series_decoded")
                    w.write_series(sid, rec)
                    wrote = True
        else:
            for sid, rec in iter_merged_series(readers):
                rec = transform(rec, sid)
                if rec.num_rows:
                    w.write_series(sid, rec)
                    wrote = True
        if wrote:
            w.finalize()
            # crash here: merged output published, inputs still on
            # disk — restart loads BOTH; duplicate (series, time) rows
            # carry identical values and the read path's last-wins
            # merge collapses them, so the swap is crash-idempotent
            # (the next compaction round re-plans and re-merges)
            failpoint.inject("compact.swap.crash")
            new_reader = TSSPReader(out_path)
        else:
            w.abort()
            new_reader = None
        with shard._lock:
            files = shard._files.get(mst, [])
            drop = set(id(r) for r in readers)
            # swap in at the position of the OLDEST input (the read path
            # resolves duplicate timestamps by list order, later wins);
            # files flushed concurrently since the snapshot are kept
            new_list = []
            inserted = new_reader is None
            for r in files:
                if id(r) in drop:
                    if not inserted:
                        new_list.append(new_reader)
                        inserted = True
                    continue
                new_list.append(r)
            if not inserted:
                new_list.append(new_reader)
            shard._files[mst] = new_list
        remove_reader_files(readers)
        return out_path if new_reader is not None else None


def size_level(sz: int) -> int:
    lvl = 0
    while sz >= BASE_SIZE << (lvl + 1) and lvl < MAX_LEVEL:
        lvl += 1
    return lvl


def file_level(path: str) -> int:
    return size_level(os.path.getsize(path))


def reader_level(r: TSSPReader) -> int:
    """Level from the reader's view size — works for local mmaps and
    detached object-store sources alike (the local path is gone)."""
    return size_level(len(r._mm))


class Compactor:
    """Per-shard compactor; invoked by the shard after flush or by the
    compaction service."""

    def __init__(self, shard, fanout: int = DEFAULT_FANOUT):
        self.shard = shard
        self.fanout = fanout

    def plan(self) -> dict[str, list[TSSPReader]]:
        """measurement → CONTIGUOUS run of same-level files to merge.
        Contiguity in the file list is required for correctness: the read
        path resolves duplicate timestamps by list order (later wins), so a
        merged output may only replace neighbouring inputs."""
        out = {}
        with self.shard._lock:
            for mst, readers in self.shard._files.items():
                if len(readers) < self.fanout:
                    continue
                levels = [reader_level(r) for r in readers]
                best: list[TSSPReader] = []
                i = 0
                while i < len(readers):
                    j = i
                    while j + 1 < len(readers) and levels[j + 1] == levels[i]:
                        j += 1
                    run = readers[i:j + 1]
                    if len(run) >= self.fanout and len(run) > len(best):
                        best = run
                    i = j + 1
                if best:
                    out[mst] = best
        return out

    def compact_measurement(self, mst: str,
                            readers: list[TSSPReader]) -> str | None:
        """Merge `readers` (a CONTIGUOUS, oldest→newest slice of the
        shard's file list) into one new file; swap it in at the slice's
        position; delete inputs. Returns the new path."""
        out_path = merge_and_swap(self.shard, mst, readers)
        if out_path is not None:
            log.info("compacted %s: %d files -> %s", mst, len(readers),
                     os.path.basename(out_path))
        return out_path

    def run_once(self) -> int:
        """One compaction pass; returns number of merges performed."""
        n = 0
        for mst, readers in self.plan().items():
            self.compact_measurement(mst, readers)
            n += 1
        return n
