"""Shard: one time-range slice of a partition — WAL + memtable + immutable
TSSP files + series index (role of reference engine/shard.go:119).

Write path (reference shard.WriteRows :478 → writeRowsToTable :813):
    rows → sid lookup/create (index) → WAL append → memtable
Flush (reference ts_storage.go:155 shouldSnapshot → writeSnapshot):
    snapshot memtables → one TSSP file per measurement → commit, drop WAL
Read path: per-series merge of memtable + TSSP files (newer wins), the
tsm_merge_cursor analog done record-wise.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ..index import SeriesIndex, TagFilter
from ..record import (ColVal, DataType, Field, Record, Schema,
                      merge_sorted_records)
from ..utils import failpoint, fileops, get_logger, knobs
from ..utils.errors import ErrTypeConflict
from .colstore import ColumnStoreReader, ColumnStoreWriter
from .memtable import MemTable, MemTables, field_type_of
from .rows import PointRow
from .tssp import TSSPReader, TSSPWriter, SEGMENT_SIZE

log = get_logger(__name__)

DEFAULT_FLUSH_BYTES = 256 * 1024 * 1024


_SHARD_SERIALS = __import__("itertools").count(1)


class Shard:
    def __init__(self, path: str, shard_id: int,
                 start_time: int, end_time: int,
                 flush_bytes: int = DEFAULT_FLUSH_BYTES,
                 wal_sync: bool = False,
                 wal_compression: str = "zstd",
                 segment_size: int = SEGMENT_SIZE,
                 cs_options: dict | None = None,
                 obs_store=None):
        self.path = path
        self.shard_id = shard_id
        self.start_time = start_time
        self.end_time = end_time
        self.flush_bytes = flush_bytes
        self.segment_size = segment_size
        # {measurement: {"primary_key": [...], "indexes": {col: kind},
        #  "fragment_rows": int}} — shared dict owned by the Database
        # (reference: column-store measurements declared in ts-meta,
        # engine-type dispatch cs_storage.go:42)
        self.cs_options = cs_options if cs_options is not None else {}
        # object-store tier for detached (cold) TSSP files (reference
        # hierarchical storage + detached OBS reads, SURVEY §2.1/§2.7)
        self.obs_store = obs_store
        os.makedirs(path, exist_ok=True)
        os.makedirs(os.path.join(path, "tssp"), exist_ok=True)
        os.makedirs(os.path.join(path, "colstore"), exist_ok=True)
        self.index = SeriesIndex(os.path.join(path, "series.log"))
        from .wal import WAL
        self.wal = WAL(os.path.join(path, "wal"), sync=wal_sync,
                       compression=wal_compression)
        self.mem = MemTables()
        self.serial = next(_SHARD_SERIALS)   # process-unique (vs id())
        self._files: dict[str, list[TSSPReader]] = {}
        self._cs_files: dict[str, list[ColumnStoreReader]] = {}
        self._file_seq = 0
        self._lock = threading.RLock()
        # serializes whole-table file rewrites (compaction, downsample,
        # delete): two concurrent merges over overlapping file sets would
        # each swap in their own output and resurrect replaced data.
        # RLock: delete_rows holds it across its whole rewrite loop while
        # each inner merge_and_swap re-acquires it
        self.table_lock = threading.RLock()
        # durable measurement→field→type registry: memtable schemas reset at
        # flush, so type stability across flushes must be enforced here
        # (role of the reference's measurement schema in ts-meta)
        self._schema_path = os.path.join(path, "fields.idx")
        self._schemas: dict[str, dict[str, DataType]] = {}
        # startup recovery report for this shard (WAL replay tallies,
        # quarantined files, orphans removed, recovery_ms) — recorded
        # into storage.wal's process-wide ring for /debug/vars
        self.recovery: dict = {"shard": shard_id, "path": path}
        self._sweep_orphans()
        self._load_schemas()
        self._load_files()
        self._replay_wal()
        from .wal import record_recovery
        record_recovery(self.recovery)

    # ---- open ------------------------------------------------------------

    def _sweep_orphans(self) -> None:
        """Remove crash leftovers before anything loads: a ``.tmp``
        file is by construction unpublished work (TSSP finalize,
        colstore publish, index snapshot and detach markers all write
        ``<name>.tmp`` and rename only after fsync) — after a crash it
        is garbage that must not survive the restart, let alone two
        (the crash-harness orphan contract)."""
        from .wal import WAL_STATS
        from ..utils.stats import bump as _bump
        n = 0
        for d in (self.path, os.path.join(self.path, "tssp"),
                  os.path.join(self.path, "colstore"),
                  os.path.join(self.path, "wal")):
            if not os.path.isdir(d):
                continue
            removed_here = 0
            for fn in os.listdir(d):
                if fn.endswith(".tmp"):
                    try:
                        os.unlink(os.path.join(d, fn))
                        removed_here += 1
                    except OSError:
                        pass
            if removed_here:          # fsync only mutated directories
                n += removed_here
                fileops.fsync_dir(d)
        if n:
            log.info("shard %d: removed %d orphan .tmp file(s) at "
                     "open", self.shard_id, n)
            _bump(WAL_STATS, "orphans_removed", n)
            self.recovery["orphans_removed"] = n

    def _quarantine_file(self, path: str, why) -> None:
        """Quarantine-and-continue for an unreadable immutable file:
        rename to ``<name>.corrupt`` (durable) so the open proceeds
        without it and a second restart doesn't re-trip; off-switch
        OG_STORAGE_QUARANTINE=0 restores the log-only behavior."""
        from .wal import WAL_STATS
        from ..utils.stats import bump as _bump
        if not knobs.get("OG_STORAGE_QUARANTINE"):
            log.error("skipping corrupt %s: %s", path, why)
            return
        try:
            size = os.path.getsize(path)
            fileops.durable_replace(path, path + ".corrupt")
        except OSError as e:
            log.error("failed to quarantine %s: %s", path, e)
            return
        log.error("quarantined corrupt %s -> .corrupt (%s)", path, why)
        _bump(WAL_STATS, "quarantined_files")
        _bump(WAL_STATS, "quarantined_bytes", size)
        self.recovery["quarantined_files"] = (
            self.recovery.get("quarantined_files", 0) + 1)

    def _load_schemas(self) -> None:
        if not os.path.exists(self._schema_path):
            return
        with open(self._schema_path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) != 3:
                    continue
                if parts[1] == "__drop__" and parts[2] == "-1":
                    # drop-measurement tombstone (append-only registry);
                    # type -1 disambiguates from a user field that is
                    # literally named __drop__ (always a real DataType)
                    self._schemas.pop(parts[0], None)
                    continue
                self._schemas.setdefault(parts[0], {})[parts[1]] = (
                    DataType(int(parts[2])))

    def _check_fields(self, staged: dict, mst: str, fields: dict) -> None:
        """Two-phase type check: validates fields against registry + already
        staged additions, staging new (mst, field)→type entries into
        ``staged``. Nothing is applied until _commit_fields — a conflict
        anywhere in a batch must leave the registry untouched."""
        sch = self._schemas.get(mst, {})
        for k, v in fields.items():
            ft = field_type_of(v)
            cur = sch.get(k) or staged.get((mst, k))
            if cur is None:
                staged[(mst, k)] = ft
            elif cur != ft and not (cur == DataType.FLOAT
                                    and ft == DataType.INTEGER):
                raise ErrTypeConflict(
                    f"field {k}: {ft.name} conflicts with {cur.name}")

    def _commit_fields(self, staged: dict) -> None:
        if not staged:
            return
        lines = []
        for (mst, k), ft in staged.items():
            self._schemas.setdefault(mst, {})[k] = ft
            lines.append(f"{mst}\t{k}\t{int(ft)}\n")
        self._persist_schema_lines(lines)

    def _persist_schema_lines(self, lines: list[str]) -> None:
        with open(self._schema_path, "a", encoding="utf-8") as f:
            f.writelines(lines)
            f.flush()
            os.fsync(f.fileno())

    def _load_files(self) -> None:
        import struct as _struct
        d = os.path.join(self.path, "tssp")
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".tssp.detached"):
                # cold file living in the object store (hierarchical tier)
                import json as _json
                base = fn[:-len(".detached")]
                mst, seq = base[:-5].rsplit("_", 1)
                self._file_seq = max(self._file_seq, int(seq))
                if self.obs_store is None:
                    log.error("detached file %s but no object store "
                              "configured; data unavailable", base)
                    continue
                try:
                    with open(os.path.join(d, fn)) as mf:
                        key = _json.load(mf)["key"]
                    from .obs import DetachedSource
                    self._files.setdefault(mst, []).append(
                        TSSPReader(os.path.join(d, base),
                                   source=DetachedSource(self.obs_store,
                                                         key)))
                except (ValueError, KeyError, OSError,
                        _struct.error) as e:
                    log.error("skipping detached tssp %s: %s", fn, e)
                continue
            if not fn.endswith(".tssp"):
                continue
            mst, seq = fn[:-5].rsplit("_", 1)
            self._file_seq = max(self._file_seq, int(seq))
            try:
                self._files.setdefault(mst, []).append(
                    TSSPReader(os.path.join(d, fn)))
            except (ValueError, _struct.error, OSError) as e:
                # open-time verification failed (bad magic/trailer
                # bounds/meta checksum): quarantine and serve the rest
                # — a restart must never crash-loop on one bad file
                self._quarantine_file(os.path.join(d, fn), e)
        cd = os.path.join(self.path, "colstore")
        for fn in sorted(os.listdir(cd)):
            if not fn.endswith(".ogcf"):
                continue
            try:
                mst, seq = fn[:-5].rsplit("_", 1)
                self._file_seq = max(self._file_seq, int(seq))
                self._cs_files.setdefault(mst, []).append(
                    ColumnStoreReader(os.path.join(cd, fn)))
            except (ValueError, _struct.error, OSError, KeyError) as e:
                self._quarantine_file(os.path.join(cd, fn), e)

    def _coerce(self, mst: str, fields: dict) -> dict:
        """int→float coercion for fields registered as FLOAT, so memtable
        arrays always match the durable schema type."""
        sch = self._schemas.get(mst)
        if not sch:
            return fields
        out = None
        for k, v in fields.items():
            if (type(v) is int and sch.get(k) == DataType.FLOAT):
                if out is None:
                    out = dict(fields)
                out[k] = float(v)
        return out if out is not None else fields

    def _replay_wal(self) -> None:
        import time as _time
        t0 = _time.perf_counter()
        n = bad = 0
        for batch in self.wal.replay(report=self.recovery):
            if isinstance(batch, tuple) and batch[0] == "cols":
                for mst, sid, times, fields in batch[1]:
                    try:
                        self.mem.write_columns(mst, sid, times, fields)
                        n += len(times)
                    except Exception as e:
                        bad += len(times)
                        log.error("shard %d: dropping bad wal column "
                                  "batch (%s): %s", self.shard_id, mst, e)
                continue
            if isinstance(batch, tuple) and batch[0] == "colsb":
                mst, sids, offsets, times_cat, fields_cat = batch[1]
                try:
                    self.mem.write_columns_bulk(mst, sids, offsets,
                                                times_cat, fields_cat)
                    n += len(times_cat)
                except Exception as e:
                    bad += len(times_cat)
                    log.error("shard %d: dropping bad wal bulk frame "
                              "(%s): %s", self.shard_id, mst, e)
                continue
            for mst, sid, fields, t in batch:
                try:
                    self.mem.write(mst, sid, self._coerce(mst, fields), t)
                    n += 1
                except Exception as e:  # poison row must not block open
                    bad += 1
                    log.error("shard %d: dropping bad wal row (%s %s): %s",
                              self.shard_id, mst, fields, e)
        ms = int((_time.perf_counter() - t0) * 1e3)
        self.recovery["rows_replayed"] = n
        self.recovery["rows_dropped"] = bad
        self.recovery["recovery_ms"] = ms
        from .wal import WAL_STATS
        from ..utils.stats import bump as _bump
        _bump(WAL_STATS, "recovery_ms", ms)
        if n or bad or self.recovery.get("segments"):
            anomalous = sum(
                1 for s in self.recovery.get("segments", ())
                if s["torn"] or s["bad_crc"] or s["decode_errors"])
            log.info("shard %d: replayed %d rows from wal in %dms "
                     "(%d dropped; %d segment(s) with anomalies)",
                     self.shard_id, n, ms, bad, anomalous)

    # ---- writes ----------------------------------------------------------

    def write_rows(self, rows: list[PointRow]) -> int:
        """Returns rows written. Rows outside the shard time range are the
        caller's bug (engine routes by time)."""
        batch = []
        created_sid = False
        for r in rows:
            self._check_cs_collision(r.measurement, r.tags, r.fields)
            before = self.index.series_cardinality
            sid = self.index.get_or_create_sid(r.measurement, r.tags)
            created_sid |= self.index.series_cardinality != before
            batch.append((r.measurement, sid, r.fields, r.time))
        with self._lock:
            # validate against the durable schema registry BEFORE the batch
            # becomes durable: a type-conflicting row must never reach the
            # WAL (it would poison every replay)
            staged: dict = {}
            for mst, _sid, fields, _t in batch:
                self._check_fields(staged, mst, fields)
            self._commit_fields(staged)
            batch = [(mst, sid, self._coerce(mst, fields), t)
                     for mst, sid, fields, t in batch]
            if created_sid:
                # sid allocations must be durable before rows referencing
                # them: otherwise crash replay could reassign those sids to
                # different tag sets and merge unrelated series
                self.index.flush(snapshot=False)
            # lock spans wal.write + mem.write so a concurrent flush cannot
            # seal the WAL segment between them (which would let commit
            # delete the only durable copy of these rows)
            ticket = self.wal.write(batch, defer_sync=True)
            for mst, sid, fields, t in batch:
                self.mem.write(mst, sid, fields, t)
        # durability wait OUTSIDE the shard lock: with group commit on,
        # concurrent shards coalesce into one fsync; the write is acked
        # (returns) only once its WAL frame is covered by a sync
        self.wal.wait_durable(ticket)
        if self.mem.approx_bytes >= self.flush_bytes:
            self.flush()
        return len(batch)

    def write_columns(self, mst: str, tags: dict[str, str],
                      times, fields: dict) -> int:
        """Bulk columnar write of ONE series (reference RecordWriter /
        arrow-flight ingest path, coordinator/record_writer.go:79):
        numpy arrays straight through WAL and memtable, no per-row
        Python. Arrays are row-aligned and all-valid; int values land
        as INTEGER unless the registry says FLOAT (coerced whole-column).
        Returns rows written."""
        return self.write_columns_batch([(mst, tags, times, fields)])

    def _check_cs_collision(self, mst: str, tags: dict,
                            fields: dict) -> None:
        """Column-store measurements materialize tags as columns at
        flush: a tag/field name collision must bounce BEFORE the rows
        become durable — at flush time it would wedge the whole
        shard's snapshot loop forever. Shared by the row and bulk
        write paths."""
        if mst not in self.cs_options:
            return
        clash = set(tags) & set(fields)
        if clash:
            raise ErrTypeConflict(
                f"tag names collide with field names in "
                f"column-store measurement {mst!r}: {sorted(clash)}")

    @staticmethod
    def _normalize_cols(fields: dict, n: int):
        """Shared column normalization of the bulk write paths: numeric
        /bool arrays coerced to canonical dtypes + a one-value type
        probe for the schema check."""
        import numpy as np
        norm: dict[str, np.ndarray] = {}
        probe: dict[str, object] = {}
        for k, arr in fields.items():
            a = np.asarray(arr)
            if len(a) != n:
                raise ValueError(f"field {k}: length {len(a)} != {n}")
            if a.dtype == np.bool_:
                pass
            elif np.issubdtype(a.dtype, np.integer):
                a = a.astype(np.int64, copy=False)
            elif np.issubdtype(a.dtype, np.floating):
                a = a.astype(np.float64, copy=False)
            else:
                raise ErrTypeConflict(
                    f"field {k}: bulk writes are numeric/bool only")
            norm[k] = a
            probe[k] = a[0].item()
        return norm, probe

    def write_columns_bulk(self, mst: str, tags_list: list,
                           times_list: list, fields_list: list) -> int:
        """Many-tiny-series bulk write, one measurement, shared field
        names: per-series cost collapses to one index insert + one
        buffer append (the per-entry write_columns_batch pays
        normalize/WAL-pack/schema work per series — ~130µs at 6-row
        prom series; this path measures ~15µs). Durability order
        matches write_columns_batch: index fsync → WAL frame →
        memtable."""
        import numpy as np
        if not tags_list:
            return 0
        names = list(fields_list[0])
        self._check_cs_collision(
            mst, {k: "" for e in tags_list for k in e},
            fields_list[0])
        before = self.index.series_cardinality
        sids = self.index.get_or_create_sids(mst, tags_list)
        if self.index.series_cardinality != before:
            self.index.flush(snapshot=False)
        counts = np.fromiter((len(t) for t in times_list), np.int64,
                             len(times_list))
        offsets = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        times_cat = (np.concatenate(times_list)
                     .astype(np.int64, copy=False))
        fields_cat = {}
        probe = {}
        for k in names:
            cat = np.concatenate([np.asarray(f[k]) for f in fields_list])
            if cat.dtype == np.bool_:
                pass
            elif np.issubdtype(cat.dtype, np.integer):
                cat = cat.astype(np.int64, copy=False)
            elif np.issubdtype(cat.dtype, np.floating):
                cat = cat.astype(np.float64, copy=False)
            else:
                raise ErrTypeConflict(
                    f"field {k}: bulk writes are numeric/bool only")
            fields_cat[k] = cat
            probe[k] = cat[0].item()
        n = int(offsets[-1])
        with self._lock:
            staged: dict = {}
            self._check_fields(staged, mst, probe)
            self._commit_fields(staged)
            sch = self._schemas.get(mst, {})
            for k in names:
                if sch.get(k) == DataType.FLOAT \
                        and fields_cat[k].dtype == np.int64:
                    fields_cat[k] = fields_cat[k].astype(np.float64)
            ticket = self.wal.write_cols_bulk(
                mst, sids, offsets, times_cat, fields_cat,
                defer_sync=True)
            self.mem.write_columns_bulk(mst, sids, offsets, times_cat,
                                        fields_cat)
        # group-commit: fsync wait happens OUTSIDE the shard lock so
        # concurrent bulk writers (other shards, other Flight batches)
        # coalesce into one sync; ack only after the wait returns
        self.wal.wait_durable(ticket)
        if self.mem.approx_bytes >= self.flush_bytes:
            self.flush()
        return n

    def write_series_matrix(self, mst: str, keys: list, tag_cols: list,
                            times, fields: dict) -> int:
        """Aligned-series MATRIX write: S series sharing one tag-key
        set and one (P,) timestamp vector, each field an (S, P) value
        matrix — the scrape/remote-write shape. Per-series Python is
        zero: the index takes the tag COLUMNS (get_or_create_sids_cols),
        and the row stream for WAL + memtable is np.tile/ravel of the
        matrices. Durability order matches write_columns_bulk: index
        fsync → WAL frame → memtable."""
        import numpy as np
        S = len(tag_cols[0]) if tag_cols else 0
        times = np.ascontiguousarray(times, dtype=np.int64)
        P = len(times)
        if S == 0 or P == 0:
            return 0
        names = sorted(fields)
        self._check_cs_collision(mst, dict.fromkeys(keys, ""),
                                 fields)
        before = self.index.series_cardinality
        sids = self.index.get_or_create_sids_cols(mst, keys, tag_cols)
        if self.index.series_cardinality != before:
            self.index.flush(snapshot=False)
        offsets = np.arange(S + 1, dtype=np.int64) * P
        times_cat = np.tile(times, S)
        fields_cat = {}
        probe = {}
        for k in names:
            m = np.asarray(fields[k])
            if m.shape != (S, P):
                raise ValueError(
                    f"field {k}: want shape ({S}, {P}), got {m.shape}")
            if np.issubdtype(m.dtype, np.integer):
                m = m.astype(np.int64, copy=False)
            elif np.issubdtype(m.dtype, np.floating):
                m = m.astype(np.float64, copy=False)
            elif m.dtype != np.bool_:
                raise ErrTypeConflict(
                    f"field {k}: matrix writes are numeric/bool only")
            fields_cat[k] = m.reshape(-1)
            probe[k] = m.flat[0].item()
        with self._lock:
            staged: dict = {}
            self._check_fields(staged, mst, probe)
            self._commit_fields(staged)
            sch = self._schemas.get(mst, {})
            for k in names:
                if sch.get(k) == DataType.FLOAT \
                        and fields_cat[k].dtype == np.int64:
                    fields_cat[k] = fields_cat[k].astype(np.float64)
            ticket = self.wal.write_cols_bulk(
                mst, sids, offsets, times_cat, fields_cat,
                defer_sync=True)
            self.mem.write_columns_bulk(mst, sids, offsets, times_cat,
                                        fields_cat)
        self.wal.wait_durable(ticket)
        if self.mem.approx_bytes >= self.flush_bytes:
            self.flush()
        return S * P

    def write_columns_batch(self, entries) -> int:
        """Multi-series bulk write: [(mst, tags, times, fields)] land
        with ONE index fsync for all new series and ONE WAL frame for
        the whole batch. The per-series write_columns pays an index
        fsync per NEW series — measured 2.3s of a 4.2s 200k-row
        line-protocol ingest; this path amortizes it (the durability
        order is preserved: index entries are synced before the WAL
        frame that references their sids)."""
        import numpy as np
        prepared = []
        created_any = False
        for mst, tags, times, fields in entries:
            self._check_cs_collision(mst, tags, fields)
            n1 = len(times)
            if n1 == 0:
                continue
            times = np.ascontiguousarray(times, dtype=np.int64)
            norm, probe = self._normalize_cols(fields, n1)
            before = self.index.series_cardinality
            sid = self.index.get_or_create_sid(mst, tags)
            created_any |= self.index.series_cardinality != before
            prepared.append((mst, sid, times, norm, probe))
        if not prepared:
            return 0
        if created_any:
            self.index.flush(snapshot=False)
        n = 0
        with self._lock:
            # two-phase across the WHOLE batch: any type conflict
            # leaves the registry and WAL untouched
            staged: dict = {}
            for mst, _sid, _t, _norm, probe in prepared:
                self._check_fields(staged, mst, probe)
            self._commit_fields(staged)
            wal_entries = []
            for mst, sid, times, norm, _probe in prepared:
                sch = self._schemas.get(mst, {})
                for k in list(norm):
                    if sch.get(k) == DataType.FLOAT \
                            and norm[k].dtype == np.int64:
                        norm[k] = norm[k].astype(np.float64)
                wal_entries.append((mst, sid, times, norm))
                n += len(times)
            ticket = self.wal.write_cols(wal_entries, defer_sync=True)
            for mst, sid, times, norm in wal_entries:
                self.mem.write_columns(mst, sid, times, norm)
        self.wal.wait_durable(ticket)
        if self.mem.approx_bytes >= self.flush_bytes:
            self.flush()
        return n

    # ---- flush -----------------------------------------------------------

    def flush(self) -> None:
        """Memtable snapshot → TSSP files → commit (reference
        commitSnapshot shard.go:867)."""
        failpoint.inject("shard.flush.err")
        with self._lock:
            if not self.mem.active and self.mem.snapshot is None:
                return
            sealed_wal = self.wal.switch()
            snap = self.mem.begin_snapshot()
            try:
                new_files: list[tuple[str, str]] = []
                new_cs: list[tuple[str, str]] = []
                for mst, mt in snap.items():
                    if mt.rows == 0:
                        continue
                    self._file_seq += 1
                    if mst in self.cs_options:
                        opt = self.cs_options[mst]
                        fn = os.path.join(
                            self.path, "colstore",
                            f"{mst}_{self._file_seq:06d}.ogcf")
                        try:
                            rec = self._materialize_measurement(mst, mt)
                            if rec is not None and rec.num_rows:
                                ColumnStoreWriter(
                                    fn, opt.get("primary_key", []),
                                    opt.get("indexes"),
                                    opt.get("fragment_rows") or 4096,
                                    tag_columns=sorted(
                                        self.index.tag_keys(mst)),
                                ).write(rec)
                                new_cs.append((mst, fn))
                            continue
                        except (ErrTypeConflict, ValueError) as e:
                            # one poisoned measurement must not wedge the
                            # shard's snapshot loop forever: fall back to
                            # a durable TSSP write (loudly — recoverable
                            # by compaction/operator, invisible to the
                            # cs query path until then)
                            log.error(
                                "colstore flush of %s failed (%s); "
                                "falling back to row-store file", mst, e)
                    fn = os.path.join(self.path, "tssp",
                                      f"{mst}_{self._file_seq:06d}.tssp")
                    w = TSSPWriter(fn, segment_size=self.segment_size)
                    bulk = None
                    if mt.bulk_frames and not mt.series:
                        bulk = mt.consolidate_bulk()
                        if bulk is not None and not all(
                                c.dtype == np.float64
                                for c in bulk[3].values()):
                            bulk = None
                    if bulk is not None:
                        # many-tiny-series fast path: vectorized
                        # encode + metas, no per-series Python
                        w.write_series_bulk(*bulk)
                    else:
                        # encode-parallel flush: block encoders run on
                        # the OG_ENCODE_WORKERS pool, appends stay
                        # ordered on this thread (bytes identical to
                        # the serial loop)
                        w.write_series_stream(
                            (sid, rec) for sid in mt.sids()
                            for rec in (mt.series_record(sid),)
                            if rec is not None)
                    w.finalize()
                    new_files.append((mst, fn))
                for mst, fn in new_files:
                    self._files.setdefault(mst, []).append(TSSPReader(fn))
                for mst, fn in new_cs:
                    self._cs_files.setdefault(mst, []).append(
                        ColumnStoreReader(fn))
                self.index.flush()
                self.mem.commit_snapshot()
                # crash here: TSSP files published AND the sealed WAL
                # still present — restart replays the sealed segment
                # over data the files already hold; the last-wins
                # merge on identical rows makes that idempotent (the
                # crash harness proves no duplication)
                failpoint.inject("shard.flush.crash_commit")
                self.wal.remove_upto(sealed_wal)
            except Exception:
                self.mem.abort_snapshot()
                raise

    # ---- hierarchical tier ----------------------------------------------

    def drop_measurement(self, mst: str) -> None:
        """Remove a measurement's data, files, series and schema (role of
        the reference's DropMeasurement engine path). Callers flush first
        so the WAL holds no rows that would resurrect it on replay.
        table_lock serializes against compaction/downsample rewrites;
        readers are unlinked but NOT closed (in-flight queries may hold
        them — the mmap dies with the last reference, merge_and_swap
        convention)."""
        with self.table_lock:
            with self._lock:
                files = self._files.pop(mst, [])
                cs_files = self._cs_files.pop(mst, [])
                with self.mem._lock:
                    self.mem.active.pop(mst, None)
                    if self.mem.snapshot is not None:
                        self.mem.snapshot.pop(mst, None)
                    # visible change: scan-plan cache keys (even in
                    # OTHER executors) must stop matching
                    self.mem.mutations += 1
                self.index.drop_measurement(mst)
                if mst in self._schemas:
                    del self._schemas[mst]
                    # append-only registry: tombstone line (type -1)
                    self._persist_schema_lines(
                        [f"{mst}\t__drop__\t-1\n"])
            from .compact import remove_reader_files
            remove_reader_files(files)
            for r in cs_files:
                try:
                    os.unlink(r.path)
                except OSError:
                    pass

    def delete_rows(self, mst: str, t_min: int | None = None,
                    t_max: int | None = None,
                    sids: np.ndarray | None = None) -> int:
        """DELETE FROM mst [WHERE time/tags]: rewrite the matching TSSP
        files without the deleted rows (the reference deletes via engine
        tombstones; a rewrite is simpler and this path is rare). Each
        rewrite rides merge_and_swap, which owns the table_lock
        serialization, swap ordering, deferred reader close, and detached
        cleanup. Callers flush first so only files need rewriting.
        sids=None deletes across all series; returns rows removed."""
        del_sids = None if sids is None else {int(s) for s in sids}
        removed = {"n": 0}

        def transform(rec, sid):
            if rec.num_rows == 0 or (del_sids is not None
                                     and sid not in del_sids):
                return rec
            t = rec.times
            drop = np.ones(rec.num_rows, dtype=bool)
            if t_min is not None:
                drop &= t >= t_min
            if t_max is not None:
                drop &= t <= t_max
            if not drop.any():
                return rec
            removed["n"] += int(drop.sum())
            return rec.take(np.nonzero(~drop)[0])

        from .compact import merge_and_swap

        # hold table_lock across snapshot AND rewrites: otherwise a
        # concurrent compaction could replace a snapshotted file with a
        # merged one the loop never visits (rows silently surviving)
        with self.table_lock:
            with self._lock:
                files = list(self._files.get(mst, ()))
            for f in files:
                if (t_min is not None and f.max_time < t_min) or \
                        (t_max is not None and f.min_time > t_max):
                    continue
                if del_sids is not None and not any(
                        int(s) in del_sids for s in f.series_ids()):
                    continue
                merge_and_swap(self, mst, [f], transform=transform)
        return removed["n"]

    def detach_files(self, store, key_prefix: str) -> int:
        """Move this shard's TSSP files to the object store (warm→cold:
        reference services/hierarchical/service.go:75-139 + detached
        reads): upload, persist a .detached marker, reopen the reader
        through a DetachedSource, drop the local copy. Returns the number
        of files moved. Readcache entries stay valid: the cache keys on
        (path, offset) and the bytes are identical."""
        import json as _json
        from .obs import DetachedSource
        with self._lock:
            self.obs_store = store
            snapshot = [(mst, r) for mst, rs in self._files.items()
                        for r in rs if not r.detached]
        moved = 0
        for mst, r in snapshot:
            fn = os.path.basename(r.path)
            key = f"{key_prefix}/{fn}"
            try:
                # slow upload runs outside the locks: reads and writes
                # must not stall behind object-store I/O
                store.put_file(key, r.path)
            except FileNotFoundError:
                continue       # compacted away mid-pass; data lives on
            with self.table_lock, self._lock:
                readers = self._files.get(mst, [])
                idx = next((i for i, x in enumerate(readers) if x is r),
                           None)
                if idx is None:           # replaced since the snapshot
                    store.delete(key)
                    continue
                marker = r.path + ".detached"
                tmp = marker + ".tmp"
                with open(tmp, "w") as f:
                    _json.dump({"key": key}, f)
                    f.flush()
                    os.fsync(f.fileno())
                # marker must survive the crash or the restart loses
                # the only pointer to the cold copy while the local
                # file is already unlinked below
                fileops.durable_replace(tmp, marker)
                readers[idx] = TSSPReader(
                    r.path, source=DetachedSource(store, key))
                try:
                    os.unlink(r.path)
                except OSError:
                    pass
                # do NOT close r: in-flight queries may still hold it
                # (same deferred-close convention as merge_and_swap)
                moved += 1
        return moved

    @property
    def detached_file_count(self) -> int:
        with self._lock:
            return sum(1 for rs in self._files.values()
                       for r in rs if r.detached)

    # ---- reads -----------------------------------------------------------

    def measurements(self) -> list[str]:
        with self._lock:
            msts = set(self._files) | set(self._cs_files)
        for tbl in self.mem.tables_for_read():
            msts.update(tbl.keys())
        return sorted(msts)

    def series_ids(self, measurement: str,
                   filters: list[TagFilter] | None = None) -> np.ndarray:
        return self.index.series_ids(measurement, filters)

    def read_series(self, measurement: str, sid: int,
                    columns: list[str] | None = None,
                    t_min: int | None = None,
                    t_max: int | None = None) -> Record | None:
        """Merged view of one series: files (oldest→newest) then memtable,
        later sources winning on duplicate timestamps."""
        rec: Record | None = None
        with self._lock:
            files = list(self._files.get(measurement, ()))
        for f in files:
            part = f.read_series(sid, columns, t_min, t_max)
            if part is not None:
                rec = part if rec is None else _merge_parts(rec, part)
        for tbl in self.mem.tables_for_read()[::-1]:  # snapshot older first
            mt = tbl.get(measurement)
            if mt is None:
                continue
            part = mt.series_record(sid)
            if part is not None:
                if t_min is not None or t_max is not None:
                    part = part.time_slice(
                        t_min if t_min is not None else part.min_time,
                        t_max if t_max is not None else part.max_time)
                if part.num_rows:
                    if columns is not None:
                        part = _project(part, columns)
                    rec = part if rec is None else _merge_parts(rec, part)
        return rec

    # ---- column store ----------------------------------------------------

    def is_columnstore(self, mst: str) -> bool:
        return mst in self.cs_options

    def _materialize_measurement(self, mst: str,
                                 mt: "MemTable") -> Record | None:
        """Whole-measurement Record with tag columns materialized as
        strings — the column-store flush shape (reference cs_table.go:
        the cs memtable keeps tags as columns from the start; ours
        joins them from the series index at flush)."""
        parts: list[Record] = []
        for sid in mt.sids():
            rec = mt.series_record(sid)
            if rec is None or rec.num_rows == 0:
                continue
            tags = self.index.tags_of(sid)
            n = rec.num_rows
            fields = list(rec.schema.fields)
            cols = list(rec.cols)
            for k in sorted(tags):
                if rec.schema.field(k) is not None:
                    raise ErrTypeConflict(
                        f"tag {k!r} collides with a field name in {mst}")
                fields.append(_mk_tag_field(k))
                cols.append(ColVal.from_strings([tags[k]] * n))
            order = sorted(range(len(fields)),
                           key=lambda i: (fields[i].name == "time",
                                          fields[i].name))
            parts.append(Record(Schema([fields[i] for i in order]),
                                [cols[i] for i in order]))
        if not parts:
            return None
        return align_concat(parts)

    def scan_columnstore_extrema(self, mst: str, fields: list[str],
                                 offset: int, interval: int,
                                 t_min: int | None,
                                 t_max: int | None):
        """Metadata answer for pure min/max windowed colstore queries:
        every numeric column carries per-fragment minmax ranges
        (colstore.py writer), so a fragment wholly inside one window
        and inside the time range contributes two CANDIDATE rows (its
        mins at one timestamp, its maxes at another) instead of
        decoding — max of fragment maxes equals max of rows. Boundary
        fragments decode normally and join the candidates. Returns
        None when ineligible (unflushed rows, overlapping files,
        missing indexes — the caller runs the full scan); an empty
        Record when eligible but nothing is in range. Role of the
        reference's fragment-range pre-agg consumption in
        column_store_reader.go:42."""
        with self._lock:
            files = list(self._cs_files.get(mst, ()))
            # unflushed rows may overwrite file rows (last-wins dedup
            # needs real rows); candidates cannot see overwrites
            for tbl in self.mem.tables_for_read():
                mt = tbl.get(mst)
                if mt is not None and mt.rows:
                    return None
        if not files:
            return Record(Schema([Field("time", DataType.TIME)]), [
                ColVal(DataType.TIME, np.zeros(0, dtype=np.int64))])
        from ..index.sparse import KIND_MINMAX
        spans = []
        per_file = []
        for f in files:
            tidx = f.index("time")
            if (tidx is None or not tidx.entries
                    or tidx.kind != KIND_MINMAX):
                return None
            fr = np.array([e.minmax if e.minmax else (0, -1)
                           for e in tidx.entries], dtype=np.int64)
            vidx = {}
            for name in fields:
                ix = f.index(name)
                if (ix is None or ix.kind != KIND_MINMAX
                        or len(ix.entries) != len(fr)):
                    return None
                vidx[name] = ix
            live = fr[:, 0] <= fr[:, 1]
            if live.any():
                spans.append((int(fr[live, 0].min()),
                              int(fr[live, 1].max())))
            per_file.append((f, fr, vidx, live))
        spans.sort()
        for a, b in zip(spans, spans[1:]):
            if b[0] <= a[1]:
                return None        # overlapping files: dedup required
        parts: list[Record] = []
        names = sorted(fields)
        for f, fr, vidx, live in per_file:
            lo, hi = fr[:, 0], fr[:, 1]
            in_range = live.copy()
            if t_min is not None:
                in_range &= lo >= t_min
            if t_max is not None:
                in_range &= hi <= t_max
            one_window = ((lo - offset) // interval
                          == (hi - offset) // interval)
            # a fragment whose range is unordered (NaN content) or
            # absent for any requested field must decode — its
            # candidate rows could not reproduce the decode result
            rangeable = np.ones(len(lo), dtype=bool)
            for name in fields:
                ent = vidx[name].entries
                for fi in range(len(ent)):
                    mm = ent[fi].minmax
                    if mm is not None and mm[0] != mm[0]:
                        rangeable[fi] = False
            cand = in_range & one_window & rangeable
            rest = live & ~cand
            if t_min is not None:
                rest &= hi >= t_min
            if t_max is not None:
                rest &= lo <= t_max
            ci = np.nonzero(cand)[0]
            if len(ci):
                F = len(ci)
                times = np.repeat(lo[ci], 2)
                cols = []
                for name in names:
                    ent = vidx[name].entries
                    vals = np.zeros(2 * F, dtype=np.float64)
                    ok = np.zeros(2 * F, dtype=np.bool_)
                    for j, fi in enumerate(ci.tolist()):
                        mm = ent[fi].minmax
                        if mm is not None:
                            vals[2 * j] = mm[0]
                            vals[2 * j + 1] = mm[1]
                            ok[2 * j] = ok[2 * j + 1] = True
                    cols.append(ColVal(DataType.FLOAT, vals, ok))
                cols.append(ColVal(DataType.TIME, times))
                parts.append(Record(
                    Schema([Field(n, DataType.FLOAT) for n in names]
                           + [Field("time", DataType.TIME)]), cols))
            if rest.any():
                rec = f.read(names, rest)
                if rec.num_rows:
                    tv = rec.times
                    m = np.ones(len(tv), dtype=bool)
                    if t_min is not None:
                        m &= tv >= t_min
                    if t_max is not None:
                        m &= tv <= t_max
                    if not m.all():
                        rec = rec.take(np.nonzero(m)[0])
                    if rec.num_rows:
                        parts.append(rec)
        if not parts:
            return Record(Schema([Field("time", DataType.TIME)]), [
                ColVal(DataType.TIME, np.zeros(0, dtype=np.int64))])
        return align_concat(parts)

    def scan_columnstore(self, mst: str, expr=None,
                         columns: list[str] | None = None,
                         t_min: int | None = None,
                         t_max: int | None = None) -> Record | None:
        """Fragment-pruned scan over colstore files + unflushed memtable
        rows (ColumnStoreReader transform, column_store_reader.go:346).
        Row-level residual filtering is the caller's job; time range is
        applied row-level here (fragments are pruned by the time index
        first)."""
        with self._lock:
            files = list(self._cs_files.get(mst, ()))
        tag_cols = set(self.index.tag_keys(mst))
        for f in files:
            tag_cols.update(f.footer.get("tag_columns", ()))
        # tag columns always scanned: duplicate (tagset, time) rows across
        # files/memtable must collapse with later-writes-win, like the
        # row-store merge (_merge_parts)
        scan_cols = (None if columns is None
                     else sorted(set(columns) | tag_cols))
        parts: list[Record] = []
        for f in files:
            mask = f.prune(expr)
            tidx = f.index("time")
            if tidx is not None and (t_min is not None or t_max is not None):
                mask &= tidx.prune_range(lo=t_min, hi=t_max)
            if not mask.any():
                continue
            rec = f.read(scan_cols, mask)
            if rec.num_rows:
                parts.append(rec)
        for tbl in self.mem.tables_for_read()[::-1]:  # snapshot older first
            mt = tbl.get(mst)
            if mt is not None and mt.rows:
                rec = self._materialize_measurement(mst, mt)
                if rec is not None and rec.num_rows:
                    if scan_cols is not None:
                        keep = [c for c in scan_cols
                                if rec.schema.field(c) is not None]
                        if "time" not in keep:
                            keep.append("time")
                        rec = _project(rec, keep)
                    parts.append(rec)
        if not parts:
            return None
        rec = align_concat(parts)
        if len(parts) > 1:
            rec = _dedup_last_wins(rec, sorted(tag_cols))
        if t_min is not None or t_max is not None:
            times = rec.times
            m = np.ones(len(times), dtype=bool)
            if t_min is not None:
                m &= times >= t_min
            if t_max is not None:
                m &= times <= t_max
            if not m.all():
                rec = rec.take(np.nonzero(m)[0])
        if columns is not None:
            keep = [c for c in columns if rec.schema.field(c) is not None]
            if "time" not in keep:
                keep.append("time")
            rec = _project(rec, keep)
        return rec if rec.num_rows else None

    def close(self, close_files: bool = True) -> None:
        """close_files=False leaves TSSP mmaps open for in-flight queries
        (retention drop path); they close when the last reference drops."""
        with self._lock:
            self.wal.close()
            self.index.close()
            if close_files:
                for files in self._files.values():
                    for f in files:
                        f.close()
                for files in self._cs_files.values():
                    for f in files:
                        f.close()


def _project(rec: Record, columns: list[str]) -> Record:
    from ..record import Schema
    names = [n for n in columns
             if n != "time" and rec.schema.field_index(n) >= 0]
    fields = [rec.schema.fields[rec.schema.field_index(n)] for n in names]
    cols = [rec.cols[rec.schema.field_index(n)] for n in names]
    ti = rec.schema.time_index
    fields.append(rec.schema.fields[ti])
    cols.append(rec.cols[ti])
    return Record(Schema(fields), cols)


def _dedup_last_wins(rec: Record, tag_cols: list[str]) -> Record:
    """Collapse duplicate (tagset, time) rows keeping the latest-appended
    one (column-store analog of _merge_parts' newest-wins rule; parts are
    appended oldest-file → newest-memtable)."""
    n = rec.num_rows
    codes = np.zeros(n, dtype=np.int64)
    for t in tag_cols:
        col = rec.column(t)
        if col is None:
            continue
        vals = np.array([s if s is not None else ""
                         for s in col.to_strings()], dtype=object)
        _u, inv = np.unique(vals, return_inverse=True)
        # re-compact after each column: keeps codes < n (no radix overflow)
        codes = np.unique(codes * (inv.max() + 1) + inv,
                          return_inverse=True)[1]
    times = rec.times
    order = np.lexsort((np.arange(n), times, codes))
    same = ((codes[order][1:] == codes[order][:-1])
            & (times[order][1:] == times[order][:-1]))
    keep = np.concatenate([~same, [True]])
    if keep.all():
        return rec
    return rec.take(np.sort(order[keep]))


def _mk_tag_field(name: str):
    from ..record.schema import Field
    return Field(name, DataType.STRING)


def align_concat(parts: list[Record]) -> Record:
    """Concatenate Records with differing schemas: union of columns
    (canonical order — sorted, time last), missing columns null-filled.
    No time sort — callers window by absolute time or sort themselves."""
    if len(parts) == 1:
        return parts[0]
    types: dict[str, DataType] = {}
    for p in parts:
        for f in p.schema:
            if f.name == "time":
                continue
            cur = types.get(f.name)
            if cur is None or (cur != f.type and f.type == DataType.FLOAT):
                types[f.name] = f.type
    schema = Schema.from_pairs(sorted(types.items()))
    cols = []
    for f in schema:
        acc: ColVal | None = None
        for p in parts:
            src = p.column(f.name)
            n = p.num_rows
            if src is None or (f.name != "time" and src.type != f.type
                               and not (f.type == DataType.FLOAT
                                        and src.type == DataType.INTEGER)):
                piece = ColVal.nulls(f.type, n)
            elif f.type == DataType.FLOAT and src.type == DataType.INTEGER:
                piece = ColVal(DataType.FLOAT,
                               src.values.astype(np.float64),
                               src.valid.copy())
            else:
                piece = src.slice(0, n)  # copy so append can't alias src
            if acc is None:
                acc = piece
            else:
                acc.append(piece)
        cols.append(acc)
    return Record(schema, cols)


def _merge_parts(a: Record, b: Record) -> Record:
    """Merge two per-series records; aligns schemas first (older files may
    miss newly-added fields)."""
    if a.schema == b.schema:
        return merge_sorted_records(a, b)
    names = sorted(({f.name for f in a.schema}
                    | {f.name for f in b.schema}) - {"time"})
    from ..record import ColVal, Schema
    pairs = []
    for n in names:
        fa, fb = a.schema.field(n), b.schema.field(n)
        if fa is not None and fb is not None and fa.type != fb.type:
            # defense against type drift in old files: int promotes to float
            if {fa.type, fb.type} == {DataType.INTEGER, DataType.FLOAT}:
                pairs.append((n, DataType.FLOAT))
                continue
            raise ErrTypeConflict(
                f"field {n}: {fa.type.name} vs {fb.type.name} across "
                f"storage generations")
        pairs.append((n, (fa or fb).type))
    schema = Schema.from_pairs(pairs)
    out = []
    for rec in (a, b):
        cols = []
        for f in schema:
            i = rec.schema.field_index(f.name)
            if i >= 0:
                c = rec.cols[i]
                if c.type == DataType.INTEGER and f.type == DataType.FLOAT:
                    c = ColVal(DataType.FLOAT,
                               c.values.astype(np.float64), c.valid)
                cols.append(c)
            else:
                cols.append(ColVal.nulls(f.type, rec.num_rows))
        out.append(Record(schema, cols))
    return merge_sorted_records(out[0], out[1])
