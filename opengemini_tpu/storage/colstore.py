"""Column-store measurement files with sparse-index fragment pruning.

Role of reference engine/immutable/colstore/ (primary-key files, per-block
index, writer/reader) + engine/column_store_reader.go (fragment-pruned scan
→ Record). The reference's column-store engine stores a whole measurement
(tags materialized as columns) sorted by a user-declared primary key, in
fixed-size row fragments, with sparse indexes selecting fragments at scan
time (engine/index/sparseindex/).

TPU-first deviations:
- Fragments are the device block unit: fixed FRAGMENT_ROWS rows so pruned
  scans produce statically-shaped padded batches for the segment-reduce
  kernels (no ragged decode).
- Tag columns are additionally dictionary-encoded at write time; the scan
  can return int32 codes per tag column — group-by keys go to the device
  as dense ids, never strings.
- The primary-key "index" IS the min-max sparse index of the pk columns
  (first-fragment-row files in the reference collapse into this).

File layout ("OGCF"):
  [magic u32 | version u32]
  per fragment × column: [value block][validity block]  (encoding.blocks)
  per indexed column: packed SparseIndex blob
  footer JSON (schema, fragments, offsets, pk, dicts) | footer_len u32 | magic
"""

from __future__ import annotations

import json
import os
import struct
import threading

import numpy as np

from .. import encoding as enc
from ..index.sparse import (KIND_BLOOM, KIND_MINMAX, KIND_SET,
                            KIND_TEXT_BLOOM, SparseIndex, SparseIndexBuilder)
from ..utils import failpoint, fileops
from ..query.ast import BinaryExpr, Call, FieldRef, Literal
from ..record import ColVal, DataType, Record, Schema

MAGIC = 0x4F474346  # "OGCF"
VERSION = 1
FRAGMENT_ROWS = 4096

_KIND_NAMES = {"minmax": KIND_MINMAX, "set": KIND_SET, "bloom": KIND_BLOOM,
               "text": KIND_TEXT_BLOOM}


def _encode_col_block(col: ColVal, lo: int, hi: int) -> bytes:
    t = col.type
    if t == DataType.TIME:
        return enc.encode_time_block(col.values[lo:hi])
    if t == DataType.INTEGER:
        return enc.encode_integer_block(col.values[lo:hi])
    if t == DataType.FLOAT:
        return enc.encode_float_block(col.values[lo:hi])
    if t == DataType.BOOLEAN:
        return enc.encode_boolean_block(col.values[lo:hi])
    sub = col.slice(lo, hi)
    return enc.encode_string_block(sub.offsets, sub.data)


def _decode_col_block(t: DataType, buf, n: int) -> ColVal:
    if t == DataType.TIME:
        return ColVal(t, enc.decode_time_block(buf, n))
    if t == DataType.INTEGER:
        return ColVal(t, enc.decode_integer_block(buf, n))
    if t == DataType.FLOAT:
        return ColVal(t, enc.decode_float_block(buf, n))
    if t == DataType.BOOLEAN:
        return ColVal(t, enc.decode_boolean_block(buf, n))
    offsets, data = enc.decode_string_block(buf)
    return ColVal(t, offsets=offsets, data=data)


class ColumnStoreWriter:
    """One measurement's data -> one immutable column-store file.

    rec: full measurement Record (tag columns as STRING, fields, time).
    primary_key: column names data is sorted by (time appended implicitly).
    indexes: extra {column: kind} sparse indexes ('minmax'|'set'|'bloom'|
    'text'); pk columns and time always get minmax.
    """

    def __init__(self, path: str, primary_key: list[str],
                 indexes: dict[str, str] | None = None,
                 fragment_rows: int = FRAGMENT_ROWS,
                 tag_columns: list[str] | None = None):
        self.path = path
        self.primary_key = list(primary_key)
        self.indexes = dict(indexes or {})
        self.fragment_rows = fragment_rows
        # which columns are tags (series identity): recorded in the footer
        # so readers can dedup duplicate (tagset, time) rows across files
        self.tag_columns = list(tag_columns or [])

    def write(self, rec: Record) -> None:
        n = rec.num_rows
        if n == 0:
            raise ValueError("empty record")
        rec = _sort_by_pk(rec, self.primary_key)

        index_cols: dict[str, int] = {"time": KIND_MINMAX}
        for pk in self.primary_key:
            index_cols[pk] = KIND_MINMAX
        # every numeric column carries per-fragment min/max ranges
        # (16B/fragment): the reference colstore's fragment ranges —
        # range pruning AND the extrema (min/max) metadata fast path
        # (column_store_reader.go:42 + sparse-index roles)
        for fld in rec.schema:
            if fld.type in (DataType.FLOAT, DataType.INTEGER):
                index_cols.setdefault(fld.name, KIND_MINMAX)
        for c, kind in self.indexes.items():
            k = _KIND_NAMES.get(kind)
            if k is None:
                raise ValueError(f"unknown sparse index kind {kind!r}")
            index_cols[c] = k  # user kind wins over the pk default

        builders = {}
        for cname, kind in index_cols.items():
            if rec.schema.field(cname) is None:
                continue
            builders[cname] = SparseIndexBuilder(kind, cname)

        f = open(self.path + ".tmp", "wb")
        try:
            f.write(struct.pack("<II", MAGIC, VERSION))
            pos = 8
            frags = []
            fr = self.fragment_rows
            for lo in range(0, n, fr):
                hi = min(lo + fr, n)
                cols_meta = []
                for fld, col in zip(rec.schema, rec.cols):
                    data = _encode_col_block(col, lo, hi)
                    vb = enc.encode_validity(col.valid[lo:hi])
                    f.write(data)
                    f.write(vb)
                    cols_meta.append([pos, len(data), pos + len(data),
                                      len(vb)])
                    pos += len(data) + len(vb)
                    b = builders.get(fld.name)
                    if b is not None:
                        b.add_fragment(_index_values(col, lo, hi),
                                       col.valid[lo:hi])
                frags.append({"rows": hi - lo, "cols": cols_meta})

            index_meta = {}
            for cname, b in builders.items():
                blob = b.finish().pack()
                f.write(blob)
                index_meta[cname] = [pos, len(blob)]
                pos += len(blob)

            footer = {
                "schema": [[fld.name, int(fld.type)] for fld in rec.schema],
                "n_rows": n,
                "fragment_rows": fr,
                "fragments": frags,
                "indexes": index_meta,
                "primary_key": self.primary_key,
                "tag_columns": self.tag_columns,
            }
            fb = json.dumps(footer, separators=(",", ":")).encode()
            f.write(fb)
            f.write(struct.pack("<II", len(fb), MAGIC))
            f.flush()
            os.fsync(f.fileno())
            f.close()
            # crash here: complete-but-unpublished .tmp — swept at
            # restart; the rows still live in the sealed WAL segment
            # (the shard removes it only after this publish commits)
            failpoint.inject("colstore.publish.crash")
            fileops.durable_replace(self.path + ".tmp", self.path)
        except Exception:
            f.close()
            if os.path.exists(self.path + ".tmp"):
                os.unlink(self.path + ".tmp")
            raise


def _index_values(col: ColVal, lo: int, hi: int):
    if col.is_string_like():
        return col.slice(lo, hi).to_strings()
    return col.values[lo:hi]


def _sort_by_pk(rec: Record, pk: list[str]) -> Record:
    """Stable sort by (pk columns..., time)."""
    keys = [rec.times]
    for name in reversed(pk):
        col = rec.column(name)
        if col is None:
            # a batch can legitimately lack a declared pk column (tag not
            # yet seen); it sorts as a constant — never an error, or the
            # flush path would wedge on accepted rows
            continue
        if col.is_string_like():
            keys.append(np.array(
                [s if s is not None else "" for s in col.to_strings()]))
        else:
            keys.append(col.values)
    order = np.lexsort(keys)
    if (order == np.arange(len(order))).all():
        return rec
    return rec.take(order)


class ColumnStoreReader:
    """Fragment-pruned reads of one column-store file. The file is mmapped
    so concurrent queries can read without a shared-seek race (the HTTP
    layer is threaded)."""

    def __init__(self, path: str):
        import mmap
        self.path = path
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        mm = self._mm
        if len(mm) < 16:
            raise ValueError(f"bad column-store file {path}")
        data_magic, ver = struct.unpack_from("<II", mm, 0)
        if data_magic != MAGIC or ver != VERSION:
            raise ValueError(f"bad column-store file {path}")
        flen, tail_magic = struct.unpack_from("<II", mm, len(mm) - 8)
        if tail_magic != MAGIC:
            raise ValueError(f"corrupt column-store trailer in {path}")
        if flen > len(mm) - 16:
            raise ValueError(f"corrupt column-store footer length in "
                             f"{path}")
        try:
            self.footer = json.loads(
                bytes(mm[len(mm) - 8 - flen:len(mm) - 8]))
        except ValueError as e:
            raise ValueError(
                f"corrupt column-store footer in {path}: {e}") from e
        self.schema = Schema([_mkfield(n, t)
                              for n, t in self.footer["schema"]])
        self._indexes: dict[str, SparseIndex] = {}
        self._idx_lock = threading.Lock()

    @property
    def n_rows(self) -> int:
        return self.footer["n_rows"]

    @property
    def n_fragments(self) -> int:
        return len(self.footer["fragments"])

    def index(self, column: str) -> SparseIndex | None:
        with self._idx_lock:
            idx = self._indexes.get(column)
            if idx is None:
                meta = self.footer["indexes"].get(column)
                if meta is None:
                    return None
                off, size = meta
                idx = self._indexes[column] = SparseIndex.unpack(
                    self._mm[off:off + size])
        return idx

    # ------------------------------------------------------------ pruning

    def prune(self, expr) -> np.ndarray:
        """Fragment mask for an AND-connected condition tree. Conservative:
        anything not understood prunes nothing."""
        mask = np.ones(self.n_fragments, dtype=bool)
        if expr is None:
            return mask
        for leaf in _and_leaves(expr):
            mask &= self._prune_leaf(leaf)
        return mask

    def _prune_leaf(self, e) -> np.ndarray:
        ones = np.ones(self.n_fragments, dtype=bool)
        # match(col, 'text') full-text predicate
        if (isinstance(e, Call) and e.func == "match" and len(e.args) == 2
                and isinstance(e.args[0], FieldRef)
                and isinstance(e.args[1], Literal)):
            idx = self.index(e.args[0].name)
            return idx.prune_match(e.args[1].value) if idx is not None \
                else ones
        if not isinstance(e, BinaryExpr):
            return ones
        lhs, op, rhs = e.lhs, e.op, e.rhs
        if isinstance(rhs, FieldRef) and isinstance(lhs, Literal):
            lhs, rhs = rhs, lhs
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if not (isinstance(lhs, FieldRef) and isinstance(rhs, Literal)):
            return ones
        if lhs.name == "time":
            # time pruning happens via prune_range on the time index with
            # integer nanoseconds (scan_columnstore) — a raw literal here
            # may be an RFC3339 string that must not compare lexically
            return ones
        idx = self.index(lhs.name)
        if idx is None:
            return ones
        v = rhs.value
        if op == "=":
            return idx.prune_eq(v)
        if op == "<":
            return idx.prune_range(hi=v, hi_inc=False)
        if op == "<=":
            return idx.prune_range(hi=v)
        if op == ">":
            return idx.prune_range(lo=v, lo_inc=False)
        if op == ">=":
            return idx.prune_range(lo=v)
        return ones

    # -------------------------------------------------------------- reads

    def read(self, columns: list[str] | None = None,
             mask: np.ndarray | None = None) -> Record:
        """Decode surviving fragments, concatenated into one Record."""
        names = ([f.name for f in self.schema] if columns is None
                 else [c for c in columns if self.schema.field(c)])
        if columns is not None and "time" not in names:
            names.append("time")
        col_idx = [self.schema.field_index(c) for c in names]
        out_schema = Schema([self.schema.fields[i] for i in col_idx])
        frags = self.footer["fragments"]
        sel = range(len(frags)) if mask is None else np.nonzero(mask)[0]
        # per-column fragment PARTS concatenate once at the end:
        # incremental ColVal.append reallocates per fragment (measured
        # 0.74s of a 1.17s warm 176-fragment query)
        parts: list[list] = [[] for _ in col_idx]
        for fi in sel:
            fr = frags[fi]
            n = fr["rows"]
            for oi, ci in enumerate(col_idx):
                off, size, voff, vsize = fr["cols"][ci]
                data = memoryview(self._mm)[off:off + size]
                vb = memoryview(self._mm)[voff:voff + vsize]
                cv = _decode_col_block(out_schema.fields[oi].type, data, n)
                cv.valid = enc.decode_validity(vb, n)
                parts[oi].append(cv)
        if not len(sel):
            return Record(out_schema,
                          [_empty(f.type) for f in out_schema.fields])
        out_cols = []
        for oi, ps in enumerate(parts):
            t = out_schema.fields[oi].type
            if len(ps) == 1:
                out_cols.append(ps[0])
            elif t.is_numeric:
                out_cols.append(ColVal(
                    t, np.concatenate([p.values for p in ps]),
                    np.concatenate([p.valid for p in ps])))
            else:
                # strings: shift offsets once, join data once (the
                # append loop recopies all prior bytes per fragment)
                offs = [np.asarray(ps[0].offsets)]
                shift = int(offs[0][-1])
                datas = [bytes(ps[0].data)]
                for p in ps[1:]:
                    po = np.asarray(p.offsets)
                    offs.append(po[1:] + shift)
                    shift += int(po[-1])
                    datas.append(bytes(p.data))
                out_cols.append(ColVal(
                    t, valid=np.concatenate([p.valid for p in ps]),
                    offsets=np.concatenate(offs).astype(np.int32),
                    data=b"".join(datas)))
        return Record(out_schema, out_cols)

    def scan(self, expr=None, columns: list[str] | None = None) -> Record:
        """prune + read (the ColumnStoreReader transform's Work loop,
        column_store_reader.go:346 — residual row filtering happens in the
        executor, on device where possible)."""
        return self.read(columns, self.prune(expr))

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def __del__(self):
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass


def _empty(t: DataType) -> ColVal:
    if t in (DataType.STRING,):
        return ColVal(t, offsets=np.zeros(1, dtype=np.int32), data=b"")
    return ColVal(t, np.empty(0, dtype=t.numpy_dtype),
                  np.empty(0, dtype=np.bool_))


def _mkfield(name: str, t: int):
    from ..record.schema import Field
    return Field(name, DataType(t))


def _and_leaves(expr):
    if isinstance(expr, BinaryExpr) and expr.op in ("and", "AND"):
        yield from _and_leaves(expr.lhs)
        yield from _and_leaves(expr.rhs)
    else:
        yield expr
