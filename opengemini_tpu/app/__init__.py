"""Node-role apps (role of reference app/: ts-meta, ts-store, ts-sql,
ts-server binaries, app/command.go run scaffolding)."""

from .nodes import TsMeta, TsSql, TsStore, TsServer

__all__ = ["TsMeta", "TsStore", "TsSql", "TsServer"]
