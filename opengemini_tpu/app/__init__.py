"""Node-role apps (role of reference app/: ts-meta, ts-store, ts-sql,
ts-server binaries, app/command.go run scaffolding)."""

from .nodes import TsData, TsMeta, TsSql, TsStore, TsServer

__all__ = ["TsData", "TsMeta", "TsStore", "TsSql", "TsServer"]
