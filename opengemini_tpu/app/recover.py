"""ts-recover: restore a node's data directory from a backup set (role of
reference app/ts-recover/recover/recover.go over lib/backup).

Run: ``python -m opengemini_tpu.app.recover --backup <dir>
--data <target-dir> [--verify-only]``
"""

from __future__ import annotations

import argparse
import sys

from ..storage.backup import (BackupError, restore_backup, verify_backup)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ts-recover",
                                 description="restore from backup")
    ap.add_argument("--backup", required=True, help="backup set directory")
    ap.add_argument("--data", help="target data directory")
    ap.add_argument("--verify-only", action="store_true",
                    help="check backup integrity, restore nothing")
    args = ap.parse_args(argv)

    problems = verify_backup(args.backup)
    if problems:
        for p in problems:
            print(f"BAD: {p}", file=sys.stderr)
        return 1
    print(f"backup {args.backup}: integrity OK")
    if args.verify_only:
        return 0
    if not args.data:
        print("ERR: --data required to restore", file=sys.stderr)
        return 2
    try:
        res = restore_backup(args.backup, args.data)
    except BackupError as e:
        print(f"ERR: {e}", file=sys.stderr)
        return 1
    print(f"restored {res['files']} files to {args.data}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
