"""Thin HTTP client for the InfluxDB-1.x-compatible API (role of the
reference's client lib used by ts-cli — app/ts-cli/geminicli/cli.go talks
to /query and /write the same way)."""

from __future__ import annotations

import gzip
import json
import urllib.error
import urllib.parse
import urllib.request


class ClientError(Exception):
    pass


class HttpClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8086,
                 timeout_s: float = 30.0, gzip_writes: bool = False):
        self.base = f"http://{host}:{port}"
        self.timeout_s = timeout_s
        self.gzip_writes = gzip_writes

    def _do(self, method: str, path: str, body: bytes | None = None,
            headers: dict | None = None) -> tuple[int, bytes]:
        req = urllib.request.Request(self.base + path, data=body,
                                     method=method,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except OSError as e:
            raise ClientError(f"cannot reach {self.base}: {e}")

    def ping(self) -> bool:
        try:
            status, _ = self._do("GET", "/ping")
        except ClientError:
            return False
        return status in (200, 204)

    def query(self, q: str, db: str | None = None,
              epoch: str | None = None) -> dict:
        params = {"q": q}
        if db:
            params["db"] = db
        if epoch:
            params["epoch"] = epoch
        status, body = self._do(
            "GET", "/query?" + urllib.parse.urlencode(params))
        try:
            res = json.loads(body.decode())
        except (ValueError, UnicodeDecodeError):
            raise ClientError(f"bad response (HTTP {status}): {body[:200]!r}")
        if status != 200:
            raise ClientError(res.get("error", f"HTTP {status}"))
        return res

    def write(self, lines: str, db: str, rp: str | None = None,
              precision: str | None = None) -> None:
        params = {"db": db}
        if rp:
            params["rp"] = rp
        if precision:
            params["precision"] = precision
        body = lines.encode()
        headers = {}
        if self.gzip_writes:
            body = gzip.compress(body)
            headers["Content-Encoding"] = "gzip"
        status, resp = self._do(
            "POST", "/write?" + urllib.parse.urlencode(params), body,
            headers)
        if status not in (200, 204):
            try:
                msg = json.loads(resp.decode()).get("error", "")
            except (ValueError, UnicodeDecodeError):
                msg = resp[:200]
            raise ClientError(f"write failed (HTTP {status}): {msg}")
