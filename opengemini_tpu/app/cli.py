"""ts-cli: interactive query shell + line-protocol import tool (role of
reference app/ts-cli — geminicli/cli.go REPL with completer, import.go
batch importer, cobra commands app/ts-cli/cmd/).

Run: ``python -m opengemini_tpu.app.cli [--host H] [--port P]
[--database DB] [--execute Q] [--import-file F] [--format column|json|csv]``
"""

from __future__ import annotations

import argparse
import csv
import io
import json
import sys
import time

from .client import ClientError, HttpClient

KEYWORDS = [
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "ORDER", "LIMIT", "OFFSET",
    "SLIMIT", "SOFFSET", "INTO", "FILL", "AND", "OR", "AS", "DESC", "ASC",
    "SHOW", "DATABASES", "MEASUREMENTS", "SERIES", "TAG", "FIELD", "KEYS",
    "VALUES", "QUERIES", "CREATE", "DROP", "DATABASE", "MEASUREMENT",
    "EXPLAIN", "ANALYZE", "KILL", "QUERY", "DELETE", "INSERT", "TIME",
    "mean", "sum", "count", "min", "max", "first", "last", "median",
    "spread", "stddev", "percentile", "top", "bottom", "distinct",
    "derivative", "moving_average", "holt_winters", "castor", "rate",
]
COMMANDS = ["use", "format", "timing", "precision", "help", "exit", "quit",
            "import", "insert"]


class Cli:
    def __init__(self, client: HttpClient, database: str = "",
                 fmt: str = "column", precision: str | None = None,
                 out=None):
        self.client = client
        self.database = database
        self.format = fmt
        self.precision = precision
        self.timing = False
        self.out = out or sys.stdout
        self.last_error: str | None = None   # scripted callers' exit code

    # ------------------------------------------------------------ commands

    def run_line(self, line: str) -> bool:
        """Execute one REPL line. Returns False when the loop should end."""
        line = line.strip()
        if not line:
            return True
        self.last_error = None
        word0 = line.split()[0].lower()
        if word0 in ("exit", "quit"):
            return False
        if word0 == "help":
            self._print(self._help())
        elif word0 == "use":
            parts = line.split()
            if len(parts) == 2:
                self.database = parts[1].strip('"')
                self._print(f"Using database {self.database}")
            else:
                self._print("usage: use <database>")
        elif word0 == "format":
            parts = line.split()
            if len(parts) == 2 and parts[1] in ("column", "json", "csv"):
                self.format = parts[1]
            else:
                self._print("usage: format column|json|csv")
        elif word0 == "timing":
            self.timing = not self.timing
            self._print(f"Timing is {'on' if self.timing else 'off'}")
        elif word0 == "precision":
            parts = line.split()
            self.precision = parts[1] if len(parts) == 2 else None
        elif word0 == "insert":
            self._insert(line[len("insert"):].strip())
        elif word0 == "import":
            parts = line.split(None, 1)
            if len(parts) == 2:
                self.import_file(parts[1])
            else:
                self._print("usage: import <path>")
        else:
            self._query(line)
        return True

    def _insert(self, lp: str) -> None:
        if not self.database:
            self._err("no database selected (use <db>)")
            return
        try:
            self.client.write(lp, self.database, precision=self.precision)
        except ClientError as e:
            self._err(str(e))

    def _query(self, q: str) -> None:
        t0 = time.monotonic()
        try:
            res = self.client.query(q, db=self.database or None)
        except ClientError as e:
            self._err(str(e))
            return
        for result in res.get("results", []):
            if "error" in result:
                self.last_error = result["error"]
        self._print(self.render(res))
        if self.timing:
            self._print(f"Elapsed: {time.monotonic() - t0:.3f}s")

    # ----------------------------------------------------------- rendering

    def render(self, res: dict) -> str:
        if self.format == "json":
            return json.dumps(res, indent=2)
        out = []
        for result in res.get("results", []):
            if "error" in result:
                out.append(f"ERR: {result['error']}")
                continue
            for s in result.get("series", []):
                if self.format == "csv":
                    out.append(self._csv(s))
                else:
                    out.append(self._columns(s))
        return "\n".join(out) if out else "(empty result)"

    @staticmethod
    def _columns(s: dict) -> str:
        head = f"name: {s.get('name', '')}"
        if s.get("tags"):
            head += " tags: " + ", ".join(
                f"{k}={v}" for k, v in sorted(s["tags"].items()))
        cols = s.get("columns", [])
        rows = [[("" if v is None else str(v)) for v in row]
                for row in s.get("values", [])]
        widths = [max([len(c)] + [len(r[i]) for r in rows])
                  for i, c in enumerate(cols)]
        lines = [head,
                 "  ".join(c.ljust(w) for c, w in zip(cols, widths)),
                 "  ".join("-" * w for w in widths)]
        lines += ["  ".join(v.ljust(w) for v, w in zip(r, widths))
                  for r in rows]
        return "\n".join(lines) + "\n"

    @staticmethod
    def _csv(s: dict) -> str:
        buf = io.StringIO()
        w = csv.writer(buf)
        tags = s.get("tags", {})
        w.writerow(["name"] + list(tags.keys()) + s.get("columns", []))
        for row in s.get("values", []):
            w.writerow([s.get("name", "")] + list(tags.values()) + row)
        return buf.getvalue()

    # -------------------------------------------------------------- import

    def import_file(self, path: str, batch_size: int = 5000) -> int:
        """Line-protocol file import with batching (reference import.go).
        Lines starting with '#' are comments; '# DML'/'# CONTEXT-DATABASE:'
        directives select the target db as in influx importer format."""
        db = self.database
        n = 0
        batch: list[str] = []

        def flush():
            nonlocal n
            if batch:
                self.client.write("\n".join(batch), db,
                                  precision=self.precision)
                n += len(batch)
                batch.clear()

        try:
            with open(path) as f:
                for raw in f:
                    line = raw.strip()
                    if not line:
                        continue
                    if line.startswith("#"):
                        d = line[1:].strip()
                        if d.upper().startswith("CONTEXT-DATABASE:"):
                            flush()
                            db = d.split(":", 1)[1].strip()
                        continue
                    if not db:
                        raise ClientError(
                            "no database: use <db> or # CONTEXT-DATABASE:")
                    batch.append(line)
                    if len(batch) >= batch_size:
                        flush()
            flush()
        except (OSError, ClientError) as e:
            self._err(f"import: {e} ({n} points written)")
            return n
        self._print(f"Imported {n} points")
        return n

    # ----------------------------------------------------------- repl glue

    def _print(self, s: str) -> None:
        print(s, file=self.out)

    def _err(self, msg: str) -> None:
        self.last_error = msg
        self._print(f"ERR: {msg}")

    @staticmethod
    def _help() -> str:
        return ("Commands:\n"
                "  use <db>            set target database\n"
                "  format column|json|csv\n"
                "  timing              toggle query timing\n"
                "  precision <unit>    write precision (n,u,ms,s,m,h)\n"
                "  insert <line-protocol>\n"
                "  import <file>       import line-protocol file\n"
                "  exit | quit\n"
                "anything else is sent as a query.")

    def completer(self, text: str, state: int):
        cands = [w for w in KEYWORDS + COMMANDS
                 if w.lower().startswith(text.lower())]
        return cands[state] if state < len(cands) else None

    def repl(self) -> None:
        try:
            import readline
            readline.set_completer(self.completer)
            readline.set_completer_delims(" \t\n,();=")
            readline.parse_and_bind("tab: complete")
        except ImportError:
            pass
        self._print("opengemini-tpu CLI (type 'help' for help)")
        while True:
            try:
                line = input("> ")
            except (EOFError, KeyboardInterrupt):
                self._print("")
                break
            if not self.run_line(line):
                break


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ts-cli",
                                 description="opengemini-tpu CLI")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8086)
    ap.add_argument("--database", default="")
    ap.add_argument("--execute", help="run one query and exit")
    ap.add_argument("--import-file", dest="import_file",
                    help="import a line-protocol file and exit")
    ap.add_argument("--format", default="column",
                    choices=["column", "json", "csv"])
    ap.add_argument("--precision", default=None)
    args = ap.parse_args(argv)

    cli = Cli(HttpClient(args.host, args.port), args.database,
              args.format, args.precision)
    if not cli.client.ping():
        print(f"ERR: no server at {args.host}:{args.port}",
              file=sys.stderr)
        return 1
    if args.import_file:
        cli.import_file(args.import_file)
        return 1 if cli.last_error else 0
    if args.execute:
        cli.run_line(args.execute)
        return 1 if cli.last_error else 0
    cli.repl()
    return 0


if __name__ == "__main__":
    sys.exit(main())
