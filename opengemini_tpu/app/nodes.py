"""Cluster node roles, wired for in-process or standalone deployment.

Reference mapping:
- TsMeta   → app/ts-meta (raft catalog voter)
- TsStore  → app/ts-store (engine + RPC service + heartbeats,
             run/server.go:81)
- TsSql    → app/ts-sql (HTTP frontend + coordinator,
             sql/server.go:61-97)
- TsServer → app/ts-server (all roles one process with the in-proc
             storage shortcut, main.go:46-57 run.InitStorage — queries
             bypass RPC and hit the local engine directly)
"""

from __future__ import annotations

import threading
import time

from ..cluster.meta_store import MetaClient, MetaServer
from ..cluster.sql_node import ClusterFacade
from ..cluster.store_node import StoreNode
from ..http.server import HttpServer
from ..storage.engine import Engine, EngineOptions
from ..utils import get_logger

log = get_logger(__name__)

HEARTBEAT_S = 1.0


class TsMeta:
    """One meta voter. For a multi-voter deployment pass the full peer
    map {node_id: raft_addr}."""

    def __init__(self, node_id: str = "m0",
                 peers: dict[str, str] | None = None,
                 data_dir: str = "meta_data",
                 host: str = "127.0.0.1", client_port: int = 0,
                 raft_port: int = 0,
                 ha: bool = True,
                 failure_timeout_s: float | None = None):
        self.server = MetaServer(node_id,
                                 peers or {node_id: "127.0.0.1:0"},
                                 data_dir, host=host,
                                 client_port=client_port,
                                 raft_port=raft_port)
        self.addr = self.server.addr
        self.cluster_manager = None
        self._ha = ha
        self._failure_timeout_s = failure_timeout_s
        self._meta_client = None

    def start(self):
        self.server.start()
        if self._ha:
            # every voter runs the detector but only the current raft
            # leader sweeps (is_leader_fn gate) — takeover must not run
            # concurrently from two voters
            from ..cluster.ha import (ClusterManager,
                                      DEFAULT_FAILURE_TIMEOUT_S)
            from ..cluster.meta_store import MetaClient
            self._meta_client = MetaClient([self.addr])
            self.cluster_manager = ClusterManager(
                self._meta_client,
                failure_timeout_s=(self._failure_timeout_s
                                   or DEFAULT_FAILURE_TIMEOUT_S),
                is_leader_fn=lambda: self.server.raft.is_leader)
            self.cluster_manager.start()

    def stop(self):
        if self.cluster_manager is not None:
            self.cluster_manager.stop()
        if self._meta_client is not None:
            self._meta_client.close()
        self.server.stop()


class TsStore:
    """Storage node: engine + RPC service; registers itself with meta and
    heartbeats (role of serf gossip membership — SURVEY §2.6: heartbeats
    through the meta raft leader replace the gossip mesh)."""

    def __init__(self, data_dir: str, meta_addrs: list[str],
                 host: str = "127.0.0.1", port: int = 0,
                 opts: EngineOptions | None = None,
                 heartbeat_s: float = HEARTBEAT_S,
                 diagnostics: bool = False,
                 role: str = "both"):
        self.node = StoreNode(data_dir, host=host, port=port, opts=opts)
        self.meta = MetaClient(meta_addrs)
        self.role = role
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        # self-diagnosis plane (reference: sherlock + iodetector services
        # started by ts-store run/server.go)
        self.sherlock = None
        self.iodetector = None
        if diagnostics:
            from ..services import IODetector, Sherlock, SherlockConfig
            self.sherlock = Sherlock(
                SherlockConfig(dump_dir=f"{data_dir}/sherlock-dumps"))
            self.iodetector = IODetector(probe_dirs=(data_dir,))

    @property
    def addr(self) -> str:
        return self.node.addr

    @property
    def node_id(self) -> int | None:
        return self.node.node_id

    def start(self):
        self.node.start()
        # per-PT raft replication plane (reference partition_raft.go):
        # groups materialize lazily on replicated writes; restarts
        # rejoin persisted groups. Attached BEFORE the node registers
        # with meta: once registered it can be routed to, and a scan
        # served with replication=None would skip the read-barrier
        # soundness check and could return unflagged stale data
        from ..cluster.replication import ReplicationManager
        self.node.replication = ReplicationManager(
            self.node, self.meta, self.node.engine.path)
        self.node.node_id = self.meta.create_node(self.node.addr,
                                                  role=self.role)
        self.node.replication.reopen_local_groups()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"store-hb-{self.node.node_id}")
        self._hb_thread.start()
        if self.sherlock is not None:
            self.sherlock.start()
        if self.iodetector is not None:
            self.iodetector.start()
        log.info("ts-store node %d @ %s ready", self.node.node_id,
                 self.node.addr)

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.meta.heartbeat(self.node.node_id)
            except Exception:
                pass     # meta unreachable; keep trying

    def stop(self):
        self._stop.set()
        if self.sherlock is not None:
            self.sherlock.stop()
        if self.iodetector is not None:
            self.iodetector.stop()
        self.node.stop()
        self.meta.close()


class TsSql:
    """Stateless SQL/ingest frontend: HTTP API over the cluster facade."""

    def __init__(self, meta_addrs: list[str], host: str = "127.0.0.1",
                 http_port: int = 0, flight_port: int | None = None,
                 flight_users: dict[str, str] | None = None,
                 config=None):
        self.meta = MetaClient(meta_addrs)
        self.facade = ClusterFacade(self.meta)
        # config (utils.config.Config) wires the [data] request budgets
        # and max_failed_stores tolerance into the HTTP layer/executor
        self.http = HttpServer(self.facade, host=host, port=http_port,
                               executor=self.facade.executor,
                               config=config)
        # columnar ingest plane (reference: arrowflight service on ts-sql)
        self.flight = None
        if flight_port is not None:
            from ..services.arrowflight import ArrowFlightService
            self.flight = ArrowFlightService(self.facade, host=host,
                                             port=flight_port,
                                             users=flight_users)

    @property
    def http_addr(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    def start(self):
        self.meta.refresh()
        self.meta.start_watch()
        self.http.start()
        if self.flight is not None:
            self.flight.start()
        log.info("ts-sql ready at %s", self.http_addr)

    def stop(self):
        if self.flight is not None:
            self.flight.stop()
        self.http.stop()
        self.facade.close()
        self.meta.close()


class TsServer:
    """All-in-one single node: local engine + HTTP, no RPC hop (the
    reference's localStorageForQuery shortcut). A meta voter still runs
    so the node can later be joined by others."""

    def __init__(self, data_dir: str, host: str = "127.0.0.1",
                 http_port: int = 0, opts: EngineOptions | None = None,
                 with_meta: bool = True, config=None):
        self.engine = Engine(f"{data_dir}/store", opts)
        self.http = HttpServer(self.engine, host=host, port=http_port,
                               config=config)
        self.ts_meta = (TsMeta(data_dir=f"{data_dir}/meta", host=host)
                        if with_meta else None)
        self.meta_client: MetaClient | None = None
        # background services driven by the local catalog: retention
        # (shard TTLs + per-logstream TTLs) and continuous queries
        from ..services.continuous_query import ContinuousQueryService
        from ..services.retention import RetentionService
        self.retention = RetentionService(
            self.engine, self.http.catalog, interval_s=1800,
            logstore=self.http.logstore)
        self.cq_service = ContinuousQueryService(
            self.engine, self.http.catalog, interval_s=10)

    @property
    def http_addr(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    def start(self):
        if self.ts_meta is not None:
            self.ts_meta.start()
            self.ts_meta.server.raft.wait_leader(10.0)
            self.meta_client = MetaClient([self.ts_meta.addr])
        self.http.start()
        self.retention.start()
        self.cq_service.start()
        log.info("ts-server ready at %s", self.http_addr)

    def stop(self):
        self.cq_service.stop()
        self.retention.stop()
        self.http.stop()
        if self.meta_client is not None:
            self.meta_client.close()
        if self.ts_meta is not None:
            self.ts_meta.stop()
        self.engine.close()


class TsData:
    """sql + store combined in one process against an EXTERNAL meta
    cluster (reference app/ts-data/main.go:27 — the data-node role for
    deployments that separate compute+storage from metadata). The
    store registers and heartbeats like a standalone ts-store; the sql
    frontend scatters over the whole cluster, including this node."""

    def __init__(self, data_dir: str, meta_addrs: list[str],
                 host: str = "127.0.0.1", http_port: int = 0,
                 opts: EngineOptions | None = None,
                 heartbeat_s: float = HEARTBEAT_S, role: str = "both",
                 config=None):
        self.store = TsStore(data_dir, meta_addrs, host=host,
                             opts=opts, heartbeat_s=heartbeat_s,
                             role=role)
        self.sql = TsSql(meta_addrs, host=host, http_port=http_port,
                         config=config)

    @property
    def http(self):
        return self.sql.http

    @property
    def http_addr(self) -> str:
        return self.sql.http_addr

    @property
    def addr(self) -> str:
        return self.store.addr

    def start(self):
        self.store.start()
        self.sql.start()
        log.info("ts-data ready: store %s, http %s", self.store.addr,
                 self.http_addr)

    def stop(self):
        self.sql.stop()
        self.store.stop()


def _wait(cond, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")
