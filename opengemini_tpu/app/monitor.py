"""ts-monitor: off-node monitoring agent (role of reference
app/ts-monitor — collector/collect.go tails the components' pushed metric
files, node_monitor.go samples node-level metrics, report.go ships both
to a monitoring opengemini database over /write).

The agent:
  - tails line-protocol metric files written by StatisticsPusher
    (``push_path``), forwarding new lines verbatim (rotation-aware);
  - tails error logs, emitting ``errLogTotal`` counts per file;
  - samples node metrics: cpu%, memory, disk usage of watched paths.

Run: ``python -m opengemini_tpu.app.monitor --report-host H
--report-db monitor --metric-file F --error-log F --disk-path D``
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from ..utils import get_logger
from .client import ClientError, HttpClient

log = get_logger(__name__)


class _Tail:
    """Offset-tracking tailer with rotation detection (size shrink or
    inode change → start over)."""

    def __init__(self, path: str, from_start: bool = False):
        self.path = path
        self.offset = 0
        self.inode = -1
        if not from_start:
            # attach at end: a restart must not re-ship the whole history
            try:
                st = os.stat(path)
                self.offset, self.inode = st.st_size, st.st_ino
            except OSError:
                pass

    def read_new(self) -> list[str]:
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        if st.st_ino != self.inode or st.st_size < self.offset:
            self.inode = st.st_ino
            self.offset = 0
        if st.st_size == self.offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            chunk = f.read()
        # only complete lines; partial tail re-read next tick
        nl = chunk.rfind(b"\n")
        if nl < 0:
            return []
        self.offset += nl + 1
        return chunk[:nl].decode(errors="replace").splitlines()


def _cpu_total():
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:]
        nums = [int(x) for x in parts]
        idle = nums[3] + (nums[4] if len(nums) > 4 else 0)
        return sum(nums), idle
    except (OSError, ValueError, IndexError):
        return 0, 0


def _mem_info() -> dict[str, int]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split(":", 1)
                if k in ("MemTotal", "MemAvailable"):
                    out[k] = int(v.split()[0]) * 1024
    except (OSError, ValueError):
        pass
    return out


class TsMonitor:
    def __init__(self, client: HttpClient | None, report_db: str = "monitor",
                 metric_files: list[str] = (),
                 error_logs: list[str] = (),
                 disk_paths: list[str] = (),
                 hostname: str = "", interval_s: float = 10.0):
        self.client = client
        self.report_db = report_db
        self.metric_tails = [_Tail(p) for p in metric_files]
        self.error_tails = [_Tail(p) for p in error_logs]
        self.disk_paths = list(disk_paths)
        self.hostname = hostname or os.uname().nodename
        self.interval_s = interval_s
        self.err_counts = {p: 0 for p in error_logs}
        self._last_cpu = _cpu_total()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.reported_lines = 0

    # ------------------------------------------------------------ sampling

    def node_metrics(self) -> dict[str, float]:
        total, idle = _cpu_total()
        ltotal, lidle = self._last_cpu
        self._last_cpu = (total, idle)
        dt, di = total - ltotal, idle - lidle
        cpu_pct = 100.0 * (dt - di) / dt if dt > 0 else 0.0
        m = _mem_info()
        out = {"cpu_pct": round(cpu_pct, 2)}
        if m:
            out["mem_total_bytes"] = m.get("MemTotal", 0)
            out["mem_available_bytes"] = m.get("MemAvailable", 0)
        for p in self.disk_paths:
            try:
                st = os.statvfs(p)
            except OSError:
                continue
            tag = p.strip("/").replace("/", "_") or "root"
            out[f"disk_total_bytes_{tag}"] = st.f_frsize * st.f_blocks
            out[f"disk_free_bytes_{tag}"] = st.f_frsize * st.f_bavail
        return out

    def collect_once(self) -> list[str]:
        """One tick: gather forwarded metric lines + derived metrics as
        line protocol; ship if a report client is configured."""
        lines: list[str] = []
        for t in self.metric_tails:
            lines.extend(t.read_new())
        ts = time.time_ns()
        for t in self.error_tails:
            new = [ln for ln in t.read_new()
                   if "ERROR" in ln or "WARN" in ln]
            if t.path in self.err_counts:
                self.err_counts[t.path] += len(new)
            else:
                self.err_counts[t.path] = len(new)
            base = os.path.basename(t.path).replace(" ", "_")
            lines.append(
                f"errLogTotal,hostname={self.hostname},log={base} "
                f"total={self.err_counts[t.path]}i {ts}")
        node = self.node_metrics()
        fields = ",".join(
            f"{k}={v}" + ("i" if isinstance(v, int) else "")
            for k, v in sorted(node.items()))
        lines.append(f"nodeMetrics,hostname={self.hostname} {fields} {ts}")
        if self.client is not None and lines:
            try:
                self.client.write("\n".join(lines), self.report_db)
                self.reported_lines += len(lines)
            except ClientError as e:
                log.warning("monitor report failed: %s", e)
        return lines

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ts-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect_once()
            except Exception:
                log.exception("monitor tick failed")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ts-monitor",
                                 description="monitoring agent")
    ap.add_argument("--report-host", default="127.0.0.1")
    ap.add_argument("--report-port", type=int, default=8086)
    ap.add_argument("--report-db", default="monitor")
    ap.add_argument("--metric-file", action="append", default=[])
    ap.add_argument("--error-log", action="append", default=[])
    ap.add_argument("--disk-path", action="append", default=[])
    ap.add_argument("--interval", type=float, default=10.0)
    args = ap.parse_args(argv)

    mon = TsMonitor(HttpClient(args.report_host, args.report_port),
                    args.report_db, args.metric_file, args.error_log,
                    args.disk_path, interval_s=args.interval)
    mon.start()
    print(f"ts-monitor reporting to {args.report_host}:{args.report_port} "
          f"db={args.report_db} every {args.interval}s")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        mon.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
