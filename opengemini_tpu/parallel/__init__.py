from .mesh import (make_mesh, distributed_window_aggregate,
                   DistributedAggregator)
