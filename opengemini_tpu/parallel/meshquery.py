"""Stored-data queries over the device mesh.

This is the exchange plane running on REAL query data (VERDICT r2
missing #6): ingest → TSSP → scan plan → rows hash-sharded across the
mesh ``data`` axis → per-device segment reduction → psum/pmin/pmax
merge over ICI — the role the reference fills by streaming partial-agg
chunks through spdy RPC into sql-side merge transforms
(coordinator/shard_mapper.go:614, engine/executor/select.go:128-152,
rpc_message.go:305).

Bit-identity: sums ride the exact integer limb planes
(ops/exactsum.py) — psum of integer limb grids is order-free, so the
mesh answer equals the single-device answer bit for bit, the same
guarantee the CPU cluster path gives across stores.

Two entry points:
- ``mesh_partial_agg``: full scan→shard→reduce→merge for one SELECT on
  one engine (used by __graft_entry__.dryrun_multichip and tests).
- ``mesh_merge_partials``: the intra-host merge plane for
  ClusterExecutor — per-store partial limb/count grids psum-merged on
  the mesh instead of host numpy loops (used when the sql node has a
  local device mesh).
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops import exactsum


def _shard_pad(mesh, arrs, axis_rows: int):
    """Pad row-axis arrays to a multiple of the data-axis size and
    device_put with (data,)-sharded layout. Returns (device arrays,
    padded length)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_data = mesh.devices.shape[0]
    n = arrs[0].shape[0]
    pad = (-n) % n_data
    out = []
    for a in arrs:
        if pad:
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(a, widths)
        spec = P("data", *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out, n + pad


def mesh_exact_aggregate(mesh, values, valid, seg_ids, limbs,
                         num_segments: int, times=None):
    """Distributed windowed aggregation with exact limb sums.

    Row-sharded inputs on the ``data`` axis: values/valid (N,), seg_ids
    (N,) int32, limbs (N, K) i32, times (N,) i64 (optional — enables
    the first/last lattice). Each device reduces its slice into a full
    (num_segments,) grid; grids merge with psum (count/limbs — exact
    integer addition, order-free) and pmin/pmax. first/last merge as a
    (time, value) lattice: pmin/pmax over the per-cell extreme TIME,
    then a second collective picks the value among the global time
    winners (min value for first, max for last, on the rare duplicate-
    timestamp tie — order-free by construction, the shipped values
    cross the mesh whole so f64 bits survive the emulated backend).
    Output grids are replicated across the mesh."""
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    ns = num_segments + 1
    K = limbs.shape[-1]
    I64MAX = np.iinfo(np.int64).max
    I64MIN = np.iinfo(np.int64).min
    with_fl = times is not None

    in_specs = [P("data"), P("data"), P("data"), P("data", None)]
    out_specs = {"count": P(None), "limbs": P(None, None),
                 "min": P(None), "max": P(None)}
    if with_fl:
        in_specs.append(P("data"))
        out_specs.update({"first": P(None), "first_time": P(None),
                          "last": P(None), "last_time": P(None)})

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=out_specs)
    def step(v, m, seg, lb, *rest):
        seg = jnp.where(m, seg, num_segments)
        cnt = jax.ops.segment_sum(m.astype(jnp.int64), seg,
                                  ns)[:num_segments]
        lsum = jnp.stack(
            [jax.ops.segment_sum(
                jnp.where(m, lb[:, k], 0).astype(jnp.int64), seg,
                ns)[:num_segments] for k in range(K)], axis=-1)
        mn = jax.ops.segment_min(jnp.where(m, v, jnp.inf), seg,
                                 ns)[:num_segments]
        mx = jax.ops.segment_max(jnp.where(m, v, -jnp.inf), seg,
                                 ns)[:num_segments]
        out = {"count": jax.lax.psum(cnt, "data"),
               "limbs": jax.lax.psum(lsum, "data"),
               "min": jax.lax.pmin(mn, "data"),
               "max": jax.lax.pmax(mx, "data")}
        if with_fl:
            (t,) = rest
            tf_loc = jax.ops.segment_min(
                jnp.where(m, t, I64MAX), seg, ns)[:num_segments]
            tl_loc = jax.ops.segment_max(
                jnp.where(m, t, I64MIN), seg, ns)[:num_segments]
            t_first = jax.lax.pmin(tf_loc, "data")
            t_last = jax.lax.pmax(tl_loc, "data")
            win_f = m & (t == t_first[jnp.minimum(seg,
                                                  num_segments - 1)]
                         ) & (seg < num_segments)
            win_l = m & (t == t_last[jnp.minimum(seg,
                                                 num_segments - 1)]
                         ) & (seg < num_segments)
            vf = jax.lax.pmin(jax.ops.segment_min(
                jnp.where(win_f, v, jnp.inf), seg, ns)[:num_segments],
                "data")
            vl = jax.lax.pmax(jax.ops.segment_max(
                jnp.where(win_l, v, -jnp.inf), seg, ns)[:num_segments],
                "data")
            out.update({"first": vf, "first_time": t_first,
                        "last": vl, "last_time": t_last})
        return out

    args = (values, valid, seg_ids, limbs)
    if with_fl:
        args = args + (times,)
    return step(*args)


def mesh_partial_agg(engine, db: str, stmt, mesh) -> dict:
    """Execute one agg SELECT over stored TSSP data with the mesh as
    the reduction plane, returning an influx-style result identical
    (bit for bit on sum/mean/count) to QueryExecutor.execute.

    Full path: series-index tagsets → chunk-meta scan plan → segment
    decode (flat rows; pre-agg/dense shortcuts disabled so every row
    really crosses the exchange) → rows hash-partitioned by series
    across the data axis → per-device reduce → collective merge →
    host finalize (exact limb totals → correctly-rounded f64)."""
    from ..query.condition import analyze_condition
    from ..query.functions import classify_select
    from ..query.scan import materialize_scan, plan_rowstore_scan
    from ..query.executor import _collect_raw_slices, finalize_partials

    mst = stmt.from_measurement
    cs = classify_select(stmt)
    if cs.mode != "agg":
        raise ValueError("mesh_partial_agg handles aggregate selects")
    db_obj = engine.database(db)
    shards = list(db_obj.all_shards())
    tag_keys = set()
    for s in shards:
        tag_keys |= set(s.index.tag_keys(mst))
    cond = analyze_condition(stmt.condition, tag_keys)
    group_tags = list(stmt.group_by_tags())
    interval = stmt.group_by_interval() or 0

    global_groups: dict[tuple, int] = {}
    per_shard = []
    for s in shards:
        ts = s.index.group_by_tagsets(mst, group_tags, cond.tag_filters,
                                      cond.tag_exprs)
        pairs = []
        for key, sids in ts:
            gi = global_groups.setdefault(key, len(global_groups))
            pairs.extend((int(sid), gi) for sid in sids)
        per_shard.append((s, pairs))
    from ..query.condition import MAX_TIME, MIN_TIME
    t_lo = None if cond.t_min == MIN_TIME else cond.t_min
    t_hi = None if cond.t_max == MAX_TIME else cond.t_max
    plan = plan_rowstore_scan(per_shard, mst, t_lo, t_hi)
    G = len(global_groups)
    if not plan.has_rows or G == 0:
        return {}

    # window layout mirrors QueryExecutor.partial_agg exactly
    # (incl. GROUP BY time(i, offset) and the start-coverage step) —
    # bit-identity requires identical bucket boundaries
    offset = stmt.group_by_offset()
    if stmt.tz and interval:
        from ..query.executor import tz_bucket_offset
        offset += tz_bucket_offset(stmt.tz, interval)
    t0 = t_lo if t_lo is not None else plan.data_tmin
    if interval:
        start = (t0 - offset) // interval * interval + offset
        if start > t0:
            start -= interval
        end = t_hi if t_hi is not None else plan.data_tmax
        W = int((end - start) // interval) + 1
    else:
        start = t0
        W = 1
    raw_need = sorted({a.field for a in cs.aggs if a.needs_raw})
    needed = sorted({a.field for a in cs.aggs})
    want_fl = any(a.func in ("first", "last") for a in cs.aggs)
    scanres = materialize_scan(plan, mst, needed, t_lo, t_hi,
                               int(start), int(interval or 2**63), W,
                               G * W, allow_preagg=False,
                               allow_dense=False)
    times = scanres.times
    gids = scanres.gids
    if interval:
        w = (times - start) // interval
        w = np.where((w >= 0) & (w < W), w, W)
    else:
        w = np.zeros(len(times), dtype=np.int64)
    seg = np.where(w < W, gids * W + w, G * W).astype(np.int32)

    I64MAX = np.iinfo(np.int64).max
    I64MIN = np.iinfo(np.int64).min
    fields_out = {}
    sum_scales = {}
    raw_out = {}
    for fname in needed:
        vals, valid = scanres.fields[fname]
        vals = vals.astype(np.float64, copy=False)
        E = exactsum.pick_scale(
            float(np.abs(np.where(valid, vals, 0.0)).max())
            if len(vals) else 0.0)
        limbs, bad = exactsum.host_limbs(vals, valid, E)
        arrs = [vals, valid, seg, limbs]
        if want_fl:
            arrs.append(times)
        sharded, _ = _shard_pad(mesh, arrs, len(vals))
        out = mesh_exact_aggregate(
            mesh, *sharded[:4], G * W,
            times=sharded[4] if want_fl else None)
        cnt = np.asarray(out["count"]).reshape(G, W)
        lg = np.asarray(out["limbs"]).astype(np.float64)
        mn = np.asarray(out["min"]).reshape(G, W)
        mx = np.asarray(out["max"]).reshape(G, W)
        inex = np.zeros(G * W, dtype=bool)
        np.logical_or.at(inex, seg[valid & (seg < G * W)],
                         bad[valid & (seg < G * W)])
        st = {"count": cnt,
              "sum": exactsum.finalize_exact(lg, E).reshape(G, W),
              "min": mn, "max": mx,
              "sum_limbs": lg.reshape(G, W, exactsum.K_LIMBS),
              "sum_inexact": inex.reshape(G, W)}
        if want_fl:
            has = cnt > 0
            st["first"] = np.where(
                has, np.asarray(out["first"]).reshape(G, W), np.nan)
            st["first_time"] = np.where(
                has, np.asarray(out["first_time"]).reshape(G, W),
                I64MAX).astype(np.int64)
            st["last"] = np.where(
                has, np.asarray(out["last"]).reshape(G, W), np.nan)
            st["last_time"] = np.where(
                has, np.asarray(out["last_time"]).reshape(G, W),
                I64MIN).astype(np.int64)
        fields_out[fname] = st
        sum_scales[fname] = E
        if fname in raw_need:
            raw_out[fname] = _collect_raw_slices(
                np.asarray(seg, dtype=np.int64), vals, valid, times,
                G, W)

    group_keys = [None] * G
    for key, gi in global_groups.items():
        group_keys[gi] = list(key)
    partial = {"group_tags": group_tags,
               "group_keys": group_keys,
               "interval": interval, "start": int(start), "W": W,
               "fields": fields_out,
               "field_types": {f: "float" for f in needed},
               "sum_scales": sum_scales}
    if raw_out:
        partial["raw"] = raw_out
    return finalize_partials(stmt, mst, cs, [partial])


def mesh_merge_partials(mesh, partials: list[dict]) -> dict | None:
    """Intra-host merge plane: when every store partial is grid-aligned
    (same group keys, start, W — the common same-schema scatter), the
    per-store count/limb grids psum-merge ON THE MESH (exact integer
    addition, one collective) instead of looping host numpy. Returns
    the merged partial, or None when shapes are ragged (caller falls
    back to the host merge)."""
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(partials) < 2:
        return partials[0] if partials else None
    first = partials[0]
    n_data = mesh.devices.shape[0]
    if len(partials) > n_data:
        return None
    key0 = (first["group_keys"], first["start"], first["W"],
            sorted(first["fields"]))
    for p in partials[1:]:
        if (p["group_keys"], p["start"], p["W"],
                sorted(p["fields"])) != key0:
            return None
    fnames = sorted(first["fields"])
    mergeable = {"count", "sum", "sumsq", "min", "max",
                 "min_time", "max_time", "first", "first_time",
                 "last", "last_time", "sum_limbs", "sum_inexact"}
    for p in partials:
        if "raw" in p or "sketch" in p or "topn" in p:
            return None          # variable-size states stay host-side
        for f in fnames:
            st = p["fields"][f]
            if "sum_limbs" not in st or "count" not in st:
                return None
            if not set(st) <= mergeable:
                return None
            if p.get("sum_scales", {}).get(f) != \
                    first.get("sum_scales", {}).get(f):
                return None

    P_n = len(partials)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data", None, None, None),),
                       out_specs=P(None, None, None))
    def psum_grids(stack):
        return jax.lax.psum(jnp.sum(stack, axis=0), "data")

    merged = {k: first[k] for k in ("group_tags", "group_keys",
                                    "interval", "start", "W")}
    if "display_start" in first:
        merged["display_start"] = first["display_start"]
    merged["field_types"] = first["field_types"]
    merged["sum_scales"] = dict(first.get("sum_scales", {}))
    out_fields = {}
    for f in fnames:
        sts = [p["fields"][f] for p in partials]
        G, W = sts[0]["count"].shape
        K = sts[0]["sum_limbs"].shape[-1]
        # stack per-store [limbs..., count] grids → (P_pad, G, W, K+1),
        # one device row per store partial, psum over the data axis
        stack = np.zeros((P_n, G, W, K + 1))
        for i, st in enumerate(sts):
            stack[i, :, :, :K] = st["sum_limbs"]
            stack[i, :, :, K] = st["count"]
        pad = (-P_n) % n_data
        if pad:
            stack = np.pad(stack, [(0, pad), (0, 0), (0, 0), (0, 0)])
        dstack = jax.device_put(
            stack, NamedSharding(mesh, P("data", None, None, None)))
        tot = np.asarray(psum_grids(dstack))
        lg = tot[:, :, :K]
        cnt = tot[:, :, K].astype(np.int64)
        st = {"count": cnt,
              "sum": exactsum.finalize_exact(
                  lg, merged["sum_scales"].get(f, 0)),
              "sum_limbs": lg,
              "sum_inexact": np.logical_or.reduce(
                  [s["sum_inexact"] for s in sts])}
        # positional states (min/max times, first/last lattices,
        # sumsq) merge with the SHARED host exchange rules — one
        # source of truth, uniform identity seeding (an empty cell in
        # one partial never blocks another's real value)
        from ..query.executor import merge_aligned_positionals
        st.update(merge_aligned_positionals(sts))
        st["sum_inexact"] = np.asarray(st["sum_inexact"])
        out_fields[f] = st
    merged["fields"] = out_fields
    return merged
