"""Multi-device distribution: jax.sharding Mesh + shard_map collectives.

Role of the reference's MPP exchange plane (LogicalExchange NODE/SHARD/
SERIES levels, engine/executor/logic_plan.go:2065-2076, and the spdy RPC
data plane, SURVEY §2.6): instead of streaming partial-agg chunks over a
custom TCP protocol, partial aggregate states live in device memory and
merge with XLA collectives over ICI/DCN.

Mesh axes (the TSDB analogs of dp/tp/sp):
- ``data``  — rows partitioned by series hash (the reference's hash data
  sharding, ShardFor shardinfo.go:369): each device scans its row slice and
  produces a FULL segment-space partial state; partials merge with psum
  (sum/count), pmin/pmax (min/max). This is the SHARD/NODE exchange analog.
- ``field`` — columns partitioned across devices (the tensor axis): a
  multi-field query (e.g. TSBS high-cpu-all's 10 fields) fans fields out;
  no collective needed, outputs stay field-sharded.

Time-axis sharding (the sequence/pipeline analog) happens above this layer:
shard groups are time partitions, assigned round-robin to hosts by the meta
layer; within a query each host reduces its time slice and the final merge
is the same psum (sums/counts are time-associative).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import AggSpec
from ..ops.segment_agg import _segment_all

_FULL_SPEC = AggSpec.of("count", "sum", "min", "max")


def make_mesh(n_data: int | None = None, n_field: int = 1,
              devices=None) -> Mesh:
    """2D device mesh (data × field). Defaults to all devices on the data
    axis (pure scan parallelism). n_field must divide the device count."""
    devices = devices if devices is not None else jax.devices()
    if n_field < 1 or len(devices) % n_field != 0:
        raise ValueError(
            f"n_field={n_field} must divide device count {len(devices)}")
    if n_data is None:
        n_data = len(devices) // n_field
    if n_data < 1 or n_data * n_field > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_field} needs {n_data * n_field} devices, "
            f"have {len(devices)}")
    dev = np.array(devices[: n_data * n_field]).reshape(n_data, n_field)
    return Mesh(dev, axis_names=("data", "field"))


def _local_partial(values, valid, seg_ids, num_segments: int):
    """Per-device partial aggregation over its row slice, vmapped over the
    field axis. Reuses the single-device kernel body (_segment_all) so the
    distributed path cannot diverge from it. Returns dict of (C_local, S)."""
    return jax.vmap(
        lambda v, m: _segment_all(v, m, seg_ids, num_segments,
                                  _FULL_SPEC, sorted_ids=False)
    )(values, valid)


def distributed_window_aggregate(mesh: Mesh, values, valid, seg_ids,
                                 num_segments: int):
    """Full distributed aggregation step.

    values/valid: (C, N) sharded (field, data); seg_ids: (N,) sharded
    (data,). Each device reduces its rows locally, then partials merge
    across the data axis with psum/pmin/pmax riding ICI. Output: dict of
    (C, num_segments) arrays, field-sharded, replicated across data.
    """
    try:
        from jax import shard_map  # jax >= 0.7
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("field", "data"), P("field", "data"), P("data")),
        out_specs={k: P("field", None)
                   for k in ("count", "sum", "min", "max")})
    def step(v, m, seg):
        part = _local_partial(v, m, seg, num_segments)
        return {
            "count": jax.lax.psum(part["count"], "data"),
            "sum": jax.lax.psum(part["sum"], "data"),
            "min": jax.lax.pmin(part["min"], "data"),
            "max": jax.lax.pmax(part["max"], "data"),
        }

    return step(values, valid, seg_ids)


class DistributedAggregator:
    """Convenience wrapper: jit-compiled distributed aggregation bound to a
    mesh (one compile per (shape, num_segments) pair)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._fn = jax.jit(
            lambda v, m, s, ns: distributed_window_aggregate(
                self.mesh, v, m, s, ns),
            static_argnames=("ns",))

    def shard_inputs(self, values, valid, seg_ids, times=None,
                     by: str = "series"):
        """Place host arrays onto the mesh with the canonical shardings.

        by="series": rows in arbitrary (series-hash) order — the DP/shard
        exchange analog. by="time" (requires `times`): rows sorted so
        each device holds one contiguous TIME slice — the sequence-
        parallel analog (ring-attention's time-axis split). Both produce
        full-segment-space partials merged by the same psum/pmin/pmax
        collectives, so the partition dimension changes data locality
        (a time-bounded query touches fewer devices) without touching
        the merge math."""
        if by == "time":
            if times is None:
                raise ValueError("by='time' requires times")
            order = np.argsort(np.asarray(times), kind="stable")
            values = np.asarray(values)[:, order]
            valid = np.asarray(valid)[:, order]
            seg_ids = np.asarray(seg_ids)[order]
        elif by != "series":
            raise ValueError(f"unknown sharding axis {by!r}")
        sv = NamedSharding(self.mesh, P("field", "data"))
        ss = NamedSharding(self.mesh, P("data"))
        return (jax.device_put(values, sv), jax.device_put(valid, sv),
                jax.device_put(seg_ids, ss))

    def __call__(self, values, valid, seg_ids, num_segments: int):
        return self._fn(values, valid, seg_ids, num_segments)
