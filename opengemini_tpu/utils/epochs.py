"""Per-(db, measurement) write epochs for result-cache invalidation.

The result cache (query/resultcache.py) serves *closed* time buckets
of repeated dashboard queries from cached mergeable partial states.
Correctness hinges on one contract: a cached range must NEVER be
served after data inside it changed. This module is that contract's
ledger — a process-wide epoch counter per (db, measurement), bumped on
every ingest-path write / delete / drop with the written time range,
plus a bounded ring of recent (epoch, t_lo, t_hi) write extents so a
reader can ask "did anything land inside MY cached range since epoch
E?" exactly, and gets a conservative *yes* when the ring has already
evicted that history.

Kept in utils (no query/ops imports) so the storage write path can
bump epochs without dragging the query stack — or jax — into
storage-only contexts. Sustained ingest appends at the LIVE edge
(t > every cached watermark), so cached closed buckets keep
validating against the ring without ever rescanning; only a write
*into* a closed range (backfill, DELETE, DROP, retention shard drop)
invalidates, which is exactly the staleness the cache must not serve.
"""

from __future__ import annotations

import threading
from collections import deque

__all__ = ["note_write", "note_wipe", "snapshot", "changed_since",
           "reset", "stats"]

# ring depth per (db, mst): sized so a dashboard poll interval's worth
# of ingest batches never outruns validation; evicted history degrades
# to conservative invalidation, never to a stale serve
_RING = 512

_LOCK = threading.Lock()
# (db, mst) -> {"epoch": int, "gen": int,
#               "ring": deque[(epoch, t_lo, t_hi)]}
# gen is the per-measurement WIPE generation: a wipe clears the ring
# (its history is meaningless across destroyed data) and bumps gen,
# so entries stamped before it invalidate even when stamped at epoch
# 0 (data loaded from disk predates this process's epochs)
_STORE: dict[tuple, dict] = {}
# db -> wipe generation: DROP DATABASE / retention shard drops must
# invalidate every measurement's entries without enumerating them
_DB_GEN: dict[str, int] = {}
# bound on tracked (db, mst) pairs: measurement churn must not grow
# the store without end. Eviction is conservative — see changed_since
_MAX_TRACKED = 4096


def _ent(db: str, mst: str) -> dict:
    e = _STORE.get((db, mst))
    if e is None:
        while len(_STORE) >= _MAX_TRACKED:
            # FIFO-evict: a reader holding an evicted stamp reads
            # "changed" (its epoch/gen is nonzero — see changed_since)
            _STORE.pop(next(iter(_STORE)))
        e = _STORE[(db, mst)] = {"epoch": 0, "gen": 0,
                                 "ring": deque(maxlen=_RING)}
    return e


def note_write(db: str, mst: str, t_lo: int, t_hi: int) -> None:
    """One ingest batch landed rows for ``mst`` in [t_lo, t_hi] (ns,
    inclusive; shard-granular bounds are fine — coarser ranges only
    over-invalidate, never under)."""
    with _LOCK:
        e = _ent(db, mst)
        e["epoch"] += 1
        e["ring"].append((e["epoch"], int(t_lo), int(t_hi)))


def note_wipe(db: str, mst: str | None = None) -> None:
    """Data destroyed or rewritten non-append-wise. Per-measurement
    (DELETE, DROP MEASUREMENT): bump THAT measurement's generation
    only — a retention DELETE on one measurement must not flush every
    other dashboard's cache in the db. ``mst=None`` (DROP DATABASE,
    retention shard drop — no per-mst view) bumps the db generation
    and drops the per-mst state."""
    with _LOCK:
        if mst is None:
            _DB_GEN[db] = _DB_GEN.get(db, 0) + 1
            for key in [k for k in _STORE if k[0] == db]:
                del _STORE[key]
            return
        e = _ent(db, mst)
        # epoch bump keeps every post-wipe stamp nonzero, so a later
        # eviction of this entry still reads as changed; the ring is
        # history of destroyed data — meaningless, drop it
        e["epoch"] += 1
        e["gen"] += 1
        e["ring"].clear()


def snapshot(db: str, mst: str) -> tuple[int, int, int]:
    """(epoch, mst_generation, db_generation) to stamp on a cache
    entry BEFORE its compute scan starts — a write racing the scan
    lands a higher epoch and the entry invalidates on its next
    read."""
    with _LOCK:
        e = _STORE.get((db, mst))
        return (e["epoch"] if e else 0, e["gen"] if e else 0,
                _DB_GEN.get(db, 0))


def changed_since(db: str, mst: str, epoch: int, gen: int,
                  db_gen: int, t_lo: int, t_hi: int
                  ) -> tuple[bool, int]:
    """Did any write/wipe land inside [t_lo, t_hi) after the
    (epoch, gen, db_gen) stamp? Returns (changed, current_epoch).
    Evicted ring history, an evicted store entry under a nonzero
    stamp, or any generation bump answers True — unknown must read
    as changed. When nothing overlapped, callers refresh their epoch
    stamp to current_epoch so future checks scan only the new tail."""
    with _LOCK:
        if _DB_GEN.get(db, 0) != db_gen:
            return True, epoch
        e = _STORE.get((db, mst))
        if e is None:
            # never written in this process: a zero stamp is still
            # valid (disk-resident data, untouched); a nonzero stamp
            # means the entry was evicted — conservative
            return epoch != 0 or gen != 0, 0
        if e["gen"] != gen:
            return True, epoch
        cur = e["epoch"]
        if cur == epoch:
            return False, cur
        if epoch > cur:
            # a foreign stamp (store reset between stamp and check):
            # nothing to validate against — conservative
            return True, cur
        ring = e["ring"]
        if not ring or ring[0][0] > epoch + 1:
            # history older than the stamp already evicted
            return True, cur
        for ep, lo, hi in reversed(ring):
            if ep <= epoch:
                break
            if lo < t_hi and hi >= t_lo:
                return True, cur
        return False, cur


def stats() -> dict:
    with _LOCK:
        return {"tracked": len(_STORE),
                "epochs_total": sum(e["epoch"]
                                    for e in _STORE.values()),
                "db_wipes": sum(_DB_GEN.values())}


def reset() -> None:
    """Tests only: forget all epochs (paired with a result-cache
    purge — an empty cache cannot be served stale by a zeroed store)."""
    with _LOCK:
        _STORE.clear()
        _DB_GEN.clear()
