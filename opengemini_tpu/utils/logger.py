"""Structured logging (analog of reference lib/logger zap wrapper)."""

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"
_configured = False


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("OPENGEMINI_TPU_LOG", "INFO").upper()
        logging.basicConfig(level=level, format=_FORMAT, stream=sys.stderr)
        _configured = True
    return logging.getLogger(name)
