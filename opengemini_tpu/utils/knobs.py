"""Central registry for every ``OG_*`` environment knob.

Five PRs in, ~50 env knobs steer the device hot path, the scheduler,
the caches and the bench harness — and every one of them was a raw
``os.environ.get`` scattered across the tree: no single place to see
what exists, no types, no docs, and a few reads sat INSIDE dispatch
loops (OG_SCHED per device launch, OG_DEVICE_CACHE_MB per slab).

This module is the one place a knob may be declared and read:

- ``register()`` declares name, type, default, doc and a *scope*
  describing when the value is sampled:

  * ``dynamic``      — read from the environment on every ``get()``
    (tests and perf_smoke flip these per query/run);
  * ``module-init``  — sampled once when the owning module imports
    (the value lands in a module constant; changing the env var later
    requires a re-import, as before the registry);
  * ``cached``       — hot-path knob: ``get()`` memoizes the PARSED
    value keyed on the raw environment string, so the per-launch /
    per-slab reads these knobs serve (scheduler.enabled per device
    launch, devicecache.enabled per slab) cost two dict hits and no
    int()/try parsing. Environment flips stay visible immediately —
    only the parse is cached, never the raw read — so tests and the
    bench may still flip them per run (``set_env`` is the tidy way).

- oglint rule R2 (opengemini_tpu/lint/knob_rule.py) forbids raw
  ``os.environ``/``os.getenv`` reads of ``OG_*`` names anywhere else,
  and fails when the README's generated knob table drifts from this
  registry (``python -m opengemini_tpu.lint --knob-table``).

Bool parsing preserves both historical conventions ("!= '0'" with
default on; "== '1'" with default off): unset → default, "0" → False,
"1" → True, anything else → default. Parse failures on int/float
knobs fall back to the declared default (never raise on a typo'd
environment), matching the defensive reads they replaced.
"""

from __future__ import annotations

import os
import threading

__all__ = ["Knob", "register", "get", "get_raw", "set_env", "del_env",
           "invalidate", "all_knobs", "knob_table_md", "is_registered"]

_SCOPES = ("dynamic", "module-init", "cached")


class Knob:
    __slots__ = ("name", "ktype", "default", "doc", "scope")

    def __init__(self, name: str, ktype: type, default, doc: str,
                 scope: str):
        self.name = name
        self.ktype = ktype
        self.default = default
        self.doc = doc
        self.scope = scope

    def parse(self, raw: str | None):
        if raw is None:
            return self.default
        if self.ktype is bool:
            if raw == "0":
                return False
            if raw == "1":
                return True
            return self.default
        try:
            return self.ktype(raw)
        except (TypeError, ValueError):
            return self.default


_REGISTRY: dict[str, Knob] = {}
_CACHE: dict[str, object] = {}
_CACHE_LOCK = threading.Lock()


def register(name: str, ktype: type, default, doc: str,
             scope: str = "dynamic") -> Knob:
    if not name.startswith("OG_"):
        raise ValueError(f"knob {name!r} must start with OG_")
    if scope not in _SCOPES:
        raise ValueError(f"knob {name}: scope {scope!r} not in {_SCOPES}")
    if ktype not in (str, int, float, bool):
        raise ValueError(f"knob {name}: unsupported type {ktype!r}")
    existing = _REGISTRY.get(name)
    if existing is not None:
        return existing
    k = Knob(name, ktype, default, doc, scope)
    _REGISTRY[name] = k
    return k


def is_registered(name: str) -> bool:
    return name in _REGISTRY


def _knob(name: str) -> Knob:
    k = _REGISTRY.get(name)
    if k is None:
        raise KeyError(
            f"unregistered knob {name!r} — declare it in "
            "opengemini_tpu/utils/knobs.py (oglint R2 enforces this)")
    return k


def get(name: str):
    """Typed value of one registered knob (see module doc for scope
    semantics)."""
    k = _knob(name)
    raw = os.environ.get(name)
    if k.scope == "cached":
        key = (name, raw)
        got = _CACHE.get(key, _CACHE)
        if got is not _CACHE:
            return got
        val = k.parse(raw)
        with _CACHE_LOCK:
            _CACHE[key] = val
        return val
    return k.parse(raw)


def get_raw(name: str) -> str | None:
    """Uninterpreted environment string of a registered knob (None =
    unset) — for knobs whose raw form is tri-state (OG_DEVICE_FINALIZE
    '0'/'1'/'force') or empty-means-default (OG_FINALIZE_WORKERS)."""
    _knob(name)
    return os.environ.get(name)


def set_env(name: str, value) -> None:
    """Set a knob in the process environment AND drop any memoized
    value — the only sanctioned way to flip a ``cached`` knob at
    runtime (bench phases, tests). Values are normalized to the
    knob's declared type: a Python bool becomes "1"/"0" (str(False)
    would read back as the DEFAULT, silently un-flipping the knob)."""
    k = _knob(name)
    if isinstance(value, bool):
        if k.ktype is not bool:
            raise TypeError(
                f"knob {name} is {k.ktype.__name__}-typed; got bool")
        value = "1" if value else "0"
    os.environ[name] = str(value)
    invalidate(name)


def del_env(name: str) -> None:
    _knob(name)
    os.environ.pop(name, None)
    invalidate(name)


def invalidate(name: str | None = None) -> None:
    """Forget memoized parses of ``cached`` knobs (all of them when
    ``name`` is None) — hygiene only, since the memo is keyed on the
    raw string and can never serve a stale environment."""
    with _CACHE_LOCK:
        if name is None:
            _CACHE.clear()
        else:
            for key in [k for k in _CACHE if k[0] == name]:
                _CACHE.pop(key, None)


def all_knobs() -> list[Knob]:
    return [v for _k, v in sorted(_REGISTRY.items())]


def knob_table_md() -> str:
    """The README's knob table, generated (``python -m
    opengemini_tpu.lint --knob-table``). oglint R2 fails when the
    README block drifts from this output."""
    lines = ["| knob | type | default | scope | meaning |",
             "|---|---|---|---|---|"]
    for k in all_knobs():
        d = k.default
        if k.ktype is bool:
            d = "on" if d else "off"
        elif d == "":
            d = "(unset)"
        lines.append(f"| `{k.name}` | {k.ktype.__name__} | `{d}` "
                     f"| {k.scope} | {k.doc} |")
    return "\n".join(lines)


# ----------------------------------------------------------- registry
#
# Declared centrally (not at call sites) so the table is complete even
# when an owning module was never imported. Grouped by subsystem.

# --- device pipeline / transfer plane (ops/)
register("OG_PIPELINE_DEPTH", int, 4,
         "streaming pipeline launch window per query; 0 disables "
         "streaming (classic single-barrier pull)")
register("OG_PIPELINE_THREADS", int, 4,
         "puller threads in the shared D2H pool")
register("OG_DEVICE_FINALIZE", str, "1",
         "tri-state D2H diet gate: `0` = byte-identical legacy "
         "transport, `1` = on-device finalize + op-aware plane "
         "pruning (epilogue auto-gates off on f64-emulated backends), "
         "`force` = override the backend gate")
register("OG_LATTICE_DEVICE_FOLD", bool, True,
         "fold window lattices on device (one packed grid per "
         "field×scale crosses D2H); 0 = host C fold")
register("OG_DEVICE_TOPK", bool, True,
         "device-side ORDER BY/LIMIT cut over finalized answer "
         "planes: only the k×groups winner cells cross D2H; 0 = "
         "byte-identical full-grid pull + host slicing")
register("OG_DEVICE_SKETCH", bool, True,
         "device order-statistic finalize for percentile/median/mode "
         "over HBM-resident sorted-sample planes (terminal plans, "
         "real-f64 backends); 0 = byte-identical host raw-slice path")
register("OG_SKETCH_HBM_MB", int, 256,
         "HBM budget for the sorted-sample sketch tier (device-"
         "resident per-(field, window-layout) cell-sorted planes); "
         "0 disables the tier (planes rebuilt per query)")
register("OG_F32_TIER", bool, False,
         "opt-in f32 fast tier: dashboard-class dense-window "
         "reductions ride the VMEM-tiled Pallas kernel "
         "(ops/pallas_agg.py) in float32 — NOT bit-identical; "
         "digest-tolerance gated in perf_smoke")
register("OG_DENSE_DEVICE", bool, False,
         "dense (S,P) groups reduce on device from decoded-plane "
         "cache residency")
register("OG_EXACT_SUM", bool, True,
         "bit-identical f64 sums via binned integer limbs; 0 "
         "disables (plain pairwise summation)")
register("OG_FINALIZE_WORKERS", str, "",
         "worker count for group-sharded finalize stages; 0/1 = "
         "serial, unset = per-stage default")

# --- block aggregation kernels (ops/blockagg.py; module-init: the
#     values land in module constants at import)
register("OG_BLOCK_SLAB", int, 4096,
         "blocks per kernel launch (slab size)", scope="module-init")
register("OG_BLOCK_MASK_W", int, 64,
         "widest per-window bitmask the mask kernel packs",
         scope="module-init")
register("OG_BLOCK_PACK", bool, True,
         "packed uint32 result transport for the block path",
         scope="module-init")
register("OG_PREFIX_PLAN_MAX_ENTRIES", int, 64 * 1024 * 1024,
         "host/device budget for one slab's stage-3 gather plan",
         scope="module-init")
register("OG_ARITH_G_MAX", int, 256,
         "group-count ceiling for the one-hot matmul cell fold",
         scope="module-init")
register("OG_LATTICE_MAX_MB", int, 256,
         "per-slab byte cap for the pulled window lattice",
         scope="module-init")

# --- executor dispatch economics (query/executor.py; module-init)
register("OG_HOST_AGG_THRESHOLD", int, 16_000_000,
         "sparse rows at/below this reduce on host numpy instead of "
         "paying device dispatch latency", scope="module-init")
register("OG_BLOCK_MAX_CELLS", int, 1_000_000,
         "legacy-transport result-grid cell cap for block dispatch",
         scope="module-init")
register("OG_BLOCK_MAX_CELLS_PACKED", int, 16_000_000,
         "packed-transport result-grid cell cap", scope="module-init")
register("OG_BLOCK_MIN_RATIO", int, 16,
         "min rows/cells ratio for legacy-transport block dispatch",
         scope="module-init")
register("OG_BLOCK_MIN_RATIO_PACKED", int, 4,
         "min rows/cells ratio for packed-transport block dispatch",
         scope="module-init")
register("OG_BATCH_UPLOAD_MB", int, 512,
         "cap on the stacked multi-field upload batch",
         scope="module-init")
register("OG_GC_MAX_PAUSE_S", float, 60.0,
         "max seconds between explicit GC collections while queries "
         "hold the GC pause", scope="module-init")

# --- device/host caches (ops/devicecache.py; cached: enabled() runs
#     per slab on the dispatch path)
register("OG_DEVICE_CACHE_MB", int, 6144,
         "HBM block/plane cache budget; 0 disables ALL cache tiers",
         scope="cached")
register("OG_HOST_CACHE_MB", int, 4096,
         "host pin-cache budget (assembled dense blocks, limb sums, "
         "result grids)", scope="cached")

# --- compressed-domain device execution (encoding/dfor.py,
#     ops/device_decode.py, ops/blockagg.py; cached: consulted per
#     segment on the write path and per slab on the dispatch path)
register("OG_WRITE_DEVICE_LAYOUT", bool, True,
         "TSSP write/compaction emit the device-friendly DFOR "
         "bit-packed layout for numeric blocks when it beats the raw "
         "payload (old GORILLA/S8B/ZSTD blocks stay readable; "
         "compaction transcodes them as it rewrites); 0 = legacy "
         "codec menu only", scope="cached")
register("OG_DEVICE_DECODE", bool, True,
         "decode DFOR/CONST-DELTA block payloads ON DEVICE in the "
         "HBM slab path: compressed bytes cross H2D and expand "
         "in-kernel; 0 = host decode + dense plane upload "
         "(byte-identical escape hatch)", scope="cached")
register("OG_PACKED_PREDICATE", bool, True,
         "push WHERE residuals into packed space (ops/pushdown.py): "
         "range/equality conjuncts on one field translate to exact "
         "integer compares on DFOR lanes, envelope-skipped segments "
         "never expand, survivors late-materialize via the slab "
         "valid plane; 0 = expand-then-filter (byte-identical "
         "escape hatch)", scope="cached")
register("OG_LIMB_INT", str, "",
         "int-space limb decomposition for the device decode stage "
         "(ops/device_decode.int_limbs_batch): \"\" = auto (engages "
         "when the backend lacks real f64 — f32-pair-emulated TPUs), "
         "1 = force on (CPU parity testing), 0 = off (emulated "
         "backends keep the host decode stage)", scope="cached")
register("OG_HBM_COMPRESSED_MB", int, 1024,
         "HBM budget of the compressed payload tier (device-resident "
         "DFOR words): a slab evicted under pressure rebuilds from "
         "the ~15x denser compressed bytes with ZERO H2D; the relief "
         "ladder evicts decoded planes before compressed bytes",
         scope="cached")

# --- whole-plan fused execution (ops/fused.py, query/fusedplan.py)
register("OG_FUSED_PLAN", bool, True,
         "trace eligible TERMINAL big-grid plans (lattice route + "
         "device fold) as ONE jit program per shape class — slab "
         "lattice, cell fold, cross-slab combine, finalize epilogue "
         "and top-k cut fuse into a single device dispatch with no "
         "intermediate grids re-crossing the dispatcher; 0 = staged "
         "per-kernel dispatch (byte-identical escape hatch)")

# --- query scheduler (query/scheduler.py; OG_SCHED cached: checked on
#     every device launch)
register("OG_SCHED", bool, True,
         "device query scheduler; 0 = legacy counting gate + inline "
         "launches (byte-identical)", scope="cached")
register("OG_SCHED_SLOTS", str, "",
         "concurrent query slots (overrides config; 0 = unlimited)")
register("OG_SCHED_QUEUE", str, "",
         "admission waiting-room cap (overrides config)")
register("OG_SCHED_MAX_CELLS", str, "",
         "early-shed budget: estimated result cells above this are "
         "rejected with 429 (overrides config)")
register("OG_SCHED_DEPTH", int, 8,
         "global in-flight streamed-launch bound across all queries")

# --- sustained serving: result cache + tenant fair share
#     (query/resultcache.py, query/scheduler.py; cached: the enable
#     gate runs per SELECT on the serving hot path)
register("OG_RESULT_CACHE", bool, True,
         "time-bucketed result cache: closed time buckets of repeated "
         "dashboard aggregates serve from cached mergeable partial "
         "states, only the live edge recomputes; 0 = byte-identical "
         "full recompute on every query", scope="cached")
register("OG_RESULT_CACHE_MB", int, 256,
         "host-memory byte budget of the result cache (LRU; accounted "
         "as the `result_cache` tier in the HBM/host ledger); 0 "
         "disables the cache")
register("OG_RESULT_BUCKET_S", float, 60.0,
         "result-cache bucket width (seconds): windows ending at/after "
         "the current bucket boundary are the live edge and always "
         "recompute; closed windows are cacheable")
register("OG_TENANT_SHARES", str, "",
         "per-tenant weighted-fair shares for scheduler admission, "
         "`name:weight,name:weight` (X-OG-Tenant header selects the "
         "tenant; unlisted tenants weigh 1); unset = single-tenant "
         "PR 4 ordering")

# --- device resource observatory (ops/hbm.py, query/scheduler.py)
register("OG_DEVUTIL_MS", float, 1000.0,
         "utilization-timeline sampler interval (ms) for the device "
         "observatory (/debug/device); <= 0 disables sampling")
register("OG_DEVUTIL_RING", int, 512,
         "samples kept in the utilization-timeline ring")
register("OG_HBM_EVENTS", int, 256,
         "eviction-pressure events kept in the HBM ledger ring")
register("OG_HBM_DRIFT_PCT", float, 25.0,
         "reconcile tolerance: tracked-vs-backend HBM drift beyond "
         "max(64MiB, this percent) flags and counts")
register("OG_SCHED_CALIB", str, "1",
         "scheduler cost-model calibration: `0` = off (PR 4 "
         "byte-identical), `record` = record estimate-vs-actual "
         "only, `1` (default since round 16) = also apply the "
         "learned per-class bias to admission charges")

# --- device fault domain (ops/devicefault.py, ops/pipeline.py)
register("OG_DEVICE_RETRY", int, 2,
         "bounded retries for TRANSIENT-classified device launch "
         "errors (0 disables retry; OOM gets its pressure-ladder "
         "retry regardless)")
register("OG_DEVICE_RETRY_BACKOFF_MS", float, 25.0,
         "base backoff between transient device retries (jittered "
         "exponential, deadline-clamped)")
register("OG_DEVICE_BREAKER", bool, True,
         "per-route device circuit breakers; 0 = classify/retry only, "
         "never trip a route to its host fallback", scope="cached")
register("OG_DEVICE_BREAKER_THRESHOLD", int, 3,
         "consecutive classified device failures on one route before "
         "its breaker opens (route falls back to the byte-identical "
         "host path)")
register("OG_DEVICE_BREAKER_COOLDOWN_S", float, 5.0,
         "base breaker cooldown before a half-open probe re-tries the "
         "device route (doubles per consecutive trip, capped 8x)")
register("OG_DEVICE_HANG_S", float, 30.0,
         "hung-launch watchdog: a streamed background pull stuck "
         "longer than this (and past any tighter request deadline) is "
         "abandoned — gate slot + pipeline HBM bytes reclaimed, route "
         "breaker charged; <= 0 disables the bound")
register("OG_HBM_PRESSURE_MB", int, 0,
         "admission HBM-pressure limit: estimated query HBM plus live "
         "tracked device bytes (ledger device_cache+pipeline tiers) "
         "above this sheds 429 `hbm_pressure` with Retry-After; "
         "0 disables the check")
register("OG_HBM_PRESSURE_EVICT", bool, True,
         "OOM pressure ladder may evict the device-cache tier (ledger-"
         "mirrored) before the post-relief retry; 0 = shrink the "
         "in-flight gate only")

# --- compile-cache / transfer audit (ops/compileaudit.py)
register("OG_COMPILE_AUDIT", bool, True,
         "runtime compile auditor: record every XLA compile (kernel + "
         "shape signature) off jax's compile log for the recompile-"
         "budget and /debug/vars compile surfaces; 0 = no hook",
         scope="cached")

# Per-bench-shape recompile budgets (ops/compileaudit.py gate, run by
# bench.py --phase smoke and scripts/perf_smoke.sh): COLD = compiles a
# first run of the shape may trigger (every kernel compiles once per
# shape class — plan/lattice/pack/finalize variants included); WARM is
# always ZERO (a repeat of the same shape re-compiling ANYTHING is the
# hot-loop retrace class that erased the r05 1m win). Declared here,
# next to the knob registry, so perf knobs and perf budgets live on
# one page; drift (a new kernel variant pushing a shape over budget)
# fails the gate and is either a hazard to fix or a reviewed bump of
# this table in the same change.
RECOMPILE_BUDGETS: dict = {
    # smoke shapes (48 hosts x 1h, scripts/perf_smoke.sh): the first
    # shape pays the tiny-op first-touch compiles plus the round-14
    # device-decode classes (DFOR unpack/finish, times/validity/const
    # expanders, limb decompose, permute/slice — measured 14 cold on
    # "1h", 0 on the warm shapes). 24 leaves room for route variants
    # (prefix/lattice/pack) and extra DFOR width classes on other
    # datasets/backends while still catching the failure mode that
    # matters: a per-value shape-class explosion compiles O(slabs)
    # kernels and blows straight past this.
    # round 17 (+4): the fused whole-plan programs compile one class
    # per (shape, lattice-route, transport) combination on a shape's
    # first run — the smoke sweep touches both lattice routes and the
    # forced-lattice variant, so a shape can pay a handful of fused
    # cold compiles on top of the staged kernel classes (which still
    # compile: the escape-hatch configs run them in the same sweep).
    "1h": 28, "1m": 28, "cfg1": 28,
    # answer-sized D2H shapes (PR 12): the ORDER BY+LIMIT heavy shape
    # pays the finalize epilogue + topk cut kernels on top of the
    # lattice/block variants; the percentile shape pays the cellsort +
    # order-stat finalize pair. Same headroom rule as above, +4 for
    # the round-17 fused program classes.
    "1m-topk": 20, "pctl": 20,
    # any undeclared window label: strict by default
    "default": 0,
}

# --- flight recorder / tracing (utils/tracing.py, http/server.py)
register("OG_TRACE_SAMPLE", float, 0.05,
         "head-sampling probability for the query/write flight "
         "recorder (1 = trace everything, 0 = off; slow/failed/shed/"
         "killed requests are retained in the slow ring regardless)")
register("OG_TRACE_RING", int, 64,
         "completed traces kept in the flight-recorder recent ring "
         "(/debug/requests, /debug/trace?id=)", scope="module-init")
register("OG_SMOKE_TRACE_OVERHEAD_PCT", float, 3.0,
         "perf_smoke tracing gate: max e2e overhead (percent) of a "
         "live span tree vs untraced on the 1h shape")
register("OG_SMOKE_OBS_OVERHEAD_PCT", float, 3.0,
         "perf_smoke observatory gate: max e2e overhead (percent) of "
         "the fast-ticking utilization sampler + ctx attribution + "
         "calibration recording vs the plain path on the 1h shape")
register("OG_SLOW_QUERY_MS", float, 0.0,
         "slow-query threshold in ms (logged + kept in the slow "
         "trace ring); 0 = use [http] slow_query_threshold from "
         "the config (default 10s)")

# --- HTTP result path (http/serializer.py)
register("OG_STREAM_JSON", bool, True,
         "chunked streaming JSON/CSV responses (byte-identical to "
         "the buffered route)")
register("OG_STREAM_QUEUE", int, 8,
         "bounded piece queue between serializer and socket writer")

# --- PromQL device path (promql/engine.py; module-init)
register("OG_PROM_DEVICE_MIN_ROWS", int, 16_000_000,
         "rows below this fold on host numpy (device bucket kernel "
         "pays 15 transfer round trips)", scope="module-init")
register("OG_PROM_DEVICE_CHUNK_ROWS", int, 16_000_000,
         "rows per device launch in the chunked PromQL fold",
         scope="module-init")

# --- storage / index / ingest
register("OG_ENCODE_WORKERS", str, "",
         "TSSP flush encode pool size; unset = auto (min(4, cores), "
         "serial for small flushes) — DFOR made encode numpy-bound so "
         "the pool now wins; `1` pins the serial pre-PR-20 behavior")
register("OG_FLIGHT_COLUMNAR", bool, True,
         "Arrow Flight DoPut columnar fast lane: land Arrow columns "
         "directly in Engine.write_record_batch (no per-row "
         "PointRow materialization); 0 = row-wise batch_to_rows path")
register("OG_WAL_GROUP_COMMIT_US", int, 0,
         "WAL group commit window in microseconds: concurrent "
         "writers coalesce into one fsync (leader waits this long "
         "for followers before syncing); 0 = every write syncs "
         "itself (pre-PR-20 behavior)")
register("OG_INGEST_WORKERS", int, 4,
         "bench --phase ingest: concurrent open-loop ingest writer "
         "threads")
register("OG_ENCODE_SERIAL_CUTOFF", int, 32,
         "flushes with <= this many series stay serial even when "
         "OG_ENCODE_WORKERS > 1 (pool startup would dominate); the "
         "crash harness lowers it to force the parallel publish "
         "path on its small deterministic flushes")
register("OG_TSI_SNAP_BYTES", int, 4 << 20,
         "TSI log-size threshold that triggers an index snapshot",
         scope="module-init")

# --- storage crash consistency (storage/wal.py, tests/crashharness.py)
register("OG_WAL_SALVAGE", bool, False,
         "WAL replay scans forward past a bad-CRC frame to the next "
         "valid frame instead of stopping the segment (the bad region "
         "is still quarantined); off = stop at the first bad frame "
         "(the corrupt tail is quarantined and the segment truncated "
         "to its valid prefix)")
register("OG_STORAGE_QUARANTINE", bool, True,
         "quarantine corrupt storage artifacts (WAL tails, unreadable "
         "TSSP/colstore files) to <name>.corrupt instead of leaving "
         "them in place; 0 = log-only (pre-PR-10 behavior)")
register("OG_CRASH_OK", bool, False,
         "arming guard for the `crash` failpoint action (SIGKILLs the "
         "process): only crash-harness subprocesses set it")
register("OG_CRASH_HARNESS_S", float, 120.0,
         "crash harness: wall budget per crash-cycle subprocess "
         "before the parent declares it hung and fails the cycle")

# --- cluster
register("OG_READER_ROUTING", bool, True,
         "replica-aware reader routing; 0 = primary-only reads",
         scope="module-init")
register("OG_MAX_FAILED_STORES", int, 0,
         "write fan-out tolerates this many failed stores before the "
         "write errors", scope="module-init")

# --- native loader
register("OG_NATIVE_LIB", str, "",
         "override path of the native libogn.so (sanitizer builds: "
         "scripts/sanitize_tests.sh points this at libogn-san.so)")

# --- test harness
register("OG_TEST_STACKDUMP_S", float, 300.0,
         "per-test watchdog that dumps all thread stacks on a hang; "
         "0 disables")
register("OG_LOCKRANK", str, "",
         "lock-rank runtime checker: `1` force-on, `0` force-off, "
         "unset = on under pytest only (tests/conftest.py)")

# --- bench harness (bench.py, benchmarks/, __graft_entry__.py)
register("OG_BENCH_HOSTS", int, 16000, "bench: TSBS host count")
register("OG_BENCH_HOURS", float, 12.0, "bench: hours of data")
register("OG_BENCH_CS_HOSTS", int, 2000,
         "bench: colstore phase host count")
register("OG_BENCH_PROM_SERIES", int, 1_000_000,
         "bench: PromQL remote-read series count")
register("OG_BENCH_SCALE_ROWS", int, 500_000_000,
         "bench: synthetic scale phase row count")
register("OG_BENCH_CONC_HOSTS", str, "",
         "bench: concurrent phase host count (unset = min(hosts, "
         "1000))")
register("OG_BENCH_SUST_QPS", float, 40.0,
         "bench sustained phase: open-loop offered arrival rate "
         "(requests/second over HTTP)")
register("OG_BENCH_SUST_REQS", int, 1200,
         "bench sustained phase: total requests per measured run")
register("OG_BENCH_SUST_WORKERS", int, 64,
         "bench sustained phase: HTTP client worker threads (the "
         "open-loop schedule charges wait-for-worker time to latency)")
register("OG_BENCH_SUST_HEAVY_PCT", float, 2.0,
         "bench sustained phase: percent of requests that are the "
         "heavy (1m-grid) shape; the rest are dashboard shapes")
register("OG_BENCH_SUST_SLO_MS", float, 0.0,
         "bench sustained phase: dashboard p99 SLO gate in ms "
         "(0 = report only, no gate)")
register("OG_BENCH_EST_SUST", int, 420,
         "bench: sustained phase budget s")
register("OG_BENCH_EST_PROM", int, 1300, "bench: prom phase budget s")
register("OG_BENCH_EST_CS", int, 420, "bench: colstore budget s")
register("OG_BENCH_EST_CONC", int, 420, "bench: concurrent budget s")
register("OG_BENCH_EST_SCALE", int, 3000, "bench: scale budget s")
register("OG_BENCH_EST_INGEST", int, 240, "bench: ingest budget s")
register("OG_BENCH_INGEST_BATCHES", int, 24,
         "bench --phase ingest: 65536-row Arrow batches per rep")
register("OG_BENCH_BUDGET_S", float, 1800.0,
         "bench: total wall budget the orchestrator sub-divides")
register("OG_SERIES_BENCH_N", int, 1_000_000,
         "series-index microbench: series count")
register("OG_SERIES_BENCH_PROM_N", str, "",
         "series-index microbench: prom series count (unset = all)")
register("OG_DRYRUN_SERIES", int, 100_000,
         "driver dryrun: series count")
register("OG_DRYRUN_POINTS", int, 104, "driver dryrun: points/series")
