from .errors import GeminiError, ErrInvalidLineProtocol, ErrTypeConflict
from .logger import get_logger
from . import failpoint
