"""Durable file operations (role of the reference's lib/fileops/
fsync discipline around rename-publish: engine/immutable writers fsync
the file AND the directory before a .tmp swap becomes the published
name).

``os.replace`` alone is NOT durable on Linux: the rename is a
directory mutation, and until the parent directory is fsynced a crash
can roll it back — the published file vanishes (or the pre-rename
name reappears) after restart, even though the file's own bytes were
fsynced.  Every publish-by-rename in ``storage/`` must ride
``durable_replace`` (oglint rule R8 enforces this); the same applies
to newly created WAL segments, whose directory entry is what makes an
fsynced frame findable after a crash (``fsync_dir``).

The helpers are deliberately tiny and dependency-free: storage-layer
modules import them at the top of their publish paths, and the crash
harness (tests/crashharness.py) SIGKILLs processes between these calls
to prove the recovery contract.
"""

from __future__ import annotations

import os

from .stats import register_counters, bump

FILEOPS_STATS = register_counters("fileops", {
    "durable_replaces": 0, "dir_fsyncs": 0, "dir_fsync_errors": 0,
    "file_fsyncs": 0})


def fsync_dir(path: str) -> bool:
    """fsync a DIRECTORY so renames/creates/unlinks inside it survive a
    crash. Best-effort: some filesystems (and non-POSIX platforms)
    refuse O_RDONLY opens of directories — counted, never fatal (the
    caller's data-file fsync already happened; losing the rename is
    the pre-PR-10 behavior, not a new failure mode)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        bump(FILEOPS_STATS, "dir_fsync_errors")
        return False
    try:
        os.fsync(fd)
        bump(FILEOPS_STATS, "dir_fsyncs")
        return True
    except OSError:
        bump(FILEOPS_STATS, "dir_fsync_errors")
        return False
    finally:
        os.close(fd)


def fsync_file(path: str) -> None:
    """fsync an existing file by path (for copies made via shutil,
    which never sync)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
        bump(FILEOPS_STATS, "file_fsyncs")
    finally:
        os.close(fd)


def durable_replace(src: str, dst: str, sync_src: bool = False) -> None:
    """``os.replace(src, dst)`` with rename durability: optionally
    fsync ``src`` first (callers that already fsynced before closing
    skip it), then fsync ``dst``'s parent directory so the rename
    itself survives a crash. The one sanctioned rename-publish in
    ``storage/`` (oglint R8)."""
    if sync_src:
        fsync_file(src)
    os.replace(src, dst)  # oglint: disable=R801
    bump(FILEOPS_STATS, "durable_replaces")
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def durable_write(path: str, data: bytes) -> None:
    """Atomically publish ``data`` at ``path``: write to ``path.tmp``,
    fsync the file, durable-rename into place. Used for small metadata
    files (quarantine markers, recovery artifacts)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    durable_replace(tmp, path)
