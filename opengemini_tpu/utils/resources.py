"""Resource allocator: caps on concurrent queries and per-query series
counts (role of reference lib/resourceallocator/resource_allocator.go,
which meters series/shard parallelism resources per query type)."""

from __future__ import annotations

import threading
import time

from . import deadline as _deadline
from .errors import ErrQueryError, ErrQueryTimeout


class ResourceExhausted(ErrQueryError):
    pass


class BoundedGate:
    """Counting semaphore with a bounded wait queue: at most `limit`
    holders; at most `max_queued` waiters; waiters past the queue cap or
    the timeout are rejected (the reference rejects rather than queues
    unboundedly — resource_allocator.go).

    A queued waiter is no longer deaf while parked: it waits
    ``min(remaining_deadline, timeout_s)`` instead of a fixed 30s, and
    an optional ``ctx`` (QueryContext) is polled so KILL QUERY ejects a
    QUEUED query immediately (it used to be unkillable until it won a
    slot). The query/scheduler subsystem replaces this gate when
    OG_SCHED is on; this stays as the OG_SCHED=0 fallback."""

    def __init__(self, limit: int, max_queued: int = 64,
                 timeout_s: float = 30.0):
        self.limit = limit
        self.max_queued = max_queued
        self.timeout_s = timeout_s
        self._sem = threading.BoundedSemaphore(limit) if limit > 0 else None
        self._queued = 0
        self._lock = threading.Lock()

    def acquire(self, ctx=None) -> None:
        if self._sem is None:
            return
        with self._lock:
            if self._queued >= self.max_queued:
                raise ResourceExhausted(
                    f"too many queued requests (> {self.max_queued})")
            self._queued += 1
        if ctx is not None and hasattr(ctx, "mark_queued"):
            ctx.mark_queued()
        dl = _deadline.current()
        left = _deadline.remaining()
        if left is not None and left <= 0:
            with self._lock:
                self._queued -= 1
            raise ErrQueryTimeout(
                "query deadline exceeded while queued")
        budget = self.timeout_s if left is None \
            else min(self.timeout_s, left)
        t0 = time.monotonic()
        enq_ns = time.perf_counter_ns()
        try:
            # poll in short slices so a queued query stays killable and
            # deadline-honoring (a blocking 30s semaphore wait was both
            # kill- and deadline-blind)
            while True:
                left = budget - (time.monotonic() - t0)
                if left <= 0:
                    if dl is not None and dl.expired:
                        raise ErrQueryTimeout(
                            "query deadline exceeded while queued "
                            f"(budget {dl.budget_s:.3g}s)")
                    raise ResourceExhausted(
                        f"timed out waiting for a slot "
                        f"({self.limit} concurrent)")
                if self._sem.acquire(timeout=min(0.05, left)):
                    if ctx is not None and hasattr(ctx, "mark_running"):
                        ctx.mark_running(
                            time.perf_counter_ns() - enq_ns)
                    return
                if ctx is not None and getattr(ctx, "killed", False):
                    raise ErrQueryError(
                        f"query {getattr(ctx, 'qid', '?')} killed "
                        "while queued")
        finally:
            with self._lock:
                self._queued -= 1

    def release(self) -> None:
        if self._sem is not None:
            self._sem.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class QueryResources:
    """Per-process limits wired from DataConfig: concurrent queries and
    series touched by one query."""

    def __init__(self, max_concurrent_queries: int = 0,
                 max_queued_queries: int = 64,
                 max_series_per_query: int = 0):
        self.queries = BoundedGate(max_concurrent_queries,
                                   max_queued_queries)
        self.max_series_per_query = max_series_per_query

    def check_series(self, n: int) -> None:
        if self.max_series_per_query and n > self.max_series_per_query:
            raise ResourceExhausted(
                f"query touches {n} series > limit "
                f"{self.max_series_per_query}")
