"""Resource allocator: caps on concurrent queries and per-query series
counts (role of reference lib/resourceallocator/resource_allocator.go,
which meters series/shard parallelism resources per query type)."""

from __future__ import annotations

import threading

from .errors import ErrQueryError


class ResourceExhausted(ErrQueryError):
    pass


class BoundedGate:
    """Counting semaphore with a bounded wait queue: at most `limit`
    holders; at most `max_queued` waiters; waiters past the queue cap or
    the timeout are rejected (the reference rejects rather than queues
    unboundedly — resource_allocator.go)."""

    def __init__(self, limit: int, max_queued: int = 64,
                 timeout_s: float = 30.0):
        self.limit = limit
        self.max_queued = max_queued
        self.timeout_s = timeout_s
        self._sem = threading.BoundedSemaphore(limit) if limit > 0 else None
        self._queued = 0
        self._lock = threading.Lock()

    def acquire(self) -> None:
        if self._sem is None:
            return
        with self._lock:
            if self._queued >= self.max_queued:
                raise ResourceExhausted(
                    f"too many queued requests (> {self.max_queued})")
            self._queued += 1
        try:
            if not self._sem.acquire(timeout=self.timeout_s):
                raise ResourceExhausted(
                    f"timed out waiting for a slot "
                    f"({self.limit} concurrent)")
        finally:
            with self._lock:
                self._queued -= 1

    def release(self) -> None:
        if self._sem is not None:
            self._sem.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


class QueryResources:
    """Per-process limits wired from DataConfig: concurrent queries and
    series touched by one query."""

    def __init__(self, max_concurrent_queries: int = 0,
                 max_queued_queries: int = 64,
                 max_series_per_query: int = 0):
        self.queries = BoundedGate(max_concurrent_queries,
                                   max_queued_queries)
        self.max_series_per_query = max_series_per_query

    def check_series(self, n: int) -> None:
        if self.max_series_per_query and n > self.max_series_per_query:
            raise ResourceExhausted(
                f"query touches {n} series > limit "
                f"{self.max_series_per_query}")
