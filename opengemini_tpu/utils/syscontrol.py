"""Runtime admin plane (role of reference lib/syscontrol/syscontrol.go +
`/debug/ctrl` HTTP handler and engine/sysctrl.go: runtime knobs toggled
over HTTP and consulted by the engine/services).

Commands (query params: ?mod=<cmd>[&switchon=true|false]):
    flush          — flush all memtables to TSSP now
    snapshot       — alias of flush (reference snapshot ctrl)
    readonly       — reject writes while on
    compaction     — enable/disable background compaction
    purgecache     — drop the decoded-block read cache
    verbose        — debug logging on/off
    stat           — return current flag states
    failpoint      — arm/disarm fault injection (&point=&action=
                     [&arg=][&maxhits=N][&pct=P]); no point: list
    circuitbreaker — per-peer breaker states; &addr=<host:port>
                     &switchon=true trips it, =false resets it
    devicebreaker  — per-route DEVICE breaker states (device fault
                     domain, ops/devicefault.py) + confiscated gate
                     permits; &route=<block|lattice|dense|segagg|
                     finalize|pipeline> &switchon=true force-opens it
                     (route serves from its host fallback), =false
                     closes it; &action=reset drops all breaker state
                     and returns gate permits
    scheduler      — device query scheduler: no action returns the
                     counters; &action=pause|resume|drain[&timeout=S]
                     (pause stops granting slots — running queries
                     finish; drain waits until in-flight work ends)
    profile        — one-shot jax.profiler device capture:
                     &action=start[&dir=/path] opens a trace,
                     &action=stop closes it (the deep-dive companion
                     of the always-on flight recorder: sampled traces
                     show WHICH pull was slow, the profiler shows why
                     at the device level)
"""

from __future__ import annotations

import logging
import threading

from . import get_logger

log = get_logger(__name__)


class SysControl:
    def __init__(self, engine=None, stats_pusher=None):
        self.engine = engine
        self.stats_pusher = stats_pusher
        self._lock = threading.Lock()
        self.readonly = False
        self.compaction_enabled = True
        self.verbose = False
        self.profile_dir: str | None = None   # live jax.profiler dir

    def _flag(self, params: dict) -> bool:
        v = str(params.get("switchon", "true")).lower()
        return v in ("1", "true", "on", "yes")

    def handle(self, mod: str, params: dict) -> tuple[int, dict]:
        with self._lock:
            if mod in ("flush", "snapshot"):
                if self.engine is None:
                    return 400, {"error": "no local engine"}
                self.engine.flush_all()
                return 200, {"flush": "done"}
            if mod == "readonly":
                self.readonly = self._flag(params)
                return 200, {"readonly": self.readonly}
            if mod == "compaction":
                self.compaction_enabled = self._flag(params)
                return 200, {"compaction": self.compaction_enabled}
            if mod == "purgecache":
                from ..ops import devicecache
                from ..storage import readcache
                readcache.global_cache().purge()
                devicecache.global_cache().purge()
                devicecache.host_cache().purge()
                return 200, {"purgecache": "done"}
            if mod == "verbose":
                self.verbose = self._flag(params)
                logging.getLogger("opengemini_tpu").setLevel(
                    logging.DEBUG if self.verbose else logging.INFO)
                return 200, {"verbose": self.verbose}
            if mod == "stat":
                from ..cluster import transport
                return 200, {"readonly": self.readonly,
                             "compaction": self.compaction_enabled,
                             "verbose": self.verbose,
                             "circuit_breakers":
                                 transport.breaker_stats()}
            if mod == "circuitbreaker":
                # per-peer breaker visibility + operator override
                # (tripping drains a peer; resetting re-probes it now).
                # The override requires an EXPLICIT switchon param —
                # addr alone is a read and must not mutate state
                from ..cluster import transport
                addr = params.get("addr")
                if not addr:
                    return 200, {"circuit_breakers":
                                 transport.breaker_stats()}
                if "switchon" not in params:
                    snap = transport.breaker_stats().get(addr)
                    if snap is None:
                        return 404, {"error":
                                     f"no breaker for {addr!r}"}
                    return 200, {"addr": addr, **snap}
                br = transport.breaker_for(addr)
                br.force(self._flag(params))
                return 200, {"addr": addr, **br.snapshot()}
            if mod == "devicebreaker":
                # per-route device breaker visibility + operator
                # override (forcing open parks the route on its byte-
                # identical host fallback; closing re-probes the
                # device now). Same explicit-switchon contract as the
                # per-peer transport breakers above
                from ..ops import devicefault as df
                route = params.get("route")
                if params.get("action") == "reset":
                    df.reset_breakers()
                    return 200, {"devicebreaker": "reset"}
                if not route:
                    return 200, {"device_breakers":
                                 df.breaker_snapshot(),
                                 "gate_permits_shrunk":
                                 df.shrunk_permits()}
                if route not in df.ROUTES:
                    return 404, {"error": f"unknown device route "
                                 f"{route!r} (routes: "
                                 f"{', '.join(df.ROUTES)})"}
                if "switchon" not in params:
                    return 200, {"route": route,
                                 **df.breaker_for(route).snapshot()}
                br = df.breaker_for(route)
                br.force(self._flag(params))
                return 200, {"route": route, **br.snapshot()}
            if mod == "scheduler":
                # serving-runtime admin plane (query/scheduler.py):
                # stats snapshot, pause/resume of slot grants + launch
                # dispatch, drain-to-idle for maintenance windows
                from ..query import scheduler as qs
                sch = qs.get_scheduler()
                action = params.get("action", "")
                out = {"enabled": qs.enabled()}
                if action == "pause":
                    sch.pause()
                elif action == "resume":
                    sch.resume()
                elif action == "drain":
                    try:
                        t = float(params.get("timeout", "30"))
                    except ValueError:
                        t = 30.0
                    out["drained"] = sch.drain(t)
                elif action:
                    return 400, {"error":
                                 f"unknown scheduler action {action!r}"}
                out["scheduler"] = sch.snapshot()
                return 200, out
            if mod == "profile":
                # one-shot device-level capture (jax.profiler): the
                # flight recorder's deep-dive hook. start/stop are
                # idempotent-checked so a crashed client can't wedge
                # the profiler in a half-open state silently
                action = params.get("action", "start")
                if action == "start":
                    if self.profile_dir is not None:
                        return 400, {"error": "profiler already "
                                     "capturing to "
                                     f"{self.profile_dir!r}; stop it "
                                     "first"}
                    pdir = params.get("dir") or "/tmp/og_profile"
                    try:
                        import jax
                        jax.profiler.start_trace(pdir)
                    except Exception as e:
                        return 400, {"error":
                                     f"profiler start failed: {e}"}
                    self.profile_dir = pdir
                    return 200, {"profile": "started", "dir": pdir}
                if action == "stop":
                    if self.profile_dir is None:
                        return 400, {"error": "no capture in flight"}
                    pdir, self.profile_dir = self.profile_dir, None
                    try:
                        import jax
                        jax.profiler.stop_trace()
                    except Exception as e:
                        return 400, {"error":
                                     f"profiler stop failed: {e}"}
                    return 200, {"profile": "stopped", "dir": pdir}
                if action == "stat":
                    return 200, {"capturing": self.profile_dir
                                 is not None,
                                 "dir": self.profile_dir}
                return 400, {"error":
                             f"unknown profile action {action!r}"}
            if mod == "failpoint":
                # arm/disarm fault-injection points (reference failpoint
                # toggles over the syscontrol admin plane, SURVEY.md §5)
                from . import failpoint as fp
                point = params.get("point")
                if not point:
                    return 200, {"failpoints": fp.list_points()}
                if not self._flag(params):
                    fp.disable(point)
                    return 200, {"failpoint": point, "enabled": False}
                action = params.get("action", "error")
                if action == "call":
                    # call takes a python callable — tests-only, not
                    # representable as an HTTP string param
                    return 400, {"error":
                                 "action 'call' is not available "
                                 "over HTTP"}
                try:
                    fp.enable(point, action, params.get("arg"),
                              maxhits=params.get("maxhits"),
                              pct=params.get("pct"))
                except ValueError as e:
                    return 400, {"error": str(e)}
                return 200, {"failpoint": point, "enabled": True}
            return 400, {"error": f"unknown syscontrol mod {mod!r}"}
