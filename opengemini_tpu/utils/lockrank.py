"""Lock-rank checker for the device hot path's lock web.

The dispatcher thread, the pull pool, the HTTP handler threads and the
stats pusher all meet in four locks: the scheduler lock
(query/scheduler.py), the device/host cache locks (ops/devicecache.py),
the pipeline bookkeeping locks (ops/pipeline.py) and the stats counter
lock (utils/stats.py). Today their nesting is deadlock-free by
convention only — e.g. ``bump()`` (stats) runs inside ``with
self._lock`` blocks of the scheduler, so stats must stay INNERMOST
forever. This module turns the convention into a checked invariant:

- Every lock in the web is a ``RankedLock``/``RankedRLock`` with an
  explicit rank. Outer locks get LOW ranks; a thread may only acquire
  a lock whose rank is STRICTLY greater than the highest rank it
  holds. Any cycle in lock acquisition would need a rank inversion
  somewhere, so rank-clean runs are deadlock-free by construction.
- The checker is OFF in production (a pass-through around
  threading.Lock — one attribute hop per acquire) and enabled under
  tests (tests/conftest.py) or via OG_LOCKRANK=1. Violations raise
  ``LockRankError`` with both lock names — a deterministic test
  failure instead of a wedged tier-1 run.
- A *blocking re-acquire of a non-reentrant lock by its owner* — the
  classic self-deadlock — raises immediately instead of hanging.
- oglint rule R4 (opengemini_tpu/lint/lockrank_rule.py) is the static
  half: it scans ``with``-blocks on ranked locks for blocking calls
  (time.sleep, Future.result, device pulls) and for nested
  acquisitions that contradict the declared ranks.

Ranks (gaps left for future locks):
    SCHED_HANDLE(5) < SCHED(10) < RESULTCACHE(12) < DEVCACHE_FILL(15)
    < DEVCACHE(20) < PIPELINE_POOL(25) < PIPELINE(30) < HBM(35)
    < STATS(40)
"""

from __future__ import annotations

import threading

__all__ = ["RANK_SCHED_HANDLE", "RANK_SCHED", "RANK_RESULTCACHE",
           "RANK_DEVCACHE_FILL",
           "RANK_DEVCACHE", "RANK_PIPELINE_POOL", "RANK_PIPELINE",
           "RANK_HBM", "RANK_STATS", "LockRankError", "RankedLock",
           "RankedRLock", "enable", "enabled", "held_ranks"]

RANK_SCHED_HANDLE = 5     # scheduler singleton construction
RANK_SCHED = 10           # QueryScheduler._lock (admission + dispatch)
RANK_RESULTCACHE = 12     # query/resultcache.py LRU (entry get/store;
# may book its ledger tier (HBM 35) and bump stats (40) while held)
RANK_DEVCACHE_FILL = 15   # decoded-plane base-fill stripes
RANK_DEVCACHE = 20        # DeviceBlockCache._lock (HBM + host tiers)
RANK_PIPELINE_POOL = 25   # shared pull-pool construction
RANK_PIPELINE = 30        # StreamingPipeline._lock (per-query)
RANK_HBM = 35             # ops/hbm.py HBMLedger (called from cache/
# pipeline critical sections; may still bump the innermost stats)
RANK_STATS = 40           # utils.stats.COUNTER_LOCK — innermost


class LockRankError(RuntimeError):
    """A lock acquisition violated the declared rank order (or an
    owner blocked on its own non-reentrant lock)."""


_tls = threading.local()


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def held_ranks() -> list[tuple[int, str]]:
    """(rank, name) of locks the calling thread holds, outermost
    first — diagnostic surface for tests and the static scan's
    fixtures."""
    return [(lk.rank, lk.name) for lk in _held()]


from . import knobs as _knobs  # noqa: E402  (leaf module, no cycle)

_enabled = _knobs.get_raw("OG_LOCKRANK") == "1"


def enable(on: bool = True) -> None:
    """Flip the runtime checker process-wide (tests/conftest.py turns
    it on for the whole tier-1 run)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class RankedLock:
    """threading.Lock with a declared rank, checked when the runtime
    checker is enabled. Supports the Condition protocol (Condition
    re-enters through acquire/release, which keeps the held-stack
    accurate across ``wait``)."""

    _reentrant = False

    def __init__(self, name: str, rank: int):
        self.name = name
        self.rank = int(rank)
        self._lock = self._make_lock()
        self._owner: int | None = None
        self._depth = 0

    def _make_lock(self):
        return threading.Lock()

    # -- checking ------------------------------------------------------

    def _check(self, blocking: bool) -> None:
        if not blocking:
            # try-acquire cannot deadlock — and Condition._is_owned
            # probes owned locks with acquire(False), which must stay
            # a plain False, not an error
            return
        me = threading.get_ident()
        if self._owner == me:
            # only the owner can observe its own ident here, so this
            # read is race-free for the thread it matters to
            if self._reentrant:
                return     # owner re-entry is legal at ANY stack depth
            raise LockRankError(
                f"re-acquire of non-reentrant lock {self.name!r} "
                "(rank {}) by its owner thread — guaranteed "
                "self-deadlock".format(self.rank))
        held = _held()
        if held:
            top = held[-1]
            if self.rank <= top.rank:
                raise LockRankError(
                    f"lock rank violation: acquiring {self.name!r} "
                    f"(rank {self.rank}) while holding {top.name!r} "
                    f"(rank {top.rank}) — ranks must strictly "
                    "increase inward")

    # -- lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _enabled:
            self._check(blocking)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._depth += 1
            if _enabled:
                _held().append(self)
        return got

    def release(self) -> None:
        self._depth -= 1
        if self._depth <= 0:
            self._owner = None
        # pop UNCONDITIONALLY: a lock acquired while the checker was
        # enabled but released after enable(False) must not leave a
        # phantom held-entry that poisons the thread with spurious
        # rank errors once the checker comes back on
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.name} rank={self.rank}>"


class RankedRLock(RankedLock):
    """Reentrant variant: the owner may re-acquire freely (no rank
    check against itself); distinct-lock rank order still applies."""

    _reentrant = True

    def _make_lock(self):
        return threading.RLock()

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        if self._lock.acquire(blocking=False):
            self._lock.release()
            return False
        return True
