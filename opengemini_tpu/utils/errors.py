"""Error types (analog of reference lib/errno — coded errors, but pythonic).

The reference keeps a numeric errno registry (lib/errno/errno.go); here we use
an exception hierarchy with an optional numeric code for API compatibility.
"""


class GeminiError(Exception):
    """Base error for opengemini_tpu."""

    code = 0

    def __init__(self, msg: str = "", code: int | None = None):
        super().__init__(msg or self.__class__.__name__)
        if code is not None:
            self.code = code


class ErrInvalidLineProtocol(GeminiError):
    code = 1001


class ErrTypeConflict(GeminiError):
    """Field written with a different type than its schema (reference:
    engine/mutable/ts_table.go type-conflict checks)."""

    code = 1002


class ErrDatabaseNotFound(GeminiError):
    code = 2001


class ErrMeasurementNotFound(GeminiError):
    code = 2002


class ErrRetentionPolicyNotFound(GeminiError):
    code = 2003


class ErrShardNotFound(GeminiError):
    code = 2004


class ErrQueryError(GeminiError):
    code = 3001


class ErrQueryKilled(GeminiError):
    code = 3002


class ErrQueryTimeout(GeminiError):
    code = 3003
