"""Typed TOML configuration (role of the reference's config system:
`lib/config/config.go:55` Config interface, `lib/config/store.go:78` TSStore,
`lib/config/sql.go:72` TSSql, `lib/config/meta.go:72` TSMeta, and the
section layout of `config/openGemini.conf`).

One file configures any node role; each section is a dataclass with
defaults, parsed with stdlib tomllib, validated on load. Durations accept
either numbers (seconds) or influx duration strings ("10s", "1h").
"""

from __future__ import annotations

import os

try:
    import tomllib                       # 3.11+
except ModuleNotFoundError:              # 3.10: the tomllib backport
    import tomli as tomllib
from dataclasses import dataclass, field, fields

from .errors import GeminiError

NS = 10**9


class ConfigError(GeminiError):
    pass


def _duration_ns(v, what: str) -> int:
    """Accept seconds (int/float) or a duration string → ns."""
    if isinstance(v, bool):
        raise ConfigError(f"{what}: expected duration, got bool")
    if isinstance(v, (int, float)):
        return int(v * NS)
    if isinstance(v, str):
        from ..query.influxql import ParseError, parse_duration
        try:
            return parse_duration(v)
        except ParseError as e:
            raise ConfigError(f"{what}: {e}")
    raise ConfigError(f"{what}: expected duration, got {type(v).__name__}")


def _size_bytes(v, what: str) -> int:
    """Accept bytes (int) or a size string ("256m", "4g", "512k")."""
    if isinstance(v, int) and not isinstance(v, bool):
        return v
    if isinstance(v, str):
        s = v.strip().lower()
        mult = 1
        if s and s[-1] in "kmg":
            mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[s[-1]]
            s = s[:-1]
        try:
            return int(float(s) * mult)
        except ValueError:
            raise ConfigError(f"{what}: bad size {v!r}")
    raise ConfigError(f"{what}: expected size, got {type(v).__name__}")


@dataclass
class CommonConfig:
    """[common] — reference `config/openGemini.conf` [common]."""
    meta_join: list[str] = field(default_factory=list)
    node_id: str = ""
    cpu_num: int = 0                      # 0 = auto


@dataclass
class HTTPConfig:
    """[http] — reference [http] bind-address, auth, limits."""
    bind_address: str = "127.0.0.1:8086"
    auth_enabled: bool = False
    flux_enabled: bool = True             # reference: flux-enabled
    max_body_size: int = 100 * 1024 * 1024
    # slow-query threshold: queries over this wall are logged, kept in
    # /debug/vars slow_log and retained in the flight recorder's slow
    # ring (http/server._slow_threshold_ns; OG_SLOW_QUERY_MS overrides)
    slow_query_threshold_ns: int = 10 * NS
    flight_address: str = ""              # arrow-flight-style ingest

    @property
    def host(self) -> str:
        return self.bind_address.rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        return int(self.bind_address.rsplit(":", 1)[1])


@dataclass
class DataConfig:
    """[data] — reference [data] store dirs, wal, compaction, cache."""
    store_data_dir: str = "./data"
    wal_sync: bool = False
    wal_compression: str = "zstd"         # zstd | lz4 | none
    shard_duration_ns: int = 24 * 3600 * NS
    flush_bytes: int = 256 * 1024 * 1024
    segment_size: int = 8192
    compact_enabled: bool = True
    read_cache_bytes: int = 256 * 1024 * 1024
    max_concurrent_queries: int = 0       # 0 = unlimited
    max_queued_queries: int = 64
    max_series_per_query: int = 0         # 0 = unlimited
    # end-to-end request budgets (utils.deadline): one budget per HTTP
    # query/write, consumed across every scatter hop and retry — a slow
    # store spends the remainder, never a fresh per-call timeout
    query_timeout_ns: int = 60 * NS       # 0 = unbounded
    write_timeout_ns: int = 30 * NS       # 0 = unbounded
    # scatter-gather degradation: how many dead stores a query may
    # tolerate, returning a `partial`-flagged result (0 = fail cleanly)
    max_failed_stores: int = 0


@dataclass
class MetaConfig:
    """[meta] — reference [meta] dirs and bind addresses."""
    bind_address: str = "127.0.0.1:8091"
    dir: str = "./meta"


@dataclass
class GossipConfig:
    """[gossip] — reference [gossip]; heartbeats stand in for serf."""
    enabled: bool = True
    heartbeat_ns: int = 1 * NS
    suspect_after_ns: int = 5 * NS


@dataclass
class LoggingConfig:
    """[logging]."""
    level: str = "info"
    path: str = ""                        # empty = stderr


@dataclass
class RetentionConfig:
    """[retention] — reference services/retention."""
    enabled: bool = True
    check_interval_ns: int = 30 * 60 * NS


@dataclass
class DownsampleConfig:
    """[downsample] — reference services/downsample."""
    enabled: bool = True
    check_interval_ns: int = 60 * 60 * NS


@dataclass
class SherlockConfig:
    """[sherlock] — reference lib/config/sherlock.go."""
    enabled: bool = False
    dump_path: str = "./sherlock"
    cpu_threshold: float = 0.9
    mem_threshold: float = 0.9
    cooldown_ns: int = 5 * 60 * NS
    check_interval_ns: int = 10 * NS


@dataclass
class IODetectorConfig:
    """[io-detector] — reference lib/iodetector."""
    enabled: bool = False
    timeout_ns: int = 60 * NS
    check_interval_ns: int = 10 * NS


@dataclass
class SpecLimitConfig:
    """[spec-limit] — reference write/query guardrails."""
    max_tag_value_len: int = 65536
    max_fields_per_point: int = 1024
    max_measurement_len: int = 1024


@dataclass
class StatsConfig:
    """[monitor]/statistics — reference lib/statisticsPusher config."""
    enabled: bool = False
    interval_ns: int = 10 * NS
    push_path: str = ""                   # file path; empty = in-memory
    store_database: str = "_internal"     # write-back db ("" = off)


@dataclass
class Config:
    common: CommonConfig = field(default_factory=CommonConfig)
    http: HTTPConfig = field(default_factory=HTTPConfig)
    data: DataConfig = field(default_factory=DataConfig)
    meta: MetaConfig = field(default_factory=MetaConfig)
    gossip: GossipConfig = field(default_factory=GossipConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    retention: RetentionConfig = field(default_factory=RetentionConfig)
    downsample: DownsampleConfig = field(default_factory=DownsampleConfig)
    sherlock: SherlockConfig = field(default_factory=SherlockConfig)
    iodetector: IODetectorConfig = field(default_factory=IODetectorConfig)
    spec_limit: SpecLimitConfig = field(default_factory=SpecLimitConfig)
    stats: StatsConfig = field(default_factory=StatsConfig)

    def engine_options(self):
        from ..storage.engine import EngineOptions
        d = self.data
        return EngineOptions(shard_duration=d.shard_duration_ns,
                             flush_bytes=d.flush_bytes,
                             wal_sync=d.wal_sync,
                             wal_compression=d.wal_compression,
                             segment_size=d.segment_size)

    def validate(self) -> None:
        if self.data.wal_compression not in ("zstd", "lz4", "none"):
            raise ConfigError(
                f"data.wal_compression: unknown codec "
                f"{self.data.wal_compression!r}")
        if self.data.segment_size <= 0:
            raise ConfigError("data.segment_size must be > 0")
        if self.data.shard_duration_ns <= 0:
            raise ConfigError("data.shard_duration must be > 0")
        if self.data.query_timeout_ns < 0 or self.data.write_timeout_ns < 0:
            raise ConfigError("data.query_timeout/write_timeout must "
                              "be >= 0 (0 disables the budget)")
        if self.data.max_failed_stores < 0:
            raise ConfigError("data.max_failed_stores must be >= 0")
        for addr_name in ("http.bind_address", "meta.bind_address"):
            sec, key = addr_name.split(".")
            v = getattr(getattr(self, sec), key)
            if ":" not in v:
                raise ConfigError(f"{addr_name}: expected host:port, "
                                  f"got {v!r}")
            try:
                int(v.rsplit(":", 1)[1])
            except ValueError:
                raise ConfigError(f"{addr_name}: bad port in {v!r}")
        lvl = self.logging.level.lower()
        if lvl not in ("debug", "info", "warning", "error"):
            raise ConfigError(f"logging.level: unknown level {lvl!r}")


# section name in TOML → (attr on Config, special-typed keys)
_SECTIONS = {
    "common": "common",
    "http": "http",
    "data": "data",
    "meta": "meta",
    "gossip": "gossip",
    "logging": "logging",
    "retention": "retention",
    "downsample": "downsample",
    "sherlock": "sherlock",
    "io-detector": "iodetector",
    "spec-limit": "spec_limit",
    "monitor": "stats",
}

# keys parsed as durations (TOML key without the _ns suffix is accepted)
_DURATION_SUFFIX = "_ns"
_SIZE_KEYS = {"max_body_size", "flush_bytes", "read_cache_bytes"}


def _apply_section(target, table: dict, section: str) -> None:
    known = {f.name: f for f in fields(target)}
    for key, value in table.items():
        attr = key.replace("-", "_")
        if attr in known:
            pass
        elif attr + _DURATION_SUFFIX in known:
            attr = attr + _DURATION_SUFFIX
        else:
            raise ConfigError(f"[{section}] unknown key {key!r}")
        f = known[attr]
        if attr.endswith(_DURATION_SUFFIX):
            value = _duration_ns(value, f"[{section}] {key}")
        elif attr in _SIZE_KEYS:
            value = _size_bytes(value, f"[{section}] {key}")
        elif f.type in ("int", int) and isinstance(value, float):
            value = int(value)
        want = {"int": int, "float": float, "str": str, "bool": bool,
                "list[str]": list}.get(f.type if isinstance(f.type, str)
                                       else f.type.__name__)
        if want is float and isinstance(value, int) \
                and not isinstance(value, bool):
            value = float(value)
        if want is not None and not isinstance(value, want) \
                or (want in (int, float) and isinstance(value, bool)):
            raise ConfigError(
                f"[{section}] {key}: expected {want.__name__}, "
                f"got {type(value).__name__}")
        setattr(target, attr, value)


def load_config(path: str | None = None,
                text: str | None = None) -> Config:
    """Load and validate a TOML config; missing file → defaults."""
    cfg = Config()
    if text is None:
        if path is None or not os.path.exists(path):
            cfg.validate()
            return cfg
        with open(path, "rb") as fp:
            data = tomllib.load(fp)
    else:
        data = tomllib.loads(text)
    for section, table in data.items():
        attr = _SECTIONS.get(section)
        if attr is None:
            raise ConfigError(f"unknown config section [{section}]")
        if not isinstance(table, dict):
            raise ConfigError(f"[{section}] must be a table")
        _apply_section(getattr(cfg, attr), table, section)
    cfg.validate()
    return cfg
