"""Statistics pusher (role of reference lib/statisticsPusher:
statistics_pusher.go:38 interval loop + ~40 collector modules under
lib/statisticsPusher/statistics/; pushers write to files or the internal
monitoring database).

Collectors are callables returning {metric: number}; the pusher samples
them on an interval and emits line protocol to a file sink and/or writes
points back into a database (the `_internal` analog). A bounded in-memory
ring keeps the latest samples for /debug/vars.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import get_logger

log = get_logger(__name__)


class StatisticsPusher:
    def __init__(self, interval_s: float = 10.0, push_path: str = "",
                 engine=None, store_database: str = "_internal",
                 node_tag: str = ""):
        self.interval_s = interval_s
        self.push_path = push_path
        self.engine = engine
        self.store_database = store_database
        self.node_tag = node_tag
        self._collectors: dict[str, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ring: deque = deque(maxlen=64)     # (ts, {name: metrics})

    def register(self, name: str, fn) -> None:
        """fn() -> dict[str, int|float]. Collector errors are logged and
        skipped, never fatal (reference collectors are isolated too)."""
        with self._lock:
            self._collectors[name] = fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    # ------------------------------------------------------------- sampling

    def sample(self) -> dict[str, dict]:
        out = {}
        with self._lock:
            items = list(self._collectors.items())
        for name, fn in items:
            try:
                m = fn()
                if m:
                    out[name] = dict(m)
            except Exception as e:
                log.warning("stats collector %s failed: %s", name, e)
        return out

    def push_once(self) -> dict[str, dict]:
        ts = time.time()
        sample = self.sample()
        self.ring.append((ts, sample))
        if not sample:
            return sample
        lines = self._to_line_protocol(sample, int(ts * 1e9))
        if self.push_path:
            try:
                with open(self.push_path, "a") as f:
                    f.write("\n".join(lines) + "\n")
            except OSError as e:
                log.warning("stats file push failed: %s", e)
        if self.engine is not None and self.store_database:
            try:
                from ..utils.lineprotocol import parse_lines
                self.engine.write_points(
                    self.store_database,
                    parse_lines("\n".join(lines)))
            except Exception as e:
                log.warning("stats write-back failed: %s", e)
        return sample

    def _to_line_protocol(self, sample: dict, ts_ns: int) -> list[str]:
        tag = f",hostname={self.node_tag}" if self.node_tag else ""
        lines = []
        for name, metrics in sorted(sample.items()):
            fields = ",".join(
                f"{k}={v}" + ("i" if isinstance(v, int)
                              and not isinstance(v, bool) else "")
                for k, v in sorted(metrics.items())
                if isinstance(v, (int, float)))
            if fields:
                lines.append(f"{name}{tag} {fields} {ts_ns}")
        return lines

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="stats-pusher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.push_once()

    def latest(self) -> dict:
        if not self.ring:
            return {}
        ts, sample = self.ring[-1]
        return {"ts": ts, "stats": sample}


# ------------------------------------------------- standard collectors

# Innermost lock of the hot path's lock web (utils/lockrank.py):
# bump() runs inside scheduler/devicecache/pipeline critical sections,
# so the stats lock must out-rank them all and never wrap a blocking
# call.
from .lockrank import RANK_STATS, RankedLock  # noqa: E402

COUNTER_LOCK = RankedLock("stats.counter", RANK_STATS)

# Registry of every shared counter dict (oglint rule R6): a metric
# name is legal only if it appears in the registered dict's literal
# declaration, and read-modify-write increments must go through
# bump()/COUNTER_LOCK. Modules register at import:
#     MY_STATS = register_counters("subsystem", {...})
COUNTER_REGISTRY: dict[str, dict] = {}


def register_counters(name: str, counters: dict) -> dict:
    """Register one subsystem's counter dict under the shared metric
    registry (idempotent per name; re-registration must pass the same
    dict — a second dict would fork the metric namespace)."""
    old = COUNTER_REGISTRY.get(name)
    if old is not None and old is not counters:
        raise ValueError(f"counter registry {name!r} already bound")
    COUNTER_REGISTRY[name] = counters
    return counters


def bump(counters: dict, key: str, n: int = 1) -> None:
    """Locked increment for the module-level metric dicts — `d[k] += n`
    is a non-atomic read-modify-write and drops counts under the
    threaded HTTP/RPC servers."""
    with COUNTER_LOCK:
        counters[key] = counters.get(key, 0) + n


def runtime_collector():
    """Process runtime metrics (reference statistics/runtime.go analog)."""
    import resource
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return {
        "rss_bytes": ru.ru_maxrss * 1024,
        "user_cpu_s": ru.ru_utime,
        "sys_cpu_s": ru.ru_stime,
        "threads": threading.active_count(),
    }


def engine_collector(engine):
    """Storage engine metrics (reference statistics/engine/immutable
    collectors analog)."""
    def collect():
        dbs = list(engine.databases)
        n_shards = 0
        n_files = 0
        for db in dbs:
            try:
                dbo = engine.database(db)
                n_shards += len(dbo.discovered_shards())
                for s in dbo.opened_shards():
                    n_files += len(getattr(s, "_tables", {}) or {})
            except KeyError:
                continue
        return {"databases": len(dbs), "shards": n_shards,
                "tssp_tables": n_files}
    return collect


def readcache_collector():
    from ..storage import readcache
    return readcache.global_cache().stats()


def executor_collector():
    """Query executor metrics (reference statistics/executor.go analog):
    scan-path counters accumulated across queries."""
    from ..query.executor import EXEC_STATS
    return dict(EXEC_STATS)


def devicecache_collector():
    """Device block cache metrics (readcache analog, HBM tier) plus
    the host-side pin cache and the decoded-plane tier — flattened:
    the pusher's line-protocol writer drops non-scalar fields."""
    from ..ops import devicecache
    if not devicecache.enabled():
        return {"enabled": 0}
    out = devicecache.global_cache().stats()
    for k, v in devicecache.host_cache().stats().items():
        out[f"host_{k}"] = v
    out.update(devicecache.PLANE_STATS)
    return out


def compaction_collector():
    """Compaction/merge metrics (reference statistics/compact.go)."""
    from ..storage.compact import COMPACT_STATS
    return dict(COMPACT_STATS)


def rpc_collector():
    """Cluster transport metrics (reference statistics/spdy.go)."""
    from ..cluster.transport import RPC_STATS
    return dict(RPC_STATS)


def device_collector():
    """Device-plane metrics (ops/devstats): D2H/H2D bytes, pull wait,
    kernel launches, HBM slab footprint — the numbers that decide query
    latency on a tunnel-attached TPU (no reference counterpart: PCIe
    GPUs never made transfer volume the bottleneck)."""
    from ..ops.devstats import device_collector as _dc
    return _dc()


def scheduler_collector():
    """Device query scheduler metrics (query/scheduler.py): admission
    counters (admitted/shed/queued), dispatcher coalescing, singleflight
    hits, plus live active/queued gauges — the serving-runtime signals
    for /metrics, /debug/vars and the pusher."""
    from ..query.scheduler import sched_collector
    return sched_collector()


def wal_collector():
    """WAL metrics (reference statistics/wal analog)."""
    from ..storage.wal import WAL_STATS
    return dict(WAL_STATS)


def raft_collector():
    """Replication raft metrics (elections, snapshots, proposes)."""
    from ..cluster.raft import RAFT_STATS
    return dict(RAFT_STATS)


def subscriber_collector():
    """Subscription forwarding metrics (statistics/subscriber analog)."""
    from ..services.subscriber import SUB_STATS
    return dict(SUB_STATS)
