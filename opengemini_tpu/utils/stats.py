"""Statistics pusher (role of reference lib/statisticsPusher:
statistics_pusher.go:38 interval loop + ~40 collector modules under
lib/statisticsPusher/statistics/; pushers write to files or the internal
monitoring database).

Collectors are callables returning {metric: number}; the pusher samples
them on an interval and emits line protocol to a file sink and/or writes
points back into a database (the `_internal` analog). A bounded in-memory
ring keeps the latest samples for /debug/vars.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import get_logger

log = get_logger(__name__)


class StatisticsPusher:
    def __init__(self, interval_s: float = 10.0, push_path: str = "",
                 engine=None, store_database: str = "_internal",
                 node_tag: str = ""):
        self.interval_s = interval_s
        self.push_path = push_path
        self.engine = engine
        self.store_database = store_database
        self.node_tag = node_tag
        self._collectors: dict[str, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.ring: deque = deque(maxlen=64)     # (ts, {name: metrics})

    def register(self, name: str, fn) -> None:
        """fn() -> dict[str, int|float]. Collector errors are logged and
        skipped, never fatal (reference collectors are isolated too)."""
        with self._lock:
            self._collectors[name] = fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    # ------------------------------------------------------------- sampling

    def sample(self) -> dict[str, dict]:
        out = {}
        with self._lock:
            items = list(self._collectors.items())
        for name, fn in items:
            try:
                m = fn()
                if m:
                    out[name] = dict(m)
            except Exception as e:
                log.warning("stats collector %s failed: %s", name, e)
        return out

    def push_once(self) -> dict[str, dict]:
        ts = time.time()
        sample = self.sample()
        self.ring.append((ts, sample))
        if not sample:
            return sample
        lines = self._to_line_protocol(sample, int(ts * 1e9))
        if self.push_path:
            try:
                with open(self.push_path, "a") as f:
                    f.write("\n".join(lines) + "\n")
            except OSError as e:
                log.warning("stats file push failed: %s", e)
        if self.engine is not None and self.store_database:
            try:
                from ..utils.lineprotocol import parse_lines
                self.engine.write_points(
                    self.store_database,
                    parse_lines("\n".join(lines)))
            except Exception as e:
                log.warning("stats write-back failed: %s", e)
        return sample

    def _to_line_protocol(self, sample: dict, ts_ns: int) -> list[str]:
        tag = f",hostname={self.node_tag}" if self.node_tag else ""
        lines = []
        for name, metrics in sorted(sample.items()):
            fields = ",".join(
                f"{k}={v}" + ("i" if isinstance(v, int)
                              and not isinstance(v, bool) else "")
                for k, v in sorted(metrics.items())
                if isinstance(v, (int, float)))
            if fields:
                lines.append(f"{name}{tag} {fields} {ts_ns}")
        return lines

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="stats-pusher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.push_once()

    def latest(self) -> dict:
        if not self.ring:
            return {}
        ts, sample = self.ring[-1]
        return {"ts": ts, "stats": sample}


# ------------------------------------------------- standard collectors

# Innermost lock of the hot path's lock web (utils/lockrank.py):
# bump() runs inside scheduler/devicecache/pipeline critical sections,
# so the stats lock must out-rank them all and never wrap a blocking
# call.
from .lockrank import RANK_STATS, RankedLock  # noqa: E402

COUNTER_LOCK = RankedLock("stats.counter", RANK_STATS)

# Registry of every shared counter dict (oglint rule R6): a metric
# name is legal only if it appears in the registered dict's literal
# declaration, and read-modify-write increments must go through
# bump()/COUNTER_LOCK. Modules register at import:
#     MY_STATS = register_counters("subsystem", {...})
COUNTER_REGISTRY: dict[str, dict] = {}


def register_counters(name: str, counters: dict) -> dict:
    """Register one subsystem's counter dict under the shared metric
    registry (idempotent per name). A re-registration with the SAME
    declared keys adopts and returns the existing dict — that is a
    module loaded twice (``python -m`` runs it as __main__ while the
    package import loads it again) and both copies must share one set
    of live counters. Different keys mean a genuine namespace fork:
    loud error."""
    old = COUNTER_REGISTRY.get(name)
    if old is not None and old is not counters:
        if set(old) != set(counters):
            raise ValueError(f"counter registry {name!r} already bound")
        return old
    COUNTER_REGISTRY[name] = counters
    return counters


def bump(counters: dict, key: str, n: int = 1) -> None:
    """Locked increment for the module-level metric dicts — `d[k] += n`
    is a non-atomic read-modify-write and drops counts under the
    threaded HTTP/RPC servers."""
    with COUNTER_LOCK:
        counters[key] = counters.get(key, 0) + n


# ------------------------------------------------------- histograms

def exp_bounds(lo: float, hi: float, factor: float = 2.0) -> tuple:
    """Fixed exponential bucket bounds lo, lo*f, ... up to >= hi."""
    out = [float(lo)]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


class Histogram:
    """Fixed exponential-bucket latency/size histogram.

    Lock-striped: observe() picks a stripe by thread id, so the hot
    HTTP/pull threads never contend on one lock (the COUNTER_LOCK
    pattern is right for rare bumps, wrong for per-request observes);
    snapshot() merges the stripes under all stripe locks. Counts are
    cumulative like Prometheus buckets are NOT — snapshot() returns
    per-bucket counts and the exporter accumulates the `le` form.

    Exemplars: a flight-recorder-sampled observation may carry its
    trace id; the last one lands per bucket (value, trace_id, unix ts)
    and the OpenMetrics exposition attaches it to that bucket line —
    a slow bucket links straight to /debug/trace?id=<trace_id>. Only
    sampled requests pay the (single-lock) exemplar write; the hot
    unsampled path is untouched.
    """

    N_STRIPES = 8
    __slots__ = ("bounds", "_stripes", "_ex_lock", "_exemplars")

    def __init__(self, bounds):
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError("histogram bounds must ascend")
        nb = len(self.bounds) + 1                 # + overflow bucket
        self._stripes = [
            {"lock": threading.Lock(), "counts": [0] * nb,
             "sum": 0.0, "count": 0}
            for _ in range(self.N_STRIPES)]
        self._ex_lock = threading.Lock()
        self._exemplars: dict[int, tuple] = {}    # bucket → (v, tid, ts)

    def _bucket(self, v: float) -> int:
        from bisect import bisect_left
        return bisect_left(self.bounds, v)

    def observe(self, v, trace_id: str | None = None) -> None:
        v = float(v)
        i = self._bucket(v)
        # get_ident() on Linux is a pthread struct address, 64-byte
        # aligned — the low bits are ALWAYS zero, so a plain modulo
        # maps every thread to stripe 0 and the striping is theater.
        # Shift the alignment bits off first.
        st = self._stripes[(threading.get_ident() >> 6)
                           % self.N_STRIPES]
        with st["lock"]:
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1
        if trace_id:
            # in-bucket by construction (stored per bucket index), as
            # the OpenMetrics spec wants histogram exemplars to be.
            # Trace ids are client-forceable (X-OG-Trace): restrict to
            # a label-safe charset HERE so a hostile id can never
            # forge or break exposition lines downstream.
            import re
            tid = re.sub(r"[^A-Za-z0-9_.:-]", "_",
                         str(trace_id))[:64]
            with self._ex_lock:
                self._exemplars[i] = (v, tid, time.time())

    def exemplars(self) -> dict[int, tuple]:
        with self._ex_lock:
            return dict(self._exemplars)

    def snapshot(self) -> dict:
        nb = len(self.bounds) + 1
        counts = [0] * nb
        total = 0
        vsum = 0.0
        for st in self._stripes:
            with st["lock"]:
                for i in range(nb):
                    counts[i] += st["counts"][i]
                total += st["count"]
                vsum += st["sum"]
        return {"counts": counts, "count": total, "sum": vsum}

    def quantile(self, q: float, snap: dict | None = None) -> float:
        """Bucket-interpolated quantile (0..1); 0.0 when empty. The
        overflow bucket reports its lower bound (no upper edge)."""
        s = snap or self.snapshot()
        if s["count"] == 0:
            return 0.0
        target = q * s["count"]
        seen = 0
        for i, c in enumerate(s["counts"]):
            if seen + c >= target and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else lo
                frac = (target - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.bounds[-1]

    def reset(self) -> None:
        for st in self._stripes:
            with st["lock"]:
                st["counts"] = [0] * (len(self.bounds) + 1)
                st["sum"] = 0.0
                st["count"] = 0
        with self._ex_lock:
            self._exemplars.clear()


# Registry of every shared histogram dict, parallel to
# COUNTER_REGISTRY (oglint R6: an observe() against an unregistered
# dict or an undeclared metric key fails lint). Modules register at
# import:
#     MY_HIST = register_histograms("subsystem", {"latency_ms": ...})
HISTOGRAM_REGISTRY: dict[str, dict] = {}


def register_histograms(name: str, histos: dict) -> dict:
    """Register one subsystem's histogram dict (idempotent per name).
    Same-keyed re-registration adopts the existing dict (a module
    double-loaded as __main__ + package import must observe into ONE
    set of live histograms); different keys are a namespace fork and
    raise."""
    old = HISTOGRAM_REGISTRY.get(name)
    if old is not None and old is not histos:
        if set(old) != set(histos):
            raise ValueError(f"histogram registry {name!r} "
                             "already bound")
        return old
    HISTOGRAM_REGISTRY[name] = histos
    return histos


def observe(histos: dict, key: str, v,
            trace_id: str | None = None) -> None:
    """Record one observation into a registered histogram dict —
    KeyError on an undeclared metric name (the runtime twin of oglint
    R605: a typo'd key must fail loudly, not mint a hidden series).
    ``trace_id`` attaches a flight-recorder exemplar (OpenMetrics
    exposition links the bucket to /debug/trace?id=)."""
    histos[key].observe(v, trace_id=trace_id)


def _exemplar_suffix(ex: tuple | None) -> str:
    """OpenMetrics exemplar clause for one bucket line:
    ` # {trace_id="…"} value timestamp`."""
    if ex is None:
        return ""
    v, tid, ts = ex
    return f' # {{trace_id="{tid}"}} {v:g} {ts:.3f}'


def histograms_prometheus(prefix: str = "opengemini",
                          openmetrics: bool = False) -> list[str]:
    """Histogram text exposition of every registered histogram:
    `_bucket{le=...}` (cumulative), `_sum`, `_count`, each family with
    a HELP/TYPE pair. ``openmetrics=True`` emits the OpenMetrics 1.0
    dialect: trace-id exemplars ride the bucket lines (the classic
    Prometheus text format has no exemplar syntax — they are only
    emitted here)."""
    lines: list[str] = []
    for grp in sorted(HISTOGRAM_REGISTRY):
        for key in sorted(HISTOGRAM_REGISTRY[grp]):
            h = HISTOGRAM_REGISTRY[grp][key]
            s = h.snapshot()
            exs = h.exemplars() if openmetrics else {}
            name = f"{prefix}_{grp}_{key}"
            lines.append(f"# HELP {name} {grp} {key} distribution")
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for i, (b, c) in enumerate(zip(h.bounds, s["counts"])):
                cum += c
                le = f"{b:g}"
                lines.append(f'{name}_bucket{{le="{le}"}} {cum}'
                             + _exemplar_suffix(exs.get(i)))
            lines.append(f'{name}_bucket{{le="+Inf"}} {s["count"]}'
                         + _exemplar_suffix(exs.get(len(h.bounds))))
            lines.append(f'{name}_sum {s["sum"]:g}')
            lines.append(f'{name}_count {s["count"]}')
    return lines


def histogram_summaries() -> dict:
    """p50/p95/p99 + count per registered histogram, for /debug/vars
    and the stats pusher (quantiles are bucket-interpolated — good
    enough for SLO dashboards, cheap enough for a 10s pusher loop)."""
    out: dict[str, dict] = {}
    for grp, histos in HISTOGRAM_REGISTRY.items():
        g: dict = {}
        for key, h in histos.items():
            s = h.snapshot()
            g[f"{key}_count"] = s["count"]
            if s["count"]:
                g[f"{key}_p50"] = round(h.quantile(0.50, s), 3)
                g[f"{key}_p95"] = round(h.quantile(0.95, s), 3)
                g[f"{key}_p99"] = round(h.quantile(0.99, s), 3)
        if g:
            out[grp] = g
    return out


def latency_collector():
    """utils.stats collector: flattened histogram summaries (the
    line-protocol writer drops nested dicts)."""
    out = {}
    for grp, g in histogram_summaries().items():
        for k, v in g.items():
            out[f"{grp}_{k}"] = v
    return out


def runtime_collector():
    """Process runtime metrics (reference statistics/runtime.go analog)."""
    import resource
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return {
        "rss_bytes": ru.ru_maxrss * 1024,
        "user_cpu_s": ru.ru_utime,
        "sys_cpu_s": ru.ru_stime,
        "threads": threading.active_count(),
    }


def engine_collector(engine):
    """Storage engine metrics (reference statistics/engine/immutable
    collectors analog)."""
    def collect():
        dbs = list(engine.databases)
        n_shards = 0
        n_files = 0
        for db in dbs:
            try:
                dbo = engine.database(db)
                n_shards += len(dbo.discovered_shards())
                for s in dbo.opened_shards():
                    n_files += len(getattr(s, "_tables", {}) or {})
            except KeyError:
                continue
        return {"databases": len(dbs), "shards": n_shards,
                "tssp_tables": n_files}
    return collect


def readcache_collector():
    from ..storage import readcache
    return readcache.global_cache().stats()


def executor_collector():
    """Query executor metrics (reference statistics/executor.go analog):
    scan-path counters accumulated across queries."""
    from ..query.executor import EXEC_STATS
    return dict(EXEC_STATS)


def devicecache_collector():
    """Device block cache metrics (readcache analog, HBM tier) plus
    the host-side pin cache and the decoded-plane tier — flattened:
    the pusher's line-protocol writer drops non-scalar fields."""
    from ..ops import devicecache
    if not devicecache.enabled():
        return {"enabled": 0}
    out = devicecache.global_cache().stats()
    for k, v in devicecache.host_cache().stats().items():
        out[f"host_{k}"] = v
    for k, v in devicecache.compressed_cache().stats().items():
        out[f"compressed_{k}"] = v
    out.update(devicecache.PLANE_STATS)
    return out


def device_decode_collector():
    """Compressed-domain decode-stage metrics (round 14): blocks
    expanded on device, batch launches, per-block host heals and the
    compressed-tier rebuild counters (ops/device_decode.py)."""
    from ..ops.device_decode import DECODE_STATS
    return dict(DECODE_STATS)


def compaction_collector():
    """Compaction/merge metrics (reference statistics/compact.go)."""
    from ..storage.compact import COMPACT_STATS
    return dict(COMPACT_STATS)


def rpc_collector():
    """Cluster transport metrics (reference statistics/spdy.go)."""
    from ..cluster.transport import RPC_STATS
    return dict(RPC_STATS)


def device_collector():
    """Device-plane metrics (ops/devstats): D2H/H2D bytes, pull wait,
    kernel launches, HBM slab footprint — the numbers that decide query
    latency on a tunnel-attached TPU (no reference counterpart: PCIe
    GPUs never made transfer volume the bottleneck)."""
    from ..ops.devstats import device_collector as _dc
    return _dc()


def scheduler_collector():
    """Device query scheduler metrics (query/scheduler.py): admission
    counters (admitted/shed/queued), dispatcher coalescing, singleflight
    hits, plus live active/queued gauges — the serving-runtime signals
    for /metrics, /debug/vars and the pusher."""
    from ..query.scheduler import sched_collector
    return sched_collector()


def hbm_collector():
    """Device resource observatory metrics (ops/hbm.py): per-tier HBM
    ledger bytes / high-watermarks / entry counts plus pressure and
    reconcile counters — the global device-residency view next to the
    per-cache devicecache stats."""
    from ..ops.hbm import collector
    return collector()


def resultcache_collector():
    """Result-cache metrics (query/resultcache.py): hit/partial/miss/
    bypass counters, invalidations, evictions, live entry/byte gauges
    and the derived hit ratio — the sustained-serving dedup signals."""
    from ..query.resultcache import resultcache_collector as _rcc
    return _rcc()


def devicefault_collector():
    """Device fault domain metrics (ops/devicefault.py): classified
    error counts, retry/pressure-ladder/fallback counters, per-route
    breaker state codes and trip counts, and confiscated in-flight
    gate permits — the signals that say the TPU hot path is degrading
    to host rather than failing."""
    from ..ops.devicefault import devicefault_collector as _dfc
    return _dfc()


def wal_collector():
    """WAL metrics (reference statistics/wal analog)."""
    from ..storage.wal import WAL_STATS
    return dict(WAL_STATS)


def flight_collector():
    """Arrow Flight ingest metrics (services/arrowflight.py): rows,
    batches, columnar fast-lane batches and write errors. The
    columnar_batches / batches ratio says how much DoPut traffic is
    riding the vectorized lane vs the row hatch."""
    from ..services.arrowflight import FLIGHT_STATS
    return dict(FLIGHT_STATS)


def compileaudit_collector():
    """Compile-cache audit metrics (ops/compileaudit.py): XLA compile
    / retrace totals, duplicate (kernel, signature) compiles — the
    hot-loop retrace smoking gun — and recompile-budget breaches."""
    from ..ops.compileaudit import compileaudit_collector as _cc
    return _cc()


def xfer_collector():
    """Per-site transfer manifest (ops/compileaudit.py): H2D/D2H
    bytes and events by declared mover site, plus the pipeline
    est-vs-actual ledger cross-check counters — every byte that
    crosses the accelerator link names who moved it."""
    from ..ops.compileaudit import xfer_collector as _xc
    return _xc()


def raft_collector():
    """Replication raft metrics (elections, snapshots, proposes)."""
    from ..cluster.raft import RAFT_STATS
    return dict(RAFT_STATS)


def subscriber_collector():
    """Subscription forwarding metrics (statistics/subscriber analog)."""
    from ..services.subscriber import SUB_STATS
    return dict(SUB_STATS)
