"""Per-request deadline propagation.

A query or write gets ONE time budget at the HTTP boundary; every hop it
fans out through (sql-node scatter, points-writer fan-out, transport RPC
retries) consumes the REMAINING budget instead of starting a fresh
per-call timeout — so a slow store can never stack `n_hops x 60s` of
waiting behind one client request (the role of context deadlines in the
reference's Go coordinator paths).

Usage:

    with deadline.bind(budget_s):          # HTTP boundary
        ...                                # same-thread call chain

    dl = deadline.current()                # capture BEFORE fan-out
    rpc_timeout = dl.clamp(60.0) if dl else 60.0

``bind`` stores the deadline in a contextvar, which does NOT propagate
into worker threads — fan-out paths must capture ``current()`` in the
dispatching thread and close over it (see sql_node._scatter,
points_writer._scatter_send).
"""

from __future__ import annotations

import contextvars
import time

from .errors import ErrQueryTimeout

__all__ = ["Deadline", "bind", "current", "clamp", "check",
           "remaining"]


class Deadline:
    """Absolute monotonic deadline for one request."""

    __slots__ = ("at", "budget_s", "what")

    def __init__(self, budget_s: float, what: str = "request"):
        self.budget_s = float(budget_s)
        self.at = time.monotonic() + self.budget_s
        self.what = what

    def remaining(self) -> float:
        """Seconds left (may be negative once expired)."""
        return self.at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, where: str = "") -> None:
        """Raise the typed budget-exhausted error when expired."""
        if self.expired:
            raise ErrQueryTimeout(self._msg(where))

    def clamp(self, timeout: float) -> float:
        """min(timeout, remaining); raises when the budget is gone so a
        caller never issues an RPC it cannot wait for."""
        left = self.remaining()
        if left <= 0:
            raise ErrQueryTimeout(self._msg("clamp"))
        return min(timeout, left)

    def _msg(self, where: str) -> str:
        w = f" at {where}" if where else ""
        return (f"{self.what} deadline exceeded "
                f"(budget {self.budget_s:.3g}s){w}")


_current: contextvars.ContextVar[Deadline | None] = \
    contextvars.ContextVar("og_deadline", default=None)


def current() -> Deadline | None:
    """The calling thread's bound deadline (None when unbounded)."""
    return _current.get()


class bind:
    """Context manager binding a deadline for the with-block's call
    chain. budget_s None or <= 0 binds nothing (unbounded)."""

    def __init__(self, budget_s: float | None, what: str = "request"):
        self.deadline = (Deadline(budget_s, what)
                         if budget_s is not None and budget_s > 0
                         else None)
        self._tok = None

    def __enter__(self) -> Deadline | None:
        if self.deadline is not None:
            self._tok = _current.set(self.deadline)
        return self.deadline

    def __exit__(self, *exc):
        if self._tok is not None:
            _current.reset(self._tok)
        return False


def clamp(timeout: float) -> float:
    """Clamp a per-call timeout by the bound deadline, if any."""
    dl = current()
    return dl.clamp(timeout) if dl is not None else timeout


def check(where: str = "") -> None:
    dl = current()
    if dl is not None:
        dl.check(where)


def remaining(default: float | None = None) -> float | None:
    """Seconds left on the bound deadline (may be <= 0 once spent), or
    ``default`` when unbounded. The admission paths (query scheduler,
    BoundedGate) clamp their queue waits with this so a parked request
    never outsleeps its own budget."""
    dl = current()
    return dl.remaining() if dl is not None else default
