"""zstandard import gate.

The WAL and block codecs want python-zstandard; some deployment images
ship without it. Importing `zstandard` from this module returns the real
package when installed, else a zlib-backed shim covering the API subset
the codebase uses (ZstdCompressor.compress, ZstdDecompressor.decompress
with max_output_size, get_frame_parameters().content_size).

The shim's frames are NOT zstd frames (they carry a ``ZSZL`` magic +
declared size + a zlib stream), so data written under one codec is
unreadable under the other — but every writer AND reader in this
codebase routes through this module, so any single deployment stays
self-consistent. Mixed fleets must install python-zstandard everywhere.
"""

from __future__ import annotations

try:
    import zstandard                               # noqa: F401
except ModuleNotFoundError:                        # pragma: no cover gate
    import struct
    import types
    import zlib

    _MAGIC = b"ZSZL"
    _HDR = struct.Struct("<4sQ")

    class ZstdError(Exception):
        pass

    class _FrameParams:
        __slots__ = ("content_size",)

        def __init__(self, content_size: int):
            self.content_size = content_size

    class ZstdCompressor:
        def __init__(self, level: int = 3):
            # zstd levels reach 22; clamp into zlib's 1..9
            self._level = max(1, min(int(level), 9))

        def compress(self, data) -> bytes:
            raw = bytes(data)
            return _HDR.pack(_MAGIC, len(raw)) \
                + zlib.compress(raw, self._level)

    class ZstdDecompressor:
        def decompress(self, data, max_output_size: int = 0) -> bytes:
            b = bytes(data)
            if len(b) < _HDR.size or b[:4] != _MAGIC:
                raise ZstdError("invalid frame (zlib-shim codec)")
            (_, size) = _HDR.unpack_from(b)
            if max_output_size and size > max_output_size:
                raise ZstdError(
                    f"frame declares {size} bytes > cap {max_output_size}")
            try:
                out = zlib.decompress(b[_HDR.size:])
            except zlib.error as e:
                raise ZstdError(str(e)) from e
            if max_output_size and len(out) > max_output_size:
                raise ZstdError("decompressed past max_output_size")
            return out

    def get_frame_parameters(data) -> _FrameParams:
        b = bytes(data[:_HDR.size])
        if len(b) == _HDR.size and b[:4] == _MAGIC:
            return _FrameParams(_HDR.unpack_from(b)[1])
        return _FrameParams(0)

    zstandard = types.SimpleNamespace(
        ZstdCompressor=ZstdCompressor,
        ZstdDecompressor=ZstdDecompressor,
        ZstdError=ZstdError,
        get_frame_parameters=get_frame_parameters,
        __shim__="zlib",
    )

__all__ = ["zstandard"]
