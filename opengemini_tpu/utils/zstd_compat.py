"""zstandard import gate.

The WAL and block codecs want python-zstandard; some deployment images
ship without it. Importing `zstandard` from this module returns the real
package when installed, else a zlib-backed shim covering the API subset
the codebase uses (ZstdCompressor.compress, ZstdDecompressor.decompress
with max_output_size, get_frame_parameters().content_size).

The shim's frames are NOT zstd frames (a 4-byte magic + declared size
+ payload): ``ZSZL`` carries a zlib stream, ``ZSLZ`` a native-LZ4
block (native/lz4.cpp — ~5-10× the zlib-1 throughput; low levels
prefer it, so WAL framing stops dominating bulk ingest). Readers
dispatch per frame on the magic, so archives mixing both shim codecs
stay readable — but neither is a zstd frame, so data written under
the shim is unreadable under real zstandard and vice versa. Every
writer AND reader in this codebase routes through this module, so any
single deployment stays self-consistent. Mixed fleets must install
python-zstandard everywhere.
"""

from __future__ import annotations

try:
    import zstandard                               # noqa: F401
except ModuleNotFoundError:                        # pragma: no cover gate
    import struct
    import types
    import zlib

    _MAGIC = b"ZSZL"
    _MAGIC_LZ4 = b"ZSLZ"
    _HDR = struct.Struct("<4sQ")
    _NATIVE_LZ4 = None          # tri-state: None unknown, False no

    def _native_lz4():
        """Lazy native-LZ4 probe (the import builds the shared lib on
        first touch — must not run at utils import time)."""
        global _NATIVE_LZ4
        if _NATIVE_LZ4 is None:
            try:
                from .. import native
                _NATIVE_LZ4 = native if native.native_available() \
                    else False
            except Exception:
                _NATIVE_LZ4 = False
        return _NATIVE_LZ4

    class ZstdError(Exception):
        pass

    class _FrameParams:
        __slots__ = ("content_size",)

        def __init__(self, content_size: int):
            self.content_size = content_size

    class ZstdCompressor:
        def __init__(self, level: int = 3):
            # zstd levels reach 22; clamp into zlib's 1..9
            self._level = max(1, min(int(level), 9))

        def compress(self, data) -> bytes:
            raw = bytes(data)
            if self._level <= 1:
                # the fastest tier (the WAL's level=1 frames — zlib-1
                # measured as 70% of the bulk ingest write path) takes
                # the native LZ4 block codec when built; ratio tiers
                # (persistent column blocks at level 3+) keep zlib
                nat = _native_lz4()
                if nat:
                    return _HDR.pack(_MAGIC_LZ4, len(raw)) \
                        + nat.lz4_compress(raw)
            return _HDR.pack(_MAGIC, len(raw)) \
                + zlib.compress(raw, self._level)

    class ZstdDecompressor:
        def decompress(self, data, max_output_size: int = 0) -> bytes:
            b = bytes(data)
            if len(b) < _HDR.size \
                    or b[:4] not in (_MAGIC, _MAGIC_LZ4):
                raise ZstdError("invalid frame (zlib-shim codec)")
            (magic, size) = _HDR.unpack_from(b)
            if max_output_size and size > max_output_size:
                raise ZstdError(
                    f"frame declares {size} bytes > cap {max_output_size}")
            if magic == _MAGIC_LZ4:
                nat = _native_lz4()
                try:
                    if nat:
                        out = nat.lz4_decompress(b[_HDR.size:], size)
                    else:
                        from ..native import _py_lz4_decompress
                        out = _py_lz4_decompress(b[_HDR.size:], size)
                except (ValueError, IndexError) as e:
                    # IndexError: the pure-Python fallback walking off
                    # a truncated frame — corruption must surface as
                    # ZstdError (the shim's documented contract)
                    raise ZstdError(str(e)) from e
            else:
                try:
                    out = zlib.decompress(b[_HDR.size:])
                except zlib.error as e:
                    raise ZstdError(str(e)) from e
            if max_output_size and len(out) > max_output_size:
                raise ZstdError("decompressed past max_output_size")
            return out

    def get_frame_parameters(data) -> _FrameParams:
        b = bytes(data[:_HDR.size])
        if len(b) == _HDR.size and b[:4] in (_MAGIC, _MAGIC_LZ4):
            return _FrameParams(_HDR.unpack_from(b)[1])
        return _FrameParams(0)

    zstandard = types.SimpleNamespace(
        ZstdCompressor=ZstdCompressor,
        ZstdDecompressor=ZstdDecompressor,
        ZstdError=ZstdError,
        get_frame_parameters=get_frame_parameters,
        __shim__="zlib",
    )

__all__ = ["zstandard"]
