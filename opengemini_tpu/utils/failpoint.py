"""Failpoint fault injection.

Role of the reference's pingcap **failpoint** usage (SURVEY.md §4:
`go.mod:41`; injection sites via `failpoint.Inject` in engine/shard.go,
engine/wal.go, coordinator/write_helper.go, spdy transport,
ts-meta member_event_handler.go; `make gotest` toggles them on/off around
the unit-test run). Production code plants named points with
``failpoint.inject("name")``; tests and the syscontrol admin plane arm
them with actions:

    error[:message]   raise FailpointError(message)
    sleep:<ms>        delay the call site
    drop              return True (site-specific: caller drops the work)
    call              invoke a python callable (tests)

The disarmed fast path is one module-global bool check — safe to leave in
hot loops."""

from __future__ import annotations

import threading
import time

__all__ = ["FailpointError", "enable", "disable", "disable_all",
           "inject", "active", "Failpoint", "list_points"]


class FailpointError(RuntimeError):
    """Raised by an armed `error` failpoint."""


_lock = threading.Lock()
_points: dict[str, tuple[str, object]] = {}
ACTIVE = False                    # fast-path gate (no lock on reads)
_hits: dict[str, int] = {}


def enable(name: str, action: str = "error", arg: object = None) -> None:
    """Arm a failpoint. action: error | sleep | drop | call."""
    global ACTIVE
    if action not in ("error", "sleep", "drop", "call"):
        raise ValueError(f"unknown failpoint action {action}")
    if action == "call" and not callable(arg):
        raise ValueError("action 'call' requires a callable arg")
    if action == "sleep":
        try:
            arg = float(arg or 0)
        except (TypeError, ValueError):
            raise ValueError("action 'sleep' requires a numeric ms arg")
    with _lock:
        _points[name] = (action, arg)
        ACTIVE = True


def disable(name: str) -> None:
    global ACTIVE
    with _lock:
        _points.pop(name, None)
        _hits.pop(name, None)
        ACTIVE = bool(_points)


def disable_all() -> None:
    global ACTIVE
    with _lock:
        _points.clear()
        _hits.clear()
        ACTIVE = False


def active(name: str) -> bool:
    return ACTIVE and name in _points


def list_points() -> dict:
    with _lock:
        return {n: {"action": a, "hits": _hits.get(n, 0)}
                for n, (a, _arg) in _points.items()}


def inject(name: str) -> bool:
    """Call at an injection site. Returns True when the site should DROP
    the work (action `drop`); raises FailpointError for `error`; sleeps
    for `sleep`. Disarmed cost: one global bool check."""
    if not ACTIVE:
        return False
    with _lock:
        spec = _points.get(name)
        if spec is None:
            return False
        _hits[name] = _hits.get(name, 0) + 1
        action, arg = spec
    if action == "error":
        raise FailpointError(arg or f"failpoint {name}")
    if action == "sleep":
        time.sleep(float(arg or 0) / 1000.0)
        return False
    if action == "drop":
        return True
    if action == "call":
        arg()
        return False
    return False


class Failpoint:
    """Context manager for tests:
    ``with Failpoint("wal.write.err"): ...``"""

    def __init__(self, name: str, action: str = "error",
                 arg: object = None):
        self.name = name
        self.action = action
        self.arg = arg

    def __enter__(self):
        enable(self.name, self.action, self.arg)
        return self

    def __exit__(self, *exc):
        disable(self.name)
        return False
