"""Failpoint fault injection.

Role of the reference's pingcap **failpoint** usage (SURVEY.md §4:
`go.mod:41`; injection sites via `failpoint.Inject` in engine/shard.go,
engine/wal.go, coordinator/write_helper.go, spdy transport,
ts-meta member_event_handler.go; `make gotest` toggles them on/off around
the unit-test run). Production code plants named points with
``failpoint.inject("name")``; tests and the syscontrol admin plane arm
them with actions:

    error[:message]   raise FailpointError(message)
    sleep:<ms>        delay the call site
    drop              return True (site-specific: caller drops the work)
    call              invoke a python callable (tests)
    oom               raise FailpointOOM — message carries
                      RESOURCE_EXHAUSTED so the device-error classifier
                      (ops/devicefault.py) takes its real OOM path
    transient         raise FailpointTransient — message carries
                      UNAVAILABLE (the classifier's transient path)
    hang              sleep arg ms (default 60000) in small slices,
                      waking early when disable()/disable_all() runs —
                      models a hung device launch the pull watchdog
                      must bound without wedging test teardown
    crash             SIGKILL the current process at the site — the
                      storage crash-consistency harness's kill switch
                      (tests/crashharness.py): no atexit, no buffer
                      flush, no finally blocks, exactly what a power
                      cut leaves behind. Arming requires OG_CRASH_OK=1
                      in the environment so a stray schedule can never
                      take down a pytest runner or a serving process

Arming modifiers (pingcap term-expression analogs ``3*return`` /
``10%return``):

    maxhits=N         fire at most N times, then auto-disarm
    pct=P             each pass fires with probability P (0..100)
    skip=K            let the first K passes through unfired (a crash
                      schedule lands the kill on the K+1-th append /
                      flush / publish instead of always the first)

Site naming convention: ``<module>.<operation>.<fault>`` — e.g.
``wal.write.err``, ``transport.send.drop``, ``raft.replicate.drop``.

The disarmed fast path is one module-global bool check — safe to leave in
hot loops."""

from __future__ import annotations

import os
import random
import threading
import time

__all__ = ["FailpointError", "FailpointOOM", "FailpointTransient",
           "enable", "disable", "disable_all",
           "inject", "active", "Failpoint", "list_points"]


class FailpointError(RuntimeError):
    """Raised by an armed `error` failpoint."""


class FailpointOOM(FailpointError):
    """Injected device OOM. The message deliberately carries the
    backend's RESOURCE_EXHAUSTED marker so the classifier in
    ops/devicefault.py exercises the same string patterns a real
    XlaRuntimeError would hit."""


class FailpointTransient(FailpointError):
    """Injected transient device/launch failure (UNAVAILABLE marker —
    see FailpointOOM)."""


class _Spec:
    __slots__ = ("action", "arg", "maxhits", "pct", "skip")

    def __init__(self, action, arg, maxhits, pct, skip=0):
        self.action = action
        self.arg = arg
        self.maxhits = maxhits
        self.pct = pct
        self.skip = skip


_lock = threading.Lock()
_points: dict[str, _Spec] = {}
ACTIVE = False                    # fast-path gate (no lock on reads)
_hits: dict[str, int] = {}
# probabilistic (pct) arming draws from a dedicated generator so chaos
# schedules can make a whole run reproducible without touching the
# global random state
_rng = random.Random()
# disarm epoch: `hang` sleeps poll this so disable()/disable_all()
# (the conftest leak guard, a chaos heal) wakes a hung site instead of
# leaving a background thread asleep for the full arg duration
_EPOCH = 0


def seed(n) -> None:
    """Seed the pct-draw generator (deterministic chaos schedules)."""
    _rng.seed(n)


def enable(name: str, action: str = "error", arg: object = None,
           maxhits: int | None = None, pct: float | None = None,
           skip: int = 0) -> None:
    """Arm a failpoint. action: error | sleep | drop | call | oom |
    transient | hang | crash (see the module docstring for semantics;
    crash requires OG_CRASH_OK=1 in the environment).
    maxhits=N auto-disarms the point after N fires (one-shot: N=1);
    pct=P fires each pass with probability P percent; skip=K lets the
    first K passes through unfired (crash schedules use it to land the
    kill on the K+1-th WAL append / flush instead of always the first
    — maxhits counts only actual fires, after the skips)."""
    global ACTIVE
    if action not in ("error", "sleep", "drop", "call", "oom",
                      "transient", "hang", "crash"):
        raise ValueError(f"unknown failpoint action {action}")
    if action == "crash":
        from . import knobs
        if not knobs.get("OG_CRASH_OK"):
            raise ValueError(
                "refusing to arm a 'crash' failpoint without "
                "OG_CRASH_OK=1 — it SIGKILLs the whole process "
                "(crash-harness subprocesses only)")
    if action == "call" and not callable(arg):
        raise ValueError("action 'call' requires a callable arg")
    if action in ("sleep", "hang"):
        try:
            arg = float(arg) if arg is not None else \
                (60_000.0 if action == "hang" else 0.0)
        except (TypeError, ValueError):
            raise ValueError(
                f"action {action!r} requires a numeric ms arg")
    if maxhits is not None:
        try:
            maxhits = int(maxhits)
        except (TypeError, ValueError):
            raise ValueError("maxhits must be an integer")
        if maxhits <= 0:
            raise ValueError("maxhits must be > 0")
    if pct is not None:
        try:
            pct = float(pct)
        except (TypeError, ValueError):
            raise ValueError("pct must be a number (0..100)")
        if not 0 <= pct <= 100:
            raise ValueError("pct must be within 0..100")
    try:
        skip = int(skip)
    except (TypeError, ValueError):
        raise ValueError("skip must be an integer")
    if skip < 0:
        raise ValueError("skip must be >= 0")
    with _lock:
        _points[name] = _Spec(action, arg, maxhits, pct, skip)
        _hits.pop(name, None)      # hit counts reset on (re)arm
        ACTIVE = True


def disable(name: str) -> None:
    global ACTIVE, _EPOCH
    with _lock:
        _points.pop(name, None)
        _hits.pop(name, None)
        ACTIVE = bool(_points)
        _EPOCH += 1


def disable_all() -> None:
    global ACTIVE, _EPOCH
    with _lock:
        _points.clear()
        _hits.clear()
        ACTIVE = False
        _EPOCH += 1


def active(name: str) -> bool:
    if not ACTIVE:                 # disarmed fast path: one bool check
        return False
    with _lock:                    # armed: consistent read of _points
        return name in _points


def list_points() -> dict:
    with _lock:
        return {n: {"action": s.action, "hits": _hits.get(n, 0),
                    **({"maxhits": s.maxhits}
                       if s.maxhits is not None else {}),
                    **({"pct": s.pct} if s.pct is not None else {}),
                    **({"skip": s.skip} if s.skip else {})}
                for n, s in _points.items()}


def inject(name: str) -> bool:
    """Call at an injection site. Returns True when the site should DROP
    the work (action `drop`); raises FailpointError for `error`; sleeps
    for `sleep`. Disarmed cost: one global bool check."""
    global ACTIVE
    if not ACTIVE:
        return False
    with _lock:
        spec = _points.get(name)
        if spec is None:
            return False
        if spec.pct is not None and _rng.random() * 100.0 >= spec.pct:
            return False           # armed but this pass doesn't fire
        _hits[name] = _hits.get(name, 0) + 1
        if _hits[name] <= spec.skip:
            return False           # armed but still in the skip window
        if spec.maxhits is not None and \
                _hits[name] - spec.skip >= spec.maxhits:
            _points.pop(name, None)        # one-shot/N-shot: auto-disarm
            ACTIVE = bool(_points)
        action, arg = spec.action, spec.arg
    if action == "crash":
        # a real crash persists nothing: no flush, no atexit, no
        # finally. SIGKILL is the closest a process can get to a
        # power cut (the kernel reaps it mid-instruction).
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    if action == "error":
        raise FailpointError(arg or f"failpoint {name}")
    if action == "oom":
        raise FailpointOOM(
            f"RESOURCE_EXHAUSTED: injected device OOM "
            f"(failpoint {name})")
    if action == "transient":
        raise FailpointTransient(
            f"UNAVAILABLE: injected transient device failure "
            f"(failpoint {name})")
    if action == "sleep":
        time.sleep(float(arg or 0) / 1000.0)
        return False
    if action == "hang":
        # bounded hang, woken early by any disarm — the site stays
        # blocked the way a wedged launch would, but test teardown
        # (disable_all) never inherits a sleeping background thread
        epoch0 = _EPOCH
        end = time.monotonic() + float(arg or 0) / 1000.0
        while time.monotonic() < end:
            if _EPOCH != epoch0:
                break
            time.sleep(0.05)
        return False
    if action == "drop":
        return True
    if action == "call":
        arg()
        return False
    return False


class Failpoint:
    """Context manager for tests:
    ``with Failpoint("wal.write.err"): ...``"""

    def __init__(self, name: str, action: str = "error",
                 arg: object = None, maxhits: int | None = None,
                 pct: float | None = None):
        self.name = name
        self.action = action
        self.arg = arg
        self.maxhits = maxhits
        self.pct = pct

    def __enter__(self):
        enable(self.name, self.action, self.arg,
               maxhits=self.maxhits, pct=self.pct)
        return self

    def __exit__(self, *exc):
        disable(self.name)
        return False
