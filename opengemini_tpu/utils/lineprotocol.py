"""InfluxDB line protocol parser (role of the reference's zero-copy parser,
lib/util/lifted/vm/protoparser/influx/parser.go).

Syntax:  measurement[,tag=val...] field=value[,field=value...] [timestamp]
Escapes: '\\,' '\\ ' '\\=' in identifiers/tags; field strings are
double-quoted with '\\"' escapes. Values: float (default), int with ``i``
suffix, bool (t/T/true/f/F/false), string ("...").
"""

from __future__ import annotations

from ..storage.rows import PointRow
from .errors import ErrInvalidLineProtocol

# ns multiplier per precision unit — the single source of truth shared by
# the write path (timestamp scaling) and query epoch conversion
PRECISION_NS = {"ns": 1, "u": 1000, "µ": 1000, "ms": 10**6,
                "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9}


def ts_overflows(ts, mult: int) -> bool:
    """True if any lexed int64 timestamp would wrap when scaled to ns.
    Asymmetric bounds: int64 min is a valid lexed value, and abs() of
    it wraps, so compare against floor/ceil of the range instead."""
    if mult == 1 or not getattr(ts, "size", 0):
        return False
    hi = (2 ** 63 - 1) // mult
    lo = -(2 ** 63 // mult)
    return bool(((ts > hi) | (ts < lo)).any())


def parse_lines(data: str, default_time_ns: int = 0,
                precision: str = "ns") -> list[PointRow]:
    mult = PRECISION_NS.get(precision)
    if mult is None:
        raise ErrInvalidLineProtocol(f"bad precision {precision}")
    rows = []
    for raw in data.split("\n"):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        rows.append(_parse_line(line, default_time_ns, mult))
    return rows


def _split_unescaped(s: str, sep: str) -> list[str]:
    """Split on sep respecting backslash escapes, PRESERVING the escape
    sequences in the output (unescape happens once, at the end, via
    _unescape — otherwise nested splits lose track of what was escaped)."""
    out = []
    cur = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_line(line: str, default_time: int, mult: int) -> PointRow:
    # split into measurement+tags | fields | timestamp on unescaped,
    # unquoted spaces
    parts = []
    cur = []
    in_quote = False
    i = 0
    while i < len(line):
        c = line[i]
        if c == "\\" and i + 1 < len(line):
            cur.append(c)
            cur.append(line[i + 1])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
            cur.append(c)
            i += 1
            continue
        if c == " " and not in_quote:
            if cur:
                parts.append("".join(cur))
                cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    if cur:
        parts.append("".join(cur))
    if len(parts) < 2 or len(parts) > 3:
        raise ErrInvalidLineProtocol(f"malformed line: {line!r}")

    measurement, tags = parse_series_key(parts[0])

    fields: dict = {}
    for fpart in _split_fields(parts[1]):
        eq = _find_unescaped_eq(fpart)
        if eq < 0:
            raise ErrInvalidLineProtocol(f"bad field {fpart!r} in {line!r}")
        fields[_unescape(fpart[:eq])] = _parse_value(fpart[eq + 1:], line)
    if not fields:
        raise ErrInvalidLineProtocol(f"no fields: {line!r}")

    if len(parts) == 3:
        try:
            ts = int(parts[2]) * mult
        except ValueError:
            raise ErrInvalidLineProtocol(f"bad timestamp in {line!r}")
        if not -2**63 <= ts < 2**63:
            raise ErrInvalidLineProtocol(
                f"timestamp out of int64 ns range in {line!r}")
    else:
        ts = default_time
    return PointRow(measurement, tags, fields, ts)


def _split_fields(s: str) -> list[str]:
    """Split the field section on unescaped, unquoted commas."""
    out = []
    cur = []
    in_quote = False
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
            cur.append(c)
            i += 1
            continue
        if c == "," and not in_quote:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    if cur:
        out.append("".join(cur))
    return out


def _find_unescaped_eq(s: str) -> int:
    i = 0
    in_quote = False
    while i < len(s):
        c = s[i]
        if c == "\\":
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
        elif c == "=" and not in_quote:
            return i
        i += 1
    return -1


def _parse_value(v: str, line: str):
    if not v:
        raise ErrInvalidLineProtocol(f"empty field value in {line!r}")
    if v[0] == '"':
        if len(v) < 2 or v[-1] != '"':
            raise ErrInvalidLineProtocol(f"bad string value in {line!r}")
        return v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if v in ("t", "T", "true", "True", "TRUE"):
        return True
    if v in ("f", "F", "false", "False", "FALSE"):
        return False
    if v[-1] in ("i", "u"):
        try:
            return int(v[:-1])
        except ValueError:
            raise ErrInvalidLineProtocol(f"bad int value {v!r} in {line!r}")
    try:
        return float(v)
    except ValueError:
        raise ErrInvalidLineProtocol(f"bad value {v!r} in {line!r}")


# ------------------------------------------------- columnar fast ingest

def parse_series_key(key: str) -> tuple[str, dict]:
    """'measurement[,tag=val...]' (escapes preserved) → (name, tags)."""
    head = _split_unescaped(key, ",")
    measurement = _unescape(head[0])
    if not measurement:
        raise ErrInvalidLineProtocol(f"empty measurement in {key!r}")
    tags = {}
    for t in head[1:]:
        kv = _split_unescaped(t, "=")
        if len(kv) != 2 or not kv[0]:
            raise ErrInvalidLineProtocol(f"bad tag {t!r} in {key!r}")
        tags[_unescape(kv[0])] = _unescape(kv[1])
    return measurement, tags


def ingest_lines(engine, db_name: str, data: bytes,
                 default_time_ns: int = 0,
                 precision: str = "ns",
                 text: str | None = None) -> int:
    """Columnar fast-path ingest: the native lexer
    (native/lineprotocol.cpp — the role of the reference's optimized
    protoparser, lib/util/lifted/vm/protoparser/influx/parser.go)
    produces flat arrays; lines group by raw series-key bytes, series
    keys parse ONCE per unique key, and values reach the engine as
    numpy arrays via write_record — no per-row Python objects.

    Falls back to parse_lines + write_points whenever the payload needs
    richer handling: native lib unavailable, parse errors (for the
    Python parser's error messages), string/bool fields, >256 distinct
    field names, or lines of one series with differing field sets."""
    import numpy as np

    mult = PRECISION_NS.get(precision)
    if mult is None:
        raise ErrInvalidLineProtocol(f"bad precision {precision}")
    if isinstance(data, str):
        data = data.encode()

    def slow() -> int:
        t = (text if text is not None
             else data.decode("utf-8", errors="replace"))
        rows = parse_lines(t, default_time_ns, precision)
        return engine.write_points(db_name, rows)

    if not hasattr(engine, "write_record"):
        return slow()
    from ..native import LpParseError, lp_lex
    try:
        lex = lp_lex(data)
    except LpParseError:
        return slow()                 # python path's error messages
    if lex is None or lex.n_lines == 0:
        return slow()
    if lex.ftype.size and int(lex.ftype.max()) >= 2:
        return slow()                 # strings/bools: schema-rich path
    names = []
    for nb in lex.names:
        s = nb.decode("utf-8", errors="replace")
        names.append(_unescape(s) if "\\" in s else s)

    if ts_overflows(lex.ts, mult):
        return slow()                 # int64 overflow: loud python path
    ts = np.where(lex.has_ts.astype(bool),
                  lex.ts * mult, default_time_ns)
    # group lines by raw series-key bytes
    mv = memoryview(data)
    gids = np.empty(lex.n_lines, dtype=np.int64)
    gmap: dict[bytes, int] = {}
    key_list: list[bytes] = []
    so, sl = lex.series_off, lex.series_len
    for i in range(lex.n_lines):
        k = bytes(mv[so[i]:so[i] + sl[i]])
        gi = gmap.get(k)
        if gi is None:
            gi = gmap[k] = len(key_list)
            key_list.append(k)
        gids[i] = gi

    line_of_field = np.repeat(np.arange(lex.n_lines), lex.field_n)
    gid_f = gids[line_of_field]
    order = np.lexsort((lex.fname_id, gid_f))
    sgid = gid_f[order]
    sfid = lex.fname_id[order]
    glo = np.searchsorted(sgid, np.arange(len(key_list)))
    ghi = np.searchsorted(sgid, np.arange(1, len(key_list) + 1))
    group_sizes = np.bincount(gids, minlength=len(key_list))
    # validate and assemble EVERY group before writing anything — a
    # mid-loop fallback after a partial write would double-ingest
    batches = []
    for gi, key in enumerate(key_list):
        seg = order[glo[gi]:ghi[gi]]
        fids_g = sfid[glo[gi]:ghi[gi]]
        n_lines_g = int(group_sizes[gi])
        fields: dict = {}
        times_g = None
        for fid in np.unique(fids_g):
            rows_f = seg[fids_g == fid]
            if len(rows_f) != n_lines_g:
                return slow()         # sparse field sets: per-row path
            ity = lex.ftype[rows_f]
            if int(ity.min()) != int(ity.max()):
                return slow()         # mixed types within one field
            tg = ts[line_of_field[rows_f]]
            if times_g is None:
                times_g = tg
            elif not np.array_equal(times_g, tg):
                return slow()         # field/time misalignment
            fields[names[int(fid)]] = (lex.ival[rows_f]
                                       if int(ity[0]) == 1
                                       else lex.fval[rows_f])
        if not fields:
            return slow()
        mst, tags = parse_series_key(key.decode("utf-8",
                                                errors="replace"))
        batches.append((mst, tags, times_g, fields))
    if hasattr(engine, "write_record_batch"):
        return engine.write_record_batch(db_name, batches)
    n = 0
    for mst, tags, times_g, fields in batches:
        n += engine.write_record(db_name, mst, tags, times_g, fields)
    return n
