"""Query tracing (role of reference lib/tracing: trace.go Span tree,
tree.go rendering; spans threaded through cursors/transforms e.g.
engine/aggregate_cursor.go:51,91-97 and select handler
app/ts-store/transport/handler/select.go:279).

A Trace is a tree of Spans with ns timestamps and free-form fields.
EXPLAIN ANALYZE attaches one to the executor; kernels/stages wrap their
work in `with span.child("..."):`. Rendering matches the reference's
tree output shape (indented names with durations + fields).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start_ns: int = 0
    end_ns: int = 0
    fields: dict = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def child(self, name: str) -> "Span":
        s = Span(name)
        with self._lock:
            self.children.append(s)
        return s

    def add(self, **kv) -> "Span":
        with self._lock:
            self.fields.update(kv)
        return self

    def __enter__(self) -> "Span":
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        self.end_ns = time.perf_counter_ns()

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    def render(self, indent: int = 0) -> list[str]:
        pad = "  " * indent
        dur = self.duration_ns / 1e6
        line = f"{pad}{self.name}: {dur:.3f}ms"
        if self.fields:
            kv = " ".join(f"{k}={v}" for k, v in sorted(
                self.fields.items()))
            line += f" [{kv}]"
        out = [line]
        for c in self.children:
            out.extend(c.render(indent + 1))
        return out


def new_trace(name: str) -> Span:
    s = Span(name)
    s.start_ns = time.perf_counter_ns()
    return s
