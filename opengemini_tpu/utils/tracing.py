"""Query tracing (role of reference lib/tracing: trace.go Span tree,
tree.go rendering; spans threaded through cursors/transforms e.g.
engine/aggregate_cursor.go:51,91-97 and the store select handler
app/ts-store/transport/handler/select.go:279).

A Trace is a tree of Spans with ns timestamps and free-form fields.
Through PR 6 the only consumer was EXPLAIN ANALYZE; this module is now
the always-on **flight recorder**:

- **Head sampling** (``OG_TRACE_SAMPLE``): every HTTP query/write rolls
  a deterministic 1-in-N sample at arrival. Sampled requests carry a
  full span tree through the executor, the streaming pipeline and the
  scheduler; sampled-out requests allocate NO span objects (the hot
  path sees ``span is None``, exactly the pre-PR-7 behavior).
- **Trace context propagation**: ``bind()`` parks the active span +
  trace id in a thread-local; ``cluster/transport.py`` ships the
  context on RPC frames (header key ``tc``) and returns the store-side
  span tree on the final frame (header key ``tspan``), so a sql→store
  scatter merges into ONE tree under the HTTP root span.
- **Flight recorder rings**: the last N completed traces
  (``OG_TRACE_RING``) plus an always-kept slow/error ring (slow,
  failed, shed and killed queries are retained even when their sample
  roll missed — they get a span-less record). Exposed at
  ``/debug/requests`` and ``/debug/trace?id=`` (http/server.py).
- **Chrome trace-event export**: ``chrome_events()`` lays the span
  tree on a per-lane timeline (HTTP/scheduler lane, executor lane, one
  lane per pipeline pull worker) loadable in Perfetto / chrome://tracing.

Span names that measure an executor phase use the SAME stable names as
the ``phases_ms`` aggregation (ops/devstats.QUERY_PHASE_NS); every
other emitted name must be declared in STRUCTURAL_SPANS — the tier-1
phase-drift test (tests/test_tracing.py) enforces both.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field

from . import knobs

# Span names that are NOT phases: structure of the request (roots,
# per-statement containers, RPC hops, pipeline lanes). Everything an
# executor/pipeline/scheduler trace emits is either one of these, a
# prefix-match ("rpc:", "store:"), or a phase name shared with
# ops/devstats.QUERY_PHASE_NS — tests/test_tracing.py fails on drift.
STRUCTURAL_SPANS = {"query", "write", "statement", "scatter",
                    "pipeline.pull", "pipeline.unpack"}
STRUCTURAL_PREFIXES = ("rpc:", "store:")


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class Span:
    name: str
    start_ns: int = 0
    end_ns: int = 0
    fields: dict = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def child(self, name: str) -> "Span":
        s = Span(name)
        with self._lock:
            self.children.append(s)
        return s

    def add(self, **kv) -> "Span":
        with self._lock:
            self.fields.update(kv)
        return self

    def attach(self, child: "Span") -> "Span":
        """Graft an already-built span (a deserialized remote tree)."""
        with self._lock:
            self.children.append(child)
        return child

    def __enter__(self) -> "Span":
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        self.end_ns = time.perf_counter_ns()

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    def walk(self):
        yield self
        for c in list(self.children):
            yield from c.walk()

    def render(self, indent: int = 0) -> list[str]:
        pad = "  " * indent
        dur = self.duration_ns / 1e6
        line = f"{pad}{self.name}: {dur:.3f}ms"
        if self.fields:
            kv = " ".join(f"{k}={v}" for k, v in sorted(
                self.fields.items()))
            line += f" [{kv}]"
        out = [line]
        for c in self.children:
            out.extend(c.render(indent + 1))
        return out

    # ------------------------------------------------- serialization

    def to_dict(self) -> dict:
        """JSON-safe tree (RPC ``tspan`` header, /debug/trace JSON).
        Non-scalar field values degrade to str — the tree must always
        survive json.dumps."""
        fields = {}
        for k, v in self.fields.items():
            if isinstance(v, (int, float, str, bool)) or v is None:
                fields[k] = v
            else:
                fields[k] = str(v)
        return {"name": self.name, "start_ns": int(self.start_ns),
                "end_ns": int(self.end_ns), "fields": fields,
                "children": [c.to_dict() for c in self.children]}

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        s = cls(str(d.get("name", "?")),
                start_ns=int(d.get("start_ns", 0)),
                end_ns=int(d.get("end_ns", 0)),
                fields=dict(d.get("fields") or {}))
        s.children = [cls.from_dict(c) for c in d.get("children", ())]
        return s


def new_trace(name: str) -> Span:
    s = Span(name)
    s.start_ns = time.perf_counter_ns()
    return s


def rebase_into(root: Span, lo_ns: int, hi_ns: int) -> Span:
    """Shift a deserialized REMOTE span tree into the local clock
    window [lo_ns, hi_ns] (the client-side RPC span). Span timestamps
    are perf_counter_ns, whose base is per-process/per-host — a tree
    from another machine lands at a garbage offset in the merged view.
    A tree already inside the window (same-process transport, tests)
    is left untouched so real same-clock timing survives; otherwise
    the whole tree shifts rigidly (durations and relative offsets are
    clock-rate-true either way) to sit centered in the RPC window and
    the root is marked ``clock_rebased`` so the view is honest."""
    if lo_ns <= root.start_ns and root.end_ns <= hi_ns:
        return root
    slack = max(0, (hi_ns - lo_ns) - root.duration_ns)
    shift = (lo_ns + slack // 2) - root.start_ns
    for s in root.walk():
        if s.start_ns:
            s.start_ns += shift
        if s.end_ns:
            s.end_ns += shift
    root.add(clock_rebased=True)
    return root


def annotate_overlap(root: Span, phase_names=None) -> int:
    """Record ``phase_sum_ns``/``overlap_ns`` on a finished root span:
    with the streaming pipeline the phase spans OVERLAP, so their sum
    exceeding the root is the design working — the explicit marker
    makes phase-sum > span self-describing (BENCH_r05 showed
    device_agg 671ms next to device_pull 647ms with no marker)."""
    if phase_names is None:
        from ..ops.devstats import PHASE_NAMES
        phase_names = PHASE_NAMES
    phase_sum = sum(s.duration_ns for s in root.walk()
                    if s is not root and s.name in phase_names)
    overlap = max(0, phase_sum - root.duration_ns)
    root.add(phase_sum_ns=int(phase_sum), overlap_ns=int(overlap))
    return overlap


# ------------------------------------------------- thread-local context

class _Ctx(threading.local):
    def __init__(self):
        self.stack = []


_CTX = _Ctx()


class bind:
    """Bind (span, trace_id) as the thread's active trace context —
    transport.call_stream ships it on RPC frames, the streaming
    pipeline and scatter workers re-bind it on their own threads."""

    def __init__(self, span: Span | None, trace_id: str | None = None):
        self.span = span
        self.trace_id = trace_id

    def __enter__(self):
        _CTX.stack.append((self.span, self.trace_id))
        return self.span

    def __exit__(self, *exc):
        _CTX.stack.pop()


def current_span() -> Span | None:
    return _CTX.stack[-1][0] if _CTX.stack else None


def current_trace_id() -> str | None:
    return _CTX.stack[-1][1] if _CTX.stack else None


# ----------------------------------------------------------- sampling

_SAMPLE_LOCK = threading.Lock()
_SAMPLE_ACC = 0.0


def should_sample() -> bool:
    """Deterministic head sample: OG_TRACE_SAMPLE is a probability
    (>= 1 always, <= 0 never). A fractional accumulator fires exactly
    rate×N times over any N requests — deterministic (tests and the
    perf gate are exact) and honest for EVERY rate, where a
    1-in-round(1/rate) counter silently turned 0.7 into 1.0 and 0.4
    into 0.5."""
    rate = float(knobs.get("OG_TRACE_SAMPLE"))
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    global _SAMPLE_ACC
    with _SAMPLE_LOCK:
        _SAMPLE_ACC += rate
        if _SAMPLE_ACC >= 1.0:
            _SAMPLE_ACC -= 1.0
            return True
        return False


# ----------------------------------------------------- flight recorder

@dataclass
class TraceRecord:
    """One completed request in the recorder. ``root`` is None for a
    sampled-out request retained only because it was slow/failed
    (the overhead guarantee: no span tree unless the head sample
    hit)."""
    trace_id: str
    kind: str                      # "query" | "write"
    text: str                      # redacted statement text
    db: str
    start_wall: float              # unix seconds
    duration_ns: int
    status: str = "ok"             # ok|error|slow|shed|killed
    error: str = ""
    sampled: bool = True
    root: Span | None = None
    # sustained-serving columns: which tenant's fair share the request
    # charged (X-OG-Tenant) and how the result cache resolved it
    # (hit/partial/miss/bypass; "" for writes / non-SELECTs)
    tenant: str = ""
    cache_status: str = ""

    def summary(self) -> dict:
        txt = self.text
        if len(txt) > 160:
            txt = txt[:157] + "..."
        return {"trace_id": self.trace_id, "kind": self.kind,
                "query": txt, "db": self.db,
                "start": self.start_wall,
                "duration_ms": round(self.duration_ns / 1e6, 3),
                "status": self.status, "sampled": self.sampled,
                "tenant": self.tenant or "default",
                "cache_status": self.cache_status,
                **({"error": self.error} if self.error else {})}


class FlightRecorder:
    """Bounded rings of completed traces: ``recent`` keeps the last N
    sampled traces of any status; ``slow`` always keeps slow / error /
    shed / killed requests (span-less when their sample roll missed),
    driven by the now-wired slow_query_threshold_ns."""

    def __init__(self, recent_cap: int | None = None,
                 slow_cap: int = 64):
        if recent_cap is None:
            recent_cap = max(1, int(knobs.get("OG_TRACE_RING")))
        self._lock = threading.Lock()
        self.recent: deque = deque(maxlen=recent_cap)
        self.slow: deque = deque(maxlen=slow_cap)
        self._by_id: dict[str, TraceRecord] = {}

    def record(self, rec: TraceRecord) -> None:
        with self._lock:
            if rec.sampled:
                self._evict(self.recent)
                self.recent.append(rec)
                self._by_id[rec.trace_id] = rec
            if rec.status != "ok":
                self._evict(self.slow)
                self.slow.append(rec)
                self._by_id[rec.trace_id] = rec

    def _evict(self, ring: deque) -> None:
        """Drop the id-index entry a full ring is about to push out —
        unless the other ring still holds the record, or the index
        already points at a NEWER record under the same id (a client
        can force-reuse a trace id via X-OG-Trace; evicting the old
        record must not orphan the live one)."""
        if len(ring) == ring.maxlen:
            old = ring[0]
            if self._by_id.get(old.trace_id) is not old:
                return
            other = self.slow if ring is self.recent else self.recent
            if not any(r is old for r in other):
                self._by_id.pop(old.trace_id, None)

    def get(self, trace_id: str) -> TraceRecord | None:
        with self._lock:
            return self._by_id.get(trace_id)

    def summaries(self) -> dict:
        with self._lock:
            return {"recent": [r.summary() for r in
                               reversed(self.recent)],
                    "slow": [r.summary() for r in reversed(self.slow)],
                    "recent_cap": self.recent.maxlen,
                    "slow_cap": self.slow.maxlen}

    def reset(self) -> None:
        with self._lock:
            self.recent.clear()
            self.slow.clear()
            self._by_id.clear()


_RECORDER: FlightRecorder | None = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> FlightRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


# ------------------------------------------------ chrome trace export

def _lane_of(span: Span, parent_lane: str) -> str:
    lane = span.fields.get("lane")
    if lane:
        return str(lane)
    if span.name in ("query", "write", "statement"):
        return "http"
    if span.name == "sched_queue":
        return "scheduler"
    if span.name.startswith(STRUCTURAL_PREFIXES) \
            or span.name == "scatter":
        return "rpc"
    if span.name.startswith("pipeline."):
        return "pipeline"
    if parent_lane in ("http", "scheduler"):
        return "executor"
    return parent_lane


def chrome_events(rec: TraceRecord) -> list[dict]:
    """Chrome trace-event (Perfetto-loadable) view of one trace: spans
    become complete ("X") events laid out per lane — HTTP/scheduler,
    executor, RPC hops, and one lane per pipeline pull worker — with
    span fields (D2H bytes, transport labels) as event args."""
    if rec.root is None:
        return []
    lanes: dict[str, int] = {}
    events: list[dict] = []
    t0 = rec.root.start_ns

    def tid_of(lane: str) -> int:
        if lane not in lanes:
            lanes[lane] = len(lanes) + 1
        return lanes[lane]

    def emit(span: Span, parent_lane: str):
        lane = _lane_of(span, parent_lane)
        start = span.start_ns or t0
        end = max(span.end_ns, start)
        args = {k: v for k, v in span.fields.items()
                if isinstance(v, (int, float, str, bool))}
        events.append({"name": span.name, "ph": "X", "pid": 1,
                       "tid": tid_of(lane),
                       "ts": (start - t0) / 1e3,
                       "dur": (end - start) / 1e3,
                       "cat": rec.kind, "args": args})
        for c in list(span.children):
            emit(c, lane)

    emit(rec.root, "http")
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": lane}}
            for lane, tid in sorted(lanes.items(), key=lambda kv: kv[1])]
    meta.append({"name": "process_name", "ph": "M", "pid": 1,
                 "args": {"name": f"trace {rec.trace_id} "
                                  f"({rec.status})"}})
    return meta + events


def chrome_json(rec: TraceRecord) -> str:
    return json.dumps({"traceEvents": chrome_events(rec),
                       "displayTimeUnit": "ms",
                       "otherData": rec.summary()})
