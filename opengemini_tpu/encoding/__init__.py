from .blocks import (
    encode_integer_block, decode_integer_block,
    encode_float_block, decode_float_block,
    encode_boolean_block, decode_boolean_block,
    encode_string_block, decode_string_block,
    encode_time_block, decode_time_block,
    encode_validity, decode_validity,
)
