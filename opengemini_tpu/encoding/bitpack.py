"""Fixed-width bit packing, fully vectorized with numpy.

Building block for the simple8b and delta codecs. All packing is big-endian
bit order within the stream.
"""

from __future__ import annotations

import numpy as np


def pack_bits(values: np.ndarray, width: int) -> bytes:
    """Pack uint64 values into a big-endian bitstream of `width` bits each."""
    n = len(values)
    if n == 0 or width == 0:
        return b""
    v = values.astype(">u8", copy=False)
    # (n, 64) bit matrix, keep low `width` bits of each value
    bits = np.unpackbits(v.view(np.uint8).reshape(n, 8), axis=1)[:, 64 - width:]
    return np.packbits(bits.reshape(-1)).tobytes()


def unpack_bits(buf: bytes | memoryview, n: int, width: int) -> np.ndarray:
    """Inverse of pack_bits: read n values of `width` bits."""
    if n == 0 or width == 0:
        return np.zeros(n, dtype=np.uint64)
    raw = np.frombuffer(buf, dtype=np.uint8, count=(n * width + 7) // 8)
    bits = np.unpackbits(raw)[: n * width].reshape(n, width)
    full = np.zeros((n, 64), dtype=np.uint8)
    full[:, 64 - width:] = bits
    return np.packbits(full, axis=1).view(">u8").reshape(n).astype(np.uint64)


def zigzag_encode(v: np.ndarray) -> np.ndarray:
    """int64 -> uint64 zigzag (small magnitudes -> small codes)."""
    v = v.astype(np.int64, copy=False)
    return ((v.astype(np.uint64) << np.uint64(1))
            ^ (v >> np.int64(63)).astype(np.uint64))


def zigzag_decode(u: np.ndarray) -> np.ndarray:
    u = u.astype(np.uint64, copy=False)
    return ((u >> np.uint64(1)).astype(np.int64)
            ^ -(u & np.uint64(1)).astype(np.int64))


def bit_widths(v: np.ndarray) -> np.ndarray:
    """Number of significant bits per uint64 value (0 -> 0 bits)."""
    v = v.astype(np.uint64, copy=False)
    w = np.zeros(len(v), dtype=np.int64)
    x = v.copy()
    for shift in (32, 16, 8, 4, 2, 1):
        hi = x >> np.uint64(shift)
        mask = hi != 0
        w[mask] += shift
        x = np.where(mask, hi, x)
    w[v != 0] += 1
    return w
