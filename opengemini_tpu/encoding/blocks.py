"""Column block codecs with adaptive codec selection.

Role of reference lib/encoding/encoding.go:325-389 (EncodeIntegerBlock /
DecodeFloatBlock etc.) and lib/compress/float.go (RLE floats). Every block is
``[1-byte codec id][payload]``; the encoder picks the cheapest codec for the
data, the decoder dispatches on the id. All codecs are lossless and
bit-exact.

Codec menu (TPU-first bias: decode speed on a single host core matters more
than the last 5% of ratio, because decoded blocks feed device DMA):

ints:    CONST / DELTA_S8B (zigzag delta + simple8b) / S8B / ZSTD raw
floats:  CONST / RLE / GORILLA / ZSTD raw
bools:   BITPACK
strings: ZSTD of offsets+bytes / RAW
time:    CONST_DELTA (t0, step, n) / DELTA_S8B / ZSTD raw
"""

from __future__ import annotations

import struct
import threading

import numpy as np

from ..utils.zstd_compat import zstandard

from . import dfor, gorilla, simple8b
from .bitpack import zigzag_decode, zigzag_encode

# codec ids (shared namespace across column types)
RAW = 0
ZSTD = 1
CONST = 2
CONST_DELTA = 3
DELTA_S8B = 4
S8B = 5
RLE = 6
GORILLA = 7
BITPACK = 8
# device-friendly frame-of-reference bit-packed layout (dfor.py):
# fixed-width u32 lanes whose decode is shifts+masks — the codec tier
# ops/device_decode.dfor_expand expands IN-KERNEL so compressed bytes
# (not dense f64 planes) cross the H2D link
DFOR = 9


def _device_layout_on() -> bool:
    """Gate for EMITTING the DFOR tier (OG_WRITE_DEVICE_LAYOUT,
    default on). Decoders dispatch on the codec byte regardless, so
    flipping the knob never strands written data."""
    from ..utils import knobs
    return bool(knobs.get("OG_WRITE_DEVICE_LAYOUT"))

# zstandard (de)compressor objects are not safe for concurrent use from
# multiple threads; keep one pair per thread (flush/compaction run parallel)
_tls = threading.local()


def _zstd_c(b: bytes) -> bytes:
    c = getattr(_tls, "zc", None)
    if c is None:
        c = _tls.zc = zstandard.ZstdCompressor(level=3)
    return c.compress(b)


def _zstd_c_fast(b: bytes) -> bytes:
    """Speed-tier compressor for NUMERIC raw payloads (level 1 — the
    zlib-shim routes it to the native LZ4 block codec): f64/int64
    mantissa bytes barely reward zlib's extra search, while encode AND
    decode speed feed the flush and scan paths directly. Strings keep
    the ratio tier (repetitive tags compress 2-5× better there)."""
    c = getattr(_tls, "zcf", None)
    if c is None:
        c = _tls.zcf = zstandard.ZstdCompressor(level=1)
    return c.compress(b)


# cap on a single decompressed block: segments are <=64k values of 8 bytes
# plus headers, so anything claiming more is corrupt or hostile
_MAX_BLOCK_BYTES = 64 * 1024 * 1024


def _zstd_d(b) -> bytes:
    d = getattr(_tls, "zd", None)
    if d is None:
        d = _tls.zd = zstandard.ZstdDecompressor()
    b = bytes(b)
    params = zstandard.get_frame_parameters(b)
    if params.content_size and params.content_size > _MAX_BLOCK_BYTES:
        raise ValueError(
            f"zstd block declares {params.content_size} bytes "
            f"(> {_MAX_BLOCK_BYTES} cap); refusing to decompress")
    return d.decompress(b, max_output_size=_MAX_BLOCK_BYTES)


# ---------------------------------------------------------------- integers

# simple8b packing floor: a word with selector (count, width) carries
# EXACTLY `count` values, and a value of bit width b only fits words
# whose selector width ≥ b — whose count is at most c_max(b). So any
# s8b packing spends #words ≥ Σ 1/c_max(b_i), i.e. ≥ ceil(Σ units /
# 5040) words with units = 5040 / c_max(b) (5040 = lcm of the selector
# counts; exact integer arithmetic, no float ceilings). c_max by
# width: 0→240, 1→60, 2→30, 3→20, 4→15, 5→12, 6→10, 7→8, 8→7,
# 9-10→6, 11-12→5, 13-15→4, 16-20→3, 21-30→2, 31+→1.
_S8B_UNITS = np.array(
    [5040 // 240] + [5040 // 60] + [5040 // 30] + [5040 // 20]
    + [5040 // 15] + [5040 // 12] + [5040 // 10] + [5040 // 8]
    + [5040 // 7] + [5040 // 6] * 2 + [5040 // 5] * 2
    + [5040 // 4] * 3 + [5040 // 3] * 5 + [5040 // 2] * 10
    + [5040] * 34, dtype=np.int64)


def _s8b_floor(widths: np.ndarray) -> int:
    """Bytes ANY simple8b packing of values with these bit widths must
    spend (a provable lower bound — see _S8B_UNITS)."""
    units = int(_S8B_UNITS[np.minimum(widths, 64)].sum())
    return 8 * (-(-units // 5040))


def encode_integer_block(values: np.ndarray) -> bytes:
    from .bitpack import bit_widths
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    if n == 0:
        return bytes([RAW])
    if n > 1 and (v == v[0]).all():
        return bytes([CONST]) + struct.pack("<q", int(v[0]))
    # zigzag deltas usually tiny for counters/timestamps
    d = np.diff(v, prepend=v[0:1])
    d[0] = 0
    zz = zigzag_encode(d)
    u = v.view(np.uint64)
    # codec PRE-SELECTION from shape probes alone: the DFOR
    # frame-of-reference width costs one zigzag + one max (no
    # packing), and the s8b floors above bound the menu's other exits
    # without running the greedy packer. Two short-circuits follow:
    # (1) DFOR in the narrow-lane band (width ≤ 16, ≥ 4× under raw)
    # whose EXACT payload size undercuts both s8b floors and raw is
    # emitted directly — no possible s8b packing can beat it, and the
    # zstd trial is skipped too (heuristic, not proof: the LZ4-tier
    # codec does not reach 4× on entropy-bearing numeric lanes); the
    # device layout lands on disk so cold queries ride compressed
    # H2D. (2) An s8b trial whose floor already reaches the raw
    # payload is provably futile and skipped byte-identically.
    zz_ok = simple8b.can_encode(zz)
    u_ok = simple8b.can_encode(u)
    big = 1 << 62
    floor_delta = 8 + _s8b_floor(bit_widths(zz)) if zz_ok else big
    floor_raw = _s8b_floor(bit_widths(u)) if u_ok else big
    if _device_layout_on():
        r, ref, w = dfor.probe_int(v)
        if 0 < w <= 16:
            df_size = dfor.size_bytes(n, w)
            # the menu is first-hit, so DFOR wins by undercutting the
            # first trial that would have fired (delta-s8b when the
            # deltas are encodable, raw-s8b otherwise) plus raw
            first_floor = floor_delta if zz_ok else floor_raw
            if df_size <= min(first_floor, 8 * n):
                return bytes([DFOR]) + dfor.finish_int(r, ref, w)
    if zz_ok and floor_delta < 8 * n:
        payload = struct.pack("<q", int(v[0])) + simple8b.encode(zz)
        if len(payload) < 8 * n:
            return bytes([DELTA_S8B]) + payload
    if u_ok and floor_raw < 8 * n:
        payload = simple8b.encode(u)
        if len(payload) < 8 * n:
            return bytes([S8B]) + payload
    raw = v.tobytes()
    z = _zstd_c_fast(raw)
    # DFOR replaces the opaque byte tier for ints (delta-friendly data
    # already took the s8b exits above — those stay the compact host
    # tier; ints never stack on device): only when it beats BOTH raw
    # and zstd does the device-layout tier win here
    if _device_layout_on():
        df = dfor.encode_int(v)
        if df is not None and len(df) < min(len(raw), len(z)):
            return bytes([DFOR]) + df
    if len(z) < len(raw):
        return bytes([ZSTD]) + z
    return bytes([RAW]) + raw


def decode_integer_block(buf: bytes | memoryview, n: int) -> np.ndarray:
    codec, payload = buf[0], memoryview(buf)[1:]
    if codec == RAW:
        return np.frombuffer(payload, dtype=np.int64, count=n).copy()
    if codec == ZSTD:
        return np.frombuffer(_zstd_d(payload), dtype=np.int64,
                             count=n).copy()
    if codec == CONST:
        return np.full(n, struct.unpack("<q", payload[:8])[0], dtype=np.int64)
    if codec == S8B:
        return simple8b.decode(payload, n).view(np.int64)
    if codec == DFOR:
        return dfor.decode(payload, n, "i64")
    if codec == DELTA_S8B:
        first = struct.unpack("<q", payload[:8])[0]
        d = zigzag_decode(simple8b.decode(payload[8:], n))
        d[0] = first
        return np.cumsum(d)
    raise ValueError(f"bad integer codec {codec}")


# ------------------------------------------------------------------ floats

def encode_float_block(values: np.ndarray, prefer: str = "auto") -> bytes:
    v = np.ascontiguousarray(values, dtype=np.float64)
    n = len(v)
    if n == 0:
        return bytes([RAW])
    u = v.view(np.uint64)
    if n > 1 and (u == u[0]).all():
        return bytes([CONST]) + v[:1].tobytes()
    # RLE when the data is run-heavy (reference lib/compress/float.go:31)
    runs = 1 + int(np.count_nonzero(u[1:] != u[:-1]))
    if runs * 3 < n:
        starts = np.concatenate([[0], np.nonzero(u[1:] != u[:-1])[0] + 1])
        lengths = np.diff(np.concatenate([starts, [n]])).astype(np.uint32)
        payload = (struct.pack("<I", runs) + v[starts].tobytes()
                   + lengths.tobytes())
        return bytes([RLE]) + payload
    if prefer == "gorilla":
        return bytes([GORILLA]) + gorilla.encode(v)
    # device-friendly tier: floats are the type the HBM slab path
    # stacks, so decode locality beats the last % of ratio — DFOR wins
    # whenever it beats the RAW payload (a 2-decimal gauge packs to
    # ~14-bit lanes; full-mantissa noise hits width 64 and falls
    # through to the legacy menu). raw bytes only materialize on the
    # fall-through: the winning-DFOR path needs just the size bound
    if _device_layout_on():
        df = dfor.encode_float(v)
        if df is not None and len(df) < 8 * n:
            return bytes([DFOR]) + df
    raw = v.tobytes()
    z = _zstd_c_fast(raw)
    if len(z) < len(raw):
        return bytes([ZSTD]) + z
    return bytes([RAW]) + raw


def parse_rle_payload(payload) -> tuple[np.ndarray, np.ndarray]:
    """RLE wire format → (run values f64, run lengths i64). Shared by the
    CPU decoder and the device decoder (ops/device_decode.py)."""
    runs = struct.unpack("<I", payload[:4])[0]
    vals = np.frombuffer(payload[4:4 + 8 * runs], dtype=np.float64)
    lens = np.frombuffer(payload[4 + 8 * runs:4 + 12 * runs],
                         dtype=np.uint32).astype(np.int64)
    return vals, lens


def decode_float_block(buf: bytes | memoryview, n: int) -> np.ndarray:
    codec, payload = buf[0], memoryview(buf)[1:]
    if codec == RAW:
        return np.frombuffer(payload, dtype=np.float64, count=n).copy()
    if codec == ZSTD:
        return np.frombuffer(_zstd_d(payload), dtype=np.float64,
                             count=n).copy()
    if codec == CONST:
        return np.full(n, np.frombuffer(payload[:8], dtype=np.float64)[0])
    if codec == RLE:
        vals, lens = parse_rle_payload(payload)
        return np.repeat(vals, lens)[:n]
    if codec == GORILLA:
        return gorilla.decode(bytes(payload), n)
    if codec == DFOR:
        return dfor.decode(payload, n, "f64")
    raise ValueError(f"bad float codec {codec}")


# ----------------------------------------------------------------- boolean

def encode_boolean_block(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.bool_)
    return bytes([BITPACK]) + np.packbits(v).tobytes()


def decode_boolean_block(buf: bytes | memoryview, n: int) -> np.ndarray:
    codec, payload = buf[0], memoryview(buf)[1:]
    if codec != BITPACK:
        raise ValueError(f"bad boolean codec {codec}")
    return np.unpackbits(np.frombuffer(payload, dtype=np.uint8),
                         count=n).astype(np.bool_)


# ----------------------------------------------------------------- strings

def encode_string_block(offsets: np.ndarray, data: bytes) -> bytes:
    """Encodes arrow-style (offsets,data); reference uses snappy
    (lib/encoding/string.go:20), we use zstd."""
    n = len(offsets) - 1
    raw = struct.pack("<I", n) + offsets.astype(np.int32).tobytes() + data
    z = _zstd_c(raw)
    if len(z) < len(raw):
        return bytes([ZSTD]) + z
    return bytes([RAW]) + raw


def decode_string_block(buf: bytes | memoryview) -> tuple[np.ndarray, bytes]:
    codec, payload = buf[0], memoryview(buf)[1:]
    if codec == ZSTD:
        payload = memoryview(_zstd_d(payload))
    elif codec != RAW:
        raise ValueError(f"bad string codec {codec}")
    n = struct.unpack("<I", payload[:4])[0]
    offsets = np.frombuffer(payload[4:4 + 4 * (n + 1)], dtype=np.int32).copy()
    data = bytes(payload[4 + 4 * (n + 1):])
    return offsets, data


# -------------------------------------------------------------------- time

def encode_time_block(values: np.ndarray) -> bytes:
    """Timestamps: constant-stride fast path (the overwhelmingly common
    regular-sampling case decodes to an arange)."""
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    if n == 0:
        return bytes([RAW])
    if n >= 2:
        d = np.diff(v)
        if (d == d[0]).all():
            return bytes([CONST_DELTA]) + struct.pack(
                "<qq", int(v[0]), int(d[0]))
    if n == 1:
        return bytes([CONST_DELTA]) + struct.pack("<qq", int(v[0]), 0)
    return encode_integer_block(v)


def decode_time_block(buf: bytes | memoryview, n: int) -> np.ndarray:
    if buf[0] == CONST_DELTA:
        t0, step = struct.unpack("<qq", memoryview(buf)[1:17])
        return t0 + step * np.arange(n, dtype=np.int64)
    return decode_integer_block(buf, n)


# ---------------------------------------------------------------- validity

def encode_validity(valid: np.ndarray) -> bytes:
    """Null bitmap; all-valid collapses to a 1-byte marker (the dominant
    case — reference ColVal keeps a bitmap always, we special-case)."""
    v = np.ascontiguousarray(valid, dtype=np.bool_)
    if v.all():
        return bytes([CONST])
    return bytes([BITPACK]) + np.packbits(v).tobytes()


def decode_validity(buf: bytes | memoryview, n: int) -> np.ndarray:
    if buf[0] == CONST:
        return np.ones(n, dtype=np.bool_)
    return np.unpackbits(np.frombuffer(memoryview(buf)[1:], dtype=np.uint8),
                         count=n).astype(np.bool_)
