"""DFOR — device-friendly frame-of-reference bit-packed numeric layout.

The byte codecs (gorilla / simple8b / zstd) compress well but decode
SEQUENTIALLY: every value depends on a variable-length prefix, which
maps to neither the VPU nor a vectorized numpy gather. DFOR trades a
few percent of ratio for a layout whose decode is pure shifts+masks
over fixed-width lanes — the "GPU Acceleration of SQL Analytics on
Compressed Data" design point (PAPERS.md): ship the COMPRESSED bytes
over H2D and expand in-kernel (ops/device_decode.dfor_expand), instead
of decoding on host and moving dense f64 planes.

Wire format (after the 1-byte codec id of encoding/blocks.py):

    [transform u8][width u8][dscale u8][pad u8][n u32][ref 8B][words u32…]

One reference value + one bit width per segment; residuals are packed
little-endian (value i occupies stream bits [i·width, (i+1)·width), bit
j lives in u32 word j>>5 at lane position j&31). ``width`` is rounded
UP to a multiple of 2 (shape-class hygiene: the device unpack kernel
compiles per (width, n) class, so the encoder bounds the class count
at write time; ≤ 1 wasted bit/value).

Transforms (residual ↔ value, all bit-exact by construction):

    T_INT     zigzag(v − ref) in wrapping int64 (ints/times; ref=v[0])
    T_XORREF  bits(v) ^ bits(ref)                     (floats)
    T_XORPRED bits(v_i) ^ bits(v_{i-1}), predecessor of v_0 is ref —
              decode is a prefix-XOR scan (associative → vectorizes)
    T_SCALED  zigzag(k − k0) where v == k / 10^dscale EXACTLY in f64 —
              the decimal-quantized telemetry fast path (a 2-decimal
              gauge packs to ~14 bits instead of 52 XOR mantissa bits).
              Eligibility is VERIFIED at encode: every row must satisfy
              fl(k / 10^dscale) == v bit for bit, so decode (int→f64
              convert + one IEEE divide) reproduces the stored bits
              exactly on host and on any real-f64 device backend.

The encoder tries every eligible transform and keeps the narrowest;
callers (encoding/blocks.py) only emit DFOR when it beats the RAW
payload, behind ``OG_WRITE_DEVICE_LAYOUT``.
"""

from __future__ import annotations

import struct

import numpy as np

from .bitpack import zigzag_decode, zigzag_encode

__all__ = ["T_INT", "T_XORREF", "T_XORPRED", "T_SCALED",
           "HEADER_BYTES", "encode_int", "encode_float", "decode",
           "probe_int", "finish_int", "size_bytes",
           "parse_header", "payload_words", "unpack_words",
           "pack_words", "inverse_transform_batch", "decode_batch"]

T_INT = 0
T_XORREF = 1
T_XORPRED = 2
T_SCALED = 3

HEADER_BYTES = 16          # transform, width, dscale, pad, n u32, ref

# largest decimal scale T_SCALED probes: 10^6 keeps k·scale round-trip
# error far below 0.5 ulp for |k| < 2^51 (the verify step is still the
# authority — this only bounds the probe loop)
_MAX_DSCALE = 6

_U64_1 = np.uint64(1)
_U64_5 = np.uint64(5)
_U64_31 = np.uint64(31)
_U64_32 = np.uint64(32)
_U64_64 = np.uint64(64)


def _round_width(w: int) -> int:
    """Shape-class hygiene: widths quantize to multiples of 2 so the
    per-(width, n) device kernel classes stay bounded (≤ 32 widths)."""
    return min(64, (int(w) + 1) & ~1)


def pack_words(r: np.ndarray, width: int) -> np.ndarray:
    """Pack (n,) uint64 residuals into little-endian u32 lanes."""
    n = len(r)
    if n == 0 or width == 0:
        return np.zeros(0, dtype=np.uint32)
    r = r.astype(np.uint64, copy=False)
    if width == 64:
        # degenerate lane width: the packed stream IS the raw
        # little-endian bytes — one view, not the (n, 64) bit-matrix
        # intermediate (512 B/value of temp on the flush hot path)
        return np.ascontiguousarray(r).view("<u4").astype(
            np.uint32, copy=False)
    shifts = np.arange(width, dtype=np.uint64)
    bits = ((r[:, None] >> shifts[None, :]) & _U64_1).astype(np.uint8)
    packed = np.packbits(bits.reshape(-1), bitorder="little")
    pad = (-len(packed)) % 4
    if pad:
        packed = np.concatenate(
            [packed, np.zeros(pad, dtype=np.uint8)])
    return packed.view("<u4").copy()


def unpack_words(words: np.ndarray, n: int, width: int) -> np.ndarray:
    """Inverse of pack_words; ``words`` may be (nw,) or batched
    (nb, nw) — returns (n,) / (nb, n) uint64. The 3-word gather+shift
    form here is the SAME arithmetic the device kernel runs
    (ops/device_decode), so host/device parity is by construction."""
    shape = words.shape[:-1] + (n,)
    if n == 0 or width == 0:
        return np.zeros(shape, dtype=np.uint64)
    w64 = np.concatenate(
        [words.astype(np.uint64),
         np.zeros(words.shape[:-1] + (2,), dtype=np.uint64)], axis=-1)
    pos = np.arange(n, dtype=np.uint64) * np.uint64(width)
    iw = (pos >> _U64_5).astype(np.int64)
    off = pos & _U64_31
    lo = w64[..., iw]
    mid = w64[..., iw + 1]
    hi = w64[..., iw + 2]
    r = (lo >> off) | (mid << (_U64_32 - off))
    s3 = (_U64_64 - off) % _U64_64
    r = r | np.where(off > 0, hi << s3, np.uint64(0))
    if width < 64:
        r = r & np.uint64((1 << width) - 1)
    return r


def _header(transform: int, width: int, dscale: int, n: int,
            ref_u64: int) -> bytes:
    return struct.pack("<BBBBIQ", transform, width, dscale, 0, n,
                       ref_u64 & 0xFFFFFFFFFFFFFFFF)


def parse_header(payload) -> tuple[int, int, int, int, int]:
    """payload (after the codec byte) → (transform, width, dscale, n,
    ref_u64)."""
    transform, width, dscale, _pad, n, ref = struct.unpack(
        "<BBBBIQ", bytes(payload[:HEADER_BYTES]))
    return transform, width, dscale, n, ref


def payload_words(payload, n: int, width: int) -> np.ndarray:
    """The packed u32 lane array of one DFOR payload."""
    nw = (n * width + 31) // 32
    return np.frombuffer(bytes(payload[HEADER_BYTES:
                                       HEADER_BYTES + 4 * nw]),
                         dtype="<u4").astype(np.uint32, copy=False)


# ------------------------------------------------------------ encode

def _try_scaled(v: np.ndarray):
    """(dscale, k int64) when v is exactly k/10^dscale in f64, else
    None. Verified bit-for-bit — np.rint only proposes."""
    if len(v) == 0 or not np.isfinite(v).all():
        return None
    vu = v.view(np.uint64)
    for d in range(_MAX_DSCALE + 1):
        scale = np.float64(10.0 ** d)
        k = np.rint(v * scale)
        if not np.isfinite(k).all() or np.abs(k).max() >= 2.0 ** 51:
            return None            # larger d only grows k
        ki = k.astype(np.int64)
        if np.array_equal((ki / scale).view(np.uint64), vu):
            return d, ki
    return None


def _zz_residuals(ki: np.ndarray):
    """(residuals u64, ref u64-bits) — zigzag deltas against the first
    value, in wrapping 64-bit arithmetic (zigzag extremes round-trip
    through the wrap)."""
    ref = int(ki[0]) & 0xFFFFFFFFFFFFFFFF
    with np.errstate(over="ignore"):
        d = ki.view(np.uint64) - np.uint64(ref)
    return zigzag_encode(d.view(np.int64)), ref


def _max_width(r: np.ndarray) -> int:
    """max bit width over u64 residuals — bit_length of the max value
    (one vectorized max; the per-element bit_widths pass is only
    needed when a caller wants the full distribution)."""
    return int(r.max()).bit_length() if len(r) else 0


def probe_int(values: np.ndarray):
    """Cheap shape probe for the int menu's codec PRE-SELECTION:
    (residuals u64, ref, rounded width) without packing a single word
    — cost is one zigzag + one max. ``size_bytes(n, width)`` of the
    result tells the caller whether DFOR provably undercuts the other
    tiers BEFORE any of them runs."""
    v = np.ascontiguousarray(values, dtype=np.int64)
    r, ref = _zz_residuals(v)
    return r, ref, _round_width(_max_width(r))


def size_bytes(n: int, width: int) -> int:
    """Exact DFOR payload size (header + u32 lanes) for n values at
    ``width`` bits — computable from the probe alone."""
    return HEADER_BYTES + 4 * ((n * width + 31) // 32)


def finish_int(r: np.ndarray, ref: int, width: int) -> bytes:
    """Pack a probe_int() result into the T_INT payload."""
    return (_header(T_INT, width, 0, len(r), ref)
            + pack_words(r, width).tobytes())


def encode_int(values: np.ndarray) -> bytes | None:
    """DFOR payload for an int64/time block (T_INT), or None when the
    packed form cannot beat the raw payload (width 64)."""
    n = len(values)
    if n == 0:
        return None
    r, ref, width = probe_int(values)
    if width >= 64:
        return None
    return finish_int(r, ref, width)


def encode_float(values: np.ndarray) -> bytes | None:
    """DFOR payload for an f64 block: narrowest of T_SCALED /
    T_XORPRED / T_XORREF (bit-exact all three), or None for n == 0.

    Codec pre-selection fast path: a T_SCALED hit at width ≤ 16 (the
    decimal-quantized telemetry shape — a 2-decimal gauge packs to
    ~14-bit lanes, ≥ 4× under the raw payload) is emitted WITHOUT
    trying the XOR transforms: on data that quantizes to ≤ 16-bit
    deltas the mantissa-XOR residuals are never competitive, and the
    two skipped transform trials were the float flush encode's
    dominant cost."""
    v = np.ascontiguousarray(values, dtype=np.float64)
    n = len(v)
    if n == 0:
        return None
    u = v.view(np.uint64)
    cands: list[tuple[int, int, int, int, np.ndarray]] = []
    sc = _try_scaled(v)
    if sc is not None:
        d, ki = sc
        r, ref = _zz_residuals(ki)
        w = _round_width(_max_width(r))
        if w <= 16:
            return (_header(T_SCALED, w, d, n, ref)
                    + pack_words(r, w).tobytes())
        cands.append((w, T_SCALED, d, ref, r))
    r_pred = u ^ np.concatenate([u[:1], u[:-1]])
    cands.append((_round_width(_max_width(r_pred)),
                  T_XORPRED, 0, int(u[0]), r_pred))
    r_ref = u ^ u[0]
    cands.append((_round_width(_max_width(r_ref)),
                  T_XORREF, 0, int(u[0]), r_ref))
    width, transform, dscale, ref, r = min(
        cands, key=lambda c: (c[0], c[1]))
    words = pack_words(r, width)
    return _header(transform, width, dscale, n, ref) + words.tobytes()


# ------------------------------------------------------------ decode

def inverse_transform_batch(r: np.ndarray, refs: np.ndarray,
                            transform: int, dscale: int,
                            kind: str) -> np.ndarray:
    """Residuals (nb, n) u64 + per-row refs (nb,) u64 → decoded values
    (nb, n), f64 (kind \"f64\") or i64. Shared by the per-segment host
    decoder, the bulk flat-scan group decoder (query/scan.py) and the
    host half of the device parity tests."""
    refs = refs.astype(np.uint64, copy=False)[..., None]
    if transform in (T_INT, T_SCALED):
        with np.errstate(over="ignore"):
            k = (zigzag_decode(r).view(np.uint64)
                 + refs).view(np.int64)
        if transform == T_INT:
            return k if kind == "i64" else k.astype(np.float64)
        return k / np.float64(10.0 ** dscale)
    if transform == T_XORREF:
        u = r ^ refs
    elif transform == T_XORPRED:
        u = np.bitwise_xor.accumulate(r, axis=-1) ^ refs
    else:
        raise ValueError(f"bad DFOR transform {transform}")
    return u.view(np.float64) if kind == "f64" else u.view(np.int64)


def decode_batch(words: np.ndarray, refs: np.ndarray, n: int,
                 width: int, transform: int, dscale: int,
                 kind: str) -> np.ndarray:
    """Vectorized decode of a BATCH of same-shape DFOR segments:
    (nb, nw) u32 words + (nb,) refs → (nb, n) values. One numpy pass
    regardless of nb — the flat-scan group decoder's workhorse."""
    r = unpack_words(words, n, width)
    return inverse_transform_batch(r, refs, transform, dscale, kind)


def decode(payload, n: int, kind: str) -> np.ndarray:
    """One segment: DFOR payload (after the codec byte) → (n,) values.
    ``kind`` is \"f64\" or \"i64\" (the column type decides — the
    payload serves either view of T_INT)."""
    transform, width, dscale, n_hdr, ref = parse_header(payload)
    if n_hdr != n:
        raise ValueError(f"DFOR row-count mismatch: header {n_hdr}, "
                         f"caller {n}")
    words = payload_words(payload, n, width)
    out = decode_batch(words[None, :],
                       np.array([ref], dtype=np.uint64),
                       n, width, transform, dscale, kind)
    return out[0]
