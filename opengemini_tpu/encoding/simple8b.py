"""Simple8b integer packing (own format, role of reference lib/encoding/int.go).

64-bit words: 4-bit selector + 60-bit payload. Selector table (count, width):
  0:(240,0) 1:(120,0) 2:(60,1) 3:(30,2) 4:(20,3) 5:(15,4) 6:(12,5) 7:(10,6)
  8:(8,7) 9:(7,8) 10:(6,10) 11:(5,12) 12:(4,15) 13:(3,20) 14:(2,30) 15:(1,60)
Selectors 0/1 encode runs of zeros. Values must be < 2^60; callers fall back
to a raw codec otherwise (the reference likewise falls back to zstd,
/root/reference/lib/encoding/int.go:21-24).

Encode: greedy longest-fit per word. Feasibility per selector is precomputed
with vectorized sliding-window maxima; the python loop runs once per OUTPUT
word, and payload packing is vectorized per selector class. Designed for
per-segment blocks (<= a few thousand values), where this is plenty fast.
"""

from __future__ import annotations

import numpy as np

from .bitpack import bit_widths

# selector -> (count, width)
SELECTORS = [(240, 0), (120, 0), (60, 1), (30, 2), (20, 3), (15, 4),
             (12, 5), (10, 6), (8, 7), (7, 8), (6, 10), (5, 12),
             (4, 15), (3, 20), (2, 30), (1, 60)]

MAX_VALUE = (1 << 60) - 1


def can_encode(values: np.ndarray) -> bool:
    if len(values) == 0:
        return True
    return int(values.astype(np.uint64, copy=False).max()) <= MAX_VALUE


def encode(values: np.ndarray) -> bytes:
    """Pack uint64 values (< 2^60) into simple8b words."""
    v = values.astype(np.uint64, copy=False)
    n = len(v)
    if n == 0:
        return b""
    widths = bit_widths(v)
    if int(widths.max()) > 60:
        raise ValueError("simple8b: value exceeds 60 bits")

    # fits[sel][i] == True iff a word with selector `sel` starting at i
    # is feasible (ok[i..i+count-1] all true and in range); the greedy
    # choice at i is then the FIRST feasible selector (largest count),
    # which one vectorized argmax over the (16, n) matrix yields for
    # every start position at once — the walk below is one O(1) list
    # hop per OUTPUT word (the per-(word, selector) scalar-indexing
    # loop this replaces was the flush encode's top Python cost)
    F = np.zeros((len(SELECTORS), n), dtype=np.bool_)
    for sel, (count, width) in enumerate(SELECTORS):
        ok = widths <= width if width else (v == 0)
        if count == 1:
            F[sel] = ok
        else:
            c = np.cumsum(np.concatenate([[0], ok.astype(np.int64)]))
            last = n - count
            if last >= 0:
                F[sel, : last + 1] = (c[count:] - c[:-count]) == count
    # selector 15 (count=1, width=60) always fits → argmax finds a True
    first = np.argmax(F, axis=0)
    counts_at = np.array([c for c, _ in SELECTORS],
                         dtype=np.int64)[first].tolist()
    sel_at = first.tolist()
    sel_of_word = []
    start_of_word = []
    i = 0
    while i < n:
        sel_of_word.append(sel_at[i])
        start_of_word.append(i)
        i += counts_at[i]

    sels = np.array(sel_of_word, dtype=np.int64)
    starts = np.array(start_of_word, dtype=np.int64)
    words = np.zeros(len(sels), dtype=np.uint64)
    # vectorized payload packing per selector class
    for sel in np.unique(sels):
        count, width = SELECTORS[sel]
        idx = np.nonzero(sels == sel)[0]
        words[idx] |= np.uint64(sel) << np.uint64(60)
        if width == 0:
            continue
        # gather (nwords, count) value matrix; zero-pad past-the-end slots
        pos = starts[idx][:, None] + np.arange(count)[None, :]
        vals = v[np.minimum(pos, n - 1)]
        vals[pos >= n] = 0
        shifts = (np.uint64(width) * np.arange(count - 1, -1, -1)
                  .astype(np.uint64))
        words[idx] |= np.bitwise_or.reduce(vals << shifts[None, :], axis=1)
    return words.astype(">u8").tobytes()


def decode(buf: bytes | memoryview, n: int) -> np.ndarray:
    """Unpack n uint64 values from simple8b words."""
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    words = np.frombuffer(buf, dtype=">u8").astype(np.uint64)
    sels = (words >> np.uint64(60)).astype(np.int64)
    counts = np.array([c for c, _ in SELECTORS], dtype=np.int64)[sels]
    ends = np.cumsum(counts)
    total = int(ends[-1])
    out = np.zeros(total, dtype=np.uint64)
    offs = ends - counts
    for sel in np.unique(sels):
        count, width = SELECTORS[sel]
        idx = np.nonzero(sels == sel)[0]
        if width == 0:
            continue  # zeros already in place
        shifts = (np.uint64(width) * np.arange(count - 1, -1, -1)
                  .astype(np.uint64))
        mask = np.uint64((1 << width) - 1)
        vals = (words[idx][:, None] >> shifts[None, :]) & mask  # (nw, count)
        pos = offs[idx][:, None] + np.arange(count)[None, :]
        out[pos.reshape(-1)] = vals.reshape(-1)
    return out[:n]
