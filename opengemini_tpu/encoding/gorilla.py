"""Gorilla XOR float compression (role of reference lib/encoding/float.go:27).

Facebook Gorilla scheme: each float64 XORed with its predecessor; zero XOR
encoded as a single 0 bit; otherwise '10' + reuse previous leading/trailing
zero window, or '11' + 5-bit leading-zero count + 6-bit significant-bit count
+ the significant bits.

This is the inherently-sequential codec; the Python implementation operates on
per-segment blocks and is kept for format parity and cold data. Hot float
columns default to the vectorized codecs in blocks.py (RLE / zstd-raw), and a
C++ implementation can replace this hot loop behind the same byte format.
"""

from __future__ import annotations

import numpy as np


class _BitWriter:
    __slots__ = ("buf", "acc", "nbits")

    def __init__(self):
        self.buf = bytearray()
        self.acc = 0
        self.nbits = 0

    def write(self, value: int, bits: int):
        self.acc = (self.acc << bits) | (value & ((1 << bits) - 1))
        self.nbits += bits
        while self.nbits >= 8:
            self.nbits -= 8
            self.buf.append((self.acc >> self.nbits) & 0xFF)
        self.acc &= (1 << self.nbits) - 1

    def finish(self) -> bytes:
        if self.nbits:
            self.buf.append((self.acc << (8 - self.nbits)) & 0xFF)
            self.acc = 0
            self.nbits = 0
        return bytes(self.buf)


class _BitReader:
    """Incremental big-endian bit reader (O(n) overall; a whole-buffer
    Python-int shift would be O(n^2))."""

    __slots__ = ("data", "byte_pos", "acc", "nbits")

    def __init__(self, data: bytes):
        self.data = data
        self.byte_pos = 0
        self.acc = 0
        self.nbits = 0

    def read(self, bits: int) -> int:
        while self.nbits < bits:
            if self.byte_pos >= len(self.data):
                # same contract as the native decoder's truncated-input rc
                raise ValueError("gorilla: truncated input")
            self.acc = (self.acc << 8) | self.data[self.byte_pos]
            self.byte_pos += 1
            self.nbits += 8
        self.nbits -= bits
        out = (self.acc >> self.nbits) & ((1 << bits) - 1)
        self.acc &= (1 << self.nbits) - 1
        return out


def encode(values: np.ndarray) -> bytes:
    """Encode float64 array; first value stored raw (64 bits). Uses the
    native C++ codec (native/gorilla.cpp, byte-identical format) when the
    shared library is available."""
    from .. import native
    out = native.gorilla_encode(values)
    if out is not None:
        return out
    u = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    w = _BitWriter()
    if len(u) == 0:
        return b""
    prev = int(u[0])
    w.write(prev, 64)
    lead, sig = -1, -1  # current window (invalid)
    xors = (u[1:] ^ u[:-1]).tolist()
    for x in xors:
        if x == 0:
            w.write(0, 1)
            continue
        xl = 64 - x.bit_length()      # leading zeros
        xt = (x & -x).bit_length() - 1  # trailing zeros
        if xl > 31:
            xl = 31
        if (lead >= 0 and xl >= lead and xt >= 64 - lead - sig):
            w.write(0b10, 2)
            w.write(x >> (64 - lead - sig), sig)
        else:
            lead = xl
            sig = 64 - xl - xt
            w.write(0b11, 2)
            w.write(lead, 5)
            w.write(sig - 1, 6)
            w.write(x >> xt, sig)
    return w.finish()


def decode(buf: bytes, n: int) -> np.ndarray:
    if n == 0:
        return np.zeros(0, dtype=np.float64)
    from .. import native
    out = native.gorilla_decode(buf, n)
    if out is not None:
        return out
    r = _BitReader(bytes(buf))
    out = np.empty(n, dtype=np.uint64)
    prev = r.read(64)
    out[0] = prev
    lead = sig = 0
    for i in range(1, n):
        if r.read(1) == 0:
            out[i] = prev
            continue
        if r.read(1) == 1:
            lead = r.read(5)
            sig = r.read(6) + 1
        bits = r.read(sig)
        prev ^= bits << (64 - lead - sig)
        out[i] = prev
    return out.view(np.float64)
