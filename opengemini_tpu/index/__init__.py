from .tsi import SeriesIndex, TagFilter
