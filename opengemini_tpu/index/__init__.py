from .tsi import SeriesIndex, TagFilter
from .clv import CLVIndex, Analyzer, Collector, tokenize
from .ski import ShardKeyIndex
