"""Shard-key index (ski).

Role of the reference's `engine/index/ski/shardkey_index.go`: maps
(measurement, shard-key value) → series ids on a per-shard basis, tracks
the shard's series count, and answers *split point* queries — the keys at
which a range-sharded measurement should be cut so each resulting shard
holds an even share of series (`GetSplitPointsWithSeriesCount` :188) or
of rows (`GetSplitPointsByRowCount` :254). The split points feed shard
splitting in range-sharding mode (Engine.GetShardSplitPoints,
engine/engine.go:930).

The reference builds this on a mergeset LSM with an LRU dedup cache;
here the working set is a dict of sorted shard keys with numpy posting
arrays plus an append-only persistence log (same pattern as tsi.py —
key creation is rare relative to writes)."""

from __future__ import annotations

import os
import struct
import threading

import numpy as np

from ..utils import get_logger

log = get_logger(__name__)

_REC = struct.Struct("<IQ")      # key-bytes length, sid


class ShardKeyIndex:
    """Per-shard shard-key → series-id index with split-point queries."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.Lock()
        # key (bytes "mst,k1=v1,k2=v2") → set of sids
        self._keys: dict[bytes, set[int]] = {}
        self._series_count = 0
        self._fh = None
        if path:
            self._open()

    def _open(self) -> None:
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                data = f.read()
            off = 0
            while off + _REC.size <= len(data):
                klen, sid = _REC.unpack_from(data, off)
                off += _REC.size
                key = data[off:off + klen]
                off += klen
                self._insert(key, sid)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fh = open(self.path, "ab")

    def _insert(self, key: bytes, sid: int) -> bool:
        sids = self._keys.setdefault(key, set())
        if sid in sids:
            return False
        sids.add(sid)
        self._series_count += 1
        return True

    # ------------------------------------------------------------- write

    def create_index(self, measurement: str, shard_key: str,
                     sid: int) -> None:
        """Register series `sid` under its shard-key value (reference
        CreateIndex :103; dedup via the in-memory set, the reference's
        LRU-cache-then-mergeset-lookup)."""
        key = f"{measurement},{shard_key}".encode()
        with self._lock:
            if self._insert(key, sid) and self._fh is not None:
                self._fh.write(_REC.pack(len(key), sid) + key)

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # ------------------------------------------------------------ queries

    @property
    def series_count(self) -> int:
        return self._series_count

    def series_for(self, measurement: str,
                   shard_key: str) -> np.ndarray:
        key = f"{measurement},{shard_key}".encode()
        return np.array(sorted(self._keys.get(key, ())), dtype=np.int64)

    def get_split_points(self, positions: list[int]) -> list[str]:
        """Shard keys at the given cumulative-series-count positions, in
        shard-key sort order (reference GetSplitPointsWithSeriesCount
        :188). position i means: the key under which the i-th series (by
        cumulative count over sorted keys) falls — the split boundary for
        an even range split."""
        return self._split(positions, lambda key, sids: len(sids))

    def get_split_points_by_row_count(
            self, positions: list[int], row_count_of) -> list[str]:
        """Like get_split_points but weighting each key by data rows:
        row_count_of(measurement, sid) → rows (reference
        GetSplitPointsByRowCount :254)."""
        def weight(key: bytes, sids: set[int]) -> int:
            mst = key.split(b",", 1)[0].decode()
            return sum(int(row_count_of(mst, sid)) for sid in sids)
        return self._split(positions, weight)

    def _split(self, positions: list[int], weight) -> list[str]:
        with self._lock:
            items = sorted(self._keys.items())
        out = []
        it = iter(sorted(positions))
        want = next(it, None)
        cum = 0
        for key, sids in items:
            cum += weight(key, sids)
            while want is not None and cum > want:
                out.append(key.split(b",", 1)[1].decode())
                want = next(it, None)
        if want is not None:
            raise ValueError(
                f"split position {want} beyond total weight {cum}")
        return out
