"""Sparse (skip) indexes for the column-store engine.

Role of reference engine/index/sparseindex/ — per-fragment block pruning so
scans touch only fragments that can match the WHERE clause:
- min-max index   (min_max_index.go)   : numeric/string range pruning
- set index       (set_index.go)       : small-cardinality equality pruning
- bloom filter    (bloom_filter_index.go): high-cardinality equality pruning
- full-text bloom (bloom_filter_fulltext_index.go): token MATCH pruning,
  sharing the native tokenizer with the C++ text index (native/textindex.cpp)

TPU-first angle: pruning yields a boolean fragment mask on the host; only
surviving fragments are decoded and DMA'd to the device, so the sparse
index directly bounds HBM traffic. Fragments are fixed-size row blocks —
the device block shape — making the mask a static-shape gather list.

All indexes serialize to one blob per (column, file) with a common header,
entries aligned by fragment ordinal.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass

import numpy as np

from ..native import tokenize

KIND_MINMAX = 1
KIND_SET = 2
KIND_BLOOM = 3
KIND_TEXT_BLOOM = 4

_SET_CARDINALITY_CAP = 64          # beyond this a set entry degrades to pass
_BLOOM_BITS_PER_KEY = 10
_BLOOM_HASHES = 7


def _h64(b: bytes) -> int:
    """Deterministic 64-bit hash (FNV-1a); stable across processes, unlike
    Python's salted hash()."""
    h = 0xCBF29CE484222325
    for c in b:
        h = ((h ^ c) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class Bloom:
    """Double-hashing bloom filter over byte keys."""

    def __init__(self, bits: np.ndarray):
        self.bits = bits          # uint8 array, len multiple of 8 bits
        self.m = len(bits) * 8

    @classmethod
    def build(cls, keys: list[bytes], bits_per_key: int = _BLOOM_BITS_PER_KEY
              ) -> "Bloom":
        m = max(64, 1 << int(np.ceil(np.log2(max(1, len(keys))
                                             * bits_per_key))))
        bits = np.zeros(m // 8, dtype=np.uint8)
        for k in keys:
            h1 = _h64(k)
            h2 = zlib.crc32(k) | 1
            for i in range(_BLOOM_HASHES):
                pos = (h1 + i * h2) % m
                bits[pos >> 3] |= 1 << (pos & 7)
        return cls(bits)

    def may_contain(self, key: bytes) -> bool:
        h1 = _h64(key)
        h2 = zlib.crc32(key) | 1
        for i in range(_BLOOM_HASHES):
            pos = (h1 + i * h2) % self.m
            if not (self.bits[pos >> 3] >> (pos & 7)) & 1:
                return False
        return True


@dataclass
class FragmentEntry:
    """Per-fragment index payload; exactly one of the fields is set,
    matching the index kind."""
    minmax: tuple | None = None            # (min, max) python scalars
    values: frozenset | None = None        # set index (None => overflow)
    bloom: Bloom | None = None


class SparseIndexBuilder:
    """Builds one sparse index (one kind, one column) across fragments."""

    def __init__(self, kind: int, column: str):
        if kind not in (KIND_MINMAX, KIND_SET, KIND_BLOOM, KIND_TEXT_BLOOM):
            raise ValueError(f"bad sparse index kind {kind}")
        self.kind = kind
        self.column = column
        self.entries: list[FragmentEntry] = []

    def add_fragment(self, values: np.ndarray | list,
                     valid: np.ndarray | None = None) -> None:
        """values: the column's values within one fragment (decoded form:
        numeric ndarray or list of str)."""
        if valid is not None:
            if isinstance(values, np.ndarray):
                values = values[valid]
            else:
                values = [v for v, ok in zip(values, valid) if ok]
        if self.kind == KIND_MINMAX:
            if len(values) == 0:
                self.entries.append(FragmentEntry(minmax=None))
            elif isinstance(values, np.ndarray):
                if (np.issubdtype(values.dtype, np.floating)
                        and np.isnan(values).any()):
                    # NaN is unordered: a (nan, nan) entry means "this
                    # fragment's content cannot be ranged" — pruning
                    # passes it, the extrema path decodes it
                    self.entries.append(FragmentEntry(
                        minmax=(float("nan"), float("nan"))))
                else:
                    self.entries.append(FragmentEntry(
                        minmax=(values.min().item(),
                                values.max().item())))
            else:
                self.entries.append(FragmentEntry(
                    minmax=(min(values), max(values))))
        elif self.kind == KIND_SET:
            s = frozenset(_as_key(v) for v in values)
            self.entries.append(FragmentEntry(
                values=None if len(s) > _SET_CARDINALITY_CAP else s))
        elif self.kind == KIND_BLOOM:
            keys = list({_as_key(v) for v in values})
            self.entries.append(FragmentEntry(bloom=Bloom.build(keys)))
        else:  # KIND_TEXT_BLOOM
            toks = set()
            for v in values:
                b = v if isinstance(v, bytes) else str(v).encode()
                toks.update(tokenize(b))
            self.entries.append(FragmentEntry(bloom=Bloom.build(list(toks))))

    def finish(self) -> "SparseIndex":
        return SparseIndex(self.kind, self.column, self.entries)


def _as_key(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, (bool, np.bool_)):
        return b"\x01" if v else b"\x00"
    if isinstance(v, (int, np.integer)):
        return struct.pack("<q", int(v))
    return struct.pack("<d", float(v))


class SparseIndex:
    """Finished index: prunes fragments given a predicate on its column."""

    def __init__(self, kind: int, column: str,
                 entries: list[FragmentEntry]):
        self.kind = kind
        self.column = column
        self.entries = entries

    @property
    def n_fragments(self) -> int:
        return len(self.entries)

    # ---------------------------------------------------------- pruning

    def prune_eq(self, value) -> np.ndarray:
        """Mask of fragments that MAY contain value (False = skip)."""
        out = np.ones(len(self.entries), dtype=bool)
        for i, e in enumerate(self.entries):
            if self.kind == KIND_MINMAX:
                if e.minmax is None:
                    out[i] = False
                else:
                    lo, hi = e.minmax
                    if lo != lo:          # NaN bounds: cannot prune
                        out[i] = True
                    else:
                        out[i] = (_cmp_le(lo, value)
                                  and _cmp_le(value, hi))
            elif self.kind == KIND_SET:
                if e.values is not None:
                    out[i] = _as_key(value) in e.values
            elif self.kind in (KIND_BLOOM, KIND_TEXT_BLOOM):
                out[i] = e.bloom.may_contain(_as_key(value))
        return out

    def prune_range(self, lo=None, hi=None, lo_inc: bool = True,
                    hi_inc: bool = True) -> np.ndarray:
        """Mask for range predicates (min-max index only; other kinds
        return all-pass)."""
        out = np.ones(len(self.entries), dtype=bool)
        if self.kind != KIND_MINMAX:
            return out
        for i, e in enumerate(self.entries):
            if e.minmax is None:
                out[i] = False
                continue
            fmin, fmax = e.minmax
            if fmin != fmin:              # NaN bounds: cannot prune
                continue
            ok = True
            if lo is not None:
                ok = _cmp_le(lo, fmax) if lo_inc else _cmp_lt(lo, fmax)
            if ok and hi is not None:
                ok = _cmp_le(fmin, hi) if hi_inc else _cmp_lt(fmin, hi)
            out[i] = ok
        return out

    def prune_match(self, text: str | bytes) -> np.ndarray:
        """Full-text MATCH: every token of the query must hit the fragment's
        token bloom."""
        if self.kind != KIND_TEXT_BLOOM:
            return np.ones(len(self.entries), dtype=bool)
        b = text if isinstance(text, bytes) else text.encode()
        toks = tokenize(b)
        out = np.ones(len(self.entries), dtype=bool)
        for i, e in enumerate(self.entries):
            out[i] = all(e.bloom.may_contain(t) for t in toks)
        return out

    # ---------------------------------------------------- serialization

    def pack(self) -> bytes:
        col = self.column.encode()
        out = [struct.pack("<BHI", self.kind, len(col), len(self.entries)),
               col]
        for e in self.entries:
            if self.kind == KIND_MINMAX:
                out.append(_pack_minmax(e.minmax))
            elif self.kind == KIND_SET:
                if e.values is None:
                    out.append(struct.pack("<i", -1))
                else:
                    out.append(struct.pack("<i", len(e.values)))
                    for k in sorted(e.values):
                        out.append(struct.pack("<H", len(k)) + k)
            else:
                out.append(struct.pack("<I", len(e.bloom.bits)))
                out.append(e.bloom.bits.tobytes())
        return b"".join(out)

    @classmethod
    def unpack(cls, buf: bytes) -> "SparseIndex":
        kind, clen, n = struct.unpack_from("<BHI", buf, 0)
        pos = 7
        column = buf[pos:pos + clen].decode()
        pos += clen
        entries = []
        for _ in range(n):
            if kind == KIND_MINMAX:
                mm, pos = _unpack_minmax(buf, pos)
                entries.append(FragmentEntry(minmax=mm))
            elif kind == KIND_SET:
                (cnt,) = struct.unpack_from("<i", buf, pos)
                pos += 4
                if cnt < 0:
                    entries.append(FragmentEntry(values=None))
                else:
                    vals = []
                    for _ in range(cnt):
                        (kl,) = struct.unpack_from("<H", buf, pos)
                        pos += 2
                        vals.append(buf[pos:pos + kl])
                        pos += kl
                    entries.append(FragmentEntry(values=frozenset(vals)))
            else:
                (nb,) = struct.unpack_from("<I", buf, pos)
                pos += 4
                bits = np.frombuffer(buf[pos:pos + nb],
                                     dtype=np.uint8).copy()
                pos += nb
                entries.append(FragmentEntry(bloom=Bloom(bits)))
        return cls(kind, column, entries)


def _cmp_le(a, b) -> bool:
    try:
        return a <= b
    except TypeError:
        return str(a) <= str(b)


def _cmp_lt(a, b) -> bool:
    try:
        return a < b
    except TypeError:
        return str(a) < str(b)


# min/max payload: type tag + value (float/int/str)
def _pack_minmax(mm) -> bytes:
    if mm is None:
        return bytes([0])
    lo, hi = mm
    out = [bytes([1])]
    for v in (lo, hi):
        if isinstance(v, (bool, np.bool_)):
            out.append(b"b" + struct.pack("<?", bool(v)))
        elif isinstance(v, (int, np.integer)):
            out.append(b"i" + struct.pack("<q", int(v)))
        elif isinstance(v, (float, np.floating)):
            out.append(b"f" + struct.pack("<d", float(v)))
        else:
            b = v.encode() if isinstance(v, str) else bytes(v)
            out.append(b"s" + struct.pack("<I", len(b)) + b)
    return b"".join(out)


def _unpack_minmax(buf: bytes, pos: int):
    tag = buf[pos]
    pos += 1
    if tag == 0:
        return None, pos
    vals = []
    for _ in range(2):
        t = buf[pos:pos + 1]
        pos += 1
        if t == b"b":
            vals.append(struct.unpack_from("<?", buf, pos)[0])
            pos += 1
        elif t == b"i":
            vals.append(struct.unpack_from("<q", buf, pos)[0])
            pos += 8
        elif t == b"f":
            vals.append(struct.unpack_from("<d", buf, pos)[0])
            pos += 8
        else:
            (ln,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            vals.append(buf[pos:pos + ln].decode())
            pos += ln
    return (vals[0], vals[1]), pos
