"""CLV tokenized log-search index.

Role of the reference's `engine/index/clv/` package: a learned-vocabulary
inverted index for log text search —
- `tokenizer.go` SimpleTokenizer: split-gram byte table tokenization
  (DefaultSplitGram ``, '";=()[]{}?@&<>/:\\n\\t\\r``; bytes with the high
  bit set are always token chars so UTF-8 passes through).
- `analyzer.go` Analyzer/Collector: a dictionary of frequent multi-token
  phrases (VTokens) learned from sample logs; analysis greedily maps the
  token stream to the longest dictionary phrase, shrinking posting-list
  count for repetitive logs.
- `index.go`/`search.go` InvertIndex + Match/Match_Phrase/Fuzzy query
  types returning per-series row filters (row id = timestamp).

Design here: postings are dict[vtoken] → dict[sid] → (timestamps,
positions) with numpy int64 arrays (sorted on seal); phrase match
intersects position lists vectorially (np.isin on adjusted positions);
fuzzy matches expand over the vocabulary with fnmatch. The learned
dictionary is a plain trie of token tuples.
"""

from __future__ import annotations

import fnmatch
from collections import Counter
from dataclasses import dataclass, field

import numpy as np

DEFAULT_SPLIT_GRAM = ", '\";=()[]{}?@&<>/:\n\t\r"
MAX_PHRASE_TOKENS = 7            # reference clv MaxDicLen analog

# query types (reference engine/index/clv/index.go:40-44)
MATCH = 1
MATCH_PHRASE = 2
FUZZY = 3


def make_split_table(split_gram: str = DEFAULT_SPLIT_GRAM) -> np.ndarray:
    table = np.zeros(256, dtype=bool)
    for ch in split_gram:
        table[ord(ch)] = True
    return table


_DEFAULT_TABLE = make_split_table()


def tokenize(text: str, table: np.ndarray = _DEFAULT_TABLE
             ) -> list[tuple[str, int]]:
    """Split text into (token, position) pairs. Position counts tokens,
    not bytes (phrase adjacency = consecutive positions). Tokens are
    lower-cased (reference tokenizer byte-normalizes case)."""
    raw = text.encode("utf-8", "surrogateescape")
    b = np.frombuffer(raw, dtype=np.uint8)
    if b.size == 0:
        return []
    is_split = (b < 128) & table[b]
    # token boundaries: starts where prev is split (or SOT), ends where
    # next is split (or EOT)
    prev_split = np.concatenate([[True], is_split[:-1]])
    starts = np.nonzero(~is_split & prev_split)[0]
    next_split = np.concatenate([is_split[1:], [True]])
    ends = np.nonzero(~is_split & next_split)[0]
    out = []
    for pos, (s, e) in enumerate(zip(starts, ends)):
        out.append((raw[s:e + 1].decode("utf-8", "surrogateescape")
                    .lower(), pos))
    return out


# --------------------------------------------------------------- analyzer

class Collector:
    """Counts token n-grams from sample logs to learn the phrase
    dictionary (reference collector.go)."""

    def __init__(self, max_phrase: int = MAX_PHRASE_TOKENS):
        self.max_phrase = max_phrase
        self.counts: Counter = Counter()

    def collect(self, text: str) -> None:
        toks = [t for t, _p in tokenize(text)]
        for n in range(2, self.max_phrase + 1):
            for i in range(len(toks) - n + 1):
                self.counts[tuple(toks[i:i + n])] += 1

    def top_phrases(self, k: int, min_count: int = 2
                    ) -> list[tuple[str, ...]]:
        # prefer longer phrases on equal frequency (greedy-longest match
        # then saves more postings)
        cands = [(c, len(p), p) for p, c in self.counts.items()
                 if c >= min_count]
        cands.sort(key=lambda x: (-x[0], -x[1], x[2]))
        return [p for _c, _l, p in cands[:k]]


@dataclass
class VToken:
    text: str                     # phrase tokens joined by spaces
    pos: int                      # token position of the phrase start
    n: int = 1                    # tokens consumed


class Analyzer:
    """Maps a token stream to VTokens via greedy longest-match against a
    learned phrase dictionary (reference analyzer.go:152 findLongestTokens;
    version 0 = the default analyzer: every token is its own VToken)."""

    def __init__(self, phrases: list[tuple[str, ...]] | None = None,
                 version: int = 0):
        self.version = version
        self._trie: dict = {}
        for p in phrases or []:
            node = self._trie
            for tok in p:
                node = node.setdefault(tok, {})
            node[None] = True     # terminal

    @classmethod
    def learn(cls, samples: list[str], dict_size: int = 256,
              version: int = 1) -> "Analyzer":
        coll = Collector()
        for s in samples:
            coll.collect(s)
        return cls(coll.top_phrases(dict_size), version=version)

    def analyze(self, text: str) -> list[VToken]:
        toks = tokenize(text)
        out: list[VToken] = []
        i = 0
        while i < len(toks):
            best = 1
            node = self._trie.get(toks[i][0])
            j = i + 1
            while node is not None:
                if None in node:
                    best = max(best, j - i)
                if j >= len(toks):
                    break
                node = node.get(toks[j][0])
                j += 1
            out.append(VToken(" ".join(t for t, _p in toks[i:i + best]),
                              toks[i][1], best))
            i += best
        return out


# ------------------------------------------------------------------ index

@dataclass
class _Posting:
    rowids: list = field(default_factory=list)    # int64 timestamps
    positions: list = field(default_factory=list)


class CLVIndex:
    """One measurement+field's tokenized inverted index.

    add(sid, timestamp, text) indexes a log line; match/match_phrase/
    fuzzy return {sid: sorted int64 timestamp array} row filters
    (reference RowFilter, index.go:46: "RowId is the timestamp")."""

    def __init__(self, analyzer: Analyzer | None = None):
        self.analyzer = analyzer or Analyzer()
        self._postings: dict[str, dict[int, _Posting]] = {}
        # multi-token vocabulary entries only — single tokens hit the
        # postings dict directly; phrase entries (rare) are scanned
        self._phrase_vts: set[str] = set()
        self.docs = 0

    def add(self, sid: int, timestamp: int, text: str) -> None:
        self.docs += 1
        for vt in self.analyzer.analyze(text):
            by_sid = self._postings.setdefault(vt.text, {})
            if vt.n > 1:
                self._phrase_vts.add(vt.text)
            p = by_sid.setdefault(sid, _Posting())
            p.rowids.append(timestamp)
            p.positions.append(vt.pos)

    @property
    def vocab_size(self) -> int:
        return len(self._postings)

    # ---- search

    def search(self, query: str, qtype: int = MATCH
               ) -> dict[int, np.ndarray]:
        if qtype == MATCH:
            return self.match(query)
        if qtype == MATCH_PHRASE:
            return self.match_phrase(query)
        if qtype == FUZZY:
            return self.fuzzy(query)
        raise ValueError(f"unknown clv query type {qtype}")

    def _rows_for_vtoken(self, vt: str) -> dict[int, np.ndarray]:
        out = {}
        for sid, p in self._postings.get(vt, {}).items():
            out[sid] = np.unique(np.asarray(p.rowids, dtype=np.int64))
        return out

    def _rows_for_token(self, tok: str) -> dict[int, np.ndarray]:
        """A single query token also matches inside learned phrases —
        scan vocabulary entries containing it."""
        acc: dict[int, list] = {}
        hits = [tok] if tok in self._postings else []
        hits += [vt for vt in self._phrase_vts
                 if tok in vt.split(" ")]
        for vt in hits:
            for sid, rows in self._rows_for_vtoken(vt).items():
                acc.setdefault(sid, []).append(rows)
        return {sid: np.unique(np.concatenate(rs))
                for sid, rs in acc.items()}

    def _positions_for_token(self, tok: str
                             ) -> dict[int, tuple[np.ndarray, np.ndarray]]:
        """(rowids, absolute token positions) of every occurrence of
        `tok`, including inside learned phrases (a token at offset k of a
        phrase posted at position P sits at absolute position P+k — the
        reference's assembleId(id, offset) scheme, clv/index.go:179)."""
        acc: dict[int, list] = {}
        cands = ([tok] if tok in self._postings else []) \
            + [vt for vt in self._phrase_vts if tok in vt.split(" ")]
        for vt in cands:
            by_sid = self._postings[vt]
            toks = vt.split(" ") if " " in vt else [vt]
            offs = [k for k, t in enumerate(toks) if t == tok]
            for sid, p in by_sid.items():
                rows = np.asarray(p.rowids, dtype=np.int64)
                pos = np.asarray(p.positions, dtype=np.int64)
                for k in offs:
                    acc.setdefault(sid, []).append((rows, pos + k))
        return {sid: (np.concatenate([r for r, _p in parts]),
                      np.concatenate([p for _r, p in parts]))
                for sid, parts in acc.items()}

    def match(self, query: str) -> dict[int, np.ndarray]:
        """All query tokens appear in the log line (AND of postings,
        intersected per (sid, rowid))."""
        toks = [t for t, _p in tokenize(query)]
        if not toks:
            return {}
        sets = [self._rows_for_token(t) for t in toks]
        return _intersect_rowsets(sets)

    def match_phrase(self, query: str) -> dict[int, np.ndarray]:
        """Tokens adjacent and in order. Works at the TOKEN level (not
        vtoken), so query phrases that are sub-phrases of — or straddle —
        learned dictionary phrases still match: each query token k yields
        (rowid, abs_pos - k) pairs, and the phrase hits are the pairs
        common to every token."""
        qtoks = [t for t, _p in tokenize(query)]
        if not qtoks:
            return {}
        per_tok = [self._positions_for_token(t) for t in qtoks]
        if any(not d for d in per_tok):
            return {}
        common = set.intersection(*[set(d) for d in per_tok])
        out = {}
        for sid in sorted(common):
            rows0, pos0 = per_tok[0][sid]
            pairs = _pair_view(rows0, pos0)
            for k, d in enumerate(per_tok[1:], start=1):
                rows_k, pos_k = d[sid]
                pairs = np.intersect1d(
                    pairs, _pair_view(rows_k, pos_k - k),
                    assume_unique=False)
                if not len(pairs):
                    break
            if len(pairs):
                out[sid] = np.unique(pairs["r"])
        return out

    def fuzzy(self, pattern: str) -> dict[int, np.ndarray]:
        """Wildcard match (* and ?) over the vocabulary, OR of postings
        (reference Fuzzy via terms-index scan, search.go:85)."""
        pat = pattern.lower()
        acc: dict[int, list] = {}
        for vt in self._postings:
            toks = vt.split(" ") if " " in vt else [vt]
            if any(fnmatch.fnmatchcase(t, pat) for t in toks):
                for sid, rows in self._rows_for_vtoken(vt).items():
                    acc.setdefault(sid, []).append(rows)
        return {sid: np.unique(np.concatenate(rs))
                for sid, rs in acc.items()}


_PAIR_DT = np.dtype([("r", "<i8"), ("p", "<i8")])


def _pair_view(rows: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """(rowid, position) pairs as a structured array — set intersection
    without packing both into one int (ns timestamps would overflow)."""
    out = np.empty(len(rows), dtype=_PAIR_DT)
    out["r"] = rows
    out["p"] = pos
    return out


def _intersect_rowsets(sets: list[dict[int, np.ndarray]]
                       ) -> dict[int, np.ndarray]:
    if not sets:
        return {}
    common = set.intersection(*[set(s) for s in sets])
    out = {}
    for sid in sorted(common):
        rows = sets[0][sid]
        for s in sets[1:]:
            rows = rows[np.isin(rows, s[sid])]
            if not len(rows):
                break
        if len(rows):
            out[sid] = rows
    return out
