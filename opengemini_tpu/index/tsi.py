"""Inverted series index (role of the reference's tsi MergeSetIndex,
engine/index/tsi/mergeset_index.go:261 over lib/util/lifted/vm/mergeset).

Maps measurement → tag key → tag value → posting list of series ids, plus
sid → (measurement, tags) reverse lookup for group-by. The reference builds
this on a mergeset LSM; here the working set is dict/numpy-based in memory
with an append-only persistence log (replayed on open) — series creation is
rare relative to writes, and posting lists stay as sorted int64 arrays that
feed straight into the TPU group-lut construction.

Series ids are sequential per index (1-based), so a query's sid→group lookup
table is a dense numpy array — the device gather for group assignment is a
single vectorized indexing op.
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass

import numpy as np

from ..utils import get_logger

log = get_logger(__name__)


@dataclass(frozen=True)
class TagFilter:
    """One tag predicate: key op value (op: '=', '!=', '=~', '!~')."""
    key: str
    value: str
    op: str = "="


def series_key(measurement: str, tags: dict[str, str]) -> str:
    return measurement + "," + ",".join(
        f"{k}={tags[k]}" for k in sorted(tags))


class SeriesIndex:
    """Per-shard (or per-partition) series index."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self._key_to_sid: dict[str, int] = {}
        self._sid_to_tags: list[dict[str, str] | None] = [None]  # 1-based
        self._sid_to_mst: list[str | None] = [None]
        self._mst_sids: dict[str, list[int]] = {}
        self._postings: dict[tuple[str, str, str], list[int]] = {}
        self._log = None
        if path:
            if os.path.exists(path):
                self._replay()
            self._log = open(path, "ab")

    # ---- persistence -----------------------------------------------------

    def _append_log(self, measurement: str, tags: dict[str, str],
                    sid: int) -> None:
        if self._log is None:
            return
        items = [measurement.encode()] + [
            f"{k}={v}".encode() for k, v in sorted(tags.items())]
        payload = b"\x00".join(items)
        self._log.write(struct.pack("<IQ", len(payload), sid) + payload)

    def flush(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.flush()
                os.fsync(self._log.fileno())

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        hdr = struct.calcsize("<IQ")
        while pos + hdr <= len(data):
            ln, sid = struct.unpack_from("<IQ", data, pos)
            pos += hdr
            if pos + ln > len(data):
                log.warning("series log truncated at %d; ignoring tail", pos)
                break
            items = bytes(data[pos:pos + ln]).split(b"\x00")
            pos += ln
            measurement = items[0].decode()
            if sid == 0:
                # drop-measurement tombstone (sids are 1-based, so 0 is
                # free to mark it)
                self._drop_in_mem(measurement)
                continue
            tags = dict(i.decode().split("=", 1) for i in items[1:])
            self._insert(measurement, tags, sid)

    # ---- writes ----------------------------------------------------------

    def _insert(self, measurement: str, tags: dict[str, str],
                sid: int) -> None:
        key = series_key(measurement, tags)
        self._key_to_sid[key] = sid
        while len(self._sid_to_tags) <= sid:
            self._sid_to_tags.append(None)
            self._sid_to_mst.append(None)
        self._sid_to_tags[sid] = tags
        self._sid_to_mst[sid] = measurement
        self._mst_sids.setdefault(measurement, []).append(sid)
        for k, v in tags.items():
            self._postings.setdefault((measurement, k, v), []).append(sid)

    def _drop_in_mem(self, measurement: str) -> None:
        sids = self._mst_sids.pop(measurement, [])
        for sid in sids:
            tags = self._sid_to_tags[sid] or {}
            self._key_to_sid.pop(series_key(measurement, tags), None)
            self._sid_to_tags[sid] = None
            self._sid_to_mst[sid] = None
        for k in [k for k in self._postings if k[0] == measurement]:
            del self._postings[k]

    def drop_measurement(self, measurement: str) -> None:
        """Remove every series of a measurement (DROP MEASUREMENT;
        reference tsi DropMeasurement). Persisted as a sid=0 tombstone
        record so replay reproduces the drop."""
        with self._lock:
            self._drop_in_mem(measurement)
            if self._log is not None:
                payload = measurement.encode()
                self._log.write(struct.pack("<IQ", len(payload), 0)
                                + payload)
                # fsync: the data files are already gone — losing the
                # tombstone would resurrect the series in the index
                self._log.flush()
                os.fsync(self._log.fileno())

    def get_or_create_sid(self, measurement: str,
                          tags: dict[str, str]) -> int:
        key = series_key(measurement, tags)
        with self._lock:
            sid = self._key_to_sid.get(key)
            if sid is not None:
                return sid
            sid = len(self._sid_to_tags)
            self._insert(measurement, tags, sid)
            self._append_log(measurement, tags, sid)
            return sid

    def get_sid(self, measurement: str, tags: dict[str, str]) -> int | None:
        return self._key_to_sid.get(series_key(measurement, tags))

    # ---- queries ---------------------------------------------------------

    @property
    def series_cardinality(self) -> int:
        return len(self._key_to_sid)

    def series_keys(self, measurement: str | None = None) -> list[str]:
        """All series keys (optionally one measurement's) — callers
        union across shards for exact db-wide cardinality."""
        with self._lock:
            if measurement is None:
                return list(self._key_to_sid)
            prefix = measurement + ","
            return [k for k in self._key_to_sid
                    if k.startswith(prefix) or k == measurement]

    @property
    def max_sid(self) -> int:
        return len(self._sid_to_tags) - 1

    def measurements(self) -> list[str]:
        return sorted(self._mst_sids)

    def tags_of(self, sid: int) -> dict[str, str]:
        return self._sid_to_tags[sid] or {}

    def tag_values(self, measurement: str, key: str) -> list[str]:
        return sorted({v for (m, k, v) in self._postings
                       if m == measurement and k == key})

    def tag_keys(self, measurement: str) -> list[str]:
        return sorted({k for (m, k, _v) in self._postings
                       if m == measurement})

    def series_ids(self, measurement: str,
                   filters: list[TagFilter] | None = None) -> np.ndarray:
        """AND of tag predicates → sorted sid array (the reference's
        tag_filters.go search, simplified to the supported ops)."""
        import re
        with self._lock:
            base = self._mst_sids.get(measurement)
            if not base:
                return np.empty(0, dtype=np.int64)
            result: set[int] | None = None
            negatives: list[TagFilter] = []
            for f in filters or []:
                if f.op in ("!=", "!~"):
                    negatives.append(f)
                    continue
                if f.op == "=":
                    sids = set(self._postings.get(
                        (measurement, f.key, f.value), ()))
                elif f.op == "=~":
                    rx = re.compile(f.value)
                    sids = set()
                    for (m, k, v), lst in self._postings.items():
                        if m == measurement and k == f.key and rx.search(v):
                            sids.update(lst)
                else:
                    raise ValueError(f"bad tag filter op {f.op}")
                result = sids if result is None else (result & sids)
            if result is None:
                result = set(base)
            for f in negatives:
                if f.op == "!=":
                    result -= set(self._postings.get(
                        (measurement, f.key, f.value), ()))
                else:
                    rx = re.compile(f.value)
                    for (m, k, v), lst in self._postings.items():
                        if m == measurement and k == f.key and rx.search(v):
                            result -= set(lst)
            return np.array(sorted(result), dtype=np.int64)

    def group_by_tagsets(self, measurement: str,
                         group_keys: list[str],
                         filters: list[TagFilter] | None = None
                         ) -> list[tuple[tuple[str, ...], np.ndarray]]:
        """Partition matching series into tagsets by group_keys (the
        reference's tagset construction, engine/iterators.go:100 'Scan →
        tagsets'). Returns [(tag values tuple, sorted sid array)], sorted by
        tag values; series missing a group key get '' for it."""
        sids = self.series_ids(measurement, filters)
        groups: dict[tuple[str, ...], list[int]] = {}
        for sid in sids.tolist():
            tags = self._sid_to_tags[sid] or {}
            key = tuple(tags.get(k, "") for k in group_keys)
            groups.setdefault(key, []).append(sid)
        return [(k, np.array(v, dtype=np.int64))
                for k, v in sorted(groups.items())]

    def group_lut(self, tagsets: list[tuple[tuple[str, ...], np.ndarray]]
                  ) -> np.ndarray:
        """Dense sid → group-index lookup table for the device kernels;
        unmatched sids map to -1."""
        lut = np.full(self.max_sid + 1, -1, dtype=np.int64)
        for gi, (_k, sids) in enumerate(tagsets):
            lut[sids] = gi
        return lut

    def close(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.flush()
                self._log.close()
                self._log = None
