"""Inverted series index (role of the reference's tsi MergeSetIndex,
engine/index/tsi/mergeset_index.go:261 over lib/util/lifted/vm/mergeset,
built for the reference's >1M-series claim, README.md:40-42).

TPU-first design: instead of an LSM of raw index items (the reference's
mergeset) or per-series Python dicts (the round-2 working set), the
index is COLUMNAR — per measurement, each tag key is a dictionary-
encoded int32 code column over the series ordinals. That makes every
index operation a vectorized numpy pass:

- tag filters:     mask = (col == code) / np.isin(col, regex-matched
                   codes) — one compare over N series, no posting lists
- group-by tagset: np.unique over the stacked group-key code rows —
                   the grouping IS the codes, which then feed straight
                   into the device kernels' sid→group lookup table
- reverse lookup:  sid → (measurement ordinal) arrays, tags
                   reconstructed from code columns on demand

Memory is bounded: ~4 bytes per (series, tag key) for codes + the tag
value dictionaries (cardinality-bound) + a 16-byte hashed key→sid map —
two orders of magnitude below dict-of-dicts at 1M series.

Durability: the append-only record log (unchanged format) is the WAL;
a columnar SNAPSHOT (npz + json dictionaries) persists the working set
with the log offset it covers, so re-open loads the snapshot and
replays only the log tail (the mergeset-merge analog: snapshot = the
merged sorted run, log tail = the in-memory part).

Series ids are sequential per index (1-based), so a query's sid→group
lookup table is a dense numpy array — the device gather for group
assignment is a single vectorized indexing op.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass

import numpy as np

from ..utils import failpoint, fileops, get_logger, knobs
from .. import native as _native

log = get_logger(__name__)

_HDR = struct.calcsize("<IQ")
# snapshot when the un-snapshotted log tail exceeds this (bytes)
SNAP_THRESHOLD = int(knobs.get("OG_TSI_SNAP_BYTES"))


@dataclass(frozen=True)
class TagFilter:
    """One tag predicate: key op value (op: '=', '!=', '=~', '!~')."""
    key: str
    value: str
    op: str = "="


def series_key(measurement: str, tags: dict[str, str]) -> str:
    return measurement + "," + ",".join(
        f"{k}={tags[k]}" for k in sorted(tags))


def _key_hash(key: str) -> int:
    import hashlib
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "little")


class _MstCols:
    """One measurement's columnar tag store."""

    __slots__ = ("name", "tag_keys", "key_idx", "val_dicts", "val_codes",
                 "codes", "sids", "n")

    def __init__(self, name: str):
        self.name = name
        self.tag_keys: list[str] = []          # column order
        self.key_idx: dict[str, int] = {}
        self.val_dicts: list[list[str]] = []   # per key: code -> value
        self.val_codes: list[dict[str, int]] = []  # per key: value -> code
        self.codes: np.ndarray = np.zeros((0, 64), dtype=np.int32)
        self.sids: np.ndarray = np.zeros(64, dtype=np.int64)
        self.n = 0

    def _ensure_key(self, key: str) -> int:
        ki = self.key_idx.get(key)
        if ki is None:
            ki = len(self.tag_keys)
            self.tag_keys.append(key)
            self.key_idx[key] = ki
            # code 0 = KEY ABSENT (never a value: an explicit empty tag
            # value 'host=' is distinct from no host tag at all and
            # allocates its own code like any other string)
            self.val_dicts.append([None])
            self.val_codes.append({})
            grown = np.zeros((ki + 1, self.codes.shape[1]),
                             dtype=np.int32)
            if ki:
                grown[:ki] = self.codes
            self.codes = grown
        return ki

    def _ensure_cap(self, want: int) -> None:
        cap = self.codes.shape[1]
        if want <= cap:
            return
        new = max(cap * 2, want, 64)
        codes = np.zeros((self.codes.shape[0], new), dtype=np.int32)
        codes[:, :cap] = self.codes
        self.codes = codes
        sids = np.zeros(new, dtype=np.int64)
        sids[:cap] = self.sids
        self.sids = sids

    def add(self, tags: dict[str, str], sid: int) -> int:
        """Append one series; returns its ordinal."""
        for k in tags:
            self._ensure_key(k)
        self._ensure_cap(self.n + 1)
        o = self.n
        for ki, key in enumerate(self.tag_keys):
            v = tags.get(key)
            if v is None:
                continue               # absent key keeps code 0
            codes = self.val_codes[ki]
            c = codes.get(v)
            if c is None:
                c = len(self.val_dicts[ki])
                self.val_dicts[ki].append(v)
                codes[v] = c
            self.codes[ki, o] = c
        self.sids[o] = sid
        self.n += 1
        return o

    def tags_of_ordinal(self, o: int) -> dict[str, str]:
        out = {}
        for ki, key in enumerate(self.tag_keys):
            c = int(self.codes[ki, o])
            if c:
                out[key] = self.val_dicts[ki][c]
        return out

    def key_of_ordinal(self, o: int) -> str:
        return series_key(self.name, self.tags_of_ordinal(o))

    def expr_mask(self, expr) -> np.ndarray:
        """Vectorized evaluation of a pure-tag and/or predicate tree
        (query/condition.py tag_exprs — e.g. h = 'a' OR h = 'b') over
        the code columns."""
        op = getattr(expr, "op", None)
        if op == "and":
            return self.expr_mask(expr.lhs) & self.expr_mask(expr.rhs)
        if op == "or":
            return self.expr_mask(expr.lhs) | self.expr_mask(expr.rhs)
        tf = TagFilter(expr.lhs.name, expr.rhs.value, op)
        m = self.filter_mask([tf])
        return m if m is not None else np.zeros(self.n, dtype=bool)

    def filter_mask(self, filters: list[TagFilter],
                    tag_exprs: list | None = None) -> np.ndarray | None:
        """AND of tag predicates (+ pure-tag and/or expression trees) →
        bool mask over ordinals (None = measurement unknown/no rows)."""
        import re
        if self.n == 0:
            return None
        mask = np.ones(self.n, dtype=bool)
        for e in tag_exprs or ():
            mask &= self.expr_mask(e)
        for f in filters or ():
            ki = self.key_idx.get(f.key)
            if ki is None:
                # unknown tag key: every series behaves as having value
                # "" (same absent-key semantics as the known-key branch)
                if f.op in ("=", "!="):
                    hit = f.value == ""
                else:
                    hit = bool(re.compile(f.value).search(""))
                if f.op in ("!=", "!~"):
                    hit = not hit
                if not hit:
                    return np.zeros(self.n, dtype=bool)
                continue
            col = self.codes[ki, :self.n]
            empty_matches = False
            if f.op in ("=", "!="):
                c = self.val_codes[ki].get(f.value)
                m = (col == c) if c is not None \
                    else np.zeros(self.n, dtype=bool)
                empty_matches = f.value == ""
            else:
                rx = re.compile(f.value)
                match_codes = np.array(
                    [c for c, v in enumerate(self.val_dicts[ki])
                     if c and rx.search(v)], dtype=np.int32)
                m = np.isin(col, match_codes)
                empty_matches = bool(rx.search(""))
            # influx/prom semantics: an absent key behaves as value ""
            # (applied before inversion, so host != '' keeps exactly
            # the series that HAVE a host tag, and host =~ ".*" matches
            # series without one)
            if empty_matches:
                m |= col == 0
            if f.op in ("!=", "!~"):
                m = ~m
            mask &= m
        return mask


class SeriesIndex:
    """Per-shard (or per-partition) series index."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self._msts: dict[str, _MstCols] = {}
        self._mst_names: list[str] = []        # mst code -> name
        self._mst_code: dict[str, int] = {}
        # global sid → (measurement code, ordinal); -1 = dropped/unknown
        self._sid_mst = np.full(64, -1, dtype=np.int32)
        self._sid_ord = np.zeros(64, dtype=np.int64)
        self._next_sid = 1                     # sids are 1-based
        # hashed key → sid (native flat-array map, ~16B/series); true
        # 64-bit collisions fall back to the side dict
        self._hash_sid = _native.SidMap()
        self._collisions: dict[str, int] = {}
        self._log = None
        self._log_size = 0
        self._snap_covered = 0                 # log bytes in snapshot
        if path:
            if os.path.exists(self._snap_path()):
                try:
                    self._load_snapshot()
                except Exception as e:
                    log.warning("series snapshot unreadable (%s); full "
                                "log replay", e)
                    self.__init__(None)
                    self.path = path
            if os.path.exists(path):
                self._replay(from_off=self._snap_covered)
            self._log = open(path, "ab")
            self._log_size = self._log.tell()

    # ---- persistence -----------------------------------------------------

    def _snap_path(self) -> str:
        return self.path + ".snap"

    def _append_log(self, measurement: str, tags: dict[str, str],
                    sid: int) -> None:
        if self._log is None:
            return
        items = [measurement.encode()] + [
            f"{k}={v}".encode() for k, v in sorted(tags.items())]
        payload = b"\x00".join(items)
        rec = struct.pack("<IQ", len(payload), sid) + payload
        self._log.write(rec)
        self._log_size += len(rec)

    def flush(self, snapshot: bool = True) -> None:
        """fsync the log; optionally roll a snapshot when the
        un-snapshotted tail warrants one. Bulk WRITE paths pass
        snapshot=False (durability needs only the fsync); the shard's
        memtable flush and close() run the full form."""
        with self._lock:
            if self._log is not None:
                self._log.flush()
                os.fsync(self._log.fileno())
                # crash here: the sid log is durable but the caller's
                # commit (WAL frame referencing the sids, or the
                # memtable flush) never happened — replay must find
                # every sid a surviving WAL frame references
                failpoint.inject("tsi.flush.crash")
            # amortized trigger: a snapshot rewrites the WHOLE working
            # set, so it must only fire when the un-snapshotted tail is
            # a constant fraction of it — a fixed threshold makes bulk
            # series creation quadratic (observed: 1M-series prom
            # ingest rewrote a growing ~32MB npz every 4MB of log)
            floor = max(SNAP_THRESHOLD, self._snap_covered // 2)
            if snapshot and self._log_size - self._snap_covered > floor:
                self._write_snapshot()

    def _write_snapshot(self) -> None:
        """Persist the columnar working set + covered log offset (the
        mergeset 'merged run'). Atomic via rename."""
        if not self.path:
            return
        meta = {
            "covered": self._log_size,
            "next_sid": self._next_sid,
            "mst_names": self._mst_names,
            "msts": {},
        }
        arrays = {
            "sid_mst": self._sid_mst[:self._next_sid],
            "sid_ord": self._sid_ord[:self._next_sid],
        }
        hk, hs = self._hash_sid.items_arrays()
        arrays["hash_keys"] = hk
        arrays["hash_sids"] = hs
        meta["collisions"] = self._collisions
        for name, mc in self._msts.items():
            mi = self._mst_code[name]
            meta["msts"][name] = {
                "tag_keys": mc.tag_keys,
                "val_dicts": mc.val_dicts,
                "n": mc.n,
            }
            arrays[f"codes_{mi}"] = mc.codes[:, :mc.n]
            arrays[f"sids_{mi}"] = mc.sids[:mc.n]
        tmp = self._snap_path() + ".tmp"
        # container: uncompressed npz in memory, lz4 block around it —
        # an order of magnitude faster than savez_compressed's zlib at
        # 1M series (the snapshot sits on the bulk ingest path)
        import io
        bio = io.BytesIO()
        np.savez(bio, meta=np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8), **arrays)
        raw = bio.getvalue()
        comp = _native.lz4_compress(raw)
        with open(tmp, "wb") as f:
            f.write(b"OGSN1" + struct.pack("<Q", len(raw)) + comp)
            f.flush()
            os.fsync(f.fileno())
        fileops.durable_replace(tmp, self._snap_path())
        self._snap_covered = self._log_size

    def _open_snapshot(self):
        """np.load over either container: lz4-wrapped npz (OGSN1) or
        the legacy savez_compressed file."""
        with open(self._snap_path(), "rb") as f:
            head = f.read(13)
            if head[:5] == b"OGSN1":
                import io
                (raw_len,) = struct.unpack("<Q", head[5:13])
                raw = _native.lz4_decompress(f.read(), raw_len)
                return np.load(io.BytesIO(raw))
        return np.load(self._snap_path())

    def _load_snapshot(self) -> None:
        with self._open_snapshot() as z:
            meta = json.loads(bytes(z["meta"]).decode())
            self._snap_covered = int(meta["covered"])
            self._next_sid = int(meta["next_sid"])
            self._mst_names = list(meta["mst_names"])
            self._mst_code = {n: i for i, n in
                              enumerate(self._mst_names)}
            n = max(self._next_sid, 64)
            self._sid_mst = np.full(n, -1, dtype=np.int32)
            self._sid_ord = np.zeros(n, dtype=np.int64)
            self._sid_mst[:self._next_sid] = z["sid_mst"]
            self._sid_ord[:self._next_sid] = z["sid_ord"]
            for name, m in meta["msts"].items():
                mi = self._mst_code[name]
                mc = _MstCols(name)
                mc.tag_keys = list(m["tag_keys"])
                mc.key_idx = {k: i for i, k in enumerate(mc.tag_keys)}
                mc.val_dicts = [list(v) for v in m["val_dicts"]]
                mc.val_codes = [{v: c for c, v in enumerate(vd) if c}
                                for vd in mc.val_dicts]
                mc.n = int(m["n"])
                codes = np.array(z[f"codes_{mi}"], dtype=np.int32)
                sids = np.array(z[f"sids_{mi}"], dtype=np.int64)
                cap = max(mc.n, 64)
                mc.codes = np.zeros((len(mc.tag_keys), cap),
                                    dtype=np.int32)
                if mc.n:
                    mc.codes[:, :mc.n] = codes
                mc.sids = np.zeros(cap, dtype=np.int64)
                mc.sids[:mc.n] = sids
                self._msts[name] = mc
            # hashed key map restores from the snapshot directly (a
            # per-series rebuild would cost ~1M string builds + hashes
            # on open, defeating the snapshot)
            hk = z["hash_keys"]
            self._hash_sid = _native.SidMap(cap_hint=len(hk))
            self._hash_sid.put_batch(hk, z["hash_sids"])
            self._collisions = dict(meta.get("collisions", {}))

    def _replay(self, from_off: int = 0) -> None:
        with open(self.path, "rb") as f:
            if from_off:
                f.seek(from_off)
            data = f.read()
        self._log_size = from_off + len(data)
        pos = 0
        while pos + _HDR <= len(data):
            ln, sid = struct.unpack_from("<IQ", data, pos)
            pos += _HDR
            if pos + ln > len(data):
                log.warning("series log truncated at %d; ignoring tail",
                            from_off + pos)
                break
            items = bytes(data[pos:pos + ln]).split(b"\x00")
            pos += ln
            measurement = items[0].decode()
            if sid == 0:
                # tombstones (sids are 1-based, so 0 is free to mark
                # them): bare payload drops the measurement; a
                # __drop_sids__ item drops specific series
                if len(items) > 1 and \
                        items[1].startswith(b"__drop_sids__="):
                    dead = [int(x) for x in
                            items[1].split(b"=", 1)[1].split(b",") if x]
                    self._replay_drop_sids(measurement, dead)
                else:
                    self._drop_in_mem(measurement)
                continue
            tags = dict(i.decode().split("=", 1) for i in items[1:])
            self._insert(measurement, tags, sid)

    # ---- writes ----------------------------------------------------------

    def _register_key(self, key: str, sid: int) -> None:
        h = _key_hash(key)
        cur = self._hash_sid.put_if_absent(h, sid)
        if cur is not None and cur != sid:
            self._collisions[key] = sid

    def _lookup_key(self, key: str) -> int | None:
        sid = self._collisions.get(key)
        if sid is not None:
            return sid
        sid = self._hash_sid.get(_key_hash(key))
        if sid is None:
            return None
        # verify against the reconstruction (hash collisions must not
        # alias two different series)
        mi = self._sid_mst[sid] if sid < len(self._sid_mst) else -1
        if mi < 0:
            return None
        mc = self._msts.get(self._mst_names[mi])
        if mc is None or mc.key_of_ordinal(int(self._sid_ord[sid])) != key:
            return None
        return sid

    def _insert(self, measurement: str, tags: dict[str, str],
                sid: int) -> None:
        mc = self._msts.get(measurement)
        if mc is None:
            mc = self._msts[measurement] = _MstCols(measurement)
            if measurement not in self._mst_code:
                self._mst_code[measurement] = len(self._mst_names)
                self._mst_names.append(measurement)
        o = mc.add(tags, sid)
        if sid >= len(self._sid_mst):
            n = max(len(self._sid_mst) * 2, sid + 1)
            sm = np.full(n, -1, dtype=np.int32)
            sm[:len(self._sid_mst)] = self._sid_mst
            self._sid_mst = sm
            so = np.zeros(n, dtype=np.int64)
            so[:len(self._sid_ord)] = self._sid_ord
            self._sid_ord = so
        self._sid_mst[sid] = self._mst_code[measurement]
        self._sid_ord[sid] = o
        self._next_sid = max(self._next_sid, sid + 1)
        self._register_key(series_key(measurement, tags), sid)

    def _drop_in_mem(self, measurement: str) -> None:
        mc = self._msts.pop(measurement, None)
        if mc is None:
            return
        sids = mc.sids[:mc.n]
        self._sid_mst[sids] = -1
        # hash entries verify against _sid_mst, so stale hashes are
        # harmless; collisions side-dict entries are purged
        for k in [k for k in self._collisions
                  if k.startswith(measurement + ",")]:
            del self._collisions[k]

    def drop_measurement(self, measurement: str) -> None:
        """Remove every series of a measurement (DROP MEASUREMENT;
        reference tsi DropMeasurement). Persisted as a sid=0 tombstone
        record so replay reproduces the drop."""
        with self._lock:
            self._drop_in_mem(measurement)
            if self._log is not None:
                payload = measurement.encode()
                rec = struct.pack("<IQ", len(payload), 0) + payload
                self._log.write(rec)
                self._log_size += len(rec)
                # fsync: the data files are already gone — losing the
                # tombstone would resurrect the series in the index
                self._log.flush()
                os.fsync(self._log.fileno())

    def drop_series(self, measurement: str, sids) -> None:
        """Remove specific series of a measurement (DROP SERIES;
        reference tsi DropSeries). The measurement's columnar store is
        rebuilt with the survivors (a DDL — O(series) is fine), and a
        sid=0 tombstone with a __drop_sids__ payload makes replay
        reproduce the drop."""
        drop = {int(s) for s in np.asarray(sids).tolist()}
        if not drop:
            return
        with self._lock:
            if not self._replay_drop_sids(measurement, drop):
                return
            if self._log is not None:
                items = [measurement.encode(),
                         b"__drop_sids__=" + ",".join(
                             str(s) for s in sorted(drop)).encode()]
                payload = b"\x00".join(items)
                rec = struct.pack("<IQ", len(payload), 0) + payload
                self._log.write(rec)
                self._log_size += len(rec)
                self._log.flush()
                os.fsync(self._log.fileno())

    def _replay_drop_sids(self, measurement: str, drop) -> bool:
        """In-memory part of drop_series (also the tombstone replay).
        Returns True if anything was removed."""
        drop = set(int(s) for s in drop)
        mc = self._msts.get(measurement)
        if mc is None:
            return False
        dead_keys = []
        survivors = []
        for o in range(mc.n):
            sid = int(mc.sids[o])
            if sid in drop:
                dead_keys.append(mc.key_of_ordinal(o))
            else:
                survivors.append((mc.tags_of_ordinal(o), sid))
        if len(survivors) == mc.n:
            return False                    # nothing matched
        for sid in drop:
            if sid < len(self._sid_mst):
                self._sid_mst[sid] = -1
        for k in dead_keys:
            self._collisions.pop(k, None)
        if survivors:
            new = _MstCols(measurement)
            for tags, sid in survivors:
                o = new.add(tags, sid)
                self._sid_ord[sid] = o
            self._msts[measurement] = new
        else:
            self._msts.pop(measurement, None)
        return True

    def get_or_create_sid(self, measurement: str,
                          tags: dict[str, str]) -> int:
        key = series_key(measurement, tags)
        with self._lock:
            sid = self._lookup_key(key)
            if sid is not None:
                return sid
            sid = self._next_sid
            self._insert(measurement, tags, sid)
            self._append_log(measurement, tags, sid)
            return sid

    def get_or_create_sids(self, measurement: str,
                           tags_list) -> np.ndarray:
        """Bulk get_or_create_sid over tag DICTS: rows group by key
        set and run through the COLUMNAR path (scrape/TSBS batches
        have exactly one key set, so this is one
        get_or_create_sids_cols call; keyless rows keep the
        row-at-a-time loop). ~4.5us/series vs ~26 for the loop."""
        nb = len(tags_list)
        if nb == 0:
            return np.empty(0, dtype=np.int64)
        groups: dict[tuple, list] = {}
        for i, tags in enumerate(tags_list):
            groups.setdefault(tuple(sorted(tags)), []).append(i)
        out = np.empty(nb, dtype=np.int64)
        for keys, idxs in groups.items():
            if not keys:
                sids = self._get_or_create_sids_rows(
                    measurement, [tags_list[i] for i in idxs])
            else:
                cols = [[tags_list[i][k] for i in idxs] for k in keys]
                sids = self.get_or_create_sids_cols(
                    measurement, list(keys), cols)
            out[idxs] = sids
        return out

    def _get_or_create_sids_rows(self, measurement: str,
                                 tags_list) -> np.ndarray:
        """Row-at-a-time bulk create: one lock, one capacity grow, one
        log write for the whole batch. The per-call path costs ~47µs
        of Python per series (measured at 1M-series prom ingest);
        this loop shares every lookup structure and defers all
        bookkeeping it can to batch scope (~6µs/series)."""
        import hashlib
        nb = len(tags_list)
        out = np.empty(nb, dtype=np.int64)
        blake = hashlib.blake2b
        with self._lock:
            mc = self._msts.get(measurement)
            if mc is None:
                mc = self._msts[measurement] = _MstCols(measurement)
                if measurement not in self._mst_code:
                    self._mst_code[measurement] = len(self._mst_names)
                    self._mst_names.append(measurement)
            mcode = self._mst_code[measurement]
            mc._ensure_cap(mc.n + nb)
            want_sidcap = self._next_sid + nb
            if want_sidcap > len(self._sid_mst):
                n = max(len(self._sid_mst) * 2, want_sidcap)
                sm = np.full(n, -1, dtype=np.int32)
                sm[:len(self._sid_mst)] = self._sid_mst
                self._sid_mst = sm
                so = np.zeros(n, dtype=np.int64)
                so[:len(self._sid_ord)] = self._sid_ord
                self._sid_ord = so
            collisions = self._collisions
            hash_sid = self._hash_sid
            sid_mst = self._sid_mst
            sid_ord = self._sid_ord
            log_recs: list[bytes] = []
            mname_b = measurement.encode()
            has_log = self._log is not None
            # per-batch cache of the tag-key column indices: prom-style
            # batches repeat one key set, so the key→column resolution
            # runs once, and the per-series inner loop is just value
            # code lookups + two array stores
            last_keys: tuple | None = None
            kis: list[int] = []
            vcs: list[dict] = []
            vds: list[list] = []
            codes = mc.codes
            sids_arr = mc.sids
            prefix = measurement + ","
            for i, tags in enumerate(tags_list):
                items = sorted(tags.items())
                key = prefix + ",".join(
                    f"{k}={v}" for k, v in items)
                sid = collisions.get(key)
                if sid is None:
                    h = int.from_bytes(
                        blake(key.encode(), digest_size=8).digest(),
                        "little")
                    sid = hash_sid.get(h)
                    if sid is not None:
                        # verify (collision safety, as _lookup_key)
                        mi = sid_mst[sid] if sid < len(sid_mst) else -1
                        mc2 = (self._msts.get(self._mst_names[mi])
                               if mi >= 0 else None)
                        if mc2 is None or mc2.key_of_ordinal(
                                int(self._sid_ord[sid])) != key:
                            sid = None
                if sid is not None:
                    out[i] = sid
                    continue
                sid = self._next_sid
                self._next_sid = sid + 1
                ks = tuple(k for k, _v in items)
                if ks != last_keys:
                    kis = [mc._ensure_key(k) for k in ks]
                    vcs = [mc.val_codes[ki] for ki in kis]
                    vds = [mc.val_dicts[ki] for ki in kis]
                    codes = mc.codes        # _ensure_key may grow rows
                    last_keys = ks
                o = mc.n
                for (k, v), ki, vc, vd in zip(items, kis, vcs, vds):
                    c = vc.get(v)
                    if c is None:
                        c = len(vd)
                        vd.append(v)
                        vc[v] = c
                    codes[ki, o] = c
                sids_arr[o] = sid
                mc.n = o + 1
                sid_mst[sid] = mcode
                sid_ord[sid] = o
                cur = hash_sid.get(h)
                if cur is None:
                    hash_sid.put(h, sid)
                elif cur != sid:
                    collisions[key] = sid
                if has_log:
                    payload = b"\x00".join(
                        [mname_b] + [f"{k}={v}".encode()
                                     for k, v in items])
                    log_recs.append(
                        struct.pack("<IQ", len(payload), sid) + payload)
                out[i] = sid
            if log_recs:
                rec = b"".join(log_recs)
                self._log.write(rec)
                self._log_size += len(rec)
        return out

    def get_or_create_sids_cols(self, measurement: str, keys: list,
                                cols: list) -> np.ndarray:
        """COLUMNAR bulk get-or-create: every series shares one tag-key
        set; values arrive as per-key columns (str sequences or numpy
        'S'/'U' arrays). The per-series work of get_or_create_sids —
        sort, key-string build, hash, dict-encode, log-record pack —
        runs as numpy passes over the whole batch (key strings via
        np.char byte concatenation, hashes via the native blake2b
        batch, per-UNIQUE-value dictionary encoding), leaving only a
        hash-map probe loop in Python (~0.3µs/series). Non-ASCII tag
        values fall back to the row-at-a-time path (numpy 'S' casts
        are ASCII-only). Identical observable behavior to
        get_or_create_sids, including log format and hash map state."""
        nb = 0 if not cols else len(cols[0])
        if not keys or nb == 0:
            return self._get_or_create_sids_rows(
                measurement,
                [dict(zip(keys, vals)) for vals in zip(*cols)]
                if nb else [])
        order = sorted(range(len(keys)), key=lambda j: keys[j])
        keys_s = [keys[j] for j in order]
        try:
            cols_b = [np.asarray(cols[j], dtype=np.bytes_)
                      for j in order]
            mname_b = measurement.encode("ascii")
            keys_b = [k.encode("ascii") for k in keys_s]
        except UnicodeEncodeError:
            return self._get_or_create_sids_rows(
                measurement,
                [dict(zip(keys, vals)) for vals in zip(*cols)])
        with self._lock:
            mc = self._msts.get(measurement)
            if mc is None:
                mc = self._msts[measurement] = _MstCols(measurement)
                if measurement not in self._mst_code:
                    self._mst_code[measurement] = len(self._mst_names)
                    self._mst_names.append(measurement)
            mcode = self._mst_code[measurement]
            kis = np.array([mc._ensure_key(k) for k in keys_s],
                           dtype=np.int64)
            K = len(keys_s)
            # ---- dict-encode each value column (per UNIQUE value) ----
            code_cols = np.empty((K, nb), dtype=np.int32)
            for j in range(K):
                uniq, inv = np.unique(cols_b[j], return_inverse=True)
                vc = mc.val_codes[int(kis[j])]
                vd = mc.val_dicts[int(kis[j])]
                lut = np.empty(len(uniq), dtype=np.int32)
                for ui, vb in enumerate(uniq.tolist()):
                    v = vb.decode()
                    c = vc.get(v)
                    if c is None:
                        c = len(vd)
                        vd.append(v)
                        vc[v] = c
                    lut[ui] = c
                code_cols[j] = lut[inv]
            # ---- key strings + hashes (native single pass) ----
            seps = [mname_b + b"," + keys_b[0] + b"="] + [
                b"," + kb + b"=" for kb in keys_b[1:]]
            built = _native.build_keys(cols_b, seps)
            if built is not None:
                packed, offs = built
            else:
                acc = np.char.add(seps[0], cols_b[0])
                for j in range(1, K):
                    acc = np.char.add(np.char.add(acc, seps[j]),
                                      cols_b[j])
                W = acc.dtype.itemsize
                lens = np.char.str_len(acc).astype(np.int64)
                mat = acc.view(np.uint8).reshape(nb, W)
                packed = mat[np.arange(W)[None, :] < lens[:, None]]
                offs = np.zeros(nb + 1, dtype=np.int64)
                np.cumsum(lens, out=offs[1:])
            hashes = _native.blake2b8_batch(packed, offs)
            # ---- get-or-assign probe (one native call) ----
            next0 = self._next_sid
            out, isnew, next_sid = self._hash_sid.probe(hashes, next0)
            sid_mst = self._sid_mst
            new_pos = np.nonzero(isnew)[0]
            hit_pos = np.nonzero(~isnew)[0]
            # ---- verify every hash hit by integer code comparison ----
            # (a matching blake2b-64 with mismatched codes is a true
            # collision — resolved through the slow path's side dict)
            bad = np.empty(0, dtype=np.int64)
            if len(hit_pos):
                hsids = out[hit_pos]
                pend = hsids >= next0      # duplicates of in-batch new
                pp = hit_pos[pend]
                if len(pp):
                    fo = new_pos[hsids[pend] - next0]
                    mism = (code_cols[:, pp]
                            != code_cols[:, fo]).any(axis=0)
                    bad = pp[mism]
                ex = hit_pos[~pend]
                if len(ex):
                    esids = out[ex]
                    ok = sid_mst[esids] == mcode
                    # a cross-measurement hash collision's ordinal can
                    # exceed THIS measurement's capacity — never index
                    # with it (the row is already bad via ~ok)
                    ords = np.where(ok, self._sid_ord[esids], 0)
                    full = mc.codes[:, ords]        # (K_total, H)
                    probe = np.zeros_like(full)
                    probe[kis] = code_cols[:, ex]
                    ok &= (full == probe).all(axis=0)
                    bad = np.concatenate([bad, ex[~ok]])
            # ---- vectorized insert of the new series ----
            m = len(new_pos)
            if m:
                sids_new = next0 + np.arange(m, dtype=np.int64)
                mc._ensure_cap(mc.n + m)
                ords = mc.n + np.arange(m, dtype=np.int64)
                mc.codes[kis[:, None],
                         ords[None, :]] = code_cols[:, new_pos]
                mc.sids[ords] = sids_new
                mc.n += m
                if next_sid > len(self._sid_mst):
                    n2 = max(len(self._sid_mst) * 2, next_sid)
                    sm = np.full(n2, -1, dtype=np.int32)
                    sm[:len(self._sid_mst)] = self._sid_mst
                    self._sid_mst = sm
                    so = np.zeros(n2, dtype=np.int64)
                    so[:len(self._sid_ord)] = self._sid_ord
                    self._sid_ord = so
                self._sid_mst[sids_new] = mcode
                self._sid_ord[sids_new] = ords
                self._next_sid = next_sid
                if self._log is not None:
                    self._append_log_batch(
                        mname_b, keys_b, cols_b, new_pos, sids_new)
            if len(bad):
                # true collisions: route through the canonical path,
                # which verifies by full key and uses the side dict
                for bi in bad.tolist():
                    out[bi] = self.get_or_create_sid(
                        measurement,
                        {k: cols_b[j][bi].decode()
                         for j, k in enumerate(keys_s)})
        return out

    def _append_log_batch(self, mname_b: bytes, keys_b: list,
                          cols_b: list, idx: np.ndarray,
                          sids: np.ndarray) -> None:
        """Batch form of _append_log: same record stream, assembled
        natively (payload build + length-prefix pack) or with two
        vectorized scatters as the fallback."""
        seps = [mname_b + b"\x00" + keys_b[0] + b"="] + [
            b"\x00" + kb + b"=" for kb in keys_b[1:]]
        built = _native.build_keys([c[idx] for c in cols_b], seps)
        if built is not None:
            pbuf, poffs = built
            buf = _native.log_pack(pbuf, poffs, sids)
            if buf is not None:
                self._log.write(buf)
                self._log_size += len(buf)
                return
        payload = np.char.add(mname_b + b"\x00" + keys_b[0] + b"=",
                              cols_b[0][idx])
        for j in range(1, len(keys_b)):
            payload = np.char.add(
                np.char.add(payload, b"\x00" + keys_b[j] + b"="),
                cols_b[j][idx])
        m = len(idx)
        W = payload.dtype.itemsize
        lens = np.char.str_len(payload).astype(np.int64)
        rec_lens = _HDR + lens
        roffs = np.zeros(m + 1, dtype=np.int64)
        np.cumsum(rec_lens, out=roffs[1:])
        stream = np.zeros(int(roffs[-1]), dtype=np.uint8)
        hdr = np.empty((m, _HDR), dtype=np.uint8)
        hdr[:, :4] = lens.astype("<u4").view(np.uint8).reshape(m, 4)
        hdr[:, 4:] = sids.astype("<u8").view(np.uint8).reshape(m, 8)
        stream[(roffs[:-1, None]
                + np.arange(_HDR)[None, :]).ravel()] = hdr.ravel()
        pmat = payload.view(np.uint8).reshape(m, W)
        pvalid = np.arange(W)[None, :] < lens[:, None]
        ppos = roffs[:-1, None] + _HDR + np.arange(W)[None, :]
        stream[ppos[pvalid]] = pmat[pvalid]
        buf = stream.tobytes()
        self._log.write(buf)
        self._log_size += len(buf)

    def get_sid(self, measurement: str, tags: dict[str, str]) -> int | None:
        with self._lock:
            return self._lookup_key(series_key(measurement, tags))

    # ---- queries ---------------------------------------------------------

    @property
    def series_cardinality(self) -> int:
        with self._lock:
            return sum(mc.n for mc in self._msts.values())

    def series_keys(self, measurement: str | None = None) -> list[str]:
        """All series keys (optionally one measurement's) — callers
        union across shards for exact db-wide cardinality."""
        with self._lock:
            msts = [self._msts[measurement]] \
                if measurement in self._msts else \
                ([] if measurement is not None
                 else list(self._msts.values()))
            out = []
            for mc in msts:
                out.extend(mc.key_of_ordinal(o) for o in range(mc.n))
            return out

    @property
    def max_sid(self) -> int:
        return self._next_sid - 1

    def measurements(self) -> list[str]:
        with self._lock:
            return sorted(self._msts)

    def tags_of(self, sid: int) -> dict[str, str]:
        with self._lock:
            if sid >= len(self._sid_mst) or self._sid_mst[sid] < 0:
                return {}
            mc = self._msts.get(self._mst_names[self._sid_mst[sid]])
            if mc is None:
                return {}
            return mc.tags_of_ordinal(int(self._sid_ord[sid]))

    def tag_values(self, measurement: str, key: str) -> list[str]:
        with self._lock:
            mc = self._msts.get(measurement)
            if mc is None:
                return []
            ki = mc.key_idx.get(key)
            if ki is None:
                return []
            # only values actually referenced by a live series
            used = np.unique(mc.codes[ki, :mc.n])
            return sorted(mc.val_dicts[ki][c] for c in used if c)

    def tag_keys(self, measurement: str) -> list[str]:
        with self._lock:
            mc = self._msts.get(measurement)
            return sorted(mc.tag_keys) if mc is not None else []

    def series_ids(self, measurement: str,
                   filters: list[TagFilter] | None = None,
                   tag_exprs: list | None = None) -> np.ndarray:
        """AND of tag predicates → sorted sid array (the reference's
        tag_filters.go search, as one vectorized mask pass)."""
        with self._lock:
            mc = self._msts.get(measurement)
            if mc is None or mc.n == 0:
                return np.empty(0, dtype=np.int64)
            mask = mc.filter_mask(filters or [], tag_exprs)
            if mask is None:
                return np.empty(0, dtype=np.int64)
            return np.sort(mc.sids[:mc.n][mask])

    def group_by_tagsets(self, measurement: str,
                         group_keys: list[str],
                         filters: list[TagFilter] | None = None,
                         tag_exprs: list | None = None
                         ) -> list[tuple[tuple[str, ...], np.ndarray]]:
        """Partition matching series into tagsets by group_keys (the
        reference's tagset construction, engine/iterators.go:100 'Scan →
        tagsets'), vectorized: one np.unique over the stacked group-key
        code rows. Returns [(tag values tuple, sorted sid array)],
        sorted by tag values; series missing a group key get ''."""
        with self._lock:
            mc = self._msts.get(measurement)
            if mc is None or mc.n == 0:
                return []
            mask = mc.filter_mask(filters or [], tag_exprs)
            if mask is None or not mask.any():
                return []
            sel = np.nonzero(mask)[0]
            sids = mc.sids[:mc.n][sel]
            if not group_keys:
                return [((), np.sort(sids))]
            rows = []
            for k in group_keys:
                ki = mc.key_idx.get(k)
                rows.append(mc.codes[ki, :mc.n][sel] if ki is not None
                            else np.zeros(len(sel), dtype=np.int32))
            stacked = np.stack(rows)                   # (K, S)
            order = np.lexsort(stacked[::-1])
            ss = stacked[:, order]
            boundary = np.empty(ss.shape[1], dtype=bool)
            boundary[0] = True
            if ss.shape[1] > 1:
                boundary[1:] = (ss[:, 1:] != ss[:, :-1]).any(axis=0)
            starts = np.nonzero(boundary)[0]
            ends = np.append(starts[1:], ss.shape[1])
            out = []
            sids_sorted = sids[order]
            for s0, s1 in zip(starts, ends):
                codes = ss[:, s0]
                key = tuple(
                    mc.val_dicts[mc.key_idx[k]][int(c)]
                    if c and mc.key_idx.get(k) is not None else ""
                    for k, c in zip(group_keys, codes))
                out.append((key, np.sort(sids_sorted[s0:s1])))
            out.sort(key=lambda kv: kv[0])
            return out

    def group_lut(self, tagsets: list[tuple[tuple[str, ...], np.ndarray]]
                  ) -> np.ndarray:
        """Dense sid → group-index lookup table for the device kernels;
        unmatched sids map to -1."""
        lut = np.full(self.max_sid + 1, -1, dtype=np.int64)
        for gi, (_k, sids) in enumerate(tagsets):
            lut[sids] = gi
        return lut

    def close(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.flush()
                if self._log_size - self._snap_covered > SNAP_THRESHOLD:
                    self._write_snapshot()
                self._log.close()
                self._log = None
