"""Prometheus remote read/write codec + executor glue.

Reference: lib/util/lifted/influx/httpd/handler_prom.go:54 (servePromWrite
→ snappy.Decode → proto.Unmarshal → points), :146 (servePromRead →
per-query series matching → QueryResult). The wire format is the public
prompb protocol (remote.proto, compiled to remote_pb2.py with protoc).

Snappy BLOCK format (not the framed stream) via pyarrow's bundled codec;
the block's leading uvarint carries the uncompressed length pyarrow needs.
"""

from __future__ import annotations

import numpy as np

from ..storage.rows import PointRow
from ..utils import get_logger
from . import remote_pb2 as pb

log = get_logger(__name__)

MS = 10**6                     # prom timestamps are ms; engine is ns
VALUE_FIELD = "value"
MAX_DECOMPRESSED = 1 << 30     # 1 GiB guard against decompression bombs


def _uvarint(buf: bytes) -> tuple[int, int]:
    x = s = 0
    for i, b in enumerate(buf[:10]):
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i + 1
        s += 7
    raise ValueError("bad snappy length varint")


def snappy_decompress(body: bytes) -> bytes:
    import pyarrow as pa
    n, _hdr = _uvarint(body)
    if n > MAX_DECOMPRESSED:
        raise ValueError(f"snappy payload too large: {n}")
    return pa.decompress(body, decompressed_size=n, codec="snappy",
                         asbytes=True)


def snappy_compress(body: bytes) -> bytes:
    import pyarrow as pa
    return pa.compress(body, codec="snappy", asbytes=True)


# ------------------------------------------------------------------ write

def decode_write_request(body: bytes) -> "pb.WriteRequest":
    return pb.WriteRequest.FromString(snappy_decompress(body))


def rows_from_write_request(req: "pb.WriteRequest") -> list[PointRow]:
    """WriteRequest → engine rows: __name__ → measurement, labels →
    tags, value field carries the sample (promql/engine.py mapping).
    NaN samples are prometheus stale markers — dropped."""
    rows: list[PointRow] = []
    for ts in req.timeseries:
        name = None
        tags: dict[str, str] = {}
        for lb in ts.labels:
            if lb.name == "__name__":
                name = lb.value
            else:
                tags[lb.name] = lb.value
        if not name:
            continue
        for s in ts.samples:
            if s.value != s.value:          # NaN stale marker
                continue
            rows.append(PointRow(name, tags, {VALUE_FIELD: s.value},
                                 int(s.timestamp) * MS))
    return rows


def records_from_write_request(req: "pb.WriteRequest") -> list[tuple]:
    """WriteRequest → columnar write_record_batch entries
    [(mst, tags, times ns i64, {value: f64})] — the high-cardinality
    remote-write fast path (rows_from_write_request builds a PointRow
    per SAMPLE; this builds two numpy arrays per SERIES and lets the
    engine's bulk frame path take it from there). NaN stale markers
    drop per sample."""
    import numpy as np
    out: list[tuple] = []
    for ts in req.timeseries:
        name = None
        tags: dict[str, str] = {}
        for lb in ts.labels:
            if lb.name == "__name__":
                name = lb.value
            else:
                tags[lb.name] = lb.value
        if not name or not ts.samples:
            continue
        n = len(ts.samples)
        times = np.empty(n, dtype=np.int64)
        vals = np.empty(n, dtype=np.float64)
        for i, s in enumerate(ts.samples):
            times[i] = s.timestamp
            vals[i] = s.value
        keep = vals == vals                 # drop NaN stale markers
        if not keep.all():
            times, vals = times[keep], vals[keep]
            if not len(times):
                continue
        out.append((name, tags, times * MS, {VALUE_FIELD: vals}))
    return out


def matrices_from_write_request(req, min_group: int = 64):
    """WriteRequest → aligned-series MATRICES + leftover columnar
    records. Scrape batches overwhelmingly share one timestamp vector
    per (metric, label-key-set); those groups land as
    (mst, keys, tag_cols, times ns, values (S, P)) for
    Engine.write_series_matrix — zero per-series work downstream
    (index tag columns, tiled WAL/memtable frames). Groups smaller
    than min_group and ragged series fall out as
    records_from_write_request-shaped entries."""
    import numpy as np
    groups: dict = {}
    rest: list[tuple] = []
    for ts in req.timeseries:
        name = None
        keys: list = []
        vals: list = []
        for lb in ts.labels:
            if lb.name == "__name__":
                name = lb.value
            else:
                keys.append(lb.name)
                vals.append(lb.value)
        if not name or not ts.samples:
            continue
        n = len(ts.samples)
        times = np.empty(n, dtype=np.int64)
        sam = np.empty(n, dtype=np.float64)
        for i, s in enumerate(ts.samples):
            times[i] = s.timestamp
            sam[i] = s.value
        keep = sam == sam                  # drop NaN stale markers
        if not keep.all():
            times, sam = times[keep], sam[keep]
            if not len(times):
                continue
        if keys and not all(keys[i] < keys[i + 1]
                            for i in range(len(keys) - 1)):
            order = sorted(range(len(keys)), key=keys.__getitem__)
            keys = [keys[i] for i in order]
            vals = [vals[i] for i in order]
        g = groups.get((name, tuple(keys), times.tobytes()))
        if g is None:
            g = groups[(name, tuple(keys), times.tobytes())] = (
                [[] for _ in keys], [], times)
        for j, v in enumerate(vals):
            g[0][j].append(v)
        g[1].append(sam)
    mats = []
    for (name, keys, _tb), (cols, rows, times) in groups.items():
        # label-less series have no tag columns to key a matrix on —
        # write_series_matrix would drop them (S == 0); row path
        if keys and len(rows) >= min_group:
            mats.append((name, list(keys), cols, times * MS,
                         np.vstack(rows)))
        else:
            rest.extend(
                (name, dict(zip(keys, (c[i] for c in cols))),
                 times * MS, {VALUE_FIELD: rows[i]})
                for i in range(len(rows)))
    return mats, rest


# ------------------------------------------------------------------- read

def decode_read_request(body: bytes) -> "pb.ReadRequest":
    return pb.ReadRequest.FromString(snappy_decompress(body))


_MATCH_OPS = {pb.LabelMatcher.EQ: "=", pb.LabelMatcher.NEQ: "!=",
              pb.LabelMatcher.RE: "=~", pb.LabelMatcher.NRE: "!~"}


def _anchor(pattern: str) -> str:
    """Prometheus regex matchers are FULLY ANCHORED (m1 does not match
    m10); the engine's tag filters use search semantics, so wrap."""
    return r"\A(?:" + pattern + r")\Z"


def _match_name(matchers, measurements: list[str]) -> list[str]:
    """Resolve the __name__ matcher to measurements."""
    import re
    out = measurements
    for m in matchers:
        if m.name != "__name__":
            continue
        op = _MATCH_OPS[m.type]
        if op == "=":
            out = [n for n in out if n == m.value]
        elif op == "!=":
            out = [n for n in out if n != m.value]
        else:
            rx = re.compile(_anchor(m.value))
            keep = [n for n in out if rx.search(n)]
            out = keep if op == "=~" else \
                [n for n in out if n not in set(keep)]
    return out


def handle_remote_read(engine, db: str, req: "pb.ReadRequest"
                       ) -> "pb.ReadResponse":
    """Per query: match series via the tag index, stream raw samples in
    the range (the reference's remote-read path returns raw series; any
    PromQL evaluation — rate() etc. — happens in the client
    prometheus)."""
    from ..index import TagFilter

    resp = pb.ReadResponse()
    try:
        db_obj = engine.database(db)
    except KeyError:
        for _q in req.queries:
            resp.results.add()
        return resp
    for q in req.queries:
        result = resp.results.add()
        t_lo = int(q.start_timestamp_ms) * MS
        t_hi = int(q.end_timestamp_ms) * MS
        filters = [TagFilter(m.name,
                             _anchor(m.value)
                             if _MATCH_OPS[m.type] in ("=~", "!~")
                             else m.value,
                             _MATCH_OPS[m.type])
                   for m in q.matchers if m.name != "__name__"]
        shards = db_obj.shards_overlapping(t_lo, t_hi)
        msts = sorted({m for s in shards for m in s.measurements()})
        # per (metric, labelset): samples merged across shards
        out: dict[tuple, dict] = {}
        for name in _match_name(q.matchers, msts):
            for s in shards:
                for sid in s.series_ids(name, filters).tolist():
                    rec = s.read_series(name, sid, [VALUE_FIELD],
                                        t_lo, t_hi)
                    if rec is None or rec.num_rows == 0:
                        continue
                    col = rec.column(VALUE_FIELD)
                    if col is None or col.values is None:
                        continue
                    tags = s.index.tags_of(sid)
                    key = (name, tuple(sorted(tags.items())))
                    ent = out.setdefault(key, {"t": [], "v": []})
                    m = col.valid
                    ent["t"].append(rec.times[m])
                    ent["v"].append(
                        col.values[m].astype(np.float64, copy=False))
        for (name, tags), ent in sorted(out.items()):
            ts = result.timeseries.add()
            ts.labels.add(name="__name__", value=name)
            for k, v in tags:
                ts.labels.add(name=k, value=v)
            t = np.concatenate(ent["t"])
            v = np.concatenate(ent["v"])
            order = np.argsort(t, kind="stable")
            t_ms = (t[order] // MS).tolist()
            vals = v[order].tolist()
            for tm, vv in zip(t_ms, vals):
                ts.samples.add(value=vv, timestamp=tm)
    return resp


def encode_read_response(resp: "pb.ReadResponse") -> bytes:
    return snappy_compress(resp.SerializeToString())
