"""Prometheus remote read/write (reference handler_prom.go:54 write,
:146 read): snappy-block-compressed protobuf bodies on
/api/v1/prom/write and /api/v1/prom/read.

Mapping (same as the reference's prom ingest): metric name → measurement,
labels → tags, the sample value → the ``value`` float field — exactly
the shape promql/engine.py reads."""

from .remote import (decode_read_request, decode_write_request,
                     encode_read_response, handle_remote_read,
                     matrices_from_write_request,
                     records_from_write_request,
                     rows_from_write_request, snappy_compress,
                     snappy_decompress)

__all__ = ["decode_write_request", "decode_read_request",
           "encode_read_response", "handle_remote_read",
           "matrices_from_write_request",
           "records_from_write_request",
           "rows_from_write_request", "snappy_compress",
           "snappy_decompress"]
