"""Streaming device pipeline: overlap dispatch, D2H, and host fold.

BENCH_r05 showed the headline query spending 647ms of 789ms blocked in
one monolithic `device_pull`: every kernel was dispatched, then ONE
barrier drained the device, then ONE giant transfer crossed the slow
tunnel link, then the host unpacked — strictly serialized phases. The
accelerated-analytics literature makes the same diagnosis (PAPERS:
*GPU Acceleration of SQL Analytics on Compressed Data*; *Tailwind*):
decode/transfer must overlap compute, and reductions belong on the
accelerator so only final cells cross the link.

This module is the overlap half of that program:

- ``device_get_parallel`` — the chunked multi-stream fetch (moved from
  query/executor.py so ops-layer callers can batch their own pulls):
  per-leaf thread parallelism lifts the tunnel link's large-transfer
  bandwidth ~54 → ~70 MB/s (measured, 4 streams), chunking bounds the
  latency of any single fetch.
- ``StreamingPipeline`` — a bounded-depth launch→pull→host-fold
  pipeline. The executor submits each launch's device outputs as soon
  as the launch is issued; a background puller waits for THAT launch's
  readiness, starts its D2H immediately, and runs the host-side
  unpack/fold callback — all while later launches are still computing
  and the scan threads are still decoding. ``OG_PIPELINE_DEPTH`` bounds
  how many launches may be in flight ahead of their pulls (submit
  blocks when the window is full, so dispatch proceeds in bounded
  batches); depth 0 disables streaming entirely and the executor takes
  the classic single-barrier path.

Bit-identity: the pipeline changes WHEN results cross and WHO folds
them, never the arithmetic. Host folds that run concurrently are
restricted to order-free exact operations (integer adds, flag ORs), so
arrival order cannot change a single output bit — the perf_smoke gate
(scripts/perf_smoke.sh) asserts streaming == single-barrier cell for
cell.

Reference role: the streaming chunk return of the reference's executor
(engine/executor/chunk_codec.gen.go) — results cross the wire in
bounded pieces concurrently with upstream work, not as one monolithic
transfer after a global barrier.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..utils import knobs
from ..utils.lockrank import (RANK_PIPELINE, RANK_PIPELINE_POOL,
                              RankedLock)


def _now_ns() -> int:
    import time
    return time.perf_counter_ns()


def pipeline_depth() -> int:
    """Launch window of the streaming pipeline (0 disables). Read
    dynamically so tests and operators can flip it per query."""
    return int(knobs.get("OG_PIPELINE_DEPTH"))


def pull_threads() -> int:
    return max(1, int(knobs.get("OG_PIPELINE_THREADS")))


def device_get_parallel(tree, chunk_bytes=32 << 20, threads=6,
                        stats: dict | None = None):
    """device_get with per-leaf thread parallelism and chunked fetches
    of large leaves. The tunnel-attached link serializes transfers and
    pays a full round trip per pull; concurrent streams overlap that
    latency and lift large-transfer bandwidth ~54 → ~70 MB/s
    (measured, 4 streams). Non-device leaves pass through untouched.
    ``stats`` (optional dict) receives bytes/leaves/pulls of this call
    so per-query accounting doesn't race the global counters."""
    import concurrent.futures as cf

    import jax

    from . import devstats as _ds
    _t_pull0 = _now_ns()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    parts: list = [None] * len(leaves)
    jobs: list = []                     # (leaf_idx, chunk_idx, buf)
    total_b = 0
    n_dev = 0
    for i, x in enumerate(leaves):
        if not isinstance(x, jax.Array):
            parts[i] = x
            continue
        n_dev += 1
        total_b += x.size * x.dtype.itemsize
        nb = x.size * x.dtype.itemsize
        if x.ndim == 0 or nb <= chunk_bytes:
            jobs.append((i, None, x))
            continue
        ax = int(np.argmax(x.shape))
        n = x.shape[ax]
        k = min(-(-nb // chunk_bytes), 8)
        bounds = [n * j // k for j in range(k + 1)]
        parts[i] = ["chunks", ax, [None] * k]
        for j in range(k):
            jobs.append((i, j, (x, ax, bounds[j], bounds[j + 1])))
    if jobs:
        def _fetch(t):
            # slice lazily IN the worker: an eager device-side copy of
            # every chunk up front would double peak HBM for the
            # result set before any D2H happened
            i, j, b = t
            if isinstance(b, tuple):
                x, ax, lo, hi = b
                idx = [slice(None)] * x.ndim
                idx[ax] = slice(lo, hi)
                b = x[tuple(idx)]
            return (i, j, np.asarray(b))

        if len(jobs) == 1 or threads <= 1:
            jobs_out = [_fetch(j) for j in jobs]
        else:
            with cf.ThreadPoolExecutor(min(threads, len(jobs))) as pool:
                jobs_out = list(pool.map(_fetch, jobs))
        for i, j, arr in jobs_out:
            if j is None:
                parts[i] = arr
            else:
                parts[i][2][j] = arr
    out = [np.concatenate(p[2], axis=p[1])
           if isinstance(p, list) and p and p[0] == "chunks" else p
           for p in parts]
    _ds.bump("d2h_bytes", total_b)
    _ds.bump("d2h_pulls", len(jobs))
    _ds.bump("d2h_wait_ns", _now_ns() - _t_pull0)
    if n_dev:
        # per-call distribution (flight-recorder histograms): bytes and
        # wall of ONE batched pull — the p99 the tunnel link lives by
        _ds.observe_pull(total_b, _now_ns() - _t_pull0)
    if stats is not None:
        stats["bytes"] = stats.get("bytes", 0) + total_b
        stats["leaves"] = stats.get("leaves", 0) + n_dev
        stats["pulls"] = stats.get("pulls", 0) + len(jobs)
    return jax.tree_util.tree_unflatten(treedef, out)


_PULL_POOL: ThreadPoolExecutor | None = None
_PULL_POOL_LOCK = RankedLock("pipeline.pool", RANK_PIPELINE_POOL)


def _pull_pool() -> ThreadPoolExecutor:
    """Shared daemon puller pool: pull threads spend their lives
    blocked in the PJRT transfer (GIL released), so a small process-
    wide pool serves every concurrent query."""
    global _PULL_POOL
    with _PULL_POOL_LOCK:
        if _PULL_POOL is None:
            _PULL_POOL = ThreadPoolExecutor(
                max_workers=pull_threads(),
                thread_name_prefix="og-pipe")
        return _PULL_POOL


class StreamingPipeline:
    """Bounded-depth launch→pull→host-fold pipeline for one query.

    submit() registers one launch's device output tree right after
    dispatch; a puller thread waits for that launch's readiness
    (per-leaf, not a global barrier), starts its D2H immediately with
    the chunked multi-stream fetch, then runs the optional host
    ``post`` callback (unpack_packed / lattice fold) — concurrently
    with later launches still computing on device and the scan pool
    still decoding on host. submit() blocks while ``depth`` launches
    are already in flight, so dispatch proceeds in bounded batches and
    result HBM never exceeds depth × launch output size.

    collect() joins everything and returns {key: post_result}; worker
    exceptions re-raise there (the executor's normal error path).

    ``gate`` (optional semaphore) is the query scheduler's GLOBAL
    in-flight bound: per-query ``depth`` caps one query's result HBM,
    the shared gate caps the sum across concurrent queries (without it
    N queries × depth launches could all be in flight at once)."""

    def __init__(self, depth: int | None = None, gate=None, span=None,
                 ctx=None):
        self.depth = depth if depth is not None else pipeline_depth()
        self._sem = threading.BoundedSemaphore(max(1, self.depth))
        self.gate = gate
        # per-query working-set attribution (device observatory): the
        # submitting query's ctx carries live/peak in-flight result
        # bytes (SHOW QUERIES hbm_peak_mb, scheduler calibration)
        self.ctx = ctx
        # sampled-query tracing (utils/tracing): each launch's pull +
        # host fold gets a span on its puller thread's lane, so the
        # Chrome timeline export shows the launch/pull/unpack overlap
        # that phase sums can only hint at. None (sampled out) costs
        # nothing on the hot path.
        self.span = span
        self._futs: dict = {}
        self._lock = RankedLock("pipeline", RANK_PIPELINE)
        self.launches = 0
        self.first_ns: int | None = None    # first pull start
        self.last_ns: int | None = None     # last pull/fold end
        self.bytes = 0
        self.leaves = 0
        # per-transport D2H split (op-aware plane diet accounting):
        # the executor labels each submit (packed/legacy/finalized/
        # lattice/dense) so the pull telemetry stays attributable when
        # a query mixes transport forms
        self.bytes_by: dict = {}

    def submit(self, key, tree, post=None, transport=None) -> None:
        self._sem.acquire()
        if self.gate is not None:
            try:
                self.gate.acquire()
            except BaseException:
                self._sem.release()
                raise
        # HBM ledger (ops/hbm.py): this launch's device result buffers
        # are in flight from submit until its pull/fold completes —
        # the 'pipeline' tier is the live sum across ALL queries, the
        # ctx attribution is this query's share (metadata-only byte
        # estimate; no transfer, no sync)
        from . import hbm as _hbm
        est_b = _hbm._tree_device_bytes(tree)
        _hbm.account("pipeline", est_b)
        if self.ctx is not None and hasattr(self.ctx, "add_hbm"):
            self.ctx.add_hbm(est_b)
        try:
            fut = _pull_pool().submit(self._run, tree, post, transport,
                                      est_b)
        except BaseException:
            self._account_done(est_b)
            if self.gate is not None:
                self.gate.release()
            self._sem.release()
            raise
        with self._lock:
            self.launches += 1
            self._futs[key] = fut

    def _account_done(self, est_b: int) -> None:
        from . import hbm as _hbm
        _hbm.release("pipeline", est_b)
        if self.ctx is not None and hasattr(self.ctx, "sub_hbm"):
            self.ctx.sub_hbm(est_b)

    def _run(self, tree, post, transport=None, est_b: int = 0):
        import jax
        try:
            t0 = _now_ns()
            try:
                # drain THIS launch only: device_get on in-flight
                # arrays takes the tunnel's slow synchronous fetch path
                # (measured 6x the post-completion transfer)
                jax.block_until_ready(tree)
            except Exception:
                pass
            pull_sp = None
            if self.span is not None:
                pull_sp = self.span.child("pipeline.pull")
                pull_sp.start_ns = t0
                pull_sp.add(lane=threading.current_thread().name)
            st: dict = {}
            host = device_get_parallel(tree, stats=st)
            if pull_sp is not None:
                pull_sp.end_ns = _now_ns()
                pull_sp.add(bytes=st.get("bytes", 0),
                            **({"transport": transport}
                               if transport else {}))
                unpack_sp = None
                if post is not None:
                    unpack_sp = self.span.child("pipeline.unpack")
                    unpack_sp.start_ns = _now_ns()
                    unpack_sp.add(
                        lane=threading.current_thread().name)
            out = post(host) if post is not None else host
            if pull_sp is not None and post is not None:
                unpack_sp.end_ns = _now_ns()
            t1 = _now_ns()
            with self._lock:
                if self.first_ns is None or t0 < self.first_ns:
                    self.first_ns = t0
                if self.last_ns is None or t1 > self.last_ns:
                    self.last_ns = t1
                self.bytes += st.get("bytes", 0)
                self.leaves += st.get("leaves", 0)
                if transport is not None:
                    self.bytes_by[transport] = (
                        self.bytes_by.get(transport, 0)
                        + st.get("bytes", 0))
            return out
        finally:
            self._account_done(est_b)
            if self.gate is not None:
                self.gate.release()
            self._sem.release()

    def collect(self) -> dict:
        """Wait for every submitted pull+fold; first worker exception
        re-raises here. Safe to call with zero submissions."""
        with self._lock:
            futs = dict(self._futs)
        return {k: f.result() for k, f in futs.items()}
