"""Streaming device pipeline: overlap dispatch, D2H, and host fold.

BENCH_r05 showed the headline query spending 647ms of 789ms blocked in
one monolithic `device_pull`: every kernel was dispatched, then ONE
barrier drained the device, then ONE giant transfer crossed the slow
tunnel link, then the host unpacked — strictly serialized phases. The
accelerated-analytics literature makes the same diagnosis (PAPERS:
*GPU Acceleration of SQL Analytics on Compressed Data*; *Tailwind*):
decode/transfer must overlap compute, and reductions belong on the
accelerator so only final cells cross the link.

This module is the overlap half of that program:

- ``device_get_parallel`` — the chunked multi-stream fetch (moved from
  query/executor.py so ops-layer callers can batch their own pulls):
  per-leaf thread parallelism lifts the tunnel link's large-transfer
  bandwidth ~54 → ~70 MB/s (measured, 4 streams), chunking bounds the
  latency of any single fetch.
- ``StreamingPipeline`` — a bounded-depth launch→pull→host-fold
  pipeline. The executor submits each launch's device outputs as soon
  as the launch is issued; a background puller waits for THAT launch's
  readiness, starts its D2H immediately, and runs the host-side
  unpack/fold callback — all while later launches are still computing
  and the scan threads are still decoding. ``OG_PIPELINE_DEPTH`` bounds
  how many launches may be in flight ahead of their pulls (submit
  blocks when the window is full, so dispatch proceeds in bounded
  batches); depth 0 disables streaming entirely and the executor takes
  the classic single-barrier path.

Bit-identity: the pipeline changes WHEN results cross and WHO folds
them, never the arithmetic. Host folds that run concurrently are
restricted to order-free exact operations (integer adds, flag ORs), so
arrival order cannot change a single output bit — the perf_smoke gate
(scripts/perf_smoke.sh) asserts streaming == single-barrier cell for
cell.

Reference role: the streaming chunk return of the reference's executor
(engine/executor/chunk_codec.gen.go) — results cross the wire in
bounded pieces concurrently with upstream work, not as one monolithic
transfer after a global barrier.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout

import numpy as np

from ..utils import failpoint, knobs
from ..utils import deadline as _deadline
from ..utils.lockrank import (RANK_PIPELINE, RANK_PIPELINE_POOL,
                              RankedLock)


def _now_ns() -> int:
    import time
    return time.perf_counter_ns()


def pipeline_depth() -> int:
    """Launch window of the streaming pipeline (0 disables). Read
    dynamically so tests and operators can flip it per query."""
    return int(knobs.get("OG_PIPELINE_DEPTH"))


def pull_threads() -> int:
    return max(1, int(knobs.get("OG_PIPELINE_THREADS")))


def device_get_parallel(tree, chunk_bytes=32 << 20, threads=6,
                        stats: dict | None = None,
                        site: str = "other"):
    """device_get with per-leaf thread parallelism and chunked fetches
    of large leaves. The tunnel-attached link serializes transfers and
    pays a full round trip per pull; concurrent streams overlap that
    latency and lift large-transfer bandwidth ~54 → ~70 MB/s
    (measured, 4 streams). Non-device leaves pass through untouched.
    ``stats`` (optional dict) receives bytes/leaves/pulls of this call
    so per-query accounting doesn't race the global counters.
    ``site`` labels the pull in the per-site transfer manifest
    (ops/compileaudit.py — callers name their lane so every D2H byte
    stays attributable)."""
    import concurrent.futures as cf

    import jax

    from . import devstats as _ds
    _t_pull0 = _now_ns()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    parts: list = [None] * len(leaves)
    jobs: list = []                     # (leaf_idx, chunk_idx, buf)
    total_b = 0
    n_dev = 0
    for i, x in enumerate(leaves):
        if not isinstance(x, jax.Array):
            parts[i] = x
            continue
        n_dev += 1
        total_b += x.size * x.dtype.itemsize
        nb = x.size * x.dtype.itemsize
        if x.ndim == 0 or nb <= chunk_bytes:
            jobs.append((i, None, x))
            continue
        ax = int(np.argmax(x.shape))
        n = x.shape[ax]
        k = min(-(-nb // chunk_bytes), 8)
        bounds = [n * j // k for j in range(k + 1)]
        parts[i] = ["chunks", ax, [None] * k]
        for j in range(k):
            jobs.append((i, j, (x, ax, bounds[j], bounds[j + 1])))
    if jobs:
        def _fetch(t):
            # slice lazily IN the worker: an eager device-side copy of
            # every chunk up front would double peak HBM for the
            # result set before any D2H happened
            i, j, b = t
            if isinstance(b, tuple):
                x, ax, lo, hi = b
                idx = [slice(None)] * x.ndim
                idx[ax] = slice(lo, hi)
                b = x[tuple(idx)]
            return (i, j, np.asarray(b))

        if len(jobs) == 1 or threads <= 1:
            jobs_out = [_fetch(j) for j in jobs]
        else:
            with cf.ThreadPoolExecutor(min(threads, len(jobs))) as pool:
                jobs_out = list(pool.map(_fetch, jobs))
        for i, j, arr in jobs_out:
            if j is None:
                parts[i] = arr
            else:
                parts[i][2][j] = arr
    out = [np.concatenate(p[2], axis=p[1])
           if isinstance(p, list) and p and p[0] == "chunks" else p
           for p in parts]
    if n_dev:
        # manifest booking only when device bytes actually moved — an
        # all-host tree must not mint a phantom pull event
        from . import compileaudit as _ca
        _ca.record_d2h(site, total_b, pulls=len(jobs))
    _ds.bump("d2h_wait_ns", _now_ns() - _t_pull0)
    if n_dev:
        # per-call distribution (flight-recorder histograms): bytes and
        # wall of ONE batched pull — the p99 the tunnel link lives by
        _ds.observe_pull(total_b, _now_ns() - _t_pull0)
    if stats is not None:
        stats["bytes"] = stats.get("bytes", 0) + total_b
        stats["leaves"] = stats.get("leaves", 0) + n_dev
        stats["pulls"] = stats.get("pulls", 0) + len(jobs)
    return jax.tree_util.tree_unflatten(treedef, out)


_PULL_POOL: ThreadPoolExecutor | None = None
_PULL_POOL_LOCK = RankedLock("pipeline.pool", RANK_PIPELINE_POOL)


class _Pull:
    """One in-flight submission's resource record: the gate slot,
    depth permit, pipeline-tier ledger bytes and ctx attribution it
    holds. ``release()`` is once-only under a lock — the puller
    thread's finally and the watchdog/abandon reclaim race, exactly
    one side wins (a double BoundedSemaphore release raises; a missed
    one leaks the OG_SCHED_DEPTH slot forever)."""

    __slots__ = ("pipe", "est_b", "route", "key", "fut", "_done",
                 "_lock")

    def __init__(self, pipe: "StreamingPipeline", est_b: int,
                 route: str):
        self.pipe = pipe
        self.est_b = est_b
        self.route = route
        self.key = None
        self.fut = None
        self._done = False
        self._lock = threading.Lock()

    def release(self) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
        from . import hbm as _hbm
        _hbm.release("pipeline", self.est_b)
        pipe = self.pipe
        if pipe.ctx is not None and hasattr(pipe.ctx, "sub_hbm"):
            pipe.ctx.sub_hbm(self.est_b)
        if pipe.gate is not None:
            try:
                pipe.gate.release()
            except ValueError:
                pass               # gate rebuilt under us (tests)
        try:
            pipe._sem.release()
        except ValueError:
            pass
        return True


# per-request-thread registry of live pipelines: the executor's
# execute() finally calls reap_thread_pipes() so ANY exception path
# out of the dispatch loop (kill, deadline, device fault, plain bug)
# reclaims in-flight submissions instead of leaking gate slots and
# pipeline-tier ledger bytes (the PR 9 KILL QUERY leak fix)
_TLS = threading.local()


def _tls_pipes() -> list:
    got = getattr(_TLS, "pipes", None)
    if got is None:
        got = _TLS.pipes = []
    return got


def _tls_remove(pipe) -> None:
    got = getattr(_TLS, "pipes", None)
    if got is not None:
        try:
            got.remove(pipe)
        except ValueError:
            pass


def reap_thread_pipes() -> int:
    """Abandon every pipeline this thread created and never collected
    (error paths out of the executor). No-op on the happy path —
    collect() deregisters. Returns submissions reclaimed."""
    got = getattr(_TLS, "pipes", None)
    if not got:
        return 0
    n = 0
    for pipe in list(got):
        n += pipe.abandon("reap")
    got.clear()
    return n


def _pull_pool() -> ThreadPoolExecutor:
    """Shared daemon puller pool: pull threads spend their lives
    blocked in the PJRT transfer (GIL released), so a small process-
    wide pool serves every concurrent query."""
    global _PULL_POOL
    with _PULL_POOL_LOCK:
        if _PULL_POOL is None:
            _PULL_POOL = ThreadPoolExecutor(
                max_workers=pull_threads(),
                thread_name_prefix="og-pipe")
        return _PULL_POOL


class StreamingPipeline:
    """Bounded-depth launch→pull→host-fold pipeline for one query.

    submit() registers one launch's device output tree right after
    dispatch; a puller thread waits for that launch's readiness
    (per-leaf, not a global barrier), starts its D2H immediately with
    the chunked multi-stream fetch, then runs the optional host
    ``post`` callback (unpack_packed / lattice fold) — concurrently
    with later launches still computing on device and the scan pool
    still decoding on host. submit() blocks while ``depth`` launches
    are already in flight, so dispatch proceeds in bounded batches and
    result HBM never exceeds depth × launch output size.

    collect() joins everything and returns {key: post_result}; worker
    exceptions re-raise there (the executor's normal error path).

    ``gate`` (optional semaphore) is the query scheduler's GLOBAL
    in-flight bound: per-query ``depth`` caps one query's result HBM,
    the shared gate caps the sum across concurrent queries (without it
    N queries × depth launches could all be in flight at once)."""

    def __init__(self, depth: int | None = None, gate=None, span=None,
                 ctx=None):
        self.depth = depth if depth is not None else pipeline_depth()
        self._sem = threading.BoundedSemaphore(max(1, self.depth))
        self.gate = gate
        # device fault domain: every submission owns a _Pull record
        # whose resource release (gate slot, depth permit, HBM ledger
        # bytes, ctx attribution) is IDEMPOTENT — the puller thread's
        # finally and the hang-watchdog/abandon reclaim may race, and
        # exactly one of them must win (a double gate.release would
        # raise; a missed one wedged OG_SCHED_DEPTH forever)
        self._pulls: list[_Pull] = []
        self._abandoned = False
        _tls_pipes().append(self)
        # per-query working-set attribution (device observatory): the
        # submitting query's ctx carries live/peak in-flight result
        # bytes (SHOW QUERIES hbm_peak_mb, scheduler calibration)
        self.ctx = ctx
        # sampled-query tracing (utils/tracing): each launch's pull +
        # host fold gets a span on its puller thread's lane, so the
        # Chrome timeline export shows the launch/pull/unpack overlap
        # that phase sums can only hint at. None (sampled out) costs
        # nothing on the hot path.
        self.span = span
        self._futs: dict = {}
        self._lock = RankedLock("pipeline", RANK_PIPELINE)
        self.launches = 0
        self.first_ns: int | None = None    # first pull start
        self.last_ns: int | None = None     # last pull/fold end
        self.bytes = 0
        self.leaves = 0
        # per-transport D2H split (op-aware plane diet accounting):
        # the executor labels each submit (packed/legacy/finalized/
        # lattice/dense) so the pull telemetry stays attributable when
        # a query mixes transport forms
        self.bytes_by: dict = {}

    def _acquire_slice(self, sem) -> None:
        """Deadline/kill-aware acquire: the old blocking acquire was
        the gate-wedge half of the PR 9 leak — a killed query (or one
        whose budget was already gone) sat in gate.acquire() forever
        while holding its depth permit."""
        while not sem.acquire(timeout=0.05):
            if self.ctx is not None \
                    and getattr(self.ctx, "killed", False):
                self.ctx.check()       # raises QueryKilled
            _deadline.check("pipeline submit")

    def submit(self, key, tree, post=None, transport=None,
               route=None) -> None:
        try:
            failpoint.inject("pipeline.submit")
        except BaseException as e:
            # a device-classified submit failure (injected or real —
            # e.g. the launch handle itself reporting OOM) enters the
            # fault domain as a route failure: the statement-level
            # wrapper re-runs against the host fallback. Non-device
            # exceptions propagate untouched
            from . import devicefault as _df
            cls = _df.classify(e)
            if cls is None:
                raise
            r = route or (transport or "pipeline")
            _df._bump_class(cls)
            _df.breaker_for(r).record_failure()
            raise _df.DeviceRouteDown(r, e) from e
        self._acquire_slice(self._sem)
        if self.gate is not None:
            try:
                self._acquire_slice(self.gate)
            except BaseException:
                self._sem.release()
                raise
        # HBM ledger (ops/hbm.py): this launch's device result buffers
        # are in flight from submit until its pull/fold completes —
        # the 'pipeline' tier is the live sum across ALL queries, the
        # ctx attribution is this query's share (metadata-only byte
        # estimate; no transfer, no sync)
        from . import hbm as _hbm
        est_b = _hbm._tree_device_bytes(tree)
        _hbm.account("pipeline", est_b)
        if self.ctx is not None and hasattr(self.ctx, "add_hbm"):
            self.ctx.add_hbm(est_b)
        pull = _Pull(self, est_b, route or (transport or "pipeline"))
        try:
            fut = _pull_pool().submit(self._run, tree, post, transport,
                                      pull)
        except BaseException:
            pull.release()
            raise
        pull.fut = fut
        with self._lock:
            self.launches += 1
            self._futs[key] = fut
            self._pulls.append(pull)
            pull.key = key

    def _run(self, tree, post, transport=None, pull=None):
        import jax
        try:
            t0 = _now_ns()
            failpoint.inject("pipeline.pull")
            try:
                # drain THIS launch only: device_get on in-flight
                # arrays takes the tunnel's slow synchronous fetch path
                # (measured 6x the post-completion transfer)
                jax.block_until_ready(tree)
            except Exception as e:
                # a failed drain used to be swallowed whole; device-
                # classified failures (OOM mid-compute, backend death)
                # now surface so collect() can classify and fall back
                from . import devicefault as _df
                if _df.classify(e) is not None:
                    raise
            pull_sp = None
            if self.span is not None:
                pull_sp = self.span.child("pipeline.pull")
                pull_sp.start_ns = t0
                pull_sp.add(lane=threading.current_thread().name)
            st: dict = {}
            host = device_get_parallel(tree, stats=st, site="stream")
            if pull is not None:
                # transfer-manifest-vs-HBM-ledger exact cross-check:
                # the bytes this pull moved must equal the bytes its
                # submit accounted into the pipeline tier
                from . import compileaudit as _ca
                _ca.ledger_check(pull.est_b, st.get("bytes", 0))
            if pull_sp is not None:
                pull_sp.end_ns = _now_ns()
                pull_sp.add(bytes=st.get("bytes", 0),
                            **({"transport": transport}
                               if transport else {}))
                unpack_sp = None
                if post is not None:
                    unpack_sp = self.span.child("pipeline.unpack")
                    unpack_sp.start_ns = _now_ns()
                    unpack_sp.add(
                        lane=threading.current_thread().name)
            if post is not None:
                failpoint.inject("pipeline.unpack")
            out = post(host) if post is not None else host
            if pull_sp is not None and post is not None:
                unpack_sp.end_ns = _now_ns()
            t1 = _now_ns()
            with self._lock:
                if self.first_ns is None or t0 < self.first_ns:
                    self.first_ns = t0
                if self.last_ns is None or t1 > self.last_ns:
                    self.last_ns = t1
                self.bytes += st.get("bytes", 0)
                self.leaves += st.get("leaves", 0)
                if transport is not None:
                    self.bytes_by[transport] = (
                        self.bytes_by.get(transport, 0)
                        + st.get("bytes", 0))
            return out
        finally:
            if pull is not None:
                pull.release()

    def collect(self) -> dict:
        """Wait for every submitted pull+fold; first worker exception
        re-raises here (device-classified failures charge the
        submission's route breaker and re-raise as DeviceRouteDown so
        the statement-level wrapper falls back). Safe to call with
        zero submissions.

        Hung-launch watchdog: each wait is bounded by the request
        deadline and OG_DEVICE_HANG_S — a pull stuck past the bound is
        ABANDONED (its gate slot, depth permit and pipeline-tier
        ledger bytes reclaimed now; the wedged thread's own release
        later no-ops) instead of holding the serving plane hostage."""
        from . import devicefault as _df
        with self._lock:
            futs = dict(self._futs)
            pulls = {p.key: p for p in self._pulls}
        hang_s = float(knobs.get("OG_DEVICE_HANG_S"))
        out = {}
        for k, f in futs.items():
            t0 = time.monotonic()
            while True:
                try:
                    out[k] = f.result(timeout=0.05)
                    break
                except FuturesTimeout:
                    if self.ctx is not None \
                            and getattr(self.ctx, "killed", False):
                        self.abandon("killed")
                        self.ctx.check()
                    dl = _deadline.current()
                    if dl is not None and dl.expired:
                        self.abandon("deadline")
                        dl.check("pipeline collect")
                    if 0 < hang_s <= time.monotonic() - t0:
                        # the launch is wedged but the request still
                        # has budget: reclaim + charge the route and
                        # let the statement retry on the host path
                        pull = pulls.get(k)
                        route = pull.route if pull is not None \
                            else "pipeline"
                        _df._bump("watchdog_expired")
                        _df.breaker_for(route).record_failure()
                        self.abandon("watchdog")
                        raise _df.DeviceRouteDown(
                            route, TimeoutError(
                                f"background pull {k!r} hung > "
                                f"{hang_s:g}s"))
                except BaseException as e:
                    cls = _df.classify(e)
                    if cls is None:
                        raise
                    pull = pulls.get(k)
                    route = pull.route if pull is not None \
                        else "pipeline"
                    _df._bump_class(cls)
                    _df.breaker_for(route).record_failure()
                    self.abandon(f"pull-{cls}")
                    raise _df.DeviceRouteDown(route, e) from e
        with self._lock:
            self._pulls.clear()
        _tls_remove(self)
        return out

    def abandon(self, reason: str = "error") -> int:
        """Reclaim the resources of every submission that has not
        finished: gate slot, depth permit, pipeline-tier ledger bytes,
        ctx attribution. Idempotent per submission (the wedged puller
        thread's own finally no-ops afterwards) and a no-op after a
        clean collect(). This is the KILL QUERY / deadline-expiry leak
        fix: nothing stays booked after the query is gone."""
        with self._lock:
            pulls = list(self._pulls)
            already = self._abandoned
            self._abandoned = True
            # break the pipe<->_Pull reference cycle here too (the
            # clean-collect path clears it in collect()): the executor
            # pauses cyclic GC during queries, so an abandoned pipe
            # must not keep its pulled buffers reachable only via a
            # cycle until the next GC window
            self._pulls.clear()
        n = 0
        for p in pulls:
            if p.release():
                n += 1
        if n and not already:
            from . import devicefault as _df
            _df._bump("abandoned_pulls", n)
        _tls_remove(self)
        return n
