"""Whole-plan mega-kernel fusion (round 17): ONE compiled program per
query shape class for terminal big-grid plans.

The r07/r08 phase profiles put the heavy dashboard shape's device time
in ~6 separately launched stages (slab lattice → cell fold → cross-
slab combine → finalize epilogue → top-k cut), each materializing its
intermediate in HBM and crossing the dispatcher. With the transfer
story told (packed/finalized/winner transports, compressed HBM tier),
launch overhead and intermediate materialization are the remaining
wall. This module traces the entire chain as ONE jit program built
from the trace-composable stage functions the staged kernels now
share (ops/blockagg._lattice_stage and friends — satellite of this
round): inputs are the HBM-resident slab planes (themselves expanded
from compressed DFOR payloads by the decode stage) plus the tiny
traced scalars, outputs are the answer-sized finalized/top-k planes
AND the merged plane grid (kept resident for the sparse flagged-cell
repair pull) — no decoded lattice, merged grid, or finalize
intermediate ever round-trips through the dispatcher between stages.

Predication: WHERE time-range residuals and fill/nil handling are
already branch-free lanes inside the stage bodies (validity masks
multiply into the exact-limb cumsums; empty windows carry zero
counts), so the fused body inherits the data-parallel predicated form
— no host-side branching enters the trace.

Bit-identity with the staged dispatch is by construction: every
lattice/fold/combine value is an integer-valued f64 < 2^49 (exact,
order-free adds), and the finalize/top-k tails are the SAME traced
stage bodies the staged kernels jit individually — XLA does not
reassociate f64, so fusing the composition cannot move a bit.

Shape classes: the static residue of a plan (want/limb window/grid
geometry/per-slab lattice spans/finalize recipe/top-k spec/transport
mode) interns to a stable id in query/plancache.intern_shape_class;
the compiled program carries the class name (og_fused_c<N>) so the
compile auditor attributes fused compiles per class and the warm-
compile gate can pin repeats to zero.

Fault domain: the executor dispatches fused programs through
guarded_launch route ``fused`` (failpoint site ``device.fused.launch``
— see ops/devicefault.py); any exhausted fault heals per query to the
staged dispatch, byte-identical, and OG_FUSED_PLAN=0 is the global
escape hatch (query/fusedplan.py owns the gate and the plan
compiler)."""

from __future__ import annotations

import numpy as np

from . import blockagg, devstats, exactsum

# compiled fused programs per shape-class key — the same role as
# blockagg._JITTED: jit caches per (structure, shapes) underneath, this
# dict pins one wrapper per static class so a warm repeat dispatches
# without re-entering the builder (duplicate-compile gate clean)
_PROGRAMS: dict = {}


def _program_jit(fn, name: str):
    """jit-wrap a fused whole-plan program under its shape-class name
    (query/plancache.intern_shape_class): the compile auditor logs
    "Compiling og_fused_c<N> ..." per class instead of blurring every
    fused variant into one ``_prog`` row — the same attribution
    contract as blockagg._named_jit, keyed by class id because the
    full static key would overflow a kernel name."""
    import jax
    fn.__name__ = name
    fn.__qualname__ = name
    return jax.jit(fn)


def program_for(key: tuple):
    """Build (or fetch) the fused program for one shape-class key:

      key = (want, K, k0, G, W, slab_specs, rec, tk, mode)

    with slab_specs a tuple of per-slab (SEG, WL, sorted_cells), rec
    the finalize transport recipe (dev_mean, ship_sum, need_count) or
    None, tk the (kk, desc, offset, null_fill) top-k spec or None, and
    mode one of "merge" | "fin" | "topk". Mode "merge" ends at the
    combined plane grid (the caller ships it through the ordinary
    staged pack_grid — the rare non-finalizable corner stays two
    launches); "fin"/"topk" run the finalize epilogue (and the cut)
    in-trace and the answer planes come out of the single program.

    The program takes (slab_args, scalars, scale_lo) — slab_args a
    tuple of per-slab (valid, times, limbs, bad, gids, t0v, stepv,
    rowsv, cells) traced operands — and returns (merged, fin, cut):
    the merged (P, G·W) plane grid (stays resident for sparse repair),
    the finalize transport tuple (mode "fin") and the top-k winner
    tuple (mode "topk"). Unused outputs are None."""
    fn = _PROGRAMS.get(key)
    if fn is not None:
        return fn
    want, K, k0, G, W, slab_specs, rec, tk, mode = key
    num_segments = G * W

    def _prog(slab_args, scalars, scale_lo):
        merged = None
        for (SEG, WL, srt), args in zip(slab_specs, slab_args):
            (valid, times, limbs, bad, g, t0v, stepv, rowsv,
             cells) = args
            d = blockagg._lattice_stage(
                valid, times, limbs, bad, g, scalars, t0v, stepv,
                rowsv, want=want, K=K, SEG=SEG, WL=WL, W=W)
            o = blockagg._lattice_fold_stage(
                d[0], d[1] if len(d) > 1 else None,
                d[2] if len(d) > 2 else None, cells,
                num_segments=num_segments, want=want, K=K,
                sorted_cells=srt)
            merged = o if merged is None \
                else blockagg._combine_stage(merged, o, want=want,
                                             K=K)
        if mode == "merge":
            return (merged, None, None)
        dm, ss, nc = rec
        fin = blockagg._finalize_stage(
            merged, scale_lo, want=want, K=K, k0=k0, dev_mean=dm,
            ship_sum=ss, need_count=nc)
        if mode == "fin":
            return (merged, fin, None)
        # mode "topk": the finalize transport feeds the cut in-trace;
        # its static layout derives from the recipe exactly as the
        # staged topk_cut derives it from finalize_grid's outputs
        with_sum = ("sum" in want) and (ss or dm)
        kk, desc, offset, null_fill = tk
        cut = blockagg._topk_stage(
            fin[0], fin[1], fin[2], fin[3], G=G, W=W, kk=kk,
            desc=desc, offset=offset, null_fill=null_fill,
            need_count=nc, has_flag=with_sum,
            n_f64=(int(ss) + int(dm)) if with_sum else 0)
        return (merged, None, cut)

    from ..query import plancache
    _sid, name = plancache.intern_shape_class(key)
    _prog = _program_jit(_prog, name)
    _PROGRAMS[key] = _prog
    return _prog


def fused_launch(key: tuple, slab_args: tuple, scalars, E: int):
    """ONE device dispatch for a whole (field, scale) group: launch
    the shape class's fused program over the resident slab planes.
    The limb scale rides as the traced ``scale_lo`` operand (one
    compiled class serves every E — same contract as the staged
    finalize). Counts one kernel launch: that is the point."""
    fn = program_for(key)
    scale_lo = np.float64(2.0 ** float(E - exactsum.SPAN_BITS))
    out = fn(slab_args, scalars, scale_lo)
    devstats.bump("kernel_launches")
    devstats.bump("fused_launches")
    return out
