"""Pallas TPU kernel for the dense window reduction (f32 fast mode).

The f64 exact path (segment_agg.dense_window_aggregate) is what queries
use by default — f64 is emulated on TPU, and XLA already fuses its
reductions well. This kernel is the opt-in float32 fast mode for
dashboards that trade the last ulp for throughput: one VMEM-tiled pass
computes sum/min/max per (series, window) row of a dense (S, P) block,
reading each element exactly once (the hot loop is HBM-bound, so the
win is guaranteed single-pass locality and half the bytes of f64).

Tiling: grid over row tiles of TILE_S=8 rows (the f32 sublane height);
each program reduces a (8, P) VMEM block on the VPU. P must be a
multiple of 128 (lane width) — TSSP segments are already padded to
power-of-two sizes. Rows are padded to a multiple of 8 with zeros and
the pad outputs sliced off.

Falls back to `interpret=True` off-TPU (tests run on the CPU mesh)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

TILE_S = 8


LANES = 128


def _rowagg_kernel(x_ref, sum_ref, min_ref, max_ref, *, P_real):
    # outputs are lane-broadcast (TILE_S, 128) blocks: Mosaic requires
    # full-lane output tiles, so the per-row scalar repeats across lanes
    # and the wrapper slices lane 0. Columns >= P_real are lane padding
    # (the caller pads P up to the 128-lane width): each reduction
    # masks them with its identity, so any real P is served without a
    # per-P shape-class explosion beyond the padded tiers
    x = x_ref[...]
    shape = (TILE_S, LANES)
    P_pad = x.shape[1]
    if P_real != P_pad:
        lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        live = lane < P_real
        xs = jnp.where(live, x, jnp.float32(0.0))
        xmn = jnp.where(live, x, jnp.float32(jnp.inf))
        xmx = jnp.where(live, x, jnp.float32(-jnp.inf))
    else:
        xs = xmn = xmx = x
    sum_ref[...] = jnp.broadcast_to(
        jnp.sum(xs, axis=1, keepdims=True), shape)
    min_ref[...] = jnp.broadcast_to(
        jnp.min(xmn, axis=1, keepdims=True), shape)
    max_ref[...] = jnp.broadcast_to(
        jnp.max(xmx, axis=1, keepdims=True), shape)


@functools.lru_cache(maxsize=None)
def _rowagg_fn(S: int, P: int, P_real: int, interpret: bool):
    """Memoized pallas_call callable per (S, P) shape class. A fresh
    ``pl.pallas_call(...)`` per invocation re-traces AND re-compiles
    its wrapper on EVERY call (the compile auditor flagged the warm
    path at 2 compiles/call — the hot-loop recompile class); building
    the callable once per shape class lets the jit cache serve warm
    dashboard traffic. Shape classes are bounded: S pads to TILE_S
    multiples and P to power-of-two segment tiers."""
    out = jax.ShapeDtypeStruct((S, LANES), jnp.float32)
    return pl.pallas_call(
        functools.partial(_rowagg_kernel, P_real=P_real),
        grid=(S // TILE_S,),
        in_specs=[pl.BlockSpec((TILE_S, P), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((TILE_S, LANES),
                                lambda i: (i, 0))] * 3,
        out_shape=[out, out, out],
        interpret=interpret,
    )


def _rowagg_call(x, P_real: int, interpret: bool):
    # x64 must be OFF around the pallas trace: the session enables
    # jax_enable_x64 globally (ops/__init__) and Mosaic lowering of the
    # x64-typed grid indices crashes the remote compile helper. The
    # kernel itself is pure f32 either way.
    from jax.experimental import enable_x64   # jax.enable_x64 alias
    # was removed in newer jax releases; the experimental home remains
    S, P = x.shape
    with enable_x64(False):
        return _rowagg_fn(S, P, P_real, interpret)(x)


def pallas_dense_rowagg(values,
                        interpret: bool | None = None
                        ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(S, P) float32 block → per-row (sum, min, max), each (S,).
    P pads internally to the 128-lane width (masked with reduction
    identities), so any dense-window P is served. interpret=None
    auto-selects: real kernel on TPU, interpreter elsewhere."""
    x = np.asarray(values, dtype=np.float32)
    S, P = x.shape
    lane_pad = (-P) % 128
    if lane_pad:
        # pad the lane axis up to the 128-wide tile; the kernel masks
        # the tail with each reduction's identity
        x = np.concatenate(
            [x, np.zeros((S, lane_pad), dtype=x.dtype)], axis=1)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    pad = (-S) % TILE_S
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, P + lane_pad), dtype=x.dtype)], axis=0)
    s, mn, mx = _rowagg_call(x, P, interpret)
    return s[:S, 0], mn[:S, 0], mx[:S, 0]   # lane 0 of the broadcast


def pallas_dense_mean(values, interpret: bool | None = None) -> jax.Array:
    """Fast-mode mean per row — the f32 TSBS double-groupby-1 kernel."""
    s, _mn, _mx = pallas_dense_rowagg(values, interpret)
    return s / np.float32(values.shape[1])
