"""Device resource observatory: HBM ledger + utilization timeline.

PR 7 made *requests* observable (flight recorder, latency histograms);
the device resource side stayed dark: HBM usage was self-reported
per-cache ``nbytes`` with no global view, and the single dispatcher /
gate utilization was invisible between stats-pusher samples. Tailwind's
framing (PAPERS.md) is that an accelerator-pool scheduler is only as
good as its resource telemetry; Taurus NDP motivates accounting bytes
*at the device boundary*. This module is that telemetry spine:

- **HBM ledger** (``HBMLedger`` / module-level ``LEDGER``): a
  tier-tagged byte accountant. Tiers mirror the real residency owners:
  ``device_cache`` (HBM block-slab + decoded-plane tiers of
  ops/devicecache.py), ``host_cache`` (the host pin mirror), and
  ``pipeline`` (in-flight StreamingPipeline launch/pull result
  buffers). Every tier keeps live bytes, entry count, a high-watermark
  and cumulative account/release totals; eviction-pressure events land
  in a bounded ring (``OG_HBM_EVENTS``). The per-QUERY working set is
  attributed separately via the query ctx (QueryContext.hbm_peak —
  SHOW QUERIES' ``hbm_peak_mb`` column), not a global tier: queries
  overlap and their sum is exactly the ``pipeline`` tier.
- **Reconciliation** (``reconcile``): where the backend exposes
  ``device.memory_stats()`` (TPU runtimes do; the CPU backend does
  not), compare backend-reported ``bytes_in_use`` against the
  device-resident tracked bytes and flag drift beyond a tolerance —
  the "are we lying to ourselves" check a byte accountant needs.
  ``cross_check`` is the exact half: ledger tier bytes must equal what
  the caches themselves report, byte for byte (tier-1 tested under
  jax.transfer_guard).
- **Utilization timeline** (``UtilizationSampler``): a background
  thread (``OG_DEVUTIL_MS``; 0 disables) snapshots in-flight pulls,
  the OG_SCHED_DEPTH gate occupancy, WFQ queue depth and per-tier
  ledger bytes into a bounded ring (``OG_DEVUTIL_RING``) — exposed at
  ``/debug/device`` as JSON and as a Chrome trace-event *counter
  track* (``?format=chrome``) that lays next to the PR 7 Perfetto
  span timeline (pass ``base_ns`` from a span export to share its
  clock zero; both use perf_counter_ns).

Locking: the ledger is called from inside devicecache (rank 20) and
pipeline bookkeeping paths, so its lock ranks between PIPELINE (30)
and STATS (40) — account/release may nest inside any hot-path lock
and may still bump the innermost stats counters (oglint R4 checks the
static half; utils/lockrank.py the runtime half).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils import knobs
from ..utils.lockrank import RANK_HBM, RankedLock
from ..utils.stats import register_counters

__all__ = ["HBMLedger", "LEDGER", "account", "release", "pressure",
           "reconcile", "cross_check", "UtilizationSampler", "sampler",
           "chrome_counter_events", "collector", "HBM_STATS"]

TIERS = ("device_cache", "host_cache", "pipeline", "sketch",
         "compressed", "result_cache")

# event counters + collector-refreshed gauges (utils.stats registry —
# oglint R6 covers every bump key; the per-tier live numbers live in
# the ledger itself and flatten through collector()).
HBM_STATS: dict = register_counters("hbm", {
    "pressure_events": 0,      # evictions / over-capacity rejections
    "underflow_clamps": 0,     # release without a matching account
    "reconcile_runs": 0,
    "reconcile_flagged": 0,    # drift beyond tolerance
    # gauges (refreshed by collector()): global tracked footprint
    "tracked_bytes": 0,
    "tracked_hwm_bytes": 0,
})


def _bump(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(HBM_STATS, key, n)


def _gauge(key: str, v: int) -> None:
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        HBM_STATS[key] = int(v)


class HBMLedger:
    """Tier-tagged byte accountant with high-watermarks and an
    eviction-pressure event ring. All methods are thread-safe; the
    lock never wraps a blocking call (rank 35 — see module doc)."""

    def __init__(self, event_cap: int | None = None):
        if event_cap is None:
            event_cap = max(16, int(knobs.get("OG_HBM_EVENTS")))
        self._lock = RankedLock("hbm.ledger", RANK_HBM)
        self._tiers: dict[str, dict] = {
            t: {"bytes": 0, "n": 0, "hwm_bytes": 0,
                "accounted_bytes": 0, "released_bytes": 0}
            for t in TIERS}
        self._events: deque = deque(maxlen=event_cap)
        self._hwm_total = 0

    def _tier(self, tier: str) -> dict:
        t = self._tiers.get(tier)
        if t is None:
            raise KeyError(f"unknown HBM ledger tier {tier!r} "
                           f"(declared: {TIERS})")
        return t

    def account(self, tier: str, nbytes: int, n: int = 1) -> None:
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("account() takes non-negative bytes")
        with self._lock:
            t = self._tier(tier)
            t["bytes"] += nbytes
            t["n"] += n
            t["accounted_bytes"] += nbytes
            if t["bytes"] > t["hwm_bytes"]:
                t["hwm_bytes"] = t["bytes"]
            total = sum(x["bytes"] for x in self._tiers.values())
            if total > self._hwm_total:
                self._hwm_total = total

    def release(self, tier: str, nbytes: int, n: int = 1) -> None:
        nbytes = int(nbytes)
        clamped = False
        with self._lock:
            t = self._tier(tier)
            t["released_bytes"] += nbytes
            t["bytes"] -= nbytes
            t["n"] -= n
            if t["bytes"] < 0 or t["n"] < 0:
                # double release / release-without-account: clamp and
                # count loudly — a silently negative tier would poison
                # the reconcile math forever
                clamped = True
                t["bytes"] = max(0, t["bytes"])
                t["n"] = max(0, t["n"])
        if clamped:
            _bump("underflow_clamps")

    def pressure(self, tier: str, nbytes: int, reason: str) -> None:
        """Record one eviction-pressure event (LRU eviction, an
        over-capacity put rejection, reconcile drift…)."""
        ev = {"ts": time.time(), "tier": tier, "bytes": int(nbytes),
              "reason": str(reason)}
        with self._lock:
            self._events.append(ev)
        _bump("pressure_events")

    def snapshot(self, events: bool = True) -> dict:
        with self._lock:
            tiers = {t: dict(v) for t, v in self._tiers.items()}
            out = {
                "tiers": tiers,
                "total_bytes": sum(v["bytes"] for v in tiers.values()),
                "total_hwm_bytes": self._hwm_total,
            }
            if events:
                out["events"] = list(self._events)
        return out

    def tier_bytes(self, tier: str) -> int:
        with self._lock:
            return self._tier(tier)["bytes"]

    def tier_count(self, tier: str) -> int:
        with self._lock:
            return self._tier(tier)["n"]

    def reset(self) -> None:
        """Zero every tier and drop events (tests; never the serving
        path — live caches would instantly drift from a zeroed ledger)."""
        with self._lock:
            for t in self._tiers.values():
                for k in t:
                    t[k] = 0
            self._events.clear()
            self._hwm_total = 0


LEDGER = HBMLedger()


def account(tier: str, nbytes: int, n: int = 1) -> None:
    LEDGER.account(tier, nbytes, n)


def release(tier: str, nbytes: int, n: int = 1) -> None:
    LEDGER.release(tier, nbytes, n)


def pressure(tier: str, nbytes: int, reason: str) -> None:
    LEDGER.pressure(tier, nbytes, reason)


# --------------------------------------------------- reconciliation

def reconcile() -> dict:
    """Compare the ledger's device-resident tracked bytes
    (device_cache + pipeline tiers) against what the backend itself
    reports via ``device.memory_stats()``. TPU runtimes expose
    ``bytes_in_use``; the CPU backend returns None/raises — then the
    result says so instead of inventing numbers. Drift beyond
    max(64 MiB, OG_HBM_DRIFT_PCT%) flags (the backend legitimately
    holds MORE than the ledger: jit executables, scratch, the
    framework's own pools — the tolerance absorbs that floor, the flag
    catches a leak growing past it)."""
    from ..utils import failpoint

    # device fault domain: chaos schedules fail the reconcile itself
    # (it runs from /debug/device and the perf_smoke observatory gate —
    # a throwing reconcile must surface typed, never corrupt the ledger)
    failpoint.inject("hbm.reconcile")
    _bump("reconcile_runs")
    snap = LEDGER.snapshot(events=False)
    tracked = (snap["tiers"]["device_cache"]["bytes"]
               + snap["tiers"]["pipeline"]["bytes"])
    out: dict = {"tracked_device_bytes": int(tracked),
                 "backend": "unavailable", "flagged": False}
    per_dev = []
    try:
        import jax
        for d in jax.devices():
            ms_fn = getattr(d, "memory_stats", None)
            ms = ms_fn() if callable(ms_fn) else None
            if ms and "bytes_in_use" in ms:
                per_dev.append(
                    {"device": str(d),
                     "bytes_in_use": int(ms["bytes_in_use"]),
                     "bytes_limit": int(ms.get("bytes_limit", 0))})
    except Exception as e:  # oglint: disable=R701 — reviewed: backend
        # memory_stats probe is read-only diagnostics; a throwing
        # backend must degrade to "unavailable", not fail /debug/device
        out["backend_error"] = str(e)
    if per_dev:
        backend_b = sum(d["bytes_in_use"] for d in per_dev)
        drift = backend_b - tracked
        pct = float(knobs.get("OG_HBM_DRIFT_PCT"))
        tol = max(64 << 20, int(pct / 100.0 * max(backend_b, tracked)))
        flagged = abs(drift) > tol
        out.update(backend="memory_stats", devices=per_dev,
                   backend_bytes=int(backend_b), drift_bytes=int(drift),
                   tolerance_bytes=int(tol), flagged=flagged)
        if flagged:
            _bump("reconcile_flagged")
            LEDGER.pressure("device_cache", abs(drift),
                            "reconcile_drift")
    return out


def rebase_cache_tiers() -> None:
    """Force the cache tiers to exactly mirror the LIVE cache
    singletons. The ledger is double-entry against one mirror per
    tier; when test isolation swaps the singletons around (monkeypatch
    install + restore) the tier can end up tracking a dead instance's
    bytes in either direction. Production never needs this — the
    singletons are created once and mirrored move for move."""
    from . import devicecache as _dc
    for tier, cache in (("device_cache", _dc.global_cache()),
                        ("host_cache", _dc.host_cache()),
                        ("sketch", _dc.sketch_cache()),
                        ("compressed", _dc.compressed_cache())):
        st = cache.stats()
        with LEDGER._lock:
            t = LEDGER._tier(tier)
            t["bytes"] = int(st["bytes"])
            t["n"] = int(st["entries"])


def cross_check() -> dict:
    """Exact reconciliation against the sources the ledger mirrors:
    each cache tier's ledger bytes must EQUAL what the cache itself
    reports (the ledger is double-entry, not an estimate). The
    pipeline tier has no independent source — quiescent it must be 0.
    Returns per-tier {ledger, source, match}."""
    from . import devicecache as _dc
    # materialize the singletons BEFORE snapshotting: the side tiers
    # (sketch/compressed) pin their lifetime to the block-cache
    # instance and their constructor drains a dead predecessor's
    # ledger residue — a snapshot taken first would still show those
    # bytes against the fresh (empty) instance
    from ..query import resultcache as _rc
    tiers = (("device_cache", _dc.global_cache()),
             ("host_cache", _dc.host_cache()),
             ("sketch", _dc.sketch_cache()),
             ("compressed", _dc.compressed_cache()),
             ("result_cache", _rc.global_cache()))
    snap = LEDGER.snapshot(events=False)
    out: dict = {}
    for tier, cache in tiers:
        src = cache.stats()["bytes"]
        led = snap["tiers"][tier]["bytes"]
        out[tier] = {"ledger": led, "source": src,
                     "match": led == src}
    pl = snap["tiers"]["pipeline"]
    out["pipeline"] = {"ledger": pl["bytes"], "in_flight": pl["n"],
                       "match": True}
    out["ok"] = all(v.get("match", True) for v in out.values()
                    if isinstance(v, dict))
    return out


def collector() -> dict:
    """utils.stats collector: flattened ledger + event counters for
    /metrics, /debug/vars and the stats pusher (ts-monitor ships these
    into the monitor db)."""
    snap = LEDGER.snapshot(events=False)
    _gauge("tracked_bytes", snap["total_bytes"])
    _gauge("tracked_hwm_bytes", snap["total_hwm_bytes"])
    out = {}
    for tier, v in snap["tiers"].items():
        out[f"{tier}_bytes"] = v["bytes"]
        out[f"{tier}_hwm_bytes"] = v["hwm_bytes"]
        out[f"{tier}_entries"] = v["n"]
    out["total_bytes"] = snap["total_bytes"]
    out["total_hwm_bytes"] = snap["total_hwm_bytes"]
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        for k, v in HBM_STATS.items():
            out[k] = v
    return out


# ------------------------------------------------ utilization timeline

def _tree_device_bytes(tree) -> int:
    """Byte estimate of the device arrays in a pytree (a launch's
    in-flight result buffers). Metadata only — no transfer, no sync."""
    import jax
    tot = 0
    for x in jax.tree_util.tree_leaves(tree):
        if isinstance(x, jax.Array):
            try:
                tot += int(x.size) * int(x.dtype.itemsize)
            except Exception:
                pass
    return tot


class UtilizationSampler:
    """Background sampler of the device serving plane: per-tier ledger
    bytes, in-flight streamed pulls, scheduler gate/queue occupancy.
    Bounded ring (``OG_DEVUTIL_RING``); interval ``OG_DEVUTIL_MS`` is
    re-read every tick so operators can retune a live server; <= 0
    parks the thread (it wakes at 1s to re-check)."""

    def __init__(self, ring: int | None = None):
        if ring is None:
            ring = max(8, int(knobs.get("OG_DEVUTIL_RING")))
        self.ring: deque = deque(maxlen=ring)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._tlock = threading.Lock()   # thread start/stop only

    # ------------------------------------------------------- sampling

    def sample_once(self, record: bool = True) -> dict:
        """One snapshot; ``record=False`` leaves the ring untouched —
        the on-demand /debug/device fallback must not inject
        request-time samples into the sampler's timeline."""
        led = LEDGER.snapshot(events=False)
        out = {
            "ts": time.time(),
            "perf_ns": time.perf_counter_ns(),
            "tier_bytes": {t: v["bytes"]
                           for t, v in led["tiers"].items()},
            "total_bytes": led["total_bytes"],
            "inflight_pulls": led["tiers"]["pipeline"]["n"],
        }
        try:
            from ..query import scheduler as _qs
            if _qs.enabled():
                out.update(_qs.get_scheduler().util_gauges())
        except Exception:
            pass
        if record:
            self.ring.append(out)
        return out

    def samples(self) -> list[dict]:
        return list(self.ring)

    # ------------------------------------------------------ lifecycle

    def start(self) -> None:
        with self._tlock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="og-devutil")
            self._thread.start()

    def stop(self) -> None:
        with self._tlock:
            self._stop.set()
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=5)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        while True:
            ms = float(knobs.get("OG_DEVUTIL_MS"))
            wait_s = ms / 1e3 if ms > 0 else 1.0
            if self._stop.wait(wait_s):
                return
            if ms > 0:
                try:
                    self.sample_once()
                except Exception:   # a torn gauge must not kill the
                    pass            # sampler thread


_SAMPLER: UtilizationSampler | None = None
_SAMPLER_LOCK = threading.Lock()


def sampler() -> UtilizationSampler:
    """Process-wide sampler (one device plane per process). Created
    lazily; http/server.py starts it when OG_DEVUTIL_MS > 0."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            _SAMPLER = UtilizationSampler()
        return _SAMPLER


def chrome_counter_events(samples: list[dict],
                          base_ns: int | None = None) -> list[dict]:
    """Chrome trace-event counter track ("ph": "C") of the utilization
    timeline — loads in Perfetto next to the PR 7 span export. Both
    clock on perf_counter_ns: pass the span root's start_ns as
    ``base_ns`` to share its zero; default zero is the first sample."""
    if not samples:
        return []
    t0 = base_ns if base_ns is not None else samples[0]["perf_ns"]
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": 2,
         "args": {"name": "device observatory"}}]
    for s in samples:
        ts = (s["perf_ns"] - t0) / 1e3
        events.append({"name": "hbm_bytes", "ph": "C", "pid": 2,
                       "ts": ts,
                       "args": {**s["tier_bytes"],
                                "total": s["total_bytes"]}})
        util = {"inflight_pulls": s.get("inflight_pulls", 0)}
        for k in ("sched_active", "wfq_queued", "launch_queue",
                  "gate_in_use"):
            if k in s:
                util[k] = s[k]
        events.append({"name": "device_util", "ph": "C", "pid": 2,
                       "ts": ts, "args": util})
    return events
