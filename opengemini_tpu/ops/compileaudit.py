"""Runtime compile-cache + transfer audit layer (oglint R9/R10's
dynamic half).

Static rules catch the *patterns* that cause silent recompiles and
unaccounted transfers; this module catches the *events* — so a hazard
the AST can't see (a shape class that churns per batch, a cache
dropped by a stray re-wrap, a transfer path that dodges the counters)
still fails a gate instead of quietly eating the device win.

**Compile auditor** (``CompileAuditor`` / module ``AUDITOR``): jax
logs every XLA compile ("Compiling <name> with global shapes and
types [...]") and every retrace through its module loggers at DEBUG —
install() raises those loggers to DEBUG and attaches a parsing
handler, so the auditor sees each (kernel, shape-signature) compile
with zero hot-path cost (compiles are rare by definition; steady
state emits nothing). Per kernel it keeps compile counts and the
distinct shape signatures; a compile of a (kernel, signature) pair
seen before is a ``duplicate_compile`` — the smoking gun for a jit
cache being dropped or re-wrapped per call, and its budget is ZERO.
``mark()``/``since()`` bound audit windows: the perf_smoke gate runs
every bench shape cold (compiles ≤ the declared budget,
``utils.knobs.RECOMPILE_BUDGETS``) then warm (ZERO new compiles — a
warm-loop recompile is exactly the hazard class that erased the
BENCH r05 1m win).

**Transfer manifest**: every accounted H2D/D2H byte rides ONE funnel
— ``record_h2d(site, nbytes)`` / ``record_d2h(site, nbytes)`` — which
books the devstats totals AND a per-site manifest counter (declared
sites only; an unknown site raises). ``manifest_cross_check()`` then
has real teeth: manifest-vs-devstats totals must match to the byte
(an unfunneled bump diverges them), and the streaming pipeline
cross-checks each pull's ACTUAL bytes against the HBM-ledger booking
its submit staked (``ledger_check`` — est != actual means the PR 8
ledger is lying about in-flight HBM). perf_smoke fails on any
mismatch; /debug/vars exposes the manifest under ``xfer`` and the
compile log under ``compileaudit``.

**jaxpr stats** (``jaxpr_stats`` / ``audit_kernel``): op counts,
transfer ops and output dtypes of a traced callable — the "what did
this kernel actually lower to" numbers (f64 outputs on an f32 path,
unexpected transfer ops) for /debug/vars and the pallas/bench smokes.
"""

from __future__ import annotations

import logging
import re
import threading
import time
from collections import deque

from ..utils import knobs
from ..utils.stats import register_counters

__all__ = ["CompileAuditor", "AUDITOR", "ensure_installed",
           "record_h2d", "record_d2h", "ledger_check",
           "manifest_cross_check", "manifest_snapshot",
           "jaxpr_stats", "audit_kernel", "audit_snapshot",
           "compileaudit_collector", "xfer_collector",
           "H2D_SITES", "D2H_SITES"]

# ------------------------------------------------- transfer manifest

# Declared transfer sites — the manifest's whole point is that every
# byte names its mover, so the set is CLOSED (an unknown site raises;
# add it here AND at the call site in one reviewed change; oglint
# R1002 additionally pins every record_h2d call to a literal from
# this set). "dfor" = packed DFOR word lanes (the compressed-domain
# H2D diet), "payload" = the small per-block decode metadata (refs,
# const values, time headers, validity bitmaps) riding next to them.
H2D_SITES = ("slab", "limbs", "planes", "gids", "latcells", "scalars",
             "pplan", "decode", "dfor", "payload", "mesh", "sketch",
             "other")
# "decode" = the tiny limb-plane activity pull of the device-decode
# slab build (ops/blockagg) — 6 flags per slab.
D2H_SITES = ("stream", "batch", "segagg", "finalize", "repair",
             "topk", "decode", "other")

XFER_STATS: dict = register_counters("xfer", {
    **{f"h2d_{s}_bytes": 0 for s in H2D_SITES},
    **{f"h2d_{s}_events": 0 for s in H2D_SITES},
    **{f"d2h_{s}_bytes": 0 for s in D2H_SITES},
    **{f"d2h_{s}_events": 0 for s in D2H_SITES},
    # pipeline est-vs-actual ledger cross-check (ops/pipeline.py):
    # every streamed pull compares its actual pulled bytes against the
    # HBM-ledger bytes its submit accounted
    "ledger_checks": 0,
    "ledger_mismatches": 0,
    "ledger_mismatch_bytes": 0,
})


def record_h2d(site: str, nbytes: int, events: int = 1) -> None:
    """Book one H2D upload: devstats ``h2d_bytes``/``h2d_uploads``
    plus the per-site manifest counter. THE funnel — oglint R10 wants
    every hot-path upload to pass through here (or bump h2d_bytes
    itself, in which case the manifest cross-check will fail until it
    is converted)."""
    if site not in H2D_SITES:
        raise KeyError(f"undeclared H2D manifest site {site!r} "
                       f"(declared: {H2D_SITES})")
    from ..utils.stats import bump as _b
    from . import devstats
    nbytes = int(nbytes)
    devstats.bump("h2d_bytes", nbytes)
    devstats.bump("h2d_uploads", events)
    _b(XFER_STATS, f"h2d_{site}_bytes", nbytes)
    _b(XFER_STATS, f"h2d_{site}_events", events)


def record_d2h(site: str, nbytes: int, pulls: int = 1) -> None:
    """Book one D2H pull batch: devstats ``d2h_bytes``/``d2h_pulls``
    plus the per-site manifest counter. Called by the accounted
    transport (``device_get_parallel``, labelled by its caller) and
    the manually-accounted sparse repair pull."""
    if site not in D2H_SITES:
        raise KeyError(f"undeclared D2H manifest site {site!r} "
                       f"(declared: {D2H_SITES})")
    from ..utils.stats import bump as _b
    from . import devstats
    nbytes = int(nbytes)
    devstats.bump("d2h_bytes", nbytes)
    if pulls:
        devstats.bump("d2h_pulls", pulls)
    _b(XFER_STATS, f"d2h_{site}_bytes", nbytes)
    _b(XFER_STATS, f"d2h_{site}_events", 1)


def ledger_check(est_bytes: int, actual_bytes: int) -> None:
    """Pipeline est-vs-actual: the bytes a submit accounted into the
    HBM ledger's pipeline tier vs the bytes its pull actually moved.
    Equality is exact by construction (both sides sum the same device
    leaves); a mismatch means in-flight HBM attribution is wrong."""
    from ..utils.stats import bump as _b
    _b(XFER_STATS, "ledger_checks")
    if int(est_bytes) != int(actual_bytes):
        _b(XFER_STATS, "ledger_mismatches")
        _b(XFER_STATS, "ledger_mismatch_bytes",
           abs(int(est_bytes) - int(actual_bytes)))


def manifest_snapshot() -> dict:
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        return dict(XFER_STATS)


def manifest_cross_check() -> dict:
    """Exact attribution audit: the manifest's per-site H2D/D2H byte
    sums must EQUAL the devstats totals (every byte the counters saw
    names a site), and the pipeline ledger cross-checks must all have
    matched. Any new transfer path that books devstats directly —
    or moves bytes without booking at all while a manifest site books
    them — diverges the two and fails the perf_smoke gate."""
    from ..utils.stats import COUNTER_LOCK
    from .devstats import DEVICE_STATS
    with COUNTER_LOCK:
        xf = dict(XFER_STATS)
        dv = dict(DEVICE_STATS)
    man_h2d = sum(xf[f"h2d_{s}_bytes"] for s in H2D_SITES)
    man_d2h = sum(xf[f"d2h_{s}_bytes"] for s in D2H_SITES)
    out = {
        "h2d": {"manifest": man_h2d, "devstats": dv["h2d_bytes"],
                "match": man_h2d == dv["h2d_bytes"]},
        "d2h": {"manifest": man_d2h, "devstats": dv["d2h_bytes"],
                "match": man_d2h == dv["d2h_bytes"]},
        "ledger": {"checks": xf["ledger_checks"],
                   "mismatches": xf["ledger_mismatches"],
                   "mismatch_bytes": xf["ledger_mismatch_bytes"],
                   "match": xf["ledger_mismatches"] == 0},
    }
    out["ok"] = all(v["match"] for v in out.values())
    return out


# ------------------------------------------------- compile auditor

COMPILE_STATS: dict = register_counters("compileaudit", {
    "compiles_total": 0,       # XLA backend compiles observed
    "traces_total": 0,         # jaxpr retraces observed
    "duplicate_compiles": 0,   # same (kernel, signature) compiled again
    "budget_breaches": 0,      # recompile-budget gate failures
})

# "Compiling <name> with global shapes and types [sig]. Argument ..."
# — the signature capture must be GREEDY to the aval list's closing
# bracket ("]. Argument"): a lazy match stops at the first ']' inside
# "float64[4,4]" and collapses distinct signatures into one
_COMPILE_RE = re.compile(
    r"Compiling ([^\s]+)"
    r"(?: with global shapes and types (\[.*\])\. Argument mapping)?",
    re.S)
_TRACE_RE = re.compile(r"Finished tracing \+ transforming ([^\s]+) ")

_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch")


class _AuditHandler(logging.Handler):
    """Parses the two jax compile-log messages; everything else is
    ignored. While the auditor holds a logger at DEBUG it also owns
    propagation (install() turns it off so the raised level cannot
    flood the root handlers with per-op trace lines) — records at the
    logger's ORIGINAL threshold are re-dispatched to the root logger
    here, so a genuine jax warning still reaches the operator."""

    def __init__(self, auditor: "CompileAuditor"):
        super().__init__(level=logging.DEBUG)
        self.auditor = auditor

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        if msg.startswith("Compiling "):
            m = _COMPILE_RE.match(msg)
            if m:
                self.auditor._on_compile(m.group(1),
                                         m.group(2) or "")
        elif msg.startswith("Finished tracing"):
            m = _TRACE_RE.match(msg)
            if m:
                self.auditor._on_trace(m.group(1))
        orig = self.auditor._saved_levels.get(record.name)
        if orig is not None \
                and record.levelno >= max(orig, logging.WARNING):
            logging.getLogger().handle(record)


class CompileAuditor:
    """Process-wide compile-event recorder. ``install()`` is
    idempotent and cheap (a logging handler + two logger levels);
    events only flow when something actually compiles. NOT a sampler:
    every compile in the process is recorded, which is what lets the
    warm-window gate assert an exact zero."""

    def __init__(self, ring: int = 512):
        self._lock = threading.Lock()
        self._handler: _AuditHandler | None = None
        self._saved_levels: dict[str, int] = {}
        self._saved_raw: dict[str, int] = {}
        self._saved_prop: dict[str, bool] = {}
        # kernel -> {"compiles": int, "sigs": {sig: count}}
        self.kernels: dict[str, dict] = {}
        self.events: deque = deque(maxlen=ring)
        self._gen = 0                      # bumps on every compile

    # ------------------------------------------------------ lifecycle

    def install(self) -> None:
        with self._lock:
            if self._handler is not None:
                return
            self._handler = _AuditHandler(self)
            for name in _LOGGERS:
                lg = logging.getLogger(name)
                # effective level decides what the operator WOULD have
                # seen (re-dispatch threshold); raw level is what
                # uninstall must restore
                self._saved_levels[name] = lg.getEffectiveLevel()
                self._saved_raw[name] = lg.level
                self._saved_prop[name] = lg.propagate
                # the compile messages are emitted at DEBUG when
                # jax_log_compiles is off; raising only these two
                # loggers keeps the rest of jax quiet and costs
                # nothing between compiles. Propagation is cut while
                # the level is raised (the handler re-dispatches
                # WARNING+ records to root) so the DEBUG flood never
                # reaches the root handlers.
                lg.setLevel(logging.DEBUG)
                lg.propagate = False
                lg.addHandler(self._handler)

    def uninstall(self) -> None:
        with self._lock:
            if self._handler is None:
                return
            for name in _LOGGERS:
                lg = logging.getLogger(name)
                lg.removeHandler(self._handler)
                lg.setLevel(self._saved_raw.get(name, 0))
                lg.propagate = self._saved_prop.get(name, True)
            self._handler = None
            self._saved_levels.clear()
            self._saved_raw.clear()
            self._saved_prop.clear()

    def installed(self) -> bool:
        return self._handler is not None

    # ------------------------------------------------------ recording

    def _on_compile(self, kernel: str, sig: str) -> None:
        from ..utils.stats import bump as _b
        dup = False
        with self._lock:
            k = self.kernels.setdefault(
                kernel, {"compiles": 0, "sigs": {}})
            k["compiles"] += 1
            k["sigs"][sig] = k["sigs"].get(sig, 0) + 1
            # duplicate = same (kernel, input signature) compiled
            # again. Scoped to the repo's NAMED kernels ("og_" —
            # blockagg's _named_jit factories and the test fixtures):
            # jax's eager primitive wrappers are shape-polymorphic in
            # their OUTPUT (broadcast_in_dim for jnp.zeros of two
            # sizes logs identical input avals; iota logs an empty
            # list) and would false-positive forever. The warm/cold
            # window gates still cover every kernel regardless of
            # name.
            dup = (k["sigs"][sig] > 1 and kernel.startswith("og_")
                   and "ShapedArray" in sig)
            self._gen += 1
            self.events.append(
                {"ts": time.time(), "kernel": kernel, "sig": sig,
                 "dup": dup})
        _b(COMPILE_STATS, "compiles_total")
        if dup:
            _b(COMPILE_STATS, "duplicate_compiles")

    def _on_trace(self, kernel: str) -> None:
        from ..utils.stats import bump as _b
        _b(COMPILE_STATS, "traces_total")

    # ------------------------------------------------------- windows

    def mark(self) -> dict:
        """Snapshot token for a budget window: per-kernel compile
        counts at this instant."""
        with self._lock:
            return {k: v["compiles"] for k, v in self.kernels.items()}

    def since(self, mark: dict) -> dict:
        """Per-kernel compiles since ``mark`` (kernels with zero new
        compiles are omitted)."""
        out = {}
        with self._lock:
            for k, v in self.kernels.items():
                d = v["compiles"] - mark.get(k, 0)
                if d > 0:
                    out[k] = d
        return out

    def total_since(self, mark: dict) -> int:
        return sum(self.since(mark).values())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "installed": self._handler is not None,
                "kernels": {k: {"compiles": v["compiles"],
                                "distinct_sigs": len(v["sigs"])}
                            for k, v in self.kernels.items()},
                "recent": list(self.events)[-32:],
            }

    def reset(self) -> None:
        with self._lock:
            self.kernels.clear()
            self.events.clear()
            self._gen = 0


AUDITOR = CompileAuditor()


def ensure_installed() -> bool:
    """Install the process-wide auditor when ``OG_COMPILE_AUDIT`` is
    on (the default). Called from the executor at construction and
    from the gates; safe to call repeatedly."""
    if not bool(knobs.get("OG_COMPILE_AUDIT")):
        return False
    AUDITOR.install()
    return True


def check_recompile_budget(label: str, compiles: int,
                           budgets: dict | None = None) -> dict:
    """Grade one window against the declared per-bench-shape budget
    (``utils.knobs.RECOMPILE_BUDGETS``). Returns a report; a breach
    also bumps ``budget_breaches`` so dashboards see drift even when
    nobody reads the gate output."""
    from ..utils.knobs import RECOMPILE_BUDGETS
    from ..utils.stats import bump as _b
    budgets = budgets if budgets is not None else RECOMPILE_BUDGETS
    budget = budgets.get(label, budgets.get("default", 0))
    ok = compiles <= budget
    if not ok:
        _b(COMPILE_STATS, "budget_breaches")
    return {"label": label, "compiles": int(compiles),
            "budget": int(budget), "ok": ok}


# --------------------------------------------------- jaxpr/HLO stats

# audited-kernel reports for /debug/vars (bounded: keyed by name,
# written by audit_kernel from the bench/smoke/tests)
_JAXPR_AUDITS: dict[str, dict] = {}
_JAXPR_LOCK = threading.Lock()


def jaxpr_stats(fn, *args, static_argnums=(), **kwargs) -> dict:
    """Trace ``fn`` and report what it lowers to: equation count,
    per-primitive op counts, transfer ops (device_put / host
    callbacks), and output dtypes (an f64 output on an f32 path is
    the R903 hazard showing up at runtime)."""
    import jax
    jpr = jax.make_jaxpr(fn, static_argnums=static_argnums)(
        *args, **kwargs)
    ops: dict[str, int] = {}

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            ops[eqn.primitive.name] = ops.get(eqn.primitive.name,
                                              0) + 1
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    walk(inner)

    walk(jpr.jaxpr)
    transfer = sum(n for p, n in ops.items()
                   if p in ("device_put", "copy",
                            "convert_element_type_device"))
    out_dtypes = [str(v.aval.dtype) for v in jpr.jaxpr.outvars
                  if hasattr(v.aval, "dtype")]
    return {"eqns": sum(ops.values()), "ops": ops,
            "transfer_ops": transfer, "out_dtypes": out_dtypes,
            "f64_outputs": sum(1 for d in out_dtypes
                               if d == "float64")}


def audit_kernel(name: str, fn, *args, **kwargs) -> dict:
    """jaxpr-audit one kernel and file the report under ``name`` for
    /debug/vars (``compileaudit.jaxpr``)."""
    st = jaxpr_stats(fn, *args, **kwargs)
    # keep the report JSON-small: top ops only
    slim = dict(st)
    slim["ops"] = dict(sorted(st["ops"].items(),
                              key=lambda kv: -kv[1])[:12])
    with _JAXPR_LOCK:
        _JAXPR_AUDITS[name] = slim
    return st


def audit_snapshot() -> dict:
    """The /debug/vars ``compileaudit`` section: compile-log state,
    cumulative counters and the jaxpr audits."""
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        counters = dict(COMPILE_STATS)
    with _JAXPR_LOCK:
        jaxprs = {k: dict(v) for k, v in _JAXPR_AUDITS.items()}
    return {**AUDITOR.snapshot(), "counters": counters,
            "jaxpr": jaxprs}


# ------------------------------------------------------- collectors

def compileaudit_collector() -> dict:
    """utils.stats collector (flat numbers for the pusher/metrics):
    compile/trace totals plus the distinct-kernel gauge."""
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        out = dict(COMPILE_STATS)
    with AUDITOR._lock:
        out["kernels_distinct"] = len(AUDITOR.kernels)
        out["installed"] = 1 if AUDITOR._handler is not None else 0
    return out


def xfer_collector() -> dict:
    """utils.stats collector: the per-site transfer manifest."""
    return manifest_snapshot()
