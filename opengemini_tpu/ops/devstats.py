"""Device-plane counters (VERDICT r4 weak #8 / missing #6).

Role of the reference's per-subsystem statistics modules
(lib/statisticsPusher/statistics/ — executor.go, engine stats): on a
tunnel-attached TPU the numbers that decide query latency are the
host↔device transfer volumes, the kernel launch count, and the HBM
slab footprint — none of which the reference tracks because PCIe-local
GPUs never made them the bottleneck. Counters accumulate process-wide
and are exposed through utils.stats (StatisticsPusher → file/_internal
sinks, /metrics Prometheus text, /debug/vars, ts-monitor).

Writers use utils.stats.bump (locked read-modify-write): these paths
run under the threaded HTTP/RPC servers and the parallel pull pool.
"""

from __future__ import annotations

from ..utils.stats import register_counters

DEVICE_STATS: dict = register_counters("device", {
    "d2h_bytes": 0,          # device→host result/lattice pulls
    "d2h_pulls": 0,          # individual fetch operations (chunks)
    "d2h_wait_ns": 0,        # wall time blocked on pulls
    "h2d_bytes": 0,          # explicit uploads (stacks, gids, scalars)
    "h2d_uploads": 0,
    "kernel_launches": 0,    # block/lattice/pack/sparse dispatches
    "slabs_built": 0,        # HBM block stacks assembled
    "slab_bytes": 0,         # bytes of stacks uploaded at build time
    "stream_launches": 0,    # launches routed through the pipeline
    "stream_queries": 0,     # queries that used the streaming path
    # per-transport D2H split of the block-path grid pulls, so
    # pull_gbps/bytes stay attributable for EVERY transport form:
    # packed uint32 | legacy f64 planes (incl. the op-pruned variant)
    # | finalized answer planes (+ their sparse repair pulls) |
    # window lattices. pull_bytes_saved = bytes the packed/pruned/
    # finalized transports avoided vs the full legacy f64 plane grid.
    "d2h_bytes_packed": 0,
    "d2h_bytes_legacy": 0,
    "d2h_bytes_finalized": 0,
    "d2h_bytes_lattice": 0,
    "pull_bytes_saved": 0,
    # gauges (last completed query, not cumulative): the numbers an
    # operator needs to judge whether the pull or the kernel is the
    # current wall without attaching EXPLAIN ANALYZE
    "last_query_d2h_bytes": 0,
    "last_query_pull_ms": 0,
    "last_query_planes": 0,       # transport planes pulled (block path)
    "last_query_pull_saved": 0,   # bytes saved vs legacy f64 planes
})

# cumulative wall time per executor phase (ns), across ALL queries —
# the span tree only exists under EXPLAIN ANALYZE, but capacity
# planning needs the steady-state split (reader_scan vs device_agg vs
# device_pull vs grid_fold vs finalize). With the streaming pipeline
# the phases OVERLAP, so their sum exceeding wall clock is the design
# working, not double counting.
QUERY_PHASE_NS: dict = register_counters("query_phase", {
    "reader_scan_ns": 0,
    "device_agg_ns": 0,
    "device_pull_ns": 0,
    # finalize epilogue: the on-device answer-plane conversion launches
    # plus any host-side sparse repairs (OG_DEVICE_FINALIZE)
    "device_finalize_ns": 0,
    "grid_fold_ns": 0,
    # merge is NESTED inside finalize (exchange-merge of partials);
    # serialize is the HTTP-layer streaming JSON/CSV emit, outside the
    # executor span — so merge ⊂ finalize and serialize is additive
    "merge_ns": 0,
    "finalize_ns": 0,
    "serialize_ns": 0,
    "queries": 0,
})


def bump(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(DEVICE_STATS, key, n)


def gauge(key: str, v: int) -> None:
    """Set a last-value gauge (locked: writers run under the threaded
    HTTP servers)."""
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        DEVICE_STATS[key] = int(v)


def bump_phase(name: str, ns: int) -> None:
    from ..utils.stats import bump as _b
    _b(QUERY_PHASE_NS, name + "_ns", int(ns))


def count_query() -> None:
    from ..utils.stats import bump as _b
    _b(QUERY_PHASE_NS, "queries")


def device_collector() -> dict:
    """utils.stats collector: snapshot of the device-plane counters
    (ns accumulate losslessly; ms is derived for readability)."""
    out = dict(DEVICE_STATS)
    out["d2h_wait_ms"] = out.pop("d2h_wait_ns") // 1_000_000
    return out


def phase_collector() -> dict:
    """utils.stats collector: cumulative per-phase executor wall (ms)
    plus the query count, for /debug/vars and /metrics."""
    out = {}
    for k, v in dict(QUERY_PHASE_NS).items():
        if k.endswith("_ns"):
            out[k[:-3] + "_ms"] = v // 1_000_000
        else:
            out[k] = v
    return out
