"""Device-plane counters (VERDICT r4 weak #8 / missing #6).

Role of the reference's per-subsystem statistics modules
(lib/statisticsPusher/statistics/ — executor.go, engine stats): on a
tunnel-attached TPU the numbers that decide query latency are the
host↔device transfer volumes, the kernel launch count, and the HBM
slab footprint — none of which the reference tracks because PCIe-local
GPUs never made them the bottleneck. Counters accumulate process-wide
and are exposed through utils.stats (StatisticsPusher → file/_internal
sinks, /metrics Prometheus text, /debug/vars, ts-monitor).

Writers use utils.stats.bump (locked read-modify-write): these paths
run under the threaded HTTP/RPC servers and the parallel pull pool.
"""

from __future__ import annotations

from ..utils.stats import register_counters

DEVICE_STATS: dict = register_counters("device", {
    "d2h_bytes": 0,          # device→host result/lattice pulls
    "d2h_pulls": 0,          # individual fetch operations (chunks)
    "d2h_wait_ns": 0,        # wall time blocked on pulls
    "h2d_bytes": 0,          # explicit uploads (stacks, gids, scalars)
    "h2d_uploads": 0,
    "kernel_launches": 0,    # block/lattice/pack/sparse dispatches
    "slabs_built": 0,        # HBM block stacks assembled
    "slab_bytes": 0,         # bytes of stacks uploaded at build time
    "stream_launches": 0,    # launches routed through the pipeline
    "stream_queries": 0,     # queries that used the streaming path
    # per-transport D2H split of the block-path grid pulls, so
    # pull_gbps/bytes stay attributable for EVERY transport form:
    # packed uint32 | legacy f64 planes (incl. the op-pruned variant)
    # | finalized answer planes (+ their sparse repair pulls) |
    # window lattices. pull_bytes_saved = bytes the packed/pruned/
    # finalized transports avoided vs the full legacy f64 plane grid.
    "d2h_bytes_packed": 0,
    "d2h_bytes_legacy": 0,
    "d2h_bytes_finalized": 0,
    "d2h_bytes_lattice": 0,
    "d2h_bytes_topk": 0,
    "pull_bytes_saved": 0,
    # answer-sized D2H (PR 12): device order-statistic finalize of
    # percentile/median/mode (the acceptance counter proving the
    # route), the HBM sorted-sample tier's reuse, the device ORDER
    # BY/LIMIT cut, and the opt-in f32 fast tier
    "sketch_dev_grids": 0,     # (field, query) grids finalized on dev
    "sketch_dev_rows": 0,      # rows the cellsort kernel consumed
    "sketch_plane_hits": 0,    # warm queries served from the HBM tier
    "sketch_host_fallbacks": 0,  # breaker/fault heals to host slices
    "topk_grids": 0,           # finalized grids cut to winners on dev
    "topk_cells_pulled": 0,    # k x groups winner cells that crossed
    "f32_tier_launches": 0,    # pallas dense-window fast-tier calls
    "f32_tier_rows": 0,
    # whole-plan mega-kernel fusion (round 17): terminal big-grid
    # plans traced end-to-end as ONE program per shape class
    # (ops/fused.py) — launches, per-query heals back to the staged
    # dispatch, and answer cells produced through the fused route
    "fused_launches": 0,
    "fused_fallbacks": 0,
    "fused_cells": 0,
    # gauges (last completed query, not cumulative): the numbers an
    # operator needs to judge whether the pull or the kernel is the
    # current wall without attaching EXPLAIN ANALYZE
    "last_query_d2h_bytes": 0,
    "last_query_pull_ms": 0,
    "last_query_planes": 0,       # transport planes pulled (block path)
    "last_query_pull_saved": 0,   # bytes saved vs legacy f64 planes
})

# cumulative wall time per executor phase (ns), across ALL queries —
# span trees exist per sampled query (utils/tracing flight recorder),
# but capacity planning needs the steady-state split (reader_scan vs
# device_agg vs device_pull vs grid_fold vs finalize). With the
# streaming pipeline the phases OVERLAP, so their sum exceeding wall
# clock is the design working, not double counting — sampled query
# spans carry an explicit overlap_ns marker (tracing.annotate_overlap).
QUERY_PHASE_NS: dict = register_counters("query_phase", {
    "reader_scan_ns": 0,
    # block-path dispatch window inside the scan (stack/upload/launch)
    "block_dispatch_ns": 0,
    "device_agg_ns": 0,
    "device_pull_ns": 0,
    # finalize epilogue: the on-device answer-plane conversion launches
    # plus any host-side sparse repairs (OG_DEVICE_FINALIZE) — the
    # order-statistic (percentile/median/mode) finalize rides this
    # phase too
    "device_finalize_ns": 0,
    # device ORDER BY/LIMIT cut (OG_DEVICE_TOPK): the segmented top-k
    # kernel over finalized planes + the winner-cell unpack/repair
    "device_topk_ns": 0,
    # compressed-domain decode stage (OG_DEVICE_DECODE): the device-
    # decode slab builds — payload staging, bit-unpack/expand kernel
    # launches, limb decomposition, compressed-tier rebuilds
    "device_decode_ns": 0,
    # whole-plan fused execution (OG_FUSED_PLAN): the single fused
    # program dispatch replacing lattice/fold/combine/finalize/topk
    # launches on eligible terminal plans, plus its winner unpack
    "fused_exec_ns": 0,
    "grid_fold_ns": 0,
    # result-cache bookkeeping (query/resultcache.py): key build,
    # epoch validation, cached-prefix trim and store — NOT the fresh
    # live-edge scan, which rides the ordinary phases above
    "result_cache_ns": 0,
    # merge is NESTED inside finalize (exchange-merge of partials);
    # serialize is the HTTP-layer streaming JSON/CSV emit, outside the
    # executor span — so merge ⊂ finalize and serialize is additive
    "merge_ns": 0,
    "finalize_ns": 0,
    "serialize_ns": 0,
    # scheduler admission wait (http layer, before the executor runs)
    "sched_queue_ns": 0,
    "queries": 0,
})

# Stable phase names: the contract between the phases_ms aggregation
# and the span tree — a span measuring one of these phases MUST use
# the same name (tests/test_tracing.py::test_phase_span_drift).
PHASE_NAMES = frozenset(k[:-3] for k in QUERY_PHASE_NS
                        if k.endswith("_ns"))

# latency/size distributions of the device plane (flight-recorder
# tentpole): p50/p99 per phase and bytes-per-pull percentiles — the
# monotonic counters above cannot answer "what does a bad pull look
# like". Exported as Prometheus histograms via /metrics and summarized
# in /debug/vars (utils.stats.histogram_summaries).
from ..utils.stats import Histogram, exp_bounds  # noqa: E402
from ..utils.stats import observe as _observe  # noqa: E402
from ..utils.stats import register_histograms  # noqa: E402

DEVICE_HIST: dict = register_histograms("device", {
    # bytes per device_get_parallel call (one batched D2H)
    "d2h_pull_bytes": Histogram(exp_bounds(1024, 1 << 32)),
    # wall per pull call, ms
    "d2h_pull_ms": Histogram(exp_bounds(0.25, 1 << 20)),
})

PHASE_HIST: dict = register_histograms("query_phase", {
    name + "_ms": Histogram(exp_bounds(0.25, 1 << 20))
    for name in sorted(PHASE_NAMES)
})


def bump(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(DEVICE_STATS, key, n)


def gauge(key: str, v: int) -> None:
    """Set a last-value gauge (locked: writers run under the threaded
    HTTP servers)."""
    from ..utils.stats import COUNTER_LOCK
    with COUNTER_LOCK:
        DEVICE_STATS[key] = int(v)


def _trace_exemplar() -> str | None:
    """Flight-recorder trace id of the current request, when sampled —
    phase/D2H histogram observations carry it as an OpenMetrics
    exemplar so a slow bucket links to /debug/trace?id=. The tracing
    context is a plain thread-local list read; sampled-out requests
    bind nothing and return None (no overhead beyond the call)."""
    from ..utils.tracing import current_trace_id
    return current_trace_id()


def bump_phase(name: str, ns: int) -> None:
    from ..utils.stats import bump as _b
    _b(QUERY_PHASE_NS, name + "_ns", int(ns))
    _observe(PHASE_HIST, name + "_ms", int(ns) / 1e6,
             trace_id=_trace_exemplar())


def observe_pull(nbytes: int, ns: int) -> None:
    """Per-call D2H distribution (device_get_parallel)."""
    tid = _trace_exemplar()
    _observe(DEVICE_HIST, "d2h_pull_bytes", int(nbytes), trace_id=tid)
    _observe(DEVICE_HIST, "d2h_pull_ms", int(ns) / 1e6, trace_id=tid)


def count_query() -> None:
    from ..utils.stats import bump as _b
    _b(QUERY_PHASE_NS, "queries")


def device_collector() -> dict:
    """utils.stats collector: snapshot of the device-plane counters
    (ns accumulate losslessly; ms is derived for readability)."""
    out = dict(DEVICE_STATS)
    out["d2h_wait_ms"] = out.pop("d2h_wait_ns") // 1_000_000
    return out


def phase_collector() -> dict:
    """utils.stats collector: cumulative per-phase executor wall (ms)
    plus the query count, for /debug/vars and /metrics."""
    out = {}
    for k, v in dict(QUERY_PHASE_NS).items():
        if k.endswith("_ns"):
            out[k[:-3] + "_ms"] = v // 1_000_000
        else:
            out[k] = v
    return out
