"""Device-plane counters (VERDICT r4 weak #8 / missing #6).

Role of the reference's per-subsystem statistics modules
(lib/statisticsPusher/statistics/ — executor.go, engine stats): on a
tunnel-attached TPU the numbers that decide query latency are the
host↔device transfer volumes, the kernel launch count, and the HBM
slab footprint — none of which the reference tracks because PCIe-local
GPUs never made them the bottleneck. Counters accumulate process-wide
and are exposed through utils.stats (StatisticsPusher → file/_internal
sinks, /metrics Prometheus text, /debug/vars, ts-monitor).

Writers use utils.stats.bump (locked read-modify-write): these paths
run under the threaded HTTP/RPC servers and the parallel pull pool.
"""

from __future__ import annotations

DEVICE_STATS: dict = {
    "d2h_bytes": 0,          # device→host result/lattice pulls
    "d2h_pulls": 0,          # individual fetch operations (chunks)
    "d2h_wait_ns": 0,        # wall time blocked on pulls
    "h2d_bytes": 0,          # explicit uploads (stacks, gids, scalars)
    "h2d_uploads": 0,
    "kernel_launches": 0,    # block/lattice/pack/sparse dispatches
    "slabs_built": 0,        # HBM block stacks assembled
    "slab_bytes": 0,         # bytes of stacks uploaded at build time
}


def bump(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(DEVICE_STATS, key, n)


def device_collector() -> dict:
    """utils.stats collector: snapshot of the device-plane counters
    (ns accumulate losslessly; ms is derived for readability)."""
    out = dict(DEVICE_STATS)
    out["d2h_wait_ms"] = out.pop("d2h_wait_ns") // 1_000_000
    return out
