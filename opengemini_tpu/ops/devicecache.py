"""Device-resident block cache — the readcache analog one tier up.

Role of reference lib/readcache/blockcache.go, moved onto the device:
the host readcache already skips DECODE for hot segments; this cache
skips the host→device transfer, the (S, P) assembly, and the exact-sum
limb decomposition for repeated queries over unchanged files (the
dashboard steady state). Entries are jax Arrays keyed by a fingerprint
of the immutable source segments (file path + offset + trim), so
compaction — which writes new paths — naturally invalidates.

Byte-budgeted LRU; OG_DEVICE_CACHE_MB sets the budget (0 disables).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

_MB = 1024 * 1024


class DeviceBlockCache:
    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._map: OrderedDict[tuple, tuple[object, int]] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _nbytes(arr) -> int:
        try:
            return int(arr.nbytes)
        except Exception:
            return 0

    def get(self, key: tuple):
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return ent[0]

    def contains(self, key: tuple) -> bool:
        with self._lock:
            return key in self._map

    def put(self, key: tuple, arr) -> None:
        nb = self._nbytes(arr) + 64
        if nb > self.capacity:
            return
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._map[key] = (arr, nb)
            self._bytes += nb
            while self._bytes > self.capacity and self._map:
                # NO eager buf.delete(): an in-flight query may hold a
                # pinned reference from get(); HBM frees when the last
                # reference drops
                _k, (_buf, nb) = self._map.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1

    def purge(self) -> None:
        with self._lock:
            self._map.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._map), "bytes": self._bytes,
                    "capacity": self.capacity, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}


_CACHE: DeviceBlockCache | None = None
_HOST_CACHE: DeviceBlockCache | None = None


def capacity_bytes() -> int:
    # v5e HBM is 16 GiB; device block stacks get a healthy share by
    # default (the engine's host memory is not charged here)
    return int(os.environ.get("OG_DEVICE_CACHE_MB", "6144")) * _MB


def host_capacity_bytes() -> int:
    # separate budget for HOST-side pins (assembled dense blocks, limb
    # sums, result grids — numpy arrays in host RAM). Sharing the HBM
    # budget made the 1h query's device stacks evict the 1m query's
    # host pins and vice versa: LRU thrash, every warm run recomputing
    # decompose+reduce (measured 2x on the TSBS 1m shape).
    # OG_DEVICE_CACHE_MB=0 stays the global kill switch: a deployment
    # that disabled caching for memory headroom must not silently gain
    # 4 GiB of host pins.
    if not enabled():
        return 0
    return int(os.environ.get("OG_HOST_CACHE_MB", "4096")) * _MB


def enabled() -> bool:
    return capacity_bytes() > 0


def global_cache() -> DeviceBlockCache:
    global _CACHE
    if _CACHE is None:
        _CACHE = DeviceBlockCache(capacity_bytes())
    return _CACHE


def host_cache() -> DeviceBlockCache:
    global _HOST_CACHE
    if _HOST_CACHE is None:
        _HOST_CACHE = DeviceBlockCache(host_capacity_bytes())
    return _HOST_CACHE
