"""Device-resident block cache — the readcache analog one tier up.

Role of reference lib/readcache/blockcache.go, moved onto the device:
the host readcache already skips DECODE for hot segments; this cache
skips the host→device transfer, the (S, P) assembly, and the exact-sum
limb decomposition for repeated queries over unchanged files (the
dashboard steady state). Entries are jax Arrays keyed by a fingerprint
of the immutable source segments (file path + offset + trim), so
compaction — which writes new paths — naturally invalidates.

Three tiers share the machinery:
- HBM block-slab tier (``global_cache``): whole-file segment stacks for
  ops/blockagg.py, plus content-keyed gid/cell vectors.
- Host pin tier (``host_cache``): assembled dense blocks, limb sums and
  result grids as numpy arrays — its own budget (OG_HOST_CACHE_MB).
- Decoded-plane tier (``get_decoded_planes``/``put_decoded_planes``):
  the assembled (S, P) dense value/valid planes AND their exact-sum
  limb planes as DEVICE arrays, keyed by the dense group's fragment
  fingerprint. A hit means a repeat (dashboard) query skips decode
  (host pins), H2D, and limb decomposition entirely — the device
  dense path (OG_DENSE_DEVICE) reduces straight from residency.

Byte-budgeted LRU; OG_DEVICE_CACHE_MB sets the budget (0 disables).
"""

from __future__ import annotations

from collections import OrderedDict

from ..utils import knobs
from ..utils.lockrank import (RANK_DEVCACHE, RANK_DEVCACHE_FILL,
                              RankedLock)

_MB = 1024 * 1024


class DeviceBlockCache:
    def __init__(self, capacity_bytes: int, tier: str | None = None,
                 ledger=None):
        """``tier`` names this cache's HBM-ledger tier (ops/hbm.py);
        only the process singletons (global_cache / host_cache) pass
        one — ad-hoc instances (tests, tools) stay unledgered so they
        cannot skew the device accounting. ``ledger`` overrides the
        module LEDGER (unit tests)."""
        self.capacity = capacity_bytes
        self.tier = tier
        self._ledger = ledger
        self._lock = RankedLock("devicecache", RANK_DEVCACHE)
        self._map: OrderedDict[tuple, tuple[object, int]] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _led(self):
        if self.tier is None:
            return None
        if self._ledger is None:
            from . import hbm
            self._ledger = hbm.LEDGER
        return self._ledger

    @staticmethod
    def _nbytes(arr) -> int:
        try:
            return int(arr.nbytes)
        except Exception:
            return 0

    def get(self, key: tuple):
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return ent[0]

    def contains(self, key: tuple) -> bool:
        with self._lock:
            return key in self._map

    def put(self, key: tuple, arr) -> None:
        self.put_sized(key, arr, self._nbytes(arr))

    def put_sized(self, key: tuple, arr, nbytes: int) -> None:
        """put with an explicit byte charge — for entries whose cost
        the generic ``.nbytes`` probe can't see (tuples of device
        arrays, slab lists). Charges/evictions mirror into the HBM
        ledger (ops/hbm.py) when this cache owns a tier."""
        led = self._led()
        nb = int(nbytes) + 64
        if nb > self.capacity:
            if led is not None:
                # admission failure IS pressure: the entry was built
                # (decode + maybe H2D happened) and could not stay
                led.pressure(self.tier, nb, "over_capacity")
            return
        replaced = 0
        evicted = 0
        n_evicted = 0
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                replaced = old[1]
            self._map[key] = (arr, nb)
            self._bytes += nb
            while self._bytes > self.capacity and self._map:
                # NO eager buf.delete(): an in-flight query may hold a
                # pinned reference from get(); HBM frees when the last
                # reference drops
                _k, (_buf, enb) = self._map.popitem(last=False)
                self._bytes -= enb
                self.evictions += 1
                evicted += enb
                n_evicted += 1
            # mirror INSIDE the cache lock: were it outside, thread
            # B's release of an entry thread A charged could land
            # before A's account — the ledger's underflow clamp would
            # eat the bytes and the exact cross_check would drift
            # forever (rank DEVCACHE 20 < HBM 35 allows the nesting;
            # the ledger lock never blocks)
            if led is not None:
                led.account(self.tier, nb)
                if replaced:
                    led.release(self.tier, replaced)
                if n_evicted:
                    led.release(self.tier, evicted, n=n_evicted)
        if led is not None and n_evicted:
            led.pressure(self.tier, evicted, "lru_eviction")

    def reprice(self, key: tuple, nbytes: int) -> None:
        """Re-charge an existing entry with its REAL byte cost (block
        slab lists stake a placeholder via put(), then account their
        uploaded footprint once built — ops/blockagg.get_stacks).
        Deliberately does not evict: the slabs are already resident."""
        led = self._led()
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                return
            nb = int(nbytes) + 64
            delta = nb - ent[1]
            self._map[key] = (ent[0], nb)
            self._bytes += delta
            if led is not None and delta:
                if delta > 0:
                    led.account(self.tier, delta, n=0)
                else:
                    led.release(self.tier, -delta, n=0)

    def evict_bytes(self, nbytes: int | None = None,
                    reason: str = "oom_relief") -> int:
        """Evict LRU entries until ``nbytes`` are freed (None = the
        whole cache) — the device fault domain's HBM-pressure rung
        (ops/devicefault.hbm_pressure_relief). Ledger release happens
        INSIDE the cache lock (same torn-mirror argument as put_sized);
        the pressure event lands in the HBM ring so the observatory
        timeline shows the ladder firing. Returns bytes freed."""
        led = self._led()
        freed = 0
        n = 0
        with self._lock:
            while self._map and (nbytes is None or freed < nbytes):
                _k, (_buf, enb) = self._map.popitem(last=False)
                self._bytes -= enb
                self.evictions += 1
                freed += enb
                n += 1
            if led is not None and n:
                led.release(self.tier, freed, n=n)
        if led is not None and n:
            led.pressure(self.tier, freed, reason)
        return freed

    def purge(self) -> None:
        led = self._led()
        with self._lock:
            freed = self._bytes
            n = len(self._map)
            self._map.clear()
            self._bytes = 0
            if led is not None and n:
                led.release(self.tier, freed, n=n)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._map), "bytes": self._bytes,
                    "capacity": self.capacity, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}


_CACHE: DeviceBlockCache | None = None
_HOST_CACHE: DeviceBlockCache | None = None
_SKETCH_CACHE: DeviceBlockCache | None = None
_SKETCH_OWNER: DeviceBlockCache | None = None
_COMPRESSED_CACHE: DeviceBlockCache | None = None
_COMPRESSED_OWNER: DeviceBlockCache | None = None


def capacity_bytes() -> int:
    # v5e HBM is 16 GiB; device block stacks get a healthy share by
    # default (the engine's host memory is not charged here).
    # OG_DEVICE_CACHE_MB is a knob-cached read: enabled() runs on the
    # per-slab dispatch path, and the raw env read + int() parse it
    # used to do there was the hot-loop read oglint R2 exists to catch
    # (flip at runtime via knobs.set_env, which tests use).
    return knobs.get("OG_DEVICE_CACHE_MB") * _MB


def host_capacity_bytes() -> int:
    # separate budget for HOST-side pins (assembled dense blocks, limb
    # sums, result grids — numpy arrays in host RAM). Sharing the HBM
    # budget made the 1h query's device stacks evict the 1m query's
    # host pins and vice versa: LRU thrash, every warm run recomputing
    # decompose+reduce (measured 2x on the TSBS 1m shape).
    # OG_DEVICE_CACHE_MB=0 stays the global kill switch: a deployment
    # that disabled caching for memory headroom must not silently gain
    # 4 GiB of host pins.
    if not enabled():
        return 0
    return knobs.get("OG_HOST_CACHE_MB") * _MB


def enabled() -> bool:
    return capacity_bytes() > 0


def _rebind_tier(tier: str) -> None:
    """A fresh singleton is taking over ``tier``: drain whatever the
    PREVIOUS instance left booked in the HBM ledger. In production the
    singleton is created once against an empty tier (no-op); tests
    that swap ``_CACHE``/``_HOST_CACHE`` for isolation used to strand
    the old instance's bytes, silently breaking the exact
    ``hbm.cross_check()`` reconciliation for everything after them."""
    from . import hbm
    resid_b = hbm.LEDGER.tier_bytes(tier)
    resid_n = hbm.LEDGER.tier_count(tier)
    if resid_b or resid_n:
        hbm.LEDGER.release(tier, resid_b, n=resid_n)


def global_cache() -> DeviceBlockCache:
    global _CACHE
    if _CACHE is None:
        _rebind_tier("device_cache")
        _CACHE = DeviceBlockCache(capacity_bytes(),
                                  tier="device_cache")
    return _CACHE


def host_cache() -> DeviceBlockCache:
    global _HOST_CACHE
    if _HOST_CACHE is None:
        _rebind_tier("host_cache")
        _HOST_CACHE = DeviceBlockCache(host_capacity_bytes(),
                                       tier="host_cache")
    return _HOST_CACHE


def sketch_capacity_bytes() -> int:
    """HBM budget of the sorted-sample sketch tier (device-resident
    cell-sorted value/cell-id planes for the order-statistic finalize,
    ops/blockagg.sketch_sorted_planes). Its own budget — sharing the
    block-stack budget would let one percentile dashboard evict the
    resident segment stacks it reads next to. OG_DEVICE_CACHE_MB=0
    stays the global kill switch (same rule as the host pin tier)."""
    if not enabled():
        return 0
    return knobs.get("OG_SKETCH_HBM_MB") * _MB


def compressed_capacity_bytes() -> int:
    """HBM budget of the compressed payload tier (device-resident DFOR
    word lanes + per-block decode metadata, ops/blockagg's device-
    decode slab build). ~15x denser than the decoded slabs it can
    rebuild, so a modest budget keeps a large working set one kernel
    launch — zero H2D — away from residency. OG_DEVICE_CACHE_MB=0
    stays the global kill switch (same rule as the other tiers)."""
    if not enabled():
        return 0
    return knobs.get("OG_HBM_COMPRESSED_MB") * _MB


def compressed_cache() -> DeviceBlockCache:
    """Singleton for the HBM compressed tier (ledger tier
    \"compressed\"). The relief ladder (ops/devicefault.
    hbm_pressure_relief) evicts DECODED planes before these bytes:
    compressed payloads are the cheapest residency per decoded byte
    and the thing that makes a post-eviction rebuild H2D-free.
    Lifetime is pinned to the block-cache singleton exactly like the
    sketch tier (test isolation resets _CACHE + the ledger without
    knowing about the side tiers)."""
    global _COMPRESSED_CACHE, _COMPRESSED_OWNER
    owner = global_cache() if enabled() else None
    if _COMPRESSED_CACHE is None or _COMPRESSED_OWNER is not owner:
        _rebind_tier("compressed")
        _COMPRESSED_CACHE = DeviceBlockCache(
            compressed_capacity_bytes(), tier="compressed")
        _COMPRESSED_OWNER = owner
    return _COMPRESSED_CACHE


def sketch_cache() -> DeviceBlockCache:
    """Singleton for the HBM sketch tier (ledger tier \"sketch\" —
    evictable by the OOM relief ladder like the block/decoded tiers,
    ops/devicefault.hbm_pressure_relief). Lifetime is pinned to the
    block-cache singleton: test isolation resets ``_CACHE`` (and the
    ledger) without knowing about this tier, so a sketch cache that
    outlived its sibling would hold entries the zeroed ledger no
    longer mirrors and break the exact cross_check forever after."""
    global _SKETCH_CACHE, _SKETCH_OWNER
    owner = global_cache() if enabled() else None
    if _SKETCH_CACHE is None or _SKETCH_OWNER is not owner:
        _rebind_tier("sketch")
        _SKETCH_CACHE = DeviceBlockCache(sketch_capacity_bytes(),
                                         tier="sketch")
        _SKETCH_OWNER = owner
    return _SKETCH_CACHE


# ------------------------------------------------ decoded-plane tier

class _NoPlanes:
    """Negative marker: this (fragment, field, scale) has limb residue
    rows, so the device dense path must not claim it (the f64 fallback
    state would have to reproduce the host's summation order)."""
    nbytes = 0


NO_PLANES = _NoPlanes()

# tier-local counters (surfaced via devicecache_collector → /debug/vars
# and /metrics): a dashboard repeat hitting this tier is the proof that
# decode+H2D were skipped, so the counters are the acceptance signal
from ..utils.stats import register_counters  # noqa: E402

PLANE_STATS: dict = register_counters("devicecache_planes", {
    "plane_hits": 0, "plane_misses": 0,
    "plane_puts": 0, "plane_put_bytes": 0,
    "plane_negative": 0})


def _bump_plane(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(PLANE_STATS, key, n)


def _vals_key(fp: str, field: str) -> tuple:
    # the (S, P) value/valid planes are scale-independent — one entry
    # serves every query shape over the group
    return ("dplanes", fp, field)


def _limb_key(fp: str, field: str, E) -> tuple:
    # limb planes decomposed at scale E are only additive against
    # grids at the same scale, so E is part of THEIR identity only
    return ("dlimbs", fp, field, E)


def get_decoded_planes(fp: str, field: str, E):
    """Device-resident (vals, valid, limbs|None) planes for one dense
    group's field, or NO_PLANES (negative marker: limb residue rows at
    this scale), or None (miss). E None means the query needs no exact
    sums — the shared value/valid entry alone satisfies it."""
    if not enabled():
        return None
    cache = global_cache()
    base = cache.get(_vals_key(fp, field))
    if base is None:
        _bump_plane("plane_misses")
        return None
    if E is None:
        _bump_plane("plane_hits")
        return (base[0], base[1], None)
    lb = cache.get(_limb_key(fp, field, E))
    if lb is NO_PLANES:
        return NO_PLANES
    if lb is None:
        _bump_plane("plane_misses")
        return None
    _bump_plane("plane_hits")
    return (base[0], base[1], lb)


# base-plane fill serialization: the scheduler single-flights fills
# per (fp, field, E), but two DIFFERENT scales share the value/valid
# base entry — without a per-(fp, field) lock both leaders would
# device_put the base planes and one upload (plus its HBM) is wasted.
# STRIPED locks (fixed pool, key-hashed): no eviction means no
# evicted-while-handed-out race; a stripe collision merely serializes
# two unrelated fills, which is harmless. Ranked OUTSIDE the cache
# lock (fills call cache.get/put_sized while holding their stripe).
_BASE_FILL_LOCKS = [
    RankedLock(f"devicecache.fill[{i}]", RANK_DEVCACHE_FILL)
    for i in range(64)]


def _base_fill_lock(fp: str, field: str) -> RankedLock:
    return _BASE_FILL_LOCKS[hash((fp, field)) % len(_BASE_FILL_LOCKS)]


def put_decoded_planes(fp: str, field: str, E, vals, valid, limbs):
    """Stake one dense group's decoded (S, P) planes (and the (S, P, K)
    limb planes when the query needs exact sums) into HBM, keyed by the
    group fingerprint. The value/valid pair is shared across scales —
    an exact-sum query following a count/min-only one uploads ONLY the
    limb planes. Returns the device entry (usable immediately even
    when the cache is disabled or over budget)."""
    import jax

    from ..utils import failpoint
    from . import devstats
    # device fault domain: the decoded-plane H2D upload is a classic
    # OOM site — injection here drives the cache-fill rung of the
    # chaos schedules (tests/chaos.py device storms)
    failpoint.inject("devicecache.fill")
    cache = global_cache() if enabled() else None
    nb = 0
    with _base_fill_lock(fp, field):
        base = cache.get(_vals_key(fp, field)) if cache is not None \
            else None
        if base is None:
            dv = jax.device_put(vals)
            dm = jax.device_put(valid)
            nb += int(dv.nbytes + dm.nbytes)
            base = (dv, dm)
            if cache is not None:
                cache.put_sized(_vals_key(fp, field), base,
                                int(dv.nbytes + dm.nbytes))
    dl = None
    if limbs is not None:
        dl = jax.device_put(limbs)
        nb += int(dl.nbytes)
        if cache is not None:
            cache.put_sized(_limb_key(fp, field, E), dl,
                            int(dl.nbytes))
    if nb:
        from . import compileaudit
        compileaudit.record_h2d("planes", nb)
    if cache is not None:
        _bump_plane("plane_puts")
        _bump_plane("plane_put_bytes", nb)
    return (base[0], base[1], dl)


def stake_decoded_planes(fp: str, field: str, E, dv, dm, dl):
    """put_decoded_planes for planes that are ALREADY device-resident
    (the round-18 compressed fill, ops/blockagg.dense_fill_compressed,
    expands packed payloads on device — there is no host array to
    upload and no ``planes`` H2D to book; the payload bytes were
    recorded at staging time). Same keys, same base-fill lock, same
    failpoint, same accounting minus the device_put."""
    from ..utils import failpoint
    failpoint.inject("devicecache.fill")
    cache = global_cache() if enabled() else None
    nb = 0
    with _base_fill_lock(fp, field):
        base = cache.get(_vals_key(fp, field)) if cache is not None \
            else None
        if base is None:
            nb += int(dv.nbytes + dm.nbytes)
            base = (dv, dm)
            if cache is not None:
                cache.put_sized(_vals_key(fp, field), base,
                                int(dv.nbytes + dm.nbytes))
    if dl is not None:
        nb += int(dl.nbytes)
        if cache is not None:
            cache.put_sized(_limb_key(fp, field, E), dl,
                            int(dl.nbytes))
    if cache is not None:
        _bump_plane("plane_puts")
        _bump_plane("plane_put_bytes", nb)
    return (base[0], base[1], dl)


def put_no_planes(fp: str, field: str, E) -> None:
    """Mark (group, field, scale) as undecomposable (residue rows):
    the bad flags depend on E, so the marker lives on the limb key and
    the shared value/valid entry stays usable for non-exact queries."""
    if enabled():
        global_cache().put(_limb_key(fp, field, E), NO_PLANES)
        _bump_plane("plane_negative")
