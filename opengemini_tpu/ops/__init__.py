"""TPU compute plane: windowed group-by aggregation kernels.

This package is the device-side replacement for the reference's store-side
aggregation hot path (engine/series_agg_func.gen.go, engine/aggregate_cursor.go,
engine/agg_tagset_cursor.go — SURVEY.md §2.2): instead of streaming per-window
reducers over Go records, decoded column blocks become device arrays and
(tagset, window) pairs become segment ids for fused segment reductions.

Precision: the reference is float64 throughout; x64 is enabled here so the
"exact" path matches CPU float64 semantics. Queries may opt into float32
fast mode per-call.
"""

import jax

jax.config.update("jax_enable_x64", True)

from .segment_agg import (  # noqa: E402
    AggSpec, SegmentAggResult, segment_aggregate, window_ids,
    dense_window_aggregate, pad_bucket)
from .ogsketch import OGSketch  # noqa: E402
from .device_decode import (  # noqa: E402
    const_delta_expand, const_expand, device_decode_float_block,
    device_decode_int_block, device_decode_time_block, dfor_expand,
    rle_expand)
