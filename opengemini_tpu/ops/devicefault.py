"""Device fault domain: classify, retry, relieve pressure, fall back.

PR 1 hardened the *cluster* plane (deadlines, per-peer breakers,
failpoints); everything device-side built since — StreamingPipeline,
the device cache, on-device finalize, the scheduler, the HBM ledger —
had no fault semantics at all: a RESOURCE_EXHAUSTED/XlaRuntimeError
mid-dispatch crashed the query, wedged the OG_SCHED_DEPTH gate and
leaked pipeline-tier ledger bytes. Tailwind (PAPERS.md) makes
fallback-to-host the core accelerator-pool serving contract; Taurus
NDP prefers graceful reduce-path downgrade over failure. This module
is that contract for the one TPU:

- **Classifier** (``classify``): typed device-error classes —
  ``transient`` (UNAVAILABLE/ABORTED/connection loss — worth a bounded
  retry), ``oom`` (RESOURCE_EXHAUSTED/out-of-memory — worth one retry
  AFTER relieving HBM pressure), ``backend-fatal``
  (FAILED_PRECONDITION/DATA_LOSS/device halted — the route is sick).
  Non-device exceptions (our own bugs, kill/timeout types) classify as
  None and re-raise untouched: the ladder must never mask a logic bug.

- **Ladder** (``guarded_launch``): transient → jittered-backoff retry
  (``OG_DEVICE_RETRY``, deadline/kill-aware); oom → HBM-pressure
  relief (evict the ledger-mirrored device-cache tier, shrink the
  global in-flight gate) then ONE retry; exhaustion or fatal → charge
  the route's breaker and raise ``DeviceRouteDown``.

- **Per-route circuit breakers** (``RouteBreaker``, modeled on the
  PR 1 per-peer transport breakers with half-open probes): routes are
  the device dispatch families (block / lattice / dense / segagg /
  finalize / pipeline), each of which has an existing byte-identical
  host fallback (host scan paths, OG_LATTICE_DEVICE_FOLD=0 host fold,
  host dense, host segment aggregation, OG_DEVICE_FINALIZE=0 legacy
  transport). The executor consults ``route_on`` at every route gate,
  so an open breaker flips the route to its host path — injected
  device faults change latency, never bytes. Recovery is automatic:
  after the cooldown one query becomes the half-open probe.

- **Statement fallback** (``DeviceRouteDown``): the executor retries
  the whole statement when a route goes down mid-flight; the re-run
  takes the host path (breaker open) or a healthy device (fault gone).
  All state the retry touches is function-local, so the re-run is
  bit-identical by construction (the perf_smoke equivalence gates
  pin every fallback path to the device path cell for cell).

Failpoint sites (utils/failpoint.py; arm with actions oom / transient
/ hang / error / sleep): ``device.block.launch``,
``device.lattice.launch``, ``device.dense.launch``,
``device.segagg.launch``, ``device.finalize.launch``,
``pipeline.submit``, ``pipeline.pull``, ``pipeline.unpack``,
``devicecache.fill``, ``devicecache.evict``, ``hbm.reconcile``,
``blockagg.lattice_fold``, ``device.fused.launch``,
``device.pushdown.eval`` (round 18: packed-space predicate mask
launches — heals per batch to expand-then-filter on host-identical
masks; rides route ``block``).
"""

from __future__ import annotations

import random
import re
import threading
import time

from ..utils import failpoint, get_logger, knobs
from ..utils import deadline as _deadline
from ..utils.errors import GeminiError
from ..utils.stats import register_counters

log = get_logger(__name__)

__all__ = ["ROUTES", "DeviceRouteDown", "classify", "guarded_launch",
           "route_on", "breaker_for", "reset_breakers",
           "breaker_snapshot", "hbm_pressure_relief",
           "devicefault_collector", "DEVFAULT_STATS"]

# device dispatch families; each has a byte-identical host fallback the
# executor's route gates already implement (see module doc)
ROUTES = ("block", "lattice", "dense", "segagg", "finalize",
          "pipeline", "fused")

DEVFAULT_STATS: dict = register_counters("devicefault", {
    "transient_errors": 0,      # classified transient device failures
    "oom_errors": 0,            # classified device OOMs
    "fatal_errors": 0,          # classified backend-fatal failures
    "retries": 0,               # transient retry attempts taken
    "retry_success": 0,         # a retry (transient or post-OOM) won
    "oom_relief_runs": 0,       # pressure ladders executed
    "oom_evicted_bytes": 0,     # device-cache bytes evicted by relief
    "gate_shrinks": 0,          # in-flight gate permits confiscated
    "gate_restores": 0,         # permits returned on route recovery
    "breaker_trips": 0,
    "breaker_probes": 0,        # half-open probes granted
    "breaker_recoveries": 0,    # half-open probe closed a breaker
    "route_fallbacks": 0,       # statements re-run after RouteDown
    "watchdog_expired": 0,      # hung background pulls abandoned
    "abandoned_pulls": 0,       # in-flight pulls reclaimed (kill/err)
})


def _bump(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(DEVFAULT_STATS, key, n)


class DeviceRouteDown(GeminiError):
    """One device route is (possibly transiently) unusable: the ladder
    exhausted its retries, or the route breaker is charging toward /
    sitting open. The executor catches this at statement level and
    re-runs the statement — the route gates then steer it to the
    byte-identical host path (breaker open) or back onto a healthy
    device. Subclasses GeminiError so an escape still surfaces as a
    typed query error, never a crash."""

    def __init__(self, route: str, cause: BaseException | None = None):
        self.route = route
        self.cause = cause
        super().__init__(
            f"device route {route!r} unavailable"
            + (f": {cause}" if cause is not None else ""))


# ------------------------------------------------------- classifier

# marker → class, checked against str(exc) + repr(type). Order
# matters: RESOURCE_EXHAUSTED must win over the INTERNAL a wrapped
# backend message may also carry. Single-token markers match on WORD
# BOUNDARIES only — a bare substring test would classify a logic
# bug's "KABOOM: slab index corrupt" as a device OOM and the ladder
# would mask it (the one thing the contract above forbids).
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted",
                "Out of memory", "out of memory", "OOM",
                "Failed to allocate", "failed to allocate",
                "exceeds the memory", "hbm limit")
_TRANSIENT_MARKERS = ("UNAVAILABLE", "ABORTED", "CANCELLED",
                      "injected transient", "transfer failed",
                      "Connection reset", "connection reset",
                      "Socket closed", "premature end")
_FATAL_MARKERS = ("FAILED_PRECONDITION", "DATA_LOSS", "device halted",
                  "Device halted", "INTERNAL: program", "core dumped")


def _marker_rx(markers: tuple) -> "re.Pattern":
    parts = []
    for m in markers:
        esc = re.escape(m)
        if re.fullmatch(r"\w+", m):
            esc = r"\b" + esc + r"\b"
        parts.append(esc)
    return re.compile("|".join(parts))


_OOM_RX = _marker_rx(_OOM_MARKERS)
_TRANSIENT_RX = _marker_rx(_TRANSIENT_MARKERS)
_FATAL_RX = _marker_rx(_FATAL_MARKERS)


def classify(exc: BaseException) -> str | None:
    """Typed device-error class of one exception: ``"oom"``,
    ``"transient"``, ``"backend-fatal"``, or None (not a device error
    — the caller must re-raise untouched). Kill/timeout/query errors
    are never device errors even when a backend string leaks into
    their message."""
    if exc is None:
        return None
    if isinstance(exc, DeviceRouteDown):
        return None                    # already classified + routed
    if isinstance(exc, GeminiError):
        # typed engine/query errors (timeout, killed, parse…) own
        # their meaning; only the injection types re-enter here
        if not isinstance(exc, failpoint.FailpointError):
            return None
    if isinstance(exc, MemoryError):
        return "oom"
    text = f"{type(exc).__name__}: {exc}"
    if _OOM_RX.search(text):
        return "oom"
    if _FATAL_RX.search(text):
        return "backend-fatal"
    if _TRANSIENT_RX.search(text):
        return "transient"
    if isinstance(exc, (ConnectionError, BrokenPipeError)):
        return "transient"
    # XlaRuntimeError without a recognized status: the launch died
    # inside the backend — retryable once as transient (real-world
    # tunnel-attached launches fail transiently far more often than
    # fatally; a persistent fault trips the breaker anyway)
    if type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
        return "transient"
    return None


def _bump_class(cls: str) -> None:
    _bump({"oom": "oom_errors", "transient": "transient_errors",
           "backend-fatal": "fatal_errors"}[cls])


# -------------------------------------------------- route breakers

class RouteBreaker:
    """Per-route device circuit breaker (the PR 1 per-peer transport
    breaker, re-cut for device dispatch routes): closed → N classified
    failures → open; after the cooldown ONE caller probes half-open;
    probe success closes (and returns any confiscated gate permits),
    probe failure re-opens with the cooldown doubled (capped 8x,
    jittered)."""

    def __init__(self, route: str):
        self.route = route
        self._lock = threading.Lock()
        self.state = "closed"          # closed | open | half_open
        self.failures = 0
        self.open_cycles = 0
        self.probe_at = 0.0
        self.trips = 0
        self.probes = 0
        self.recoveries = 0
        self._probe_t = 0.0

    def _threshold(self) -> int:
        return max(1, int(knobs.get("OG_DEVICE_BREAKER_THRESHOLD")))

    def _cooldown(self) -> float:
        base = max(0.05, float(
            knobs.get("OG_DEVICE_BREAKER_COOLDOWN_S")))
        cool = base * (2 ** min(self.open_cycles, 3))
        # jitter so concurrent queries don't re-probe in lockstep
        return cool * (0.75 + 0.5 * random.random())

    def allow(self) -> bool:
        """Gate one use of the device route. True = go (and when the
        breaker was open, this caller is the half-open probe); False =
        stay on the host fallback."""
        if not bool(knobs.get("OG_DEVICE_BREAKER")):
            return True
        with self._lock:
            if self.state == "closed":
                return True
            now = time.monotonic()
            if self.state == "open" and now >= self.probe_at:
                self.state = "half_open"
                self.probes += 1
                self._probe_t = now
                _bump("breaker_probes")
                return True
            if self.state == "half_open" \
                    and now - self._probe_t > 60.0:
                # the probe's query died mid-flight and never reported
                # — promote a fresh probe instead of parking the route
                # on host forever
                self.probes += 1
                self._probe_t = now
                _bump("breaker_probes")
                return True
            return False

    def record_success(self) -> None:
        restore = False
        with self._lock:
            if self.state != "closed":
                self.recoveries += 1
                _bump("breaker_recoveries")
                restore = True
            self.state = "closed"
            self.failures = 0
            self.open_cycles = 0
        if restore:
            # the OOM ladder may have confiscated gate permits while
            # this route was sick — a recovered route returns them
            restore_gate_permits()

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == "half_open" \
                    or self.failures >= self._threshold():
                self.state = "open"
                self.trips += 1
                _bump("breaker_trips")
                self.probe_at = time.monotonic() + self._cooldown()
                self.open_cycles += 1

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self.state != "closed"

    def force(self, opened: bool) -> None:
        """Operator override (/debug/ctrl?mod=devicebreaker)."""
        restore = False
        with self._lock:
            if opened:
                self.failures = max(self.failures, self._threshold())
                self.state = "open"
                self.trips += 1
                _bump("breaker_trips")
                self.probe_at = time.monotonic() + self._cooldown()
                self.open_cycles += 1
            else:
                restore = self.state != "closed"
                self.state = "closed"
                self.failures = 0
                self.open_cycles = 0
        if restore:
            # same contract as record_success(): a recovered route —
            # operator-declared or probed — returns any gate permits
            # the OOM ladder confiscated while it was sick
            restore_gate_permits()

    def snapshot(self) -> dict:
        with self._lock:
            d = {"state": self.state, "failures": self.failures,
                 "trips": self.trips, "probes": self.probes,
                 "recoveries": self.recoveries}
            if self.state == "open":
                d["probe_in_s"] = round(
                    max(0.0, self.probe_at - time.monotonic()), 3)
            return d


_BREAKERS: dict[str, RouteBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def breaker_for(route: str) -> RouteBreaker:
    with _BREAKERS_LOCK:
        b = _BREAKERS.get(route)
        if b is None:
            b = _BREAKERS[route] = RouteBreaker(route)
        return b


def reset_breakers() -> None:
    """Drop all route-breaker state AND return confiscated gate
    permits (tests; operator full reset)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
    restore_gate_permits()


def breaker_snapshot() -> dict[str, dict]:
    with _BREAKERS_LOCK:
        items = list(_BREAKERS.items())
    return {r: b.snapshot() for r, b in items}


def route_on(route: str) -> bool:
    """Route gate the executor consults before choosing a device path:
    False = the route's breaker is open (and its cooldown not yet
    elapsed) — take the byte-identical host fallback."""
    return breaker_for(route).allow()


# --------------------------------------------- HBM pressure ladder

# permits confiscated from the scheduler's global pipeline gate by the
# OOM ladder; returned when a route breaker recovers (or on reset)
_SHRUNK_LOCK = threading.Lock()
_SHRUNK: list = []               # held semaphore handles


def _shrink_gate_permit() -> bool:
    """Confiscate ONE permit from the global OG_SCHED_DEPTH gate (the
    in-flight bound every StreamingPipeline shares): fewer concurrent
    launch result buffers is the cheapest HBM a pressure ladder can
    find. Never takes the last permit — a gate at zero would wedge
    every streamed query."""
    try:
        from ..query import scheduler as _qs
        if not _qs.enabled():
            return False
        sch = _qs.get_scheduler()
        gate = sch.pipeline_gate()
        with _SHRUNK_LOCK:
            if len(_SHRUNK) >= sch._pipe_depth - 1:
                return False       # keep >= 1 permit circulating
            if not gate.acquire(blocking=False):
                return False
            _SHRUNK.append(gate)
        _bump("gate_shrinks")
        return True
    except Exception:  # pressure relief must never add a new failure
        # oglint: disable=R701 — reviewed: best-effort relief step
        return False


def restore_gate_permits() -> None:
    """Return every confiscated gate permit (route recovery, breaker
    reset, conftest leak guard)."""
    with _SHRUNK_LOCK:
        held, _SHRUNK[:] = list(_SHRUNK), []
    for gate in held:
        try:
            gate.release()
            _bump("gate_restores")
        except ValueError:
            pass                   # gate was rebuilt under us (tests)


def shrunk_permits() -> int:
    with _SHRUNK_LOCK:
        return len(_SHRUNK)


def hbm_pressure_relief(route: str, nbytes_hint: int = 0) -> int:
    """The OOM rung of the ladder: free device HBM NOW so one retry
    can succeed — evict the ledger-mirrored device-cache tier (the
    only device residency we own outright) and confiscate one global
    in-flight gate permit. Returns bytes evicted. Every action lands
    in the HBM pressure-event ring (reason ``oom_relief``) so the
    observatory timeline shows the ladder firing."""
    _bump("oom_relief_runs")
    freed = 0
    if bool(knobs.get("OG_HBM_PRESSURE_EVICT")):
        try:
            from . import devicecache as _dc
            failpoint.inject("devicecache.evict")
            if _dc.enabled():
                # eviction order is cheapest-to-rebuild first: sketch
                # planes are pure derived state (one cellsort kernel
                # rebuilds them), DECODED slabs/planes rebuild from
                # the compressed tier with one expand kernel and ZERO
                # H2D while it survives — so the compressed payload
                # bytes (the densest residency per decoded byte) are
                # evicted LAST: only when the decoded tiers freed
                # nothing, or less than the caller's byte hint
                freed = _dc.sketch_cache().evict_bytes(
                    None, reason="oom_relief")
                freed += _dc.global_cache().evict_bytes(
                    None, reason="oom_relief")
                if freed < max(1, int(nbytes_hint)):
                    freed += _dc.compressed_cache().evict_bytes(
                        None, reason="oom_relief")
        except Exception as e:
            cls = classify(e)
            log.warning("oom relief eviction failed (route=%s, "
                        "class=%s): %s", route, cls, str(e))
    if freed:
        _bump("oom_evicted_bytes", freed)
    _shrink_gate_permit()
    log.warning("HBM pressure ladder ran for route %s: evicted %d "
                "bytes, %d gate permit(s) held", route, freed,
                shrunk_permits())
    return freed


# ------------------------------------------------------- the ladder

def _retry_budget() -> int:
    return max(0, int(knobs.get("OG_DEVICE_RETRY")))


def _backoff_sleep(attempt: int, ctx=None) -> None:
    """Jittered exponential backoff between transient retries, clamped
    to the request deadline and killable."""
    base = max(0.0, float(
        knobs.get("OG_DEVICE_RETRY_BACKOFF_MS"))) / 1e3
    delay = base * (2 ** attempt) * (0.5 + random.random())
    delay = min(delay, _deadline.remaining(delay))
    end = time.monotonic() + delay
    while time.monotonic() < end:
        if ctx is not None and getattr(ctx, "killed", False):
            ctx.check()            # raises QueryKilled
        time.sleep(min(0.02, max(0.0, end - time.monotonic())))


def guarded_launch(route: str, fn, ctx=None, span=None,
                   site: str | None = None,
                   success_resets: bool = True):
    """Run one device-launch thunk under the fault ladder. ``fn`` must
    be a pure dispatch closure (safe to re-run — every launch thunk in
    the executor is). Raises ``DeviceRouteDown(route)`` when the
    ladder exhausts (the statement-level wrapper re-runs the statement
    against the host fallback), re-raises non-device exceptions
    untouched. ``site`` overrides the failpoint site when several
    launch families share one breaker route (the device-decode slab
    expansions ride route \"block\" but inject at
    ``device.decode.launch`` so chaos schedules can target them).
    Such SECONDARY families pass ``success_resets=False``: they still
    charge failures to the shared breaker, but a success must neither
    reset the primary family's failure streak nor close a half-open
    breaker the primary's probe owns — a persistent block-kernel
    fault interleaved with healthy decode launches would otherwise
    never accumulate to the trip threshold (measured: the statement
    fallback looped 14 attempts with the breaker pinned closed)."""
    if site is None:
        site = f"device.{route}.launch"
    br = breaker_for(route)
    retries = _retry_budget()
    attempt = 0                    # transient retries taken
    oom_retried = False
    while True:
        try:
            failpoint.inject(site)
            out = fn()
            if success_resets:
                br.record_success()
            if span is not None and (attempt or oom_retried):
                span.add(device_fault_route=route,
                         device_fault_retries=attempt
                         + (1 if oom_retried else 0))
            if attempt or oom_retried:
                _bump("retry_success")
            return out
        except BaseException as e:
            cls = classify(e)
            if cls is None:
                raise              # not a device fault — never mask
            _bump_class(cls)
            # give up immediately when the request is already dead —
            # retrying for a killed/expired query only burns device
            if ctx is not None and getattr(ctx, "killed", False):
                raise
            dl = _deadline.current()
            if dl is not None and dl.expired:
                raise
            if cls == "transient" and attempt < retries:
                attempt += 1
                _bump("retries")
                # str(e), not e: a LogRecord retains its args, and a
                # live exception pins its whole traceback (frames
                # holding zero-staging mmap views) in any deferred-
                # formatting handler
                log.warning("transient device fault on route %s "
                            "(attempt %d/%d): %s", route, attempt,
                            retries, str(e))
                _backoff_sleep(attempt - 1, ctx=ctx)
                continue
            if cls == "oom" and not oom_retried:
                oom_retried = True
                hbm_pressure_relief(route)
                log.warning("device OOM on route %s — pressure ladder "
                            "ran, retrying once: %s", route, str(e))
                continue
            # exhausted (or fatal): this route is sick — charge the
            # breaker and hand the statement to the fallback wrapper
            br.record_failure()
            if span is not None:
                span.add(device_fault_route=route,
                         device_fault_class=cls,
                         device_fault_fell_back=True)
            log.warning(
                "device route %s failed (%s, retries exhausted=%s, "
                "breaker=%s): %s", route, cls, attempt >= retries,
                br.snapshot()["state"], str(e))
            raise DeviceRouteDown(route, e) from e


def note_fallback(route: str) -> None:
    """Statement-level fallback taken (executor re-run counter)."""
    _bump("route_fallbacks")


# ---------------------------------------------------- observability

def devicefault_collector() -> dict:
    """utils.stats collector: fault/ladder counters plus flattened
    per-route breaker state (0 closed / 1 half-open / 2 open) for
    /metrics, /debug/vars and the stats pusher."""
    from ..utils.stats import COUNTER_LOCK
    out: dict = {}
    with COUNTER_LOCK:
        out.update(DEVFAULT_STATS)
    state_code = {"closed": 0, "half_open": 1, "open": 2}
    for route, snap in breaker_snapshot().items():
        out[f"breaker_{route}_state"] = state_code.get(
            snap["state"], -1)
        out[f"breaker_{route}_trips"] = snap["trips"]
    out["gate_permits_shrunk"] = shrunk_permits()
    return out
