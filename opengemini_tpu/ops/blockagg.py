"""Device-resident block aggregation: the HBM tier of the storage engine.

The round-1 verdict's core critique was TPU paths living as leaves no
query reaches. This module is the opposite design point: a TSSP file's
column segments are staked into HBM ONCE — values, validity, times, and
the exact-sum limb planes (ops/exactsum.py) — and then ANY aggregate
query shape (different windows, time ranges, tag filters, groupings)
reduces ON DEVICE with only a tiny per-query gid vector uploaded and a
result grid pulled.

Why this fits the hardware (measured on the axon-attached v5e):
- The kernel is a MASKED-PASS reduction: per window, a dense axis
  reduction over the (blocks × segment) resident planes (pure VPU
  work, the same mapping as dense_window_aggregate), then ONE tiny
  scatter of per-block partials onto the (group × window) grid. The
  round-2 design scattered 12.7M rows flat through segment_sum — 8.2s
  on the v5e (large unsorted scatters don't tile; int64 scatters hit
  the 64-bit emulation path); the masked-pass form does the same
  reduction in 0.125s.
- Transfers pay ~0.1-0.25s latency EACH on the tunnel-attached chip:
  every per-cell state packs into ONE f64 plane array per file (same-E
  files combine on device), window scalars and gid vectors are
  content-keyed in the device cache, so a warm query uploads nothing
  and pulls one array.
- f64 is emulated as float32 pairs: float sums would drift, so the
  AUTHORITATIVE sums are integer limb-plane reductions (f64-held ints,
  exact below 2^49) — bit-identical with every other path. Dead limb
  planes (a 52-bit mantissa spans ≤4 of 6) are trimmed file-wide.
  min/max return row INDICES; exact values gather host-side from the
  readcache.
- Stacks are SLABBED (OG_BLOCK_SLAB blocks per kernel launch); slab
  results combine on device and ONE grid crosses D2H.

Reference roles covered: lib/readcache/blockcache.go (block cache, HBM
tier), engine/immutable/reader.go decode + series_agg_func reduce
kernels (fused here), aggregateCursor windowing (in-kernel window ids).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..utils import failpoint, get_logger, knobs
from . import devicecache, exactsum

log = get_logger(__name__)

I64MAX = np.iinfo(np.int64).max
I64MIN = np.iinfo(np.int64).min

# blocks per kernel launch: bounds the flattened row count (and hence
# XLA scatter temporaries) of one launch to SLAB × SEG rows. Each
# launch pays a full dispatch round-trip on tunnel-attached devices, so
# bigger is better until the temporaries stop fitting
SLAB_BLOCKS = int(knobs.get("OG_BLOCK_SLAB"))


@dataclass


class BlockStack:
    """One slab of a (file, field)'s segments resident in HBM.

    Device arrays (jax) all shaped (B, SEG) with ragged tails padded
    valid=False:
      values f64 | valid bool | times i64 | limbs i32 (B, SEG, K) | bad
    Host metadata: the block→series map and per-block segment refs for
    exact-value gathers. ``block0`` is this slab's global block offset
    within the file.
    """
    path: str
    field: str
    seg_rows: int                    # SEG (padded block width)
    E: int                           # limb scale (multiple of 18)
    block_sids: np.ndarray           # (B,) int64
    seg_refs: list                   # (B,) [(colmeta, segment)] host
    n_rows: int                      # real rows (un-padded)
    t_min: np.ndarray = None         # (B,) int64 host time bounds
    t_max: np.ndarray = None
    block0: int = 0
    values: object = None            # jax (B, SEG) f64
    valid: object = None             # jax (B, SEG) bool
    times: object = None             # jax (B, SEG) i64
    limbs: object = None             # jax (B, SEG, K) i32
    bad: object = None               # jax (B, SEG) bool (limb residual)
    block0_dev: object = None        # jax f64 scalar (= block0)
    k0: int = 0                      # first resident limb plane
    # const-delta time structure (arithmetic-boundary prefix kernel):
    # every real block of a bulk-written file has affine times
    # t0 + i*step; all_const gates the searchsorted-free kernel
    t_rows: np.ndarray = None        # (B,) int64 host real row counts
    all_const: bool = False
    t0_dev: object = None            # jax (B,) i64 first time
    step_dev: object = None          # jax (B,) i64 delta (1 if rows<2)
    rows_dev: object = None          # jax (B,) i32 real rows
    # int-mode slab (OG_LIMB_INT, round 18): limbs decomposed in int
    # space on device, NO values plane — the executor gates wants to
    # count/sum (min/max/sumsq need the f64 plane)
    int_only: bool = False

    @property
    def n_blocks(self) -> int:
        return len(self.block_sids)

    @property
    def nbytes(self) -> int:
        return sum(int(getattr(a, "nbytes", 0)) for a in
                   (self.values, self.valid, self.times, self.limbs,
                    self.bad, self.t0_dev, self.step_dev,
                    self.rows_dev))


def _file_layout(reader, field: str):
    """(metas, SEG, E) — or None when the column can't stack."""
    from ..record import DataType
    metas = []
    for sid in reader.series_ids():
        cm = reader.chunk_meta(sid)
        if cm is None:
            continue
        colm = cm.column(field)
        tm = cm.column("time")
        if colm is None or tm is None:
            continue
        if colm.type != DataType.FLOAT:
            # integers keep their exact typed-int64 host/sparse path
            # (the f64 staking would round above 2^53); strings/bools
            # never stack
            return None
        for si, s in enumerate(colm.segments):
            metas.append((sid, colm, s, tm.segments[si]))
    if not metas:
        return None
    seg = max(s.rows for _sid, _c, s, _t in metas)
    if seg == 0:
        return None
    mx = 0.0
    for _sid, _c, s, _t in metas:
        if s.preagg is not None and s.preagg.count:
            mx = max(mx, abs(s.preagg.min), abs(s.preagg.max))
    return metas, seg, exactsum.pick_scale(mx)


def _build_slab(reader, field: str, metas, seg: int, E: int,
                block0: int, pred=None):
    """Host-side slab assembly: decode + limb decompose. Upload happens
    in get_stacks once the file-wide active limb-plane range is known
    (most real columns use ≤4 of the 6 planes — a 52-bit mantissa spans
    at most 4; skipping dead planes cuts H2D, kernel passes, and the
    result pull alike)."""
    B = len(metas)
    vals = np.zeros((B, seg), dtype=np.float64)
    valid = np.zeros((B, seg), dtype=np.bool_)
    # padded tails hold I64MAX, NOT 0: the prefix kernel binary-
    # searches window ids along the row axis, so per-block times must
    # stay nondecreasing through the padding (padded rows are
    # valid=False everywhere, so no kernel can read them as data)
    times = np.full((B, seg), I64MAX, dtype=np.int64)
    sids = np.empty(B, dtype=np.int64)
    tmin = np.full(B, I64MAX, dtype=np.int64)
    tmax = np.full(B, I64MIN, dtype=np.int64)
    steps = np.ones(B, dtype=np.int64)
    rows_arr = np.zeros(B, dtype=np.int64)
    all_const = True
    refs: list = []
    n_rows = 0
    for b, (sid, colm, s, tseg) in enumerate(metas):
        cv = reader.read_segment(colm, s)
        tv = reader.read_segment(_TimeCol, tseg)
        r = s.rows
        vals[b, :r] = cv.values.astype(np.float64, copy=False)
        valid[b, :r] = cv.valid
        times[b, :r] = tv.values
        if r:
            tmin[b] = tv.values[0]
            tmax[b] = tv.values[r - 1]
        if r > 1:
            d = int(tv.values[1]) - int(tv.values[0])
            if d > 0 and np.all(np.diff(tv.values) == d):
                steps[b] = d
            else:
                all_const = False
        rows_arr[b] = r
        sids[b] = sid
        refs.append((colm, s))
        n_rows += r
    if pred is not None:
        # packed-predicate rows land on the VALID plane before limb
        # decomposition — the exact leaf compares eval_residual would
        # run (ops/pushdown.eval_numpy), so every downstream kernel
        # late-materializes only survivors without knowing pushdown
        # exists
        from . import pushdown as _pu
        valid &= _pu.eval_numpy(pred, vals)
    limbs, bad = exactsum.host_limbs(vals, valid, E)
    st = BlockStack(reader.path, field, seg, E, sids, refs, n_rows,
                    tmin, tmax, block0)
    # non-limb arrays upload immediately (host copies freed per slab);
    # only the i32 limb planes wait for the file-wide k-range
    import jax

    from . import compileaudit
    st.values = jax.device_put(vals)
    st.valid = jax.device_put(valid)
    st.times = jax.device_put(times)
    st.bad = jax.device_put(bad)
    st.block0_dev = jax.device_put(np.float64(block0))
    st.t_rows = rows_arr
    st.all_const = all_const
    # affine time structure for the arithmetic-boundary wide-window
    # kernel: empty/single-row blocks get step 1 (the clip produces
    # the right 0/rows boundary either way); t0 of an empty block is
    # I64MAX so every boundary clips to 0
    st.t0_dev = jax.device_put(tmin)
    st.step_dev = jax.device_put(steps)
    st.rows_dev = jax.device_put(rows_arr.astype(np.int32))
    compileaudit.record_h2d("slab", int(
        st.values.nbytes + st.valid.nbytes + st.times.nbytes
        + st.bad.nbytes + st.block0_dev.nbytes + st.t0_dev.nbytes
        + st.step_dev.nbytes + st.rows_dev.nbytes))
    return st, limbs


def _upload_limbs(st: BlockStack, limbs, k0: int, k1: int) -> None:
    import jax

    from . import compileaudit
    st.k0 = k0
    st.limbs = jax.device_put(np.ascontiguousarray(limbs[..., k0:k1]))
    compileaudit.record_h2d("limbs", int(st.limbs.nbytes))


class _TimeColMeta:
    """Minimal ColumnMeta stand-in for decoding time segments (the
    reader only consults .type)."""
    def __init__(self):
        from ..record import DataType
        self.type = DataType.TIME
        self.name = "time"


_TimeCol = _TimeColMeta()


# ------------------------------------ device-decode slab build
#
# The compressed-domain H2D diet (ROADMAP item 2): when a slab's
# blocks carry device-expandable codecs (DFOR bit-packed lanes /
# CONST values / CONST_DELTA times — query/decodestage.block_stage
# picks the stage per block), the COMPRESSED payloads are what
# crosses H2D; ops/device_decode expands them in-kernel and the limb
# decomposition runs on device from the expanded planes. A 34 B/row
# host-assembled slab (values+times+valid+bad+limbs) becomes ~2 B/row
# of payload on the 2-decimal bench data. Blocks the device cannot
# take — and any batch whose expand launch exhausts the PR 9 fault
# ladder — heal PER BLOCK through the host stage (decode + dense
# device_put, manifest site "slab"), so a sick kernel degrades one
# batch, not the file.


def _build_slab_device(reader, field: str, metas, seg: int, E: int,
                       block0: int, pred=None, int_mode: bool = False):
    """Device-decode twin of _build_slab. Returns (BlockStack with
    FULL-K limb planes, (K,) device activity flags, rebuild recipe) —
    get_stacks slices the limb range and stakes the recipe into the
    compressed HBM tier — or raises DeviceRouteDown when the decode
    ladder exhausts beyond per-batch healing (caller falls back to
    the host build)."""
    import jax

    from ..encoding import blocks as EB
    from ..encoding import dfor as _dfm
    from ..query import decodestage
    from . import compileaudit, device_decode as dd

    mm = reader._mm
    B = len(metas)
    sids = np.empty(B, dtype=np.int64)
    tmin = np.full(B, I64MAX, dtype=np.int64)
    tmax = np.full(B, I64MIN, dtype=np.int64)
    steps = np.ones(B, dtype=np.int64)
    rows_arr = np.zeros(B, dtype=np.int64)
    all_const = True
    refs: list = []
    n_rows = 0
    vbw = (seg + 7) // 8              # validity bitmap row width

    dfor_groups: dict[tuple, list] = {}   # (w, tr, ds, r) → [(b, ref, words)]
    const_blocks: list = []               # (b, value)
    rle_groups: dict[int, list] = {}      # padded runs → [(b, pv, pl)]
    host_blocks: list = []                # block indices
    cdelta_blocks: list = []                 # (b, t0, step) device times
    vbits: dict[int, np.ndarray | None] = {}   # b → bitmap | None=CONST

    for b, (sid, colm, s, tseg) in enumerate(metas):
        sids[b] = sid
        refs.append((colm, s))
        r = s.rows
        rows_arr[b] = r
        n_rows += r
        if r == 0:
            host_blocks.append(b)     # zeros/I64MAX staging, no decode
            continue
        vcodec = mm[s.offset]
        tcodec = mm[tseg.offset]
        if decodestage.block_stage(vcodec, tcodec) != "device":
            host_blocks.append(b)
            continue
        if int_mode and not _int_block_ok(mm, s, E):
            # int-space decomposition serves zigzag-delta ints whose
            # envelope fits below 2^E; everything else (XOR floats,
            # scaled decimals, CONST, RLE, wrap-risk widths) takes the
            # host stage — host f64 limb math is exact
            host_blocks.append(b)
            continue
        t0, step = struct_unpack_qq(mm, tseg.offset + 1)
        tmin[b] = t0
        tmax[b] = t0 + (r - 1) * step
        if r > 1:
            if step > 0:
                steps[b] = step
            else:
                all_const = False
        if vcodec == EB.DFOR:
            hdr = mm[s.offset + 1:s.offset + 1 + _dfm.HEADER_BYTES]
            tr, w, ds, n_hdr, ref = _dfm.parse_header(hdr)
            if n_hdr != r:
                host_blocks.append(b)
                continue
            nw = (r * w + 31) // 32
            # zero-staging: a view straight over the mapped pages —
            # no bytes() copy; the words land in wmat (a real copy)
            # before H2D, so nothing retained aliases the mmap
            words = np.frombuffer(
                memoryview(mm)[s.offset + 1 + _dfm.HEADER_BYTES:
                               s.offset + 1 + _dfm.HEADER_BYTES
                               + 4 * nw],
                dtype="<u4")
            dfor_groups.setdefault((w, tr, ds, r), []).append(
                (b, ref, words))
        elif vcodec == EB.RLE:        # arithmetic run payload
            rvals, rlens = _parse_rle(mm, s)
            pv, pl = dd._pad_runs(rvals, rlens)
            rle_groups.setdefault(len(pv), []).append((b, pv, pl))
        else:                         # CONST float value
            val = np.frombuffer(mm[s.offset + 1:s.offset + 9],
                                dtype=np.float64)[0]
            const_blocks.append((b, val))
        vb0 = mm[s.valid_offset]
        if vb0 == EB.CONST:
            vbits[b] = None
        else:
            bm = np.zeros(vbw, dtype=np.uint8)
            raw = np.frombuffer(
                mm[s.valid_offset + 1:s.valid_offset + s.valid_size],
                dtype=np.uint8)
            bm[:len(raw)] = raw[:vbw]
            vbits[b] = bm
        cdelta_blocks.append((b, t0, step))

    if not cdelta_blocks:
        raise _AllHostSlab()

    # ---- stage + upload the compressed payloads --------------------
    def _pad_rows(mat, nb_pad):
        if mat.shape[0] == nb_pad:
            return mat
        out = np.zeros((nb_pad,) + mat.shape[1:], dtype=mat.dtype)
        out[:mat.shape[0]] = mat
        return out

    recipe: dict = {"seg": seg, "E": E, "block0": block0,
                    "sids": sids, "refs": refs, "tmin": tmin,
                    "tmax": tmax, "steps": steps, "rows": rows_arr,
                    "all_const": all_const, "n_rows": n_rows,
                    "dfor": [], "rle": [], "const": None,
                    "host": None, "hsegs": [], "tbatch": None,
                    "vbatch": None, "perm": None, "tperm": None,
                    "k0": 0, "k1": 0, "int": int_mode,
                    "pred": pred, "pdmask": [], "pdf": None}
    if pred is not None:
        from . import pushdown as _pu
        # post-expand f64 thresholds (RLE batches, heals): device-
        # resident in the recipe so compressed-tier rebuilds move 0 B
        recipe["pdf"] = jax.device_put(np.array(
            [c for _op, c in pred.conjs], dtype=np.float64))
        compileaudit.record_h2d("payload",
                                int(recipe["pdf"].nbytes))

    for (w, tr, ds, r), blks in sorted(dfor_groups.items()):
        nb = len(blks)
        nb_pad = dd.pad_pow2(nb, 8)
        nw = (r * w + 31) // 32
        wmat = np.zeros((nb_pad, nw + 2), dtype=np.uint32)
        rvec = np.zeros(nb_pad, dtype=np.uint64)
        for j, (_b, ref, words) in enumerate(blks):
            wmat[j, :nw] = words
            rvec[j] = ref
        wd = jax.device_put(wmat)
        rd = jax.device_put(rvec)
        compileaudit.record_h2d("dfor", int(wd.nbytes))
        compileaudit.record_h2d("payload", int(rd.nbytes))
        recipe["dfor"].append((wd, rd, w, tr, ds, r,
                               [b for b, _r, _w in blks]))
        plan = None
        if pred is not None:
            from . import pushdown as _pu
            classes = [_pu.classify_dfor(pred, tr, w, ds, int(ref))
                       for _b, ref, _w2 in blks]
            plan = _pu.batch_mask_plan(pred, tr, w, ds, classes)
            if plan is not None:
                mode_p, sig_p, thr = plan
                thr_d = jax.device_put(thr)
                compileaudit.record_h2d("payload", int(thr_d.nbytes))
                plan = (mode_p, sig_p, thr_d)
        recipe["pdmask"].append(plan)

    for rp, blks in sorted(rle_groups.items()):
        nb_pad = dd.pad_pow2(len(blks), 8)
        pvm = np.zeros((nb_pad, rp), dtype=np.float64)
        plm = np.zeros((nb_pad, rp), dtype=np.int64)
        for j, (_b, pv, pl) in enumerate(blks):
            pvm[j] = pv
            plm[j] = pl
        rrw = _pad_rows(rows_arr[[b for b, _v, _l in blks]], nb_pad)
        pvd, pld, rrd = (jax.device_put(pvm), jax.device_put(plm),
                         jax.device_put(rrw))
        compileaudit.record_h2d("payload", int(
            pvd.nbytes + pld.nbytes + rrd.nbytes))
        recipe["rle"].append((pvd, pld, rrd,
                              [b for b, _v, _l in blks]))

    if const_blocks:
        nb_pad = dd.pad_pow2(len(const_blocks), 8)
        cvals = _pad_rows(np.array([v for _b, v in const_blocks],
                                   dtype=np.float64), nb_pad)
        crows = _pad_rows(rows_arr[[b for b, _v in const_blocks]],
                          nb_pad)
        cvd, crd = jax.device_put(cvals), jax.device_put(crows)
        compileaudit.record_h2d("payload",
                                int(cvd.nbytes + crd.nbytes))
        recipe["const"] = (cvd, crd, [b for b, _v in const_blocks])

    # host-stage blocks (legacy codecs, empty, ragged headers): the
    # per-block host heal target — decode + dense upload (site "slab")
    if host_blocks:
        _stage_host_blocks(reader, metas, host_blocks, seg, tmin,
                           tmax, steps, rows_arr, recipe)

    ndev = len(cdelta_blocks)
    nd_pad = dd.pad_pow2(ndev, 8)
    t0s = _pad_rows(np.array([t for _b, t, _s in cdelta_blocks],
                             dtype=np.int64), nd_pad)
    stp = _pad_rows(np.array([s_ for _b, _t, s_ in cdelta_blocks],
                             dtype=np.int64), nd_pad)
    drw = _pad_rows(rows_arr[[b for b, _t, _s in cdelta_blocks]], nd_pad)
    bitm = np.zeros((nd_pad, vbw), dtype=np.uint8)
    cflag = np.zeros(nd_pad, dtype=np.bool_)
    for j, (b, _t, _s) in enumerate(cdelta_blocks):
        if vbits[b] is None:
            cflag[j] = True
        else:
            bitm[j] = vbits[b]
    t0d, stpd, drwd = (jax.device_put(t0s), jax.device_put(stp),
                       jax.device_put(drw))
    bitd, cfd = jax.device_put(bitm), jax.device_put(cflag)
    compileaudit.record_h2d("payload", int(
        t0d.nbytes + stpd.nbytes + drwd.nbytes + bitd.nbytes
        + cfd.nbytes))
    recipe["tbatch"] = (t0d, stpd, drwd, bitd, cfd,
                        [b for b, _t, _s in cdelta_blocks])

    # permutations: meta order ← concatenated batch order
    recipe["perm"], recipe["tperm"] = _recipe_perms(recipe, B)
    st, act = _expand_recipe(recipe, reader, field, guarded=True)
    return st, act, recipe


class _AllHostSlab(Exception):
    """Internal: no device-decodable block in this slab — the caller
    takes the plain host build (not a fault, no breaker charge)."""


def struct_unpack_qq(mm, off: int):
    import struct as _s
    return _s.unpack("<qq", mm[off:off + 16])


def _parse_rle(mm, seg_meta):
    """Host-parse one RLE segment's (tiny) run payload from the mmap —
    what crosses H2D instead of the expanded rows."""
    from ..encoding.blocks import parse_rle_payload
    return parse_rle_payload(
        mm[seg_meta.offset + 1:seg_meta.offset + seg_meta.size])


def _int_block_ok(mm, s, E: int) -> bool:
    """Int-mode device eligibility of one value segment: zigzag-delta
    DFOR (T_INT, or T_SCALED with dscale 0 — the divide by 10^0 is the
    identity) whose header envelope bounds |k| below 2^E, so the
    static-shift limb windows of ops/device_decode.int_limbs_batch
    capture every bit and the clamp cascade never engages."""
    from ..encoding import blocks as EB
    from ..encoding import dfor as _dfm
    from . import pushdown as _pu
    if mm[s.offset] != EB.DFOR:
        return False
    hdr = mm[s.offset + 1:s.offset + 1 + _dfm.HEADER_BYTES]
    tr, w, ds, n_hdr, ref = _dfm.parse_header(hdr)
    if n_hdr != s.rows:
        return False
    if tr not in (_dfm.T_INT, _dfm.T_SCALED) or (
            tr == _dfm.T_SCALED and ds != 0):
        return False
    env = _pu.envelope_k(w, ref)
    if env is None:
        return False
    return max(abs(env[0]), abs(env[1])) < (1 << E)


def _classify_metas(reader, pred, metas):
    """Segment-envelope pre-filter (ops/pushdown.classify_dfor): drop
    segments wholly outside the predicate BEFORE any slab batching —
    they never unpack, never upload, never mask. Classification reads
    only the 16-byte DFOR header / 8-byte CONST value from the mmap.
    Non-classifiable codecs (RLE, legacy) stay and row-mask
    post-expand."""
    from ..encoding import blocks as EB
    from ..encoding import dfor as _dfm
    from . import device_decode as dd, pushdown as _pu
    mm = reader._mm
    kept = []
    skip_seg = skip_rows = 0
    for m in metas:
        _sid, _colm, s, _tseg = m
        cls = "fallback"
        if s.rows == 0:
            cls = "none"          # nothing to aggregate either way
        else:
            vcodec = mm[s.offset]
            if vcodec == EB.DFOR:
                hdr = mm[s.offset + 1:
                         s.offset + 1 + _dfm.HEADER_BYTES]
                tr, w, ds, n_hdr, ref = _dfm.parse_header(hdr)
                if n_hdr == s.rows:
                    cls = _pu.classify_dfor(pred, tr, w, ds, ref)
            elif vcodec == EB.CONST:
                val = np.frombuffer(mm[s.offset + 1:s.offset + 9],
                                    dtype=np.float64)[0]
                cls = _pu.classify_const(pred, val)
        if cls == "none":
            skip_seg += 1
            skip_rows += int(s.rows)
            continue
        kept.append(m)
    dd._bump("pushdown_segments_skipped", skip_seg)
    dd._bump("pushdown_rows_skipped", skip_rows)
    return kept


def _heal_mask(reader, seg_refs, idxs, nb_pad: int, seg: int, pred):
    """Heal of a faulted expand+mask pushdown launch: host decode of
    the batch (the same rows _heal_batch stages) PLUS the host
    eval_numpy mask — expand-then-filter, byte-identical. Returns
    (values_dev, mask_dev)."""
    import jax

    from . import compileaudit, device_decode as dd, pushdown as _pu
    hv = np.zeros((nb_pad, seg), dtype=np.float64)
    for j, b in enumerate(idxs):
        colm, s = seg_refs[b]
        if s.rows:
            cv = reader.read_segment(colm, s)
            hv[j, :s.rows] = cv.values.astype(np.float64, copy=False)
    mk = _pu.eval_numpy(pred, hv)
    hvd, mkd = jax.device_put(hv), jax.device_put(mk)
    compileaudit.record_h2d("slab", int(hvd.nbytes + mkd.nbytes))
    dd._bump("pushdown_heals", len(idxs))
    return hvd, mkd


def _heal_mask_only(reader, seg_refs, idxs, nb_pad: int, seg: int,
                    pred):
    """Heal of a faulted mask-only launch (RLE plane_mask / int-mode
    k_mask): the values (or k limbs) expanded fine — only the survivor
    mask re-derives on host."""
    import jax

    from . import compileaudit, device_decode as dd, pushdown as _pu
    hv = np.zeros((nb_pad, seg), dtype=np.float64)
    for j, b in enumerate(idxs):
        colm, s = seg_refs[b]
        if s.rows:
            cv = reader.read_segment(colm, s)
            hv[j, :s.rows] = cv.values.astype(np.float64, copy=False)
    mkd = jax.device_put(_pu.eval_numpy(pred, hv))
    compileaudit.record_h2d("slab", int(mkd.nbytes))
    dd._bump("pushdown_heals", len(idxs))
    return mkd


def _heal_limbs(reader, seg_refs, idxs, nb_pad: int, seg: int,
                E: int, pred=None):
    """Int-mode heal of a faulted k-expand/limb launch: host decode +
    exact host f64 limb decomposition (the final mask_limbs_batch
    zeroes by valid, so no pre-masking here). Returns
    (limbs_dev, bad_dev, mask_dev|None)."""
    import jax

    from . import compileaudit, device_decode as dd, exactsum, \
        pushdown as _pu
    hv = np.zeros((nb_pad, seg), dtype=np.float64)
    for j, b in enumerate(idxs):
        colm, s = seg_refs[b]
        if s.rows:
            cv = reader.read_segment(colm, s)
            hv[j, :s.rows] = cv.values.astype(np.float64, copy=False)
    hl, hb = exactsum.host_limbs(hv, None, E)
    hld, hbd = jax.device_put(hl), jax.device_put(hb)
    mkd = None
    if pred is not None:
        mkd = jax.device_put(_pu.eval_numpy(pred, hv))
        dd._bump("pushdown_heals", len(idxs))
    compileaudit.record_h2d("slab", int(
        hld.nbytes + hbd.nbytes
        + (mkd.nbytes if mkd is not None else 0)))
    dd._bump("host_heals", len(idxs))
    return hld, hbd, mkd


def _stage_host_blocks(reader, metas, host_blocks, seg, tmin, tmax,
                       steps, rows_arr, recipe):
    """Per-block host-decode staging: decode the listed blocks on
    host (values + times + validity), upload them as dense plane rows
    (manifest site \"slab\" — the same bytes the legacy build would
    have moved for them), and record their time bounds/steps. The
    recipe keeps only the (colm, seg, tseg) refs (``hsegs``): the
    dense planes themselves must NOT live in the compressed tier —
    they are exactly as big as the decoded slabs the relief ladder
    evicts first, so a rebuild re-stages them lazily instead
    (_restage_host)."""
    nbh = len(host_blocks)
    all_const = recipe["all_const"]
    for b in host_blocks:
        _sid, colm, s, tseg = metas[b]
        recipe["hsegs"].append((b, colm, s, tseg))
        r = s.rows
        if r == 0:
            continue
        tv = reader.read_segment(_TimeCol, tseg)
        tmin[b] = tv.values[0]
        tmax[b] = tv.values[r - 1]
        if r > 1:
            d = int(tv.values[1]) - int(tv.values[0])
            if d > 0 and np.all(np.diff(tv.values) == d):
                steps[b] = d
            else:
                all_const = False
    recipe["host"] = "lazy"
    recipe["all_const"] = all_const


def _restage_host(reader, recipe):
    """Decode + upload the host-stage blocks of one recipe (first
    build AND compressed-tier rebuild — the planes are deliberately
    not kept resident, see _stage_host_blocks). Returns
    (values, valid, times, idxs, limbs|None, bad|None) device
    planes (the limb pair only on int-mode recipes)."""
    import jax

    from . import compileaudit, exactsum
    seg = recipe["seg"]
    hsegs = recipe["hsegs"]
    nbh = len(hsegs)
    hv = np.zeros((nbh, seg), dtype=np.float64)
    hm = np.zeros((nbh, seg), dtype=np.bool_)
    ht = np.full((nbh, seg), I64MAX, dtype=np.int64)
    for j, (b, colm, s, tseg) in enumerate(hsegs):
        r = s.rows
        if r == 0:
            continue
        cv = reader.read_segment(colm, s)
        tv = reader.read_segment(_TimeCol, tseg)
        hv[j, :r] = cv.values.astype(np.float64, copy=False)
        hm[j, :r] = cv.valid
        ht[j, :r] = tv.values
    pred = recipe.get("pred")
    if pred is not None:
        # host-stage blocks filter in numpy BEFORE upload — the same
        # leaf compares the device mask launches run
        from . import pushdown as _pu
        hm &= _pu.eval_numpy(pred, hv)
    hld = hbd = None
    if recipe.get("int"):
        # int-mode slab: the device limb decomposition is off-limits
        # (that is the point) — host-stage blocks decompose HERE in
        # exact host f64 and ship limb planes
        hl, hb = exactsum.host_limbs(hv, hm, recipe["E"])
        hld, hbd = jax.device_put(hl), jax.device_put(hb)
        compileaudit.record_h2d("limbs", int(hld.nbytes
                                             + hbd.nbytes))
    hvd, hmd, htd = (jax.device_put(hv), jax.device_put(hm),
                     jax.device_put(ht))
    compileaudit.record_h2d("slab", int(
        hvd.nbytes + hmd.nbytes + htd.nbytes))
    return hvd, hmd, htd, [b for b, _c, _s, _t in hsegs], hld, hbd


def _recipe_perms(recipe: dict, B: int):
    """(values perm, times/valid perm): meta index → flat position in
    the concatenated batch outputs (padded batch rows are never
    selected)."""
    perm = np.zeros(B, dtype=np.int32)
    pos = 0
    from . import device_decode as dd
    for _wd, _rd, _w, _tr, _ds, _r, idxs in recipe["dfor"]:
        for j, b in enumerate(idxs):
            perm[b] = pos + j
        pos += dd.pad_pow2(len(idxs), 8)
    for _pv, _pl, _rw, idxs in recipe.get("rle", ()):
        for j, b in enumerate(idxs):
            perm[b] = pos + j
        pos += dd.pad_pow2(len(idxs), 8)
    if recipe["const"] is not None:
        _cv, _cr, idxs = recipe["const"]
        for j, b in enumerate(idxs):
            perm[b] = pos + j
        pos += dd.pad_pow2(len(idxs), 8)
    hidxs = [b for b, _c, _s, _t in recipe["hsegs"]]
    for j, b in enumerate(hidxs):
        perm[b] = pos + j
    pos += len(hidxs)
    tperm = np.zeros(B, dtype=np.int32)
    tb = recipe["tbatch"]
    tpos = 0
    if tb is not None:
        idxs = tb[5]
        for j, b in enumerate(idxs):
            tperm[b] = tpos + j
        tpos += dd.pad_pow2(len(idxs), 8)
    for j, b in enumerate(hidxs):
        tperm[b] = tpos + j
    return perm, tperm


def _expand_recipe(recipe: dict, reader, field: str,
                   guarded: bool = True):
    """Run the expansion kernels of one staged/recipe'd slab →
    (BlockStack with full-K limbs, (K,) activity flags). Shared by
    the first build and the compressed-tier rebuild (which re-enters
    with the SAME device-resident payloads and therefore zero H2D).
    Expand launches ride breaker route \"block\" under the PR 9 fault
    ladder at the ``device.decode.launch`` failpoint; a batch whose
    ladder exhausts heals through the host stage per block."""
    import jax

    from . import compileaudit, device_decode as dd, exactsum
    from .devicefault import DeviceRouteDown, guarded_launch

    import jax.numpy as jnp

    seg = recipe["seg"]
    E = recipe["E"]
    pred = recipe.get("pred")
    int_mode = bool(recipe.get("int"))

    def _launch(fn):
        if not guarded:
            return fn()
        return guarded_launch("block", fn,
                              site="device.decode.launch",
                              success_resets=False)

    def _pd_launch(fn):
        # pushdown mask launches carry their own failpoint: a sick
        # mask kernel heals THIS batch to expand-then-filter while
        # the plain decode ladder stays untouched
        if not guarded:
            return fn()
        return guarded_launch("block", fn,
                              site="device.pushdown.eval",
                              success_resets=False)

    from ..encoding import dfor as _dfm
    val_parts: list = []
    mask_parts: list = []          # pred survivor masks, values order
    part_rows: list = []           # padded batch heights, values order
    limb_parts: list = []          # int mode: limb/bad planes instead
    bad_parts: list = []           # of an f64 values plane
    pdmask = recipe.get("pdmask") or []
    pdmask = list(pdmask) + [None] * (len(recipe["dfor"])
                                      - len(pdmask))
    for (wd, rd, w, tr, ds, r, idxs), plan in zip(recipe["dfor"],
                                                  pdmask):
        nb_pad = wd.shape[0]
        mk = None
        if int_mode:
            # expand the zigzag-delta integer k itself and window its
            # bits (ops/device_decode.int_limbs_batch) — all-integer,
            # exact on f32-pair-emulated backends; T_SCALED dscale-0
            # groups share the T_INT arithmetic (_int_block_ok admits
            # only those)
            try:
                k = _launch(lambda: dd.fit_rows(dd.dfor_expand(
                    wd, rd, n=r, width=w, transform=_dfm.T_INT,
                    dscale=0, kind="i64"), seg))
                lb = _launch(lambda: dd.int_limbs_batch(k, E=E))
                bd = jnp.zeros((nb_pad, seg), dtype=jnp.bool_)
                if plan is not None and plan[0] == "int":
                    try:
                        mk = _pd_launch(lambda: dd.k_mask(
                            k, plan[2], sig=plan[1]))
                        dd._bump("pushdown_blocks_masked", len(idxs))
                    except DeviceRouteDown:
                        mk = _heal_mask_only(reader, recipe["refs"],
                                             idxs, nb_pad, seg, pred)
                elif plan is not None:
                    # int-eligible groups always translate — this is
                    # unreachable paranoia, healed on host
                    mk = _heal_mask_only(reader, recipe["refs"],
                                         idxs, nb_pad, seg, pred)
                dd._bump("dfor_blocks", len(idxs))
            except DeviceRouteDown:
                lb, bd, mk = _heal_limbs(
                    reader, recipe["refs"], idxs, nb_pad, seg, E,
                    pred if plan is not None else None)
            limb_parts.append(lb)
            bad_parts.append(bd)
        elif plan is not None:
            # ONE launch expands values AND evaluates the packed
            # predicate on the un-decoded integer k (mode "int") or
            # the decoded plane (mode "f64" — XOR fallback)
            try:
                out, mk = _pd_launch(lambda: tuple(
                    dd.fit_rows(x, seg) for x in dd.dfor_expand_pred(
                        wd, rd, plan[2], n=r, width=w, transform=tr,
                        dscale=ds, mode=plan[0], sig=plan[1])))
                dd._bump("dfor_blocks", len(idxs))
                dd._bump("pushdown_blocks_masked", len(idxs))
            except DeviceRouteDown:
                out, mk = _heal_mask(reader, recipe["refs"], idxs,
                                     nb_pad, seg, pred)
            val_parts.append(out)
        else:
            try:
                out = _launch(lambda: dd.fit_rows(dd.dfor_expand(
                    wd, rd, n=r, width=w, transform=tr, dscale=ds,
                    kind="f64"), seg))
                dd._bump("dfor_blocks", len(idxs))
            except DeviceRouteDown:
                out = _heal_batch(reader, recipe["refs"], idxs,
                                  wd.shape[0], seg)
            val_parts.append(out)
        mask_parts.append(mk)
        part_rows.append(nb_pad)
    for (pvd, pld, rrd, idxs) in recipe.get("rle", ()):
        # device RLE expansion (round 18): cumsum over run lengths —
        # the run payload crossed H2D, never the expanded rows
        nb_pad = pvd.shape[0]
        mk = None
        try:
            out = _launch(lambda: dd.rle_expand_batch(pvd, pld, rrd,
                                                      seg))
            dd._bump("rle_blocks", len(idxs))
        except DeviceRouteDown:
            out = _heal_batch(reader, recipe["refs"], idxs, nb_pad,
                              seg)
        if pred is not None:
            # runs are not frame-of-reference packed: post-expand
            # f64 mask, same compares as the escape hatch
            try:
                mk = _pd_launch(lambda: dd.plane_mask(
                    out, recipe["pdf"], sig=pred.sig))
                dd._bump("pushdown_blocks_masked", len(idxs))
            except DeviceRouteDown:
                mk = _heal_mask_only(reader, recipe["refs"], idxs,
                                     nb_pad, seg, pred)
        val_parts.append(out)
        mask_parts.append(mk)
        part_rows.append(nb_pad)
    if recipe["const"] is not None:
        cvd, crd, idxs = recipe["const"]
        try:
            out = _launch(lambda: dd.const_expand_batch(cvd, crd,
                                                        seg))
            dd._bump("const_blocks", len(idxs))
        except DeviceRouteDown:
            out = _heal_batch(reader, recipe["refs"], idxs,
                              cvd.shape[0], seg)
        val_parts.append(out)
        # surviving CONST blocks classified "all" — never masked
        mask_parts.append(None)
        part_rows.append(cvd.shape[0])
    host_planes = None
    if recipe["host"] is not None:
        # host-stage blocks re-decode + upload HERE on every expand:
        # keeping their dense planes in the compressed tier would
        # make it exactly as heavy as the decoded tier it rebuilds
        # (pred rows were already masked onto their valid plane)
        host_planes = _restage_host(reader, recipe)
        val_parts.append(host_planes[0])
        mask_parts.append(None)
        part_rows.append(host_planes[0].shape[0])
        if int_mode:
            limb_parts.append(host_planes[4])
            bad_parts.append(host_planes[5])
    if recipe.get("meta_dev") is None:
        # per-slab device metadata uploads ONCE — the recipe keeps
        # them resident so a compressed-tier rebuild moves 0 bytes
        md = (jax.device_put(np.float64(recipe["block0"])),
              jax.device_put(recipe["tmin"]),
              jax.device_put(recipe["steps"]),
              jax.device_put(recipe["rows"].astype(np.int32)),
              jax.device_put(recipe["perm"]),
              jax.device_put(recipe["tperm"]))
        compileaudit.record_h2d("payload",
                                sum(int(a.nbytes) for a in md))
        recipe["meta_dev"] = md
    block0_d, t0min_d, steps_d, rows32_d, perm_d, tperm_d = \
        recipe["meta_dev"]
    values = None
    if not int_mode:
        values = dd.permute_blocks(
            val_parts[0] if len(val_parts) == 1
            else jnp.concatenate(val_parts, axis=0), perm_d)

    t0d, stpd, drwd, bitd, cfd, dev_idxs = recipe["tbatch"]
    dd._bump("time_blocks", len(dev_idxs))
    times_parts = [_launch(lambda: dd.times_expand_batch(
        t0d, stpd, drwd, seg))]
    valid_parts = [_launch(lambda: dd.validity_expand_batch(
        bitd, cfd, drwd, seg))]
    if host_planes is not None:
        times_parts.append(host_planes[2])
        valid_parts.append(host_planes[1])
    times = dd.permute_blocks(
        times_parts[0] if len(times_parts) == 1
        else jnp.concatenate(times_parts, axis=0), tperm_d)
    valid = dd.permute_blocks(
        valid_parts[0] if len(valid_parts) == 1
        else jnp.concatenate(valid_parts, axis=0), tperm_d)

    if any(m is not None for m in mask_parts):
        # the packed-predicate survivor mask lands on the VALID plane
        # BEFORE limb decomposition: every downstream kernel (staged
        # lattice, fused whole-plan, min/max, count) sees only
        # surviving lanes without knowing pushdown exists
        mparts = [m if m is not None
                  else jnp.ones((nb, seg), dtype=jnp.bool_)
                  for m, nb in zip(mask_parts, part_rows)]
        mask_full = dd.permute_blocks(
            mparts[0] if len(mparts) == 1
            else jnp.concatenate(mparts, axis=0), perm_d)
        valid = dd.and_planes(valid, mask_full)

    if int_mode:
        limbs_cat = (limb_parts[0] if len(limb_parts) == 1
                     else jnp.concatenate(limb_parts, axis=0))
        bad_cat = (bad_parts[0] if len(bad_parts) == 1
                   else jnp.concatenate(bad_parts, axis=0))
        limbs, bad, act = _launch(lambda: dd.mask_limbs_batch(
            dd.permute_blocks(limbs_cat, perm_d),
            dd.permute_blocks(bad_cat, perm_d), valid))
        dd._bump("int_limb_slabs")
    else:
        scale0 = dd.limb_scale_dev(E)
        limbs, bad, act = _launch(
            lambda: dd.limbs_decompose(values, valid, scale0))

    st = BlockStack(reader.path, field, seg, E, recipe["sids"],
                    recipe["refs"], recipe["n_rows"], recipe["tmin"],
                    recipe["tmax"], recipe["block0"])
    st.values = values
    st.valid = valid
    st.times = times
    st.limbs = limbs                  # full K — get_stacks slices
    st.bad = bad
    st.block0_dev = block0_d
    st.t_rows = recipe["rows"]
    st.all_const = recipe["all_const"]
    st.t0_dev = t0min_d
    st.step_dev = steps_d
    st.rows_dev = rows32_d
    st.int_only = int_mode
    return st, act


def _heal_batch(reader, seg_refs, idxs, nb_pad: int, seg: int):
    """Per-block host-decode heal of ONE faulted expand batch: the
    same dense rows the device would have produced, decoded by the
    host stage and uploaded (site \"slab\"). ``seg_refs`` is the
    recipe's per-block (colmeta, segment) list, so the heal works on
    first builds AND compressed-tier rebuilds alike."""
    import jax

    from . import compileaudit, device_decode as dd
    hv = np.zeros((nb_pad, seg), dtype=np.float64)
    for j, b in enumerate(idxs):
        colm, s = seg_refs[b]
        if s.rows:
            cv = reader.read_segment(colm, s)
            hv[j, :s.rows] = cv.values.astype(np.float64, copy=False)
    hvd = jax.device_put(hv)
    compileaudit.record_h2d("slab", int(hvd.nbytes))
    dd._bump("host_heals", len(idxs))
    return hvd


def _slice_limb_range(limbs_dev, k0: int, k1: int):
    """Device row-select of the active limb-plane range (the host
    build uploads only [k0, k1); the device build decomposed all K
    and slices once the file-wide range is known)."""
    import jax.numpy as jnp
    K = int(limbs_dev.shape[2])
    if k0 == 0 and k1 == K:
        return limbs_dev
    key = ("lslice", K, k0, k1)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(x):
            return x[:, :, k0:k1]
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(limbs_dev)


def dense_fill_compressed(sources, field: str, P: int, E):
    """Decoded-plane devicecache fill for one dense (S, P) group
    straight from COMPRESSED DFOR payloads (round 18): the packed word
    lanes cross H2D (sites ``dfor``/``payload``), expansion runs in
    the shared dfor_expand kernel classes, and ONE layout-keyed
    assembly launch trims/reshapes the segments to the (S, P) planes —
    with the (S, P, K) limb decomposition fused in when the query
    needs exact sums (``E`` is not None). The dense H2D upload the
    host fill would pay never happens.

    Returns (vals_dev, valid_dev, limbs_dev | None, bad_any) or None
    when ANY segment is ineligible — non-DFOR codec, bitmapped
    validity (nulls), non-FLOAT column, header/rows mismatch, or a
    non-f64 stage mode — in which case the caller takes the classic
    host assembly upload, byte-identical planes either way. Values are
    bit-identical to the host decode (dfor_expand's pinned parity) and
    the limb planes to exactsum.host_limbs (limbs_stage's pinned
    parity), so downstream dense reductions cannot tell the fills
    apart."""
    import jax
    import jax.numpy as jnp

    from ..encoding import blocks as EBL
    from ..encoding import dfor as _dfm
    from ..query import decodestage
    from ..record import DataType
    from . import compileaudit, device_decode as dd
    if decodestage.stage_mode() != "f64" or not sources:
        return None
    segs = []
    for (reader, cm, si, lo, f) in sources:
        colm = cm.column(field)
        if colm is None or colm.type != DataType.FLOAT:
            return None
        s = colm.segments[si]
        mm = reader._mm
        if s.rows == 0 or mm[s.offset] != EBL.DFOR:
            return None
        if mm[s.valid_offset] != EBL.CONST:
            return None          # bitmapped nulls → host assembly
        hdr = mm[s.offset + 1:s.offset + 1 + _dfm.HEADER_BYTES]
        tr, w, ds, n_hdr, ref = _dfm.parse_header(hdr)
        if n_hdr != s.rows:
            return None
        nw = (s.rows * w + 31) // 32
        # zero-staging: view over the mmap, copied into wmat below
        words = np.frombuffer(
            memoryview(mm)[s.offset + 1 + _dfm.HEADER_BYTES:
                           s.offset + 1 + _dfm.HEADER_BYTES + 4 * nw],
            dtype="<u4")
        segs.append((w, tr, ds, int(s.rows), ref, int(lo), int(f),
                     words))
    # batch same-shape segments into shared dfor_expand classes; the
    # assembly order (and hence the (S, P) row order) is the sources
    # order, exactly like the host run_dense concatenation
    groups: dict = {}
    order = []                     # (group_key, row_in_group, lo, f)
    for (w, tr, ds, r, ref, lo, f, words) in segs:
        gk = (w, tr, ds, r)
        lst = groups.setdefault(gk, [])
        order.append((gk, len(lst), lo, f))
        lst.append((ref, words))
    gkeys = sorted(groups)
    outs = []
    for gk in gkeys:
        w, tr, ds, r = gk
        blks = groups[gk]
        nb_pad = dd.pad_pow2(len(blks), 8)
        nw = (r * w + 31) // 32
        wmat = np.zeros((nb_pad, nw + 2), dtype=np.uint32)
        rvec = np.zeros(nb_pad, dtype=np.uint64)
        for i, (ref, words) in enumerate(blks):
            wmat[i, :nw] = words
            rvec[i] = ref
        wd = jax.device_put(wmat)
        rd = jax.device_put(rvec)
        compileaudit.record_h2d("dfor", int(wd.nbytes))
        compileaudit.record_h2d("payload", int(rd.nbytes))
        outs.append(dd.dfor_expand(wd, rd, n=r, width=w,
                                   transform=tr, dscale=ds,
                                   kind="f64"))
    gidx = {gk: i for i, gk in enumerate(gkeys)}
    layout = tuple((gidx[gk], i, lo, f) for gk, i, lo, f in order)
    key = ("densefill", P, E is not None, layout)
    fn = _JITTED.get(key)
    if fn is None:
        K = exactsum.K_LIMBS

        def _f(parts, s0):
            vals = jnp.concatenate(
                [parts[gi][i, lo:lo + f * P].reshape(f, P)
                 for (gi, i, lo, f) in layout], axis=0)
            valid = jnp.ones(vals.shape, dtype=jnp.bool_)
            if s0 is None:
                return vals, valid, None, jnp.zeros((), jnp.bool_)
            limbs, bad, _act = dd.limbs_stage(vals, valid, s0, K=K)
            return vals, valid, limbs, bad.any()
        fn = _JITTED[key] = _named_jit(
            _f, ("densefill", P, len(layout)))
    s0 = dd.limb_scale_dev(E) if E is not None else None
    dv, dm, dl, bad = fn(tuple(outs), s0)
    bad_any = bool(np.asarray(bad))
    compileaudit.record_d2h("decode", 1)
    dd._bump("dense_fills_compressed")
    return dv, dm, dl, bad_any


def get_stacks(reader, field: str,
               pred=None) -> list[BlockStack] | None:
    """Cached slab list for (file, field); None when the column can't
    stack (missing, non-float) — negative results cache too. The
    decode stage is pluggable per block (query/decodestage.py): when
    the device stage serves a file, compressed payloads cross H2D and
    expand in-kernel, and the payload recipe stakes into the
    compressed HBM tier so a later slab eviction rebuilds with ZERO
    H2D; OG_DEVICE_DECODE=0 (or any ineligible file/backend) takes
    the classic host build below, byte-identical planes either way."""
    if not devicecache.enabled():
        return None
    from ..query import decodestage
    int_mode = decodestage.stage_mode() == "int"
    sfx: tuple = ("int",) if int_mode else ()
    if pred is not None:
        sfx += ("pd", pred.key)
    cache = devicecache.global_cache()
    key = (reader.path, field, "blockslabs") + sfx
    got = cache.get(key)
    if got is _NO_STACK:
        return None
    if got is not None:
        return got
    slabs = _stacks_from_compressed(reader, field, sfx)
    if slabs is None:
        layout = _file_layout(reader, field)
        if layout is None:
            cache.put(key, _NO_STACK)
            return None
        metas, seg, E = layout
        if pred is not None:
            # envelope pre-filter: wholly-outside segments never
            # batch, upload, or expand (counters feed the perf_smoke
            # selectivity gate)
            metas = _classify_metas(reader, pred, metas)
            if not metas:
                # every segment skipped: an EMPTY slab list (not
                # None) — the caller still consumes the sources
                cache.put(key, [])
                return []
            layout = (metas, seg, E)
        slabs = _build_stacks_device(reader, field, metas, seg, E,
                                     sfx, pred=pred,
                                     int_mode=int_mode)
    if slabs is None:
        metas, seg, E = layout
        built = []
        block0 = 0
        K = exactsum.K_LIMBS
        k0, k1 = K, 0
        for i in range(0, len(metas), SLAB_BLOCKS):
            st, limbs = _build_slab(reader, field,
                                    metas[i:i + SLAB_BLOCKS], seg, E,
                                    block0, pred=pred)
            # file-wide active limb-plane range (plane k is dead iff
            # every row's k-th limb is 0 — dead planes sum to 0, so
            # skipping them is exact)
            for k in range(K):
                if limbs[..., k].any():
                    k0 = min(k0, k)
                    k1 = max(k1, k + 1)
            built.append((st, limbs))
            block0 += st.n_blocks
        if k0 >= k1:
            k0, k1 = 0, 1        # all-zero column: keep one plane
        slabs = []
        for st, limbs in built:
            _upload_limbs(st, limbs, k0, k1)
            slabs.append(st)
        built = None
    cache.put(key, slabs)
    # account the real HBM footprint (a slab LIST has no .nbytes, so
    # put() staked a 64-byte placeholder) — reprice mirrors the charge
    # into the HBM ledger too (ops/hbm.py)
    cache.reprice(key, sum(s.nbytes for s in slabs))
    from . import device_decode as _dd, devstats
    # rows that actually expanded/staged — the packed-predicate diet
    # shrinks this vs an OG_PACKED_PREDICATE=0 run of the same query
    # (bench's selectivity gate divides the two)
    _dd._bump("pushdown_lanes_expanded",
              sum(s.n_rows for s in slabs))
    devstats.bump("slabs_built", len(slabs))
    devstats.bump("slab_bytes", sum(s.nbytes for s in slabs))
    return slabs


def _build_stacks_device(reader, field: str, metas, seg: int,
                         E: int, sfx: tuple = (), pred=None,
                         int_mode: bool = False
                         ) -> list[BlockStack] | None:
    """Device-decode build of a whole (file, field): slabs expand from
    compressed payloads in-kernel, limb planes decompose on device,
    and the payload recipes stake into the compressed HBM tier. None
    → caller takes the host build (stage ineligible, mostly-legacy
    codecs, or the decode ladder exhausted beyond per-batch heal)."""
    import time as _time

    from ..query import decodestage
    from . import compileaudit, device_decode as dd, devstats
    from .devicefault import DeviceRouteDown
    if not decodestage.device_stage_available():
        return None
    mm = reader._mm
    # per-SLAB eligibility, decided BEFORE any device work: a slab
    # window with zero device-decodable blocks would abort the build
    # mid-file (_AllHostSlab) after earlier slabs already uploaded
    # and expanded — paying the device build AND the host rebuild.
    # Checking the windows up front keeps ineligible files on the
    # host path for free.
    n_dev = 0
    for i in range(0, len(metas), SLAB_BLOCKS):
        window = metas[i:i + SLAB_BLOCKS]
        w_dev = sum(
            1 for (_sid, _colm, s, tseg) in window
            if s.rows and decodestage.block_stage(
                mm[s.offset], mm[tseg.offset]) == "device"
            and (not int_mode or _int_block_ok(mm, s, E)))
        if w_dev == 0:
            return None      # an all-host slab window: host build
        n_dev += w_dev
    if n_dev * 2 < len(metas):
        return None          # mostly legacy codecs: host build wins
    t_ns = _time.perf_counter_ns()
    built: list = []
    recipes: list = []
    block0 = 0
    try:
        for i in range(0, len(metas), SLAB_BLOCKS):
            st, act, rec = _build_slab_device(
                reader, field, metas[i:i + SLAB_BLOCKS], seg, E,
                block0, pred=pred, int_mode=int_mode)
            built.append((st, act))
            recipes.append(rec)
            block0 += st.n_blocks
    except _AllHostSlab:
        return None
    except DeviceRouteDown:
        # ladder exhausted outside the per-batch heal (times/valid/
        # limb launches): the whole file falls back to the host build
        return None
    K = exactsum.K_LIMBS
    k0, k1 = K, 0
    for _st, act in built:
        a = np.asarray(act)               # (K,) bools — one tiny pull
        compileaudit.record_d2h("decode", int(a.nbytes))
        for k in range(K):
            if a[k]:
                k0 = min(k0, k)
                k1 = max(k1, k + 1)
    if k0 >= k1:
        k0, k1 = 0, 1
    slabs = []
    for (st, _act), rec in zip(built, recipes):
        st.limbs = _slice_limb_range(st.limbs, k0, k1)
        st.k0 = k0
        rec["k0"], rec["k1"] = k0, k1
        slabs.append(st)
    _stake_compressed(reader, field, recipes, sfx)
    dd._bump("slabs_device_decoded", len(slabs))
    devstats.bump_phase("device_decode",
                        _time.perf_counter_ns() - t_ns)
    return slabs


def _recipe_nbytes(recipes: list) -> int:
    """HBM bytes a recipe holds RESIDENT: payload words/refs, the
    tiny time/validity batch vectors, and the perm tables. The
    per-slab meta arrays (block0/t0/steps/rows — meta_dev[:4]) are
    the SAME buffers BlockStack.nbytes already charges to the
    device_cache tier, so counting them here would double-book them
    in the ledger; host-stage planes are deliberately not resident
    at all (_stage_host_blocks)."""
    nb = 0
    for rec in recipes:
        for (wd, rd, _w, _tr, _ds, _r, _i) in rec["dfor"]:
            nb += int(wd.nbytes + rd.nbytes)
        for (pvd, pld, rrd, _i) in rec.get("rle", ()):
            nb += int(pvd.nbytes + pld.nbytes + rrd.nbytes)
        if rec["const"] is not None:
            nb += int(rec["const"][0].nbytes + rec["const"][1].nbytes)
        for plan in rec.get("pdmask") or ():
            if plan is not None:
                nb += int(plan[2].nbytes)
        if rec.get("pdf") is not None:
            nb += int(rec["pdf"].nbytes)
        if rec["tbatch"] is not None:
            nb += sum(int(a.nbytes) for a in rec["tbatch"][:5])
        if rec.get("meta_dev") is not None:
            nb += sum(int(a.nbytes) for a in rec["meta_dev"][4:])
    return nb


def _stake_compressed(reader, field: str, recipes: list,
                      sfx: tuple = ()) -> None:
    """Stake a file's payload recipes into the compressed HBM tier:
    the device-resident words/refs/metadata that can rebuild every
    slab with zero H2D after a decoded-tier eviction (the relief
    ladder evicts decoded planes FIRST for exactly this reason).
    ``sfx`` distinguishes pred-masked / int-mode recipe sets."""
    comp = devicecache.compressed_cache()
    comp.put_sized((reader.path, field, "dforrecipe") + sfx, recipes,
                   _recipe_nbytes(recipes))


def _stacks_from_compressed(reader, field: str, sfx: tuple = ()
                            ) -> list[BlockStack] | None:
    """Rebuild a file's slabs from the compressed HBM tier: the
    decoded planes were evicted but the payload bytes stayed device-
    resident, so the rebuild is expansion kernels only — zero H2D for
    the device-stage blocks (manifest-delta-asserted in
    tests/test_compressed_domain.py); host-stage blocks of mixed
    files re-decode + re-upload lazily (their dense planes are
    deliberately NOT kept resident — see _stage_host_blocks)."""
    import time as _time

    from ..query import decodestage
    from . import device_decode as dd, devstats
    from .devicefault import DeviceRouteDown
    if not decodestage.device_stage_available():
        return None
    recipes = devicecache.compressed_cache().get(
        (reader.path, field, "dforrecipe") + sfx)
    if recipes is None:
        return None
    t_ns = _time.perf_counter_ns()
    slabs = []
    try:
        for rec in recipes:
            st, _act = _expand_recipe(rec, reader, field,
                                      guarded=True)
            st.limbs = _slice_limb_range(st.limbs, rec["k0"],
                                         rec["k1"])
            st.k0 = rec["k0"]
            slabs.append(st)
    except DeviceRouteDown:
        return None                  # heal: full host rebuild
    # counted only once the rebuild actually SERVED (a ladder-downed
    # rebuild above fell back to the host build and served nothing)
    dd._bump("compressed_hits")
    dd._bump("compressed_rebuilds", len(slabs))
    devstats.bump_phase("device_decode",
                        _time.perf_counter_ns() - t_ns)
    return slabs


class _NoStack:
    nbytes = 0


_NO_STACK = _NoStack()


_JITTED: dict = {}


def _named_jit(fn, key: tuple):
    """jit-wrap a factory kernel under a stable, human-readable name
    derived from its cache key. Nine factories otherwise share the
    closure name ``_f``/``_p`` — the compile auditor's log
    (ops/compileaudit.py) would blur every variant into one row, and
    a duplicate-compile of one variant could hide behind another's
    first compile. The name is what jax prints in "Compiling <name>
    with global shapes ..."."""
    import jax
    parts = []
    for part in key:
        if isinstance(part, (tuple, list)):
            parts.append("-".join(map(str, part)) or "none")
        else:
            parts.append(str(part))
    name = "og_" + "_".join(parts).replace(" ", "")
    fn.__name__ = name
    fn.__qualname__ = name
    return jax.jit(fn)


# windows per query above which the unrolled masked-pass kernel would
# bloat the graph; those shapes fall back to the scatter kernel
MASK_W_MAX = int(knobs.get("OG_BLOCK_MASK_W"))

# f64-exact sentinel for "no row" index planes (I64MAX is not exactly
# representable in f64; 2^62 is, and no real flat index reaches it)
IDX_SENTINEL = float(2 ** 62)


def plane_layout(want: tuple, K: int) -> list[tuple[str, int]]:
    """Static layout of the ONE packed (P, num_segments) f64 output:
    every per-cell state is a plane so a query pulls a single array
    over the slow D2H link (per-transfer latency ≈ 0.1-0.25s measured
    on the tunnel-attached chip — leaf count, not bytes, dominates)."""
    planes = [("count", 1)]
    if "sum" in want:
        planes += [("limbs", K), ("bad", 1)]
    if "sumsq" in want:
        planes.append(("sumsq", 1))
    if "min" in want:
        planes += [("min", 1), ("min_idx", 1)]
    if "max" in want:
        planes += [("max", 1), ("max_idx", 1)]
    return planes


def pruned_layout(want: tuple, K: int) -> list[tuple[str, int]]:
    """plane_layout minus the min/max VALUE planes — the op-aware diet
    of the legacy f64 transport (the executor's fold only ever reads
    the row-INDEX planes; exact values gather host-side), applied when
    OG_DEVICE_FINALIZE is on. The full layout stays the =0 wire
    format, byte for byte."""
    return [(name, n) for name, n in plane_layout(want, K)
            if name not in ("min", "max")]


def unpack_planes(packed: np.ndarray, want: tuple, K: int,
                  k0: int = 0, K_full: int | None = None,
                  pruned: bool = False) -> dict:
    """Host-side view of the pulled packed array as the bo dict the
    executor folds (exact dtype restoration: counts/limbs are integer-
    valued f64 < 2^53). K is the resident (active) plane count; the
    limbs re-expand to K_full with zero dead planes. ``pruned`` reads
    the op-aware pruned_layout (no min/max value planes)."""
    if K_full is None:
        K_full = exactsum.K_LIMBS
    out = {}
    i = 0
    layout = pruned_layout(want, K) if pruned else plane_layout(want, K)
    for name, n in layout:
        pl = packed[i:i + n]
        i += n
        if name == "count":
            out["count"] = pl[0].astype(np.int64)
        elif name == "limbs":
            full = np.zeros((pl.shape[1], K_full))
            full[:, k0:k0 + K] = pl.T
            out["limbs"] = full                        # (S, K_full) f64
        elif name == "bad":
            out["bad"] = pl[0] > 0
        elif name in ("min_idx", "max_idx"):
            # convert in int space: mixing I64MAX into a FLOAT where()
            # would round it to 2^63 and overflow the int64 cast to
            # I64MIN (negative → Python list indexing disaster)
            p = pl[0]
            real = np.isfinite(p) & (p < IDX_SENTINEL) & (p >= 0)
            iv = np.where(real, p, 0.0).astype(np.int64)
            out[name] = np.where(real, iv, I64MAX)
        else:
            out[name] = pl[0]
    return out


def _mask_stage(values, valid, times, limbs, bad, gids, block0,
                scalars, *, num_segments: int, want: tuple,
                W: int, K: int, SEG: int):
    """Trace-composable body of _kernel (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax
    import jax.numpy as jnp

    ns = num_segments + 1
    use_mask = W <= MASK_W_MAX
    t_lo, t_hi, start, interval = (scalars[0], scalars[1],
                                   scalars[2], scalars[3])
    # shape/index sources come from the VALID plane: int-mode slabs
    # (OG_LIMB_INT, round 18) carry values=None — the executor gates
    # their wants to count/sum, so values is only ever touched under
    # sumsq/min/max
    B = valid.shape[0]
    m0 = (valid & (times >= t_lo) & (times <= t_hi)
          & (gids >= 0)[:, None])
    wid = (times - start) // interval
    m0 = m0 & (wid >= 0) & (wid < W)
    lbf = limbs.astype(jnp.float64) if "sum" in want else None
    planes = []

    if use_mask:
        wid32 = wid.astype(jnp.int32)
        gidx = (block0 * SEG
                + jnp.arange(B * SEG, dtype=jnp.float64).reshape(
                    valid.shape))
        st1 = {k: [] for k in ("count", "limbs", "bad", "sumsq",
                               "min", "min_idx", "max", "max_idx")}
        for w in range(W):
            mw = m0 & (wid32 == w)
            st1["count"].append(mw.sum(axis=1, dtype=jnp.float32)
                                .astype(jnp.float64))
            if "sum" in want:
                st1["limbs"].append(jnp.where(
                    mw[:, :, None], lbf, 0.0).sum(axis=1))
                st1["bad"].append((mw & bad).any(axis=1)
                                  .astype(jnp.float64))
            if "sumsq" in want:
                vz = jnp.where(mw, values, 0.0)
                st1["sumsq"].append((vz * vz).sum(axis=1))
            has_rows = mw.any(axis=1)
            if "min" in want:
                vm = jnp.where(mw, values, jnp.inf)
                mn = vm.min(axis=1)
                st1["min"].append(mn)
                # mask on row presence, not finiteness: a stored
                # +/-inf value is a REAL extremum whose index must
                # survive (only truly empty windows drop to the
                # sentinel); masked-out rows can't win the == test
                # because mw-false positions hold the identity
                ix = jnp.where(mw & (values == mn[:, None]), gidx,
                               IDX_SENTINEL).min(axis=1)
                st1["min_idx"].append(
                    jnp.where(has_rows, ix, IDX_SENTINEL))
            if "max" in want:
                vm = jnp.where(mw, values, -jnp.inf)
                mx = vm.max(axis=1)
                st1["max"].append(mx)
                ix = jnp.where(mw & (values == mx[:, None]), gidx,
                               IDX_SENTINEL).min(axis=1)
                st1["max_idx"].append(
                    jnp.where(has_rows, ix, IDX_SENTINEL))
        # stage 2: scatter (B*W) partials onto the cell grid
        seg2 = (gids.astype(jnp.int32)[:, None] * W
                + jnp.arange(W, dtype=jnp.int32)[None, :])
        seg2 = jnp.where(gids[:, None] >= 0, seg2,
                         num_segments).reshape(-1)

        def sc_sum(x):
            return jax.ops.segment_sum(x, seg2, ns)[:num_segments]

        def sc_min(x):
            return jax.ops.segment_min(x, seg2, ns)[:num_segments]

        def sc_max(x):
            return jax.ops.segment_max(x, seg2, ns)[:num_segments]

        def flat(name):
            return jnp.stack(st1[name], axis=1).reshape(-1)

        planes.append(sc_sum(flat("count")))
        if "sum" in want:
            lw = jnp.stack(st1["limbs"], axis=1).reshape(-1, K)
            for k in range(K):
                planes.append(sc_sum(lw[:, k]))
            planes.append(sc_max(flat("bad")))
        if "sumsq" in want:
            planes.append(sc_sum(flat("sumsq")))
        if "min" in want:
            mn = sc_min(flat("min"))
            win = flat("min") == mn[seg2.reshape(gids.shape[0], W)
                                    ].reshape(-1)
            ix = sc_min(jnp.where(win, flat("min_idx"),
                                  IDX_SENTINEL))
            planes += [mn, ix]
        if "max" in want:
            mx = sc_max(flat("max"))
            win = flat("max") == mx[seg2.reshape(gids.shape[0], W)
                                    ].reshape(-1)
            ix = sc_min(jnp.where(win, flat("max_idx"),
                                  IDX_SENTINEL))
            planes += [mx, ix]
        return jnp.stack(planes)

    # scatter fallback for wide windows (rare under the cell cap):
    # i32 segment ids + f64 accumulators — the round-2 int64
    # scatters hit the 64-bit emulation path and were ~60× slower
    n = valid.shape[0] * SEG
    v = values.reshape(n) if values is not None else None
    m = m0.reshape(n)
    lb = limbs.reshape(n, K) if "sum" in want else None
    bd = bad.reshape(n)
    g32 = jnp.repeat(gids.astype(jnp.int32), SEG)
    seg = jnp.where(m, g32 * W + wid.reshape(n).astype(jnp.int32),
                    num_segments)
    planes.append(jax.ops.segment_sum(
        m.astype(jnp.float64), seg, ns)[:num_segments])
    if "sum" in want:
        for k in range(K):
            planes.append(jax.ops.segment_sum(
                jnp.where(m, lb[:, k], 0).astype(jnp.float64),
                seg, ns)[:num_segments])
        planes.append(jax.ops.segment_max(
            (m & bd).astype(jnp.float32), seg, ns)[:num_segments]
            .astype(jnp.float64))
    if "sumsq" in want:
        vz = jnp.where(m, v, 0.0)
        planes.append(jax.ops.segment_sum(vz * vz, seg,
                                          ns)[:num_segments])
    gidx = jnp.arange(n, dtype=jnp.float64) + block0 * SEG
    if "min" in want:
        ext = jax.ops.segment_min(jnp.where(m, v, jnp.inf), seg, ns)
        at = m & (v == ext[seg])
        planes += [ext[:num_segments],
                   jax.ops.segment_min(
                       jnp.where(at, gidx, IDX_SENTINEL), seg,
                       ns)[:num_segments]]
    if "max" in want:
        ext = jax.ops.segment_max(jnp.where(m, v, -jnp.inf), seg, ns)
        at = m & (v == ext[seg])
        planes += [ext[:num_segments],
                   jax.ops.segment_min(
                       jnp.where(at, gidx, IDX_SENTINEL), seg,
                       ns)[:num_segments]]
    return jnp.stack(planes)


def _kernel(num_segments: int, want: tuple, W: int, K: int, SEG: int):
    """Per-slab reduction → ONE packed (P, num_segments) f64 array.

    TPU-first formulation (the round-2 kernel used flat
    jax.ops.segment_sum scatters — measured 8.2s over 12.7M rows on the
    v5e because large unsorted scatters don't tile; the masked-pass
    form below does the same reduction in 0.125s):
      stage 1: for each window w (static unroll, W ≤ MASK_W_MAX), a
        masked dense reduction over the segment axis → (B, W) partials.
        Pure axis reductions — the same VPU mapping as
        dense_window_aggregate, no scatter over the big axis.
      stage 2: one tiny scatter of B*W partials onto the (G*W) grid.
    Counts/limbs accumulate in f64: integer-valued, exact below 2^49
    even on the f32-pair-emulated f64 path (stage-1 sums ≤ SEG*2^18,
    stage-2 ≤ total rows * 2^18 — both far under), so bit-identity
    with the host integer limb arithmetic is preserved.
    """
    key = ("k", num_segments, want, W, K, SEG)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _f(values, valid, times, limbs, bad, gids, block0, scalars):
        return _mask_stage(values, valid, times, limbs, bad, gids,
                           block0, scalars,
                           num_segments=num_segments, want=want,
                           W=W, K=K, SEG=SEG)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


PACK = bool(knobs.get("OG_BLOCK_PACK"))
_U32M = np.int64(0xFFFFFFFF)
IDX_U32_SENTINEL = np.int64(0xFFFFFFFF)


def packed_u32_planes(want: tuple, K: int) -> int:
    """Plane count of the uint32 packed pull for (want, K)."""
    n = 1                                        # count
    if "sum" in want:
        n += 1 + (18 * K + 31) // 32             # top + digit words
    if "min" in want:
        n += 1                                   # min_idx
    if "max" in want:
        n += 1                                   # max_idx
    return n


def _pack_stage(planes, *, want: tuple, K: int):
    """Trace-composable body of _pack_kernel (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax.numpy as jnp

    Wn = (18 * K + 31) // 32
    layout = plane_layout(want, K)
    S = planes.shape[1]
    u32, f64 = [], []
    bits = jnp.zeros(0, dtype=jnp.uint32)
    i = 0
    for name, n in layout:
        pl = planes[i:i + n]
        i += n
        if name == "count":
            u32.append((pl[0].astype(jnp.int64) & _U32M)
                       .astype(jnp.uint32))
        elif name == "limbs":
            ds = [pl[k].astype(jnp.int64) for k in range(K)]
            for k in range(K - 1, 0, -1):
                c = ds[k] >> 18          # arithmetic = floor
                ds[k] = ds[k] - (c << 18)
                ds[k - 1] = ds[k - 1] + c
            top = ds[0] >> 18
            ds[0] = ds[0] - (top << 18)
            u32.append(((top & _U32M)).astype(jnp.uint32))
            # digit stream Σ d_k·2^(18(K-1-k)) sliced into 32-bit
            # words, high word first; each word overlaps ≤3 digits
            for j in range(Wn):
                w = jnp.zeros(S, dtype=jnp.int64)
                for k in range(K):
                    sh = 18 * (K - 1 - k) - 32 * (Wn - 1 - j)
                    if -18 < sh < 32:
                        t = (ds[k] << sh) if sh >= 0 \
                            else (ds[k] >> (-sh))
                        w = w | (t & _U32M)
                u32.append(w.astype(jnp.uint32))
        elif name == "bad":
            b = (pl[0] > 0).astype(jnp.uint32)
            pad = (-S) % 32
            if pad:
                b = jnp.concatenate(
                    [b, jnp.zeros(pad, dtype=jnp.uint32)])
            bits = (b.reshape(-1, 32)
                    << jnp.arange(32, dtype=jnp.uint32)[None, :]
                    ).sum(axis=1, dtype=jnp.uint32)
        elif name == "sumsq":
            f64.append(pl[0])
        elif name in ("min", "max"):
            pass                     # host fold never reads values
        elif name in ("min_idx", "max_idx"):
            p = pl[0]
            real = (p >= 0) & (p < IDX_SENTINEL)
            iv = jnp.where(real, p, 0.0).astype(jnp.int64)
            u32.append(jnp.where(real, iv, IDX_U32_SENTINEL)
                       .astype(jnp.uint32))
    out = (jnp.stack(u32), bits)
    if f64:
        out = out + (jnp.stack(f64),)
    return out


def _pack_kernel(want: tuple, K: int):
    """jit epilogue: the f64 plane grid → (uint32 planes, uint32 bad
    bitmask[, f64 extras]) — the D2H transport form.

    Rationale (measured on the tunnel-attached v5e): D2H tops out near
    30 MB/s, so the pull IS the query wall for big grids (BENCH_r03:
    device_pull 1666ms of 1959ms). The f64 plane layout spends 8 bytes
    per state; this epilogue losslessly re-encodes on device in exact
    integer arithmetic (int64 elementwise is int-emulated on TPU —
    exact, unlike the f32-pair f64 emulation):
      * limb sums carry-normalize into 18-bit digits [0, 2^18) plus a
        signed top carry, then bit-pack into ceil(18K/32) uint32 words
        (+1 top word) — 16B vs 8(K+1)B for K active planes;
      * counts are < 2^28 (guarded) → one uint32 plane;
      * bad flags bit-pack 32 cells/word;
      * min/max row-index planes → uint32 (sentinel 0xffffffff); the
        min/max VALUE planes are dropped entirely — the executor's
        fold only consumes indices (exact host gather).
    The host unpack reconstructs limb planes holding the SAME integer
    totals (top merges into the high limb), so every downstream
    consumer (rebase/merge/finalize_exact) is unchanged — bit-identical
    by construction, and the CPU baseline runs this same path.
    """
    key = ("pack", want, K)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _p(planes):
        return _pack_stage(planes, want=want, K=K)

    _p = _named_jit(_p, key)
    _JITTED[key] = _p
    return _p


def pack_eligible(want: tuple, n_rows: int, flat_n: int) -> bool:
    """Will pack_grid use the packed transport for these ranges?
      * counts/top need n_rows < 2^28 (top ≤ K·n_rows, count ≤ n_rows)
      * row-index planes need flat_n < 2^32-1 (uint32 + sentinel)
    The executor consults this up front: grids above the legacy cell
    cap must not dispatch at all when the pull would be f64 planes."""
    idx_wanted = ("min" in want) or ("max" in want)
    return (PACK and n_rows < (1 << 28)
            and not (idx_wanted and flat_n >= _U32M))


def _prune_stage(planes, *, want: tuple, K: int):
    """Trace-composable body of _prune_kernel (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax.numpy as jnp

    # derive the kept rows FROM pruned_layout so the device
    # row-select and the host unpack_planes(pruned=True) can
    # never skew
    kept = {name for name, _n in pruned_layout(want, K)}
    keep: list[int] = []
    i = 0
    for name, n in plane_layout(want, K):
        if name in kept:
            keep.extend(range(i, i + n))
        i += n
    idx = np.asarray(keep, dtype=np.int32)
    return jnp.take(planes, idx, axis=0)


def _prune_kernel(want: tuple, K: int):
    """jit row-select dropping the min/max VALUE planes from a legacy
    f64 grid before the pull (pruned_layout) — the host fold reads only
    the index planes, so shipping the values was pure D2H waste."""
    key = ("prune", want, K)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _p(planes):
        return _prune_stage(planes, want=want, K=K)

    _p = _named_jit(_p, key)
    _JITTED[key] = _p
    return _p


def pack_grid(out, want: tuple, K: int, n_rows: int, flat_n: int,
              prune_legacy: bool = False):
    """Device-side packed transport of a final plane grid, or the
    legacy f64 grid when out of the packed encoding's ranges (see
    pack_eligible). Returns ("p", u32, bits[, f64]), ("l", planes), or
    — when ``prune_legacy`` (OG_DEVICE_FINALIZE on) and the fallback
    would carry dead min/max value planes — ("lp", pruned_planes)."""
    if not pack_eligible(want, n_rows, flat_n):
        if prune_legacy and (("min" in want) or ("max" in want)):
            return ("lp", _prune_kernel(want, K)(out))
        return ("l", out)
    return ("p",) + tuple(_pack_kernel(want, K)(out))


def unpack_packed(u32: np.ndarray, bits: np.ndarray, want: tuple,
                  K: int, k0: int = 0, K_full: int | None = None,
                  f64_extra: np.ndarray | None = None) -> dict:
    """Host inverse of _pack_kernel → the same bo dict as
    unpack_planes. The digit planes reassemble into limb planes whose
    integer totals equal the kernel's limb sums (top folds into the
    high limb — limb magnitudes may differ from the legacy path, the
    represented value cannot)."""
    if K_full is None:
        K_full = exactsum.K_LIMBS
    Wn = (18 * K + 31) // 32
    S = u32.shape[1]
    out = {}
    # per-row astypes, not a full-stack copy: the sum section's native
    # path reads the uint32 planes directly
    a = u32
    out["count"] = u32[0].astype(np.int64)
    i = 1
    if "sum" in want:
        from .. import native as _native
        full = _native.unpack_limbs_fast(u32, i, i + 1, K, k0, K_full)
        if full is None:
            top = u32[i].astype(np.int64)
            top = np.where(top >= (1 << 31), top - (1 << 32), top)
            words = u32[i + 1:i + 1 + Wn].astype(np.int64)
            digits = np.zeros((K, S), dtype=np.int64)
            for k in range(K):
                for j in range(Wn):
                    # mirror of the pack shifts: digit k's low bit
                    # sits at word-bit sh of word j (negative sh: its
                    # upper bits)
                    sh = 18 * (K - 1 - k) - 32 * (Wn - 1 - j)
                    if -18 < sh < 32:
                        w = words[j]
                        part = (w >> sh) if sh >= 0 else (w << (-sh))
                        digits[k] |= part & ((1 << 18) - 1)
            digits[0] += top << 18
            full = np.zeros((S, K_full))
            full[:, k0:k0 + K] = digits.T.astype(np.float64)
        i += 1 + Wn
        out["limbs"] = full
        out["bad"] = expand_bits(bits, S)
    if "sumsq" in want:
        out["sumsq"] = np.asarray(f64_extra)[0]
    for name in ("min", "max"):
        if name in want:
            p = a[i].astype(np.int64)
            i += 1
            out[f"{name}_idx"] = np.where(p == IDX_U32_SENTINEL,
                                          I64MAX, p)
    return out


# --------------------------------------- on-device finalize epilogue

_REAL_F64: bool | None = None


def _backend_real_f64() -> bool:
    """Does the default backend compute f64 natively? TPUs emulate f64
    as float32 pairs (see the module header): the finalize cascade's
    TwoSum error terms — and therefore its own hazard test — drift
    there, so the epilogue must not trust them. ALLOWLIST of known
    real-f64 platforms, failing CLOSED on anything unrecognized (a
    TPU-tunnel PJRT plugin may report its own platform name, not
    "tpu"). Probed once."""
    global _REAL_F64
    if _REAL_F64 is None:
        try:
            import jax
            _REAL_F64 = jax.devices()[0].platform in (
                "cpu", "gpu", "cuda", "rocm")
        except Exception:  # oglint: disable=R701 — reviewed: platform
            # probe fails CLOSED (epilogue off) — the safe default on
            # any backend we cannot identify
            _REAL_F64 = False
    return _REAL_F64


def plane_diet_on() -> bool:
    """Gate for the op-aware plane PRUNING half of the D2H diet
    (per-field want sets, pruned legacy transport): pure plane
    selection, bit-identical on ANY backend — so unlike the finalize
    epilogue below it needs no real-f64 gate and stays on for TPUs.
    OG_DEVICE_FINALIZE=0 switches it off together with the epilogue
    (the byte-identical legacy wire form)."""
    return knobs.get_raw("OG_DEVICE_FINALIZE") != "0"


def device_finalize_on() -> bool:
    """Gate for the device finalize epilogue — the f64-SENSITIVE half
    of the D2H diet (OG_DEVICE_FINALIZE, default on; 0 = byte-identical
    legacy transport). Read dynamically so perf_smoke can flip it per
    query.

    On f32-pair-emulated-f64 backends (TPU) the epilogue auto-gates
    OFF regardless of the default: finalize_exact_traced needs
    correctly-rounded IEEE f64 and its hazard flag is computed in the
    same arithmetic, so drifting cells would not even be repaired.
    ``OG_DEVICE_FINALIZE=force`` overrides the backend gate for
    experimentation on hardware whose f64 emulation has been verified.

    What it buys (the "reduce before you move" rule — SURVEY §2-3's
    series_agg_reducer ships FINAL values up the cursor stack): a
    terminal query's device-merged (field, scale) grid converts to
    answer-sized planes ON DEVICE — exact limb→f64 reconstruction,
    mean = sum/count, count — so one f64 plane per selected op crosses
    the slow D2H link instead of the packed limb/count grid (~8-12
    B/cell vs ~20 B/cell for a mean at K=4 active planes). Cells the
    device cannot PROVE correctly rounded (the finalize hazard test)
    plus limb-residue cells are flagged in an on-device bitmask and
    pulled sparsely for host repair. The cluster/merge wire format is
    untouched — only terminal partials (no merge pending) finalize."""
    v = knobs.get_raw("OG_DEVICE_FINALIZE")
    if v == "0":
        return False
    if v == "force":
        return True
    return _backend_real_f64()


def finalize_fops(ops: set) -> tuple | None:
    """Transport recipe (dev_mean, ship_sum, need_count) for a field's
    SELECTED ops, or None when the op set can't finalize on device
    (extrema need the per-file index+host-gather path; sumsq/raw ops
    never reach the merged block grid).

    - mean-only queries divide ON DEVICE (one f64 mean plane + a
      presence bitmask — the heavy dashboard shape's 2.5× diet);
    - once real counts must ship anyway ("count" selected, or mean
      next to sum), the division stays on host over the answer-sized
      grid (same bytes, one shared code path with the legacy fold)."""
    if not ops or not ops <= {"count", "sum", "mean"}:
        return None
    dev_mean = "mean" in ops and not ({"sum", "count"} & ops)
    ship_sum = ("sum" in ops) or ("mean" in ops and not dev_mean)
    need_count = ("count" in ops) or ("mean" in ops and not dev_mean)
    return (dev_mean, ship_sum, need_count)


def _bits_of(b, S: int):
    """Traced 32-cells/word bitpack of a bool (S,) vector (same lane
    order as the packed transport's bad bitmask)."""
    import jax.numpy as jnp
    x = b.astype(jnp.uint32)
    pad = (-S) % 32
    if pad:
        x = jnp.concatenate([x, jnp.zeros(pad, dtype=jnp.uint32)])
    return (x.reshape(-1, 32)
            << jnp.arange(32, dtype=jnp.uint32)[None, :]
            ).sum(axis=1, dtype=jnp.uint32)


def expand_bits(bits: np.ndarray, S: int) -> np.ndarray:
    """Host inverse of _bits_of → bool (S,)."""
    lanes = ((np.asarray(bits)[:, None].astype(np.uint32)
              >> np.arange(32, dtype=np.uint32)[None, :]) & 1)
    return lanes.reshape(-1)[:S].astype(bool)


def _finalize_stage(planes, scale_lo, *, want: tuple, K: int,
                    k0: int, dev_mean: bool, ship_sum: bool,
                    need_count: bool):
    """Trace-composable body of _finalize_kernel (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax.numpy as jnp

    with_sum = ("sum" in want) and (ship_sum or dev_mean)
    S = planes.shape[1]
    cnt = planes[0]
    u32 = []
    if need_count:
        u32.append((cnt.astype(jnp.int64) & _U32M)
                   .astype(jnp.uint32))
    pres = None if need_count else _bits_of(cnt > 0, S)
    flag = None
    f64 = []
    if with_sum:
        full = []
        for j in range(exactsum.K_LIMBS):
            full.append(planes[1 + (j - k0)].astype(jnp.int64)
                        if k0 <= j < k0 + K
                        else jnp.zeros(S, dtype=jnp.int64))
        out, hazard = exactsum.finalize_exact_traced(full,
                                                     scale_lo)
        bad = planes[1 + K] > 0
        flag = _bits_of(hazard | bad, S)
        if ship_sum:
            f64.append(out)
        if dev_mean:
            # same operand values as the host finalize_moment
            # (sum / max(count, 1)) — identical IEEE division
            f64.append(out / jnp.maximum(cnt, 1.0))
    return (jnp.stack(u32) if u32 else None, pres, flag,
            jnp.stack(f64) if f64 else None)


def _finalize_kernel(want: tuple, K: int, k0: int,
                     dev_mean: bool, ship_sum: bool, need_count: bool):
    """jit finalize epilogue: the device-merged f64 plane grid → the
    answer-sized transport (u32 count-or-presence, hazard/residue flag
    bitmask, f64 answer planes). The sum reconstruction is
    exactsum.finalize_exact_traced — the SAME IEEE sequence as the
    host fast path, so non-flagged cells are bit-identical by
    construction; flagged cells (hazard ∪ limb-residue) are repaired
    host-side from a sparse pull (unpack_finalized). The limb scale
    enters as the traced ``scale_lo`` operand, so one compiled kernel
    serves every E."""
    key = ("fin", want, K, k0, dev_mean, ship_sum, need_count)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _f(planes, scale_lo):
        return _finalize_stage(planes, scale_lo, want=want, K=K,
                               k0=k0, dev_mean=dev_mean,
                               ship_sum=ship_sum,
                               need_count=need_count)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def finalize_grid(out, want: tuple, ops: set, K: int, k0: int, E: int,
                  n_rows: int):
    """Device finalize epilogue over a device-merged plane grid.
    Returns (("f", u32, pres_bits, flag_bits, f64), recipe) — the
    answer-sized transport plus the (dev_mean, ship_sum, need_count)
    recipe the kernel packed with, which the caller MUST thread to
    unpack_finalized (one derivation, no wire-format skew) — or None
    when the op set is ineligible or the count range guard trips (same
    n_rows < 2^28 bound as the packed transport's u32 counts). Caller
    keeps ``out`` resident for the sparse repair pull."""
    rec = finalize_fops(ops)
    if rec is None or n_rows >= (1 << 28):
        return None
    dev_mean, ship_sum, need_count = rec
    fn = _finalize_kernel(want, K, k0, dev_mean, ship_sum, need_count)
    from . import devstats
    devstats.bump("kernel_launches")
    scale_lo = np.float64(2.0 ** float(E - exactsum.SPAN_BITS))
    return (("f",) + tuple(fn(out, scale_lo)), rec)


def unpack_finalized(arrs, planes_dev, K: int, k0: int,
                     E: int, dev_mean: bool, ship_sum: bool,
                     need_count: bool, S: int) -> dict:
    """Pulled finalized transport → the bo dict the executor folds:
    {"final": True, "count": int64 counts-or-presence[, "sum" f64
    exact][, "mean" f64]}. The transport recipe (dev_mean/ship_sum/
    need_count) fully determines the decode — no want tuple involved.
    Flagged cells (finalize hazard ∪ limb residue) repair HERE: their
    limb/count rows gather from the still-resident pre-finalize grid
    in ONE sparse pull and re-finalize through the host finalize_exact
    (big-int backstop included) — the only extra transfer the epilogue
    ever makes; its byte count returns to the caller via the
    "_repair_nbytes" entry for per-query accounting."""
    import time as _time
    u32, pres, flag, f64 = arrs
    bo: dict = {"final": True}
    if need_count:
        bo["count"] = np.asarray(u32[0]).astype(np.int64)
    else:
        bo["count"] = expand_bits(pres, S).astype(np.int64)
    sum_p = mean_p = None
    if f64 is not None:
        fa = np.asarray(f64)
        i = 0
        if ship_sum:
            sum_p = np.array(fa[i], dtype=np.float64)
            i += 1
        if dev_mean:
            mean_p = np.array(fa[i], dtype=np.float64)
    if flag is not None:
        flagged = np.nonzero(expand_bits(flag, S))[0]
        if len(flagged):
            from . import compileaudit, devstats
            t0 = _time.perf_counter_ns()
            # sparse repair pull — manually accounted (manifest-booked
            # just below), so exempt from the R1 transport rule
            sub = np.asarray(planes_dev[:, flagged])  # oglint: disable=R103
            compileaudit.record_d2h("repair", int(sub.nbytes))
            # the per-transport (d2h_bytes_finalized) share is booked
            # by the caller from _repair_nbytes — bumping it here too
            # would double-count the repair
            bo["_repair_nbytes"] = int(sub.nbytes)
            full = np.zeros((len(flagged), exactsum.K_LIMBS))
            full[:, k0:k0 + K] = sub[1:1 + K].T
            sums = exactsum.finalize_exact(full, E)
            if sum_p is not None:
                sum_p[flagged] = sums
            if mean_p is not None:
                cnt_f = sub[0].astype(np.int64)
                mean_p[flagged] = sums / np.maximum(cnt_f, 1)
            devstats.bump_phase("device_finalize",
                                _time.perf_counter_ns() - t0)
    if sum_p is not None:
        bo["sum"] = sum_p
    if mean_p is not None:
        bo["mean"] = mean_p
    return bo


def _combine_stage(a, b, *, want: tuple, K: int):
    """Trace-composable body of _pairwise_combine (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax.numpy as jnp

    layout = plane_layout(want, K)
    out = []
    i = 0
    for name, n in layout:
        if name in ("min_idx", "max_idx"):
            continue        # consumed with its value plane below
        pa, pb = a[i:i + n], b[i:i + n]
        i += n
        if name in ("count", "limbs", "sumsq"):
            out.append(pa + pb)
        elif name == "bad":
            out.append(jnp.maximum(pa, pb))
        elif name in ("min", "max"):
            better = (pb < pa) if name == "min" else (pb > pa)
            out.append(jnp.where(better, pb, pa))
            ia, ib = a[i:i + 1], b[i:i + 1]
            i += 1
            out.append(jnp.where(better, ib, ia))
    return jnp.concatenate(out)


def _pairwise_combine(want: tuple, K: int):
    """Device combine of two packed plane arrays (same cell grid):
    adds for count/limbs/sumsq, any for bad, min/max keep the winning
    value's index (ties → the earlier operand, i.e. lower flat index
    space first — matching the scatter kernel's segment_min tie rule)."""
    key = ("pc", want, K)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _c(a, b):
        return _combine_stage(a, b, want=want, K=K)

    _c = _named_jit(_c, key)
    _JITTED[key] = _c
    return _c


def _kernel_prefix(num_segments: int, want: tuple, W: int, K: int,
                   SEG: int, WLmax: int, Cmax: int):
    """Wide-window reduction WITHOUT scatters (W > MASK_W_MAX would
    need W unrolled masked passes, and flat f64 segment_sum scatters
    cost ~0.7s per plane per 9M rows on the v5e's emulated f64):

      stage 1: per-plane EXCLUSIVE CUMSUM along the row axis in int32
        (exact: limb cumsums ≤ SEG·2^18 < 2^31, counts ≤ SEG) — one
        O(N) pass per plane, no W factor;
      stage 2: per block, the window boundaries are positions in the
        (sorted) per-row window ids — vmapped binary search over
        WLmax+1 query windows; window sums are boundary differences of
        the cumsums (exact int32 diffs → f64);
      stage 3: the (B·WLmax) partial lattice maps onto the cell grid by
        a HOST-BUILT gather index (each cell gathers its ≤Cmax
        contributing block-windows) — dense gathers + axis sums, zero
        scatters. f64 sums of integers < 2^49 — exact, order-fixed.

    min/max are not prefix-decomposable and take the scatter fallback;
    the executor's eligibility keeps them off this path. Reference
    role: the same aggregate_cursor.go:90 windowing, restructured for
    the TPU's tiling rules instead of translated.
    """
    key = ("kp", num_segments, want, W, K, SEG, WLmax, Cmax)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    def _f(values, valid, times, limbs, bad, gids, scalars,
           w0, gather_idx):
        t_lo, t_hi, start, interval = (scalars[0], scalars[1],
                                       scalars[2], scalars[3])
        B = valid.shape[0]          # values is None on int-mode slabs
        m0 = (valid & (times >= t_lo) & (times <= t_hi)
              & (gids >= 0)[:, None])
        # int64-overflow-safe window ids, monotone per block (times
        # are sorted and padded tails hold I64MAX)
        span = W * interval
        tcl = jnp.clip(times, start, start + span)
        wid = jnp.clip((tcl - start) // interval, 0, W).astype(
            jnp.int32)
        in_w = (times >= start) & (times < start + span)
        m0 = m0 & in_w

        def ecs(delta_i32):
            c = jnp.cumsum(delta_i32, axis=1, dtype=jnp.int32)
            return jnp.concatenate(
                [jnp.zeros((B, 1), jnp.int32), c], axis=1)

        planes_cs = [ecs(m0.astype(jnp.int32))]
        if "sum" in want:
            lz = jnp.where(m0[:, :, None], limbs, 0)
            for k in range(K):
                planes_cs.append(ecs(lz[:, :, k]))
            planes_cs.append(ecs((m0 & bad).astype(jnp.int32)))
        # boundary positions of windows w0+0 .. w0+WLmax (B, WLmax+1)
        wq = w0[:, None] + jnp.arange(WLmax + 1, dtype=jnp.int32)[None]
        pos = jax.vmap(
            lambda a, v: jnp.searchsorted(a, v, side="left"))(wid, wq)
        lo, hi = pos[:, :-1], pos[:, 1:]
        out = []
        for cs in planes_cs:
            p = (jnp.take_along_axis(cs, hi, axis=1)
                 - jnp.take_along_axis(cs, lo, axis=1))  # (B, WLmax)
            flat = jnp.concatenate(
                [p.reshape(-1), jnp.zeros(1, jnp.int32)])
            cells = flat[gather_idx].astype(jnp.float64).sum(axis=1)
            out.append(cells)
        return jnp.stack(out)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def _prefix_arith_stage(valid, times, limbs, bad, gids, scalars,
                        t0v, stepv, rowsv, *, num_segments: int,
                        want: tuple, W: int, K: int, SEG: int,
                        G: int):
    """Trace-composable body of _kernel_prefix_arith (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax
    import jax.numpy as jnp
    t_lo, t_hi = scalars[0], scalars[1]
    start, interval = scalars[2], scalars[3]
    B = valid.shape[0]
    m0 = (valid & (times >= t_lo) & (times <= t_hi)
          & (gids >= 0)[:, None])

    def ecs(d):
        c = jnp.cumsum(d, axis=1, dtype=jnp.int32)
        return jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int32), c], axis=1)

    planes = [ecs(m0.astype(jnp.int32))]
    if "sum" in want:
        lz = jnp.where(m0[:, :, None], limbs, 0)
        for k in range(K):
            planes.append(ecs(lz[:, :, k]))
        planes.append(ecs((m0 & bad).astype(jnp.int32)))
    bounds = start + jnp.arange(W + 1, dtype=jnp.int64) * interval
    num = bounds[None, :] - t0v[:, None]
    pos = jnp.clip(
        (num + stepv[:, None] - 1) // stepv[:, None],
        0, rowsv[:, None].astype(jnp.int64)).astype(jnp.int32)
    # flat 1D take: ~9x faster than 2D take_along_axis on the
    # v5e's gather lowering (measured 37ms vs 340ms per slab)
    P = len(planes)
    cs = jnp.stack(planes).reshape(P, B * (SEG + 1))
    fidx = (jnp.arange(B, dtype=jnp.int32)[:, None] * (SEG + 1)
            + pos).reshape(-1)
    g = jnp.take(cs, fidx, axis=1).reshape(P, B, W + 1)
    d = g[:, :, 1:] - g[:, :, :-1]                # (P, B, W) i32
    if G == 1:
        return d.astype(jnp.float64).sum(axis=1)
    oh = (gids[:, None]
          == jnp.arange(G, dtype=gids.dtype)[None, :]
          ).astype(jnp.float32)                   # (B, G)
    hp = jax.lax.Precision.HIGHEST
    d0 = (d & 0xFFF).astype(jnp.float32)
    d1 = ((d >> 12) & 0xFFF).astype(jnp.float32)
    d2 = (d >> 24).astype(jnp.float32)            # signed top
    g0 = jnp.einsum("bg,pbw->pgw", oh, d0, precision=hp)
    g1 = jnp.einsum("bg,pbw->pgw", oh, d1, precision=hp)
    g2 = jnp.einsum("bg,pbw->pgw", oh, d2, precision=hp)
    cells = (g2.astype(jnp.float64) * 16777216.0
             + g1.astype(jnp.float64) * 4096.0
             + g0.astype(jnp.float64))
    return cells.reshape(P, num_segments)


def _kernel_prefix_arith(num_segments: int, want: tuple, W: int,
                         K: int, SEG: int, G: int):
    """Wide-window reduction for CONST-DELTA blocks: no searchsorted,
    no gather plan. Blocks of a bulk-written file have affine times
    t0 + i·step, so the boundary position of window j is pure
    arithmetic: pos = clip(ceil((start + j·interval - t0)/step), 0,
    rows). Stages:
      1. per-plane exclusive int32 cumsum along rows (as the search
         kernel — exact while SEG·(2^18-1) < 2^31);
      2. (B, W+1) boundary positions — elementwise int64 arithmetic;
      3. window sums = cumsum diffs at boundaries (two gathers of
         (B, W) — the only gathers left);
      4. cell fold: G == 1 sums the block axis outright; small G folds
         through 12-bit digit-split one-hot matmuls on the MXU
         (HIGHEST precision; each digit product ≤ 4095, partial sums
         ≤ B·4095 ≤ 2^24 with B ≤ 4096 — exact in f32, recombined in
         f64). Replaces the vmapped binary search + (cells, Cmax)
         gather of _kernel_prefix, measured ~2x the whole kernel's
         wall on the tunnel-attached v5e.
    """
    key = ("kpa", num_segments, want, W, K, SEG, G)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _f(valid, times, limbs, bad, gids, scalars, t0v, stepv, rowsv):
        return _prefix_arith_stage(
            valid, times, limbs, bad, gids, scalars, t0v, stepv,
            rowsv, num_segments=num_segments, want=want, W=W,
            K=K, SEG=SEG, G=G)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def _round_up(x: int, step: int) -> int:
    return ((x + step - 1) // step) * step


# host/device budget for one slab's stage-3 plan: the partial lattice
# (B·WLmax entries) and the (cells, Cmax) gather index
PLAN_MAX_ENTRIES = int(knobs.get("OG_PREFIX_PLAN_MAX_ENTRIES"))
# group-count ceiling for the one-hot matmul cell fold (flops scale
# with G); wider groupings use the searchsorted/gather-plan kernel
ARITH_G_MAX = int(knobs.get("OG_ARITH_G_MAX"))

# per-slab byte cap for the pulled window lattice (P·B·WL·4)
LATTICE_MAX_BYTES = int(knobs.get("OG_LATTICE_MAX_MB")) * (1 << 20)


def _lattice_stage(valid, times, limbs, bad, gids, scalars, t0v,
                   stepv, rowsv, *, want: tuple, K: int, SEG: int,
                   WL: int, W: int):
    """Trace-composable body of _kernel_lattice (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax.numpy as jnp
    t_lo, t_hi = scalars[0], scalars[1]
    start, interval = scalars[2], scalars[3]
    B = valid.shape[0]
    m0 = (valid & (times >= t_lo) & (times <= t_hi)
          & (gids >= 0)[:, None])

    def ecs(d):
        c = jnp.cumsum(d, axis=1, dtype=jnp.int32)
        return jnp.concatenate(
            [jnp.zeros((B, 1), jnp.int32), c], axis=1)

    planes = [ecs(m0.astype(jnp.int32))]
    if "sum" in want:
        lz = jnp.where(m0[:, :, None], limbs, 0)
        for k in range(K):
            planes.append(ecs(lz[:, :, k]))
        planes.append(ecs((m0 & bad).astype(jnp.int32)))
    # same formula as the host fold's w0 (fold_lattices)
    w0 = jnp.clip((jnp.maximum(t0v, start) - start) // interval,
                  0, W - 1)
    wj = jnp.minimum(
        w0[:, None] + jnp.arange(WL + 1, dtype=jnp.int64)[None, :],
        W)
    bounds = start + wj * interval
    num = bounds - t0v[:, None]
    pos = jnp.clip(
        (num + stepv[:, None] - 1) // stepv[:, None],
        0, rowsv[:, None].astype(jnp.int64)).astype(jnp.int32)
    P = len(planes)
    cs = jnp.stack(planes).reshape(P, B * (SEG + 1))
    fidx = (jnp.arange(B, dtype=jnp.int32)[:, None] * (SEG + 1)
            + pos).reshape(-1)
    g = jnp.take(cs, fidx, axis=1).reshape(P, B, WL + 1)
    d = g[:, :, 1:] - g[:, :, :-1]
    # slim transport: counts fit int8 (<= rows/window, guarded by
    # lattice_eligible's R bound), bad bits fit bool — 32B/entry
    # -> 4K+2 bytes (the pull IS the wall on the tunnel link)
    if "sum" in want:
        return (d[0].astype(jnp.int8), d[1:1 + K],
                (d[1 + K] != 0))
    return (d[0].astype(jnp.int8),)


def _kernel_lattice(want: tuple, K: int, SEG: int, WL: int, W: int):
    """Big-grid reduction WITHOUT any device-side cell fold: emit the
    compact per-block window lattice d (P, B, WL) int32 and let the
    HOST scatter it into the (G·W) grid (native/limbsum.cpp
    og_fold_lattice — memory-speed, no device scatter, no einsum, no
    per-slab gather plans).

    Stages (const-delta blocks only — bulk-written files):
      1. per-plane exclusive int32 cumsum along rows (exact while
         SEG·(2^18-1) < 2^31);
      2. per-block window boundaries by ARITHMETIC: block b's first
         window w0 = clip((max(t0_b, start) - start)/interval, 0,
         W-1); boundary j sits at row ceil((start + min(w0+j, W)·
         interval - t0_b)/step) — windows past W collapse to zero-
         width (d = 0);
      3. window sums = boundary diffs of the cumsums — (P, B, WL)
         int32, the pulled transport (~P·4 bytes per LIVE window vs
         ~20B/cell of the packed grid, and lattice entries ≈ cells).

    Rationale vs the gather-plan kernel at multi-M cells: the plan's
    (cells, Cmax) index is grid-sized PER SLAB (measured 184MB × 10
    slabs — evicted the stacks and forced 3.3GB re-uploads per query);
    the lattice needs no plan at all. Reference role: the same
    aggregate_cursor.go:90 windowing, restructured for the tunnel-
    attached TPU's transfer economics."""
    key = ("kl", want, K, SEG, WL, W)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _f(valid, times, limbs, bad, gids, scalars, t0v, stepv, rowsv):
        return _lattice_stage(valid, times, limbs, bad, gids,
                              scalars, t0v, stepv, rowsv, want=want,
                              K=K, SEG=SEG, WL=WL, W=W)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def lattice_eligible(slabs: list, gids: np.ndarray, start: int,
                     interval: int, W: int, want: tuple) -> bool:
    """Cheap pre-check (no launches): every slab const-delta with a
    lattice under the byte cap, cumsums int32-exact, per-window row
    counts under the int8 transport bound, sum-only states."""
    if interval <= 0 or ({"min", "max", "sumsq"} & set(want)):
        return False
    K = slabs[0].limbs.shape[-1]
    bpe = 1 + (K * 4 + 1 if "sum" in want else 0)
    for st in slabs:
        if not (st.all_const and st.t0_dev is not None
                and st.seg_rows <= (1 << 13)):
            return False
        if _lattice_row_bound(st, interval) > 127:
            return False               # int8 count plane
        _w0, _wl, WL = _prefix_spans(
            st, gids[st.block0:st.block0 + st.n_blocks], start,
            interval, W)
        if bpe * st.n_blocks * WL > LATTICE_MAX_BYTES:
            return False
    return True


def _lattice_row_bound(st: BlockStack, interval: int) -> int:
    """Max rows any single window of this slab can hold (const-delta
    blocks: ceil(interval/step) + 1). Sizes the int8 count plane."""
    rows = np.asarray(st.t_rows, dtype=np.int64)
    live = rows > 1
    if not live.any():
        return 1
    t0 = np.asarray(st.t_min, dtype=np.int64)[live]
    t1 = np.asarray(st.t_max, dtype=np.int64)[live]
    step = np.maximum((t1 - t0) // np.maximum(rows[live] - 1, 1), 1)
    return int((-(-interval // step.min())) + 1)


def file_lattice(slabs: list, gids: np.ndarray, t_lo, t_hi,
                 start: int, interval: int, W: int, want: tuple,
                 scalars=None, gids_dev=None) -> list:
    """Launch the lattice kernel per slab; returns [(slab, d_dev, WL)]
    with d still ON DEVICE (the executor batches the pull). Caller
    must have passed lattice_eligible first."""
    import jax
    K = slabs[0].limbs.shape[-1]
    if scalars is None:
        scalars = query_scalars(t_lo, t_hi, start, interval)
    if gids_dev is None:
        # content-keyed + booked upload (oglint R10): warm repeats of
        # the same grouping re-use the resident vector, cold ones book
        # their bytes into the transfer manifest
        gids_dev = cached_gids(np.asarray(gids, dtype=np.int64))
    outs = []
    for st in slabs:
        g = gids_dev[st.block0:st.block0 + st.n_blocks]
        _w0, _wl, WL = _prefix_spans(
            st, gids[st.block0:st.block0 + st.n_blocks], start,
            interval, W)
        fn = _kernel_lattice(want, K, st.seg_rows, WL, W)
        d = fn(st.valid, st.times, st.limbs, st.bad, g, scalars,
               st.t0_dev, st.step_dev, st.rows_dev)
        from . import devstats
        devstats.bump("kernel_launches")
        outs.append((st, d, WL))
    return outs


def new_lattice_acc(num_segments: int, want: tuple, K_full: int):
    """Fresh host fold accumulators [counts, limbs|None, badg|None] for
    fold_lattice_into — shared across all slabs of one (field, scale)
    group, fillable in ANY order (every op is an exact integer add or a
    flag OR, so the streaming pipeline's arrival-order folds are
    bit-identical to the grouped fold)."""
    with_sum = "sum" in want
    return [np.zeros(num_segments, dtype=np.float64),
            np.zeros((num_segments, K_full), dtype=np.float64)
            if with_sum else None,
            np.zeros(num_segments, dtype=np.uint8) if with_sum
            else None]


def fold_lattice_into(acc: list, st: BlockStack, d, WL: int,
                      gids: np.ndarray, start: int, interval: int,
                      W: int, num_segments: int, want: tuple,
                      K_full: int) -> None:
    """Fold ONE pulled slab lattice into shared accumulators (see
    new_lattice_acc). Native single pass when available; vectorized
    bincount fallback. NOT thread-safe per accumulator — callers
    folding concurrently hold their own lock."""
    from .. import native
    ns = num_segments
    counts, limbs, badg = acc
    with_sum = "sum" in want
    K = st.limbs.shape[-1]
    k0 = st.k0
    c8 = np.ascontiguousarray(d[0], dtype=np.int8)
    l32 = (np.ascontiguousarray(d[1], dtype=np.int32)
           if with_sum else None)
    b8 = (np.ascontiguousarray(d[2], dtype=np.uint8)
          if with_sum else None)
    g = np.ascontiguousarray(gids, dtype=np.int64)
    # host w0: MUST mirror the kernel's formula
    t0 = np.asarray(st.t_min, dtype=np.int64)
    w0 = np.clip((np.maximum(t0, start) - start) // interval,
                 0, W - 1).astype(np.int64)
    if native.fold_lattice(c8, l32, b8, g, w0, W, ns, k0,
                           K if with_sum else 0, K_full, counts,
                           limbs, badg):
        return
    # numpy fallback: flat bincount per plane over live entries
    wloc = np.arange(WL, dtype=np.int64)
    wabs = w0[:, None] + wloc[None, :]
    live = (g[:, None] >= 0) & (wabs < W)
    cells = (g[:, None] * W + wabs)[live]
    counts += np.bincount(
        cells, weights=c8[live].astype(np.float64),
        minlength=ns)[:ns]
    if with_sum:
        for k in range(K):
            limbs[:, k0 + k] += np.bincount(
                cells, weights=l32[k][live].astype(np.float64),
                minlength=ns)[:ns]
        badg |= (np.bincount(
            cells, weights=(b8[live] != 0).astype(np.float64),
            minlength=ns)[:ns] > 0).astype(np.uint8)


def lattice_acc_bo(acc: list, want: tuple) -> dict:
    """Accumulators → the bo dict the executor folds."""
    counts, limbs, badg = acc
    bo = {"count": counts}
    if "sum" in want:
        bo["limbs"] = limbs
        bo["bad"] = badg.astype(bool)
    return bo


def fold_lattices(entries: list, gids_by_entry: list, start: int,
                  interval: int, W: int, num_segments: int,
                  want: tuple, K_full: int) -> dict:
    """HOST fold of pulled lattices into one bo dict (count/limbs/bad
    grids shared across all slabs of a (field, scale) group)."""
    acc = new_lattice_acc(num_segments, want, K_full)
    for (st, d, WL), g in zip(entries, gids_by_entry):
        fold_lattice_into(acc, st, d, WL, g, start, interval, W,
                          num_segments, want, K_full)
    return lattice_acc_bo(acc, want)


# -------------------------------------------- on-device lattice fold


def lattice_fold_on_device() -> bool:
    """Gate for folding window lattices ON DEVICE before the pull
    (OG_LATTICE_DEVICE_FOLD, default on): lattice entries ≥ result
    cells (several blocks of a group contribute to the same window), so
    reducing to ONE (G, W) plane-set per (field, scale) group — then
    shipping it through the packed uint32 transport — only shrinks the
    bytes crossing the slow D2H link. Read dynamically (perf_smoke
    compares both routes cell for cell)."""
    return bool(knobs.get("OG_LATTICE_DEVICE_FOLD"))


def _lattice_cells(st: BlockStack, gids: np.ndarray, start: int,
                   interval: int, W: int, WL: int,
                   num_segments: int) -> np.ndarray:
    """Host-built flat cell index of one slab's (B, WL) lattice: entry
    (b, j) lands in cell gids[b]·W + w0[b] + j; dead entries (filtered
    block, window past W) land in the trash segment. MUST mirror the
    lattice kernel's w0 formula (and fold_lattice_into's)."""
    g = np.asarray(gids, dtype=np.int64)
    t0 = np.asarray(st.t_min, dtype=np.int64)
    w0 = np.clip((np.maximum(t0, start) - start) // interval,
                 0, W - 1).astype(np.int64)
    wabs = w0[:, None] + np.arange(WL, dtype=np.int64)[None, :]
    cells = g[:, None] * W + wabs
    dead = (g[:, None] < 0) | (wabs >= W)
    return np.where(dead, num_segments, cells).reshape(-1).astype(
        np.int32)


def cached_cells(cells: np.ndarray):
    """Device copy of a lattice cell index, content-keyed in the device
    cache (the per-(slab, grouping, window) index repeats across warm
    dashboard queries — zero H2D on repeats)."""
    import jax

    from . import compileaudit
    if not devicecache.enabled():
        dev = jax.device_put(cells)
        compileaudit.record_h2d("latcells", int(dev.nbytes))
        return dev
    import hashlib
    h = hashlib.blake2b(cells.tobytes(), digest_size=16).hexdigest()
    cache = devicecache.global_cache()
    key = ("latcells", h, len(cells))
    got = cache.get(key)
    if got is not None:
        return got
    dev = jax.device_put(cells)
    compileaudit.record_h2d("latcells", int(dev.nbytes))
    cache.put_sized(key, dev, int(dev.nbytes))
    return dev


def _lattice_fold_stage(c8, l32, b8, cells, *, num_segments: int,
                        want: tuple, K: int, sorted_cells: bool):
    """Trace-composable body of _kernel_lattice_fold (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax
    import jax.numpy as jnp

    ns = num_segments + 1
    with_sum = "sum" in want
    parts = [c8.astype(jnp.float64).reshape(-1)]
    if with_sum:
        lf = l32.astype(jnp.float64).reshape(K, -1)
        parts += [lf[k] for k in range(K)]
        parts.append(b8.astype(jnp.float64).reshape(-1))
    data = jnp.stack(parts, axis=1)              # (B·WL, P)
    out = jax.ops.segment_sum(data, cells, ns,
                              indices_are_sorted=sorted_cells)
    return out[:num_segments].T                  # (P, S)


def _kernel_lattice_fold(num_segments: int, want: tuple, K: int,
                         sorted_cells: bool):
    """jit: one slab's lattice (the _kernel_lattice output) scattered
    onto the (num_segments) cell grid as a plane_layout-ordered f64
    plane grid — ONE fused (N, P) segment_sum of exact integers (every
    plane value is an int < 2^31 and every cell total < 2^49, so the
    f64 adds are exact and order-free: bit-identical to the host C
    fold). The output composes with _pairwise_combine (cross-slab /
    cross-file merge on device) and pack_grid (uint32 transport), so a
    whole (field, scale) group crosses D2H as one packed grid. The
    `bad` plane carries the COUNT of bad contributions — every
    consumer (pack kernel, unpack_planes, combine) only tests > 0."""
    key = ("klf", num_segments, want, K, sorted_cells)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _f(c8, l32, b8, cells):
        return _lattice_fold_stage(c8, l32, b8, cells,
                                   num_segments=num_segments,
                                   want=want, K=K,
                                   sorted_cells=sorted_cells)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def file_lattice_fold(slabs: list, gids: np.ndarray, t_lo, t_hi,
                      start: int, interval: int, W: int,
                      num_segments: int, want: tuple, scalars=None,
                      gids_dev=None):
    """Lattice kernel per slab + ON-DEVICE fold + on-device combine:
    one (P, num_segments) plane grid for the whole file-field, still
    resident (the caller merges across files with _pairwise_combine and
    packs ONE transport grid per (field, scale) group). Caller must
    have passed lattice_eligible first."""
    import jax

    # device fault domain: the fold kernel's launch sequence is a
    # distinct failure site from the generic device.lattice.launch
    # wrapper (it issues 2 launches per slab) — chaos schedules arm it
    # to fail the fold mid-file
    failpoint.inject("blockagg.lattice_fold")
    K = slabs[0].limbs.shape[-1]
    if scalars is None:
        scalars = query_scalars(t_lo, t_hi, start, interval)
    if gids_dev is None:
        # content-keyed + booked upload (oglint R10): warm repeats of
        # the same grouping re-use the resident vector, cold ones book
        # their bytes into the transfer manifest
        gids_dev = cached_gids(np.asarray(gids, dtype=np.int64))
    out = None
    comb = _pairwise_combine(want, K)
    from . import devstats
    for st in slabs:
        g = gids_dev[st.block0:st.block0 + st.n_blocks]
        gh = np.asarray(gids[st.block0:st.block0 + st.n_blocks],
                        dtype=np.int64)
        _w0, _wl, WL = _prefix_spans(st, gh, start, interval, W)
        fn = _kernel_lattice(want, K, st.seg_rows, WL, W)
        d = fn(st.valid, st.times, st.limbs, st.bad, g, scalars,
               st.t0_dev, st.step_dev, st.rows_dev)
        cells = _lattice_cells(st, gh, start, interval, W, WL,
                               num_segments)
        srt = bool(np.all(cells[:-1] <= cells[1:])) if len(cells) \
            else True
        ffn = _kernel_lattice_fold(num_segments, want, K, srt)
        o = ffn(d[0], d[1] if len(d) > 1 else None,
                d[2] if len(d) > 2 else None, cached_cells(cells))
        devstats.bump("kernel_launches", 2)
        out = o if out is None else comb(out, o)
    return out


def _prefix_spans(st: BlockStack, gids: np.ndarray, start: int,
                  interval: int, W: int):
    """Cheap per-block window spans (no lattice materialized): (w0,
    wl, WLmax) — the sizing inputs for the guards AND the plan."""
    B = st.n_blocks
    g = np.asarray(gids, dtype=np.int64)
    t0 = np.clip(st.t_min, start, None)
    w0 = np.clip((t0 - start) // interval, 0, W - 1)
    w1b = np.clip((np.clip(st.t_max, None,
                           start + W * interval - 1) - start)
                  // interval, 0, W - 1)
    live = (g >= 0) & (st.t_max >= start) & \
        (st.t_min < start + W * interval) & (st.t_min <= st.t_max)
    wl = np.where(live, w1b - w0 + 1, 0).astype(np.int64)
    WLmax = _round_up(max(1, int(wl.max()) if B else 1), 32)
    return w0, wl, WLmax


def prefix_plan(st: BlockStack, gids: np.ndarray, start: int,
                interval: int, W: int, num_segments: int):
    """Host-side stage-3 plan for one slab: per-block first window w0,
    and the (cells, Cmax) gather index mapping the (B·WLmax) partial
    lattice onto the cell grid (pad slot = B·WLmax → the kernel's
    appended zero). WLmax/Cmax round up to buckets so jit keys repeat
    across similar shapes."""
    B = st.n_blocks
    g = np.asarray(gids, dtype=np.int64)
    w0, wl, WLmax = _prefix_spans(st, gids, start, interval, W)
    pad = B * WLmax
    # entry per (block, local window): cell = gid·W + w0 + wl
    nb = np.nonzero(wl > 0)[0]
    reps = wl[nb]
    blk = np.repeat(nb, reps)
    local = np.concatenate([np.arange(n, dtype=np.int64)
                            for n in reps]) if len(nb) else \
        np.zeros(0, dtype=np.int64)
    cell = g[blk] * W + w0[blk] + local
    flat = blk * WLmax + local
    counts = np.bincount(cell, minlength=num_segments)
    Cmax = _round_up(max(1, int(counts.max()) if counts.size else 1),
                     4)
    # TRUE Cmax guard (the caller's per-gid bound is loose — a
    # per-host grid with 5 blocks/host bounds at 8 where the real
    # overlap is 2): reject only when the actual index over-budgets
    if num_segments * Cmax > PLAN_MAX_ENTRIES:
        return None
    idx = np.full((num_segments, Cmax), pad, dtype=np.int64)
    order = np.argsort(cell, kind="stable")
    sc, sf = cell[order], flat[order]
    starts = np.zeros(num_segments + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    rank = np.arange(len(sc)) - starts[sc]
    idx[sc, rank] = sf
    return (np.asarray(w0, dtype=np.int32), idx, WLmax, Cmax)


_SCALARS_CACHE: dict = {}


def query_scalars(t_lo, t_hi, start: int, interval: int):
    """ONE per-query H2D upload of the window parameters (each
    device_put pays the full tunnel latency — ship them together).
    Repeated warm queries (dashboards) hit the value-keyed cache and
    upload nothing."""
    import jax

    from . import compileaudit
    key = (t_lo, t_hi, start, interval)
    got = _SCALARS_CACHE.get(key)
    if got is not None:
        return got
    if len(_SCALARS_CACHE) > 256:
        _SCALARS_CACHE.clear()
    dev = jax.device_put(np.array(
        [t_lo if t_lo is not None else I64MIN,
         t_hi if t_hi is not None else I64MAX,
         start, interval], dtype=np.int64))
    compileaudit.record_h2d("scalars", int(dev.nbytes))
    _SCALARS_CACHE[key] = dev
    return dev


def cached_gids(gid_arr: np.ndarray):
    """Device copy of a query's block→group-id vector, keyed by content
    in the device block cache: a warm repeat (same grouping/filters over
    the same files) re-uses the resident vector — zero H2D."""
    import jax

    from . import compileaudit
    if not devicecache.enabled():
        dev = jax.device_put(gid_arr)
        compileaudit.record_h2d("gids", int(dev.nbytes))
        return dev
    import hashlib
    h = hashlib.blake2b(gid_arr.tobytes(), digest_size=16).hexdigest()
    cache = devicecache.global_cache()
    key = ("gids", h, len(gid_arr))
    got = cache.get(key)
    if got is not None:
        return got
    dev = jax.device_put(gid_arr)
    compileaudit.record_h2d("gids", int(dev.nbytes))
    cache.put(key, dev)
    return dev


class _NoPlan:
    nbytes = 0


_NO_PLAN = _NoPlan()


def _prefix_dev_plan(st: BlockStack, gid_slice: np.ndarray,
                     start: int, interval: int, W: int,
                     num_segments: int):
    """Device copies of one slab's stage-3 plan, content-keyed in the
    device cache so warm repeats upload nothing. Size guards run on
    the cheap per-block spans BEFORE the lattice/index materialize;
    rejected shapes cache the verdict so every repeat doesn't redo the
    sizing, and accepted entries charge their true HBM bytes to the
    cache budget."""
    import jax
    cache = devicecache.global_cache() if devicecache.enabled() \
        else None
    key = None
    if cache is not None:
        import hashlib
        h = hashlib.blake2b(gid_slice.tobytes(),
                            digest_size=16).hexdigest()
        key = ("pplan", st.path, st.field, st.block0, h, start,
               interval, W, num_segments)
        got = cache.get(key)
        if got is _NO_PLAN:
            return None
        if got is not None:
            return got

    def reject():
        if cache is not None:
            cache.put(key, _NO_PLAN)
        return None

    _w0, wl, WLmax = _prefix_spans(st, gid_slice, start, interval, W)
    entries = int(wl.sum())
    if (st.n_blocks * WLmax + 1 >= (1 << 31)     # int32 gather index
            or entries > PLAN_MAX_ENTRIES):      # lattice/host budget
        return reject()
    plan = prefix_plan(st, gid_slice, start, interval, W, num_segments)
    if plan is None:                 # true (cells, Cmax) over budget
        return reject()
    w0, idx, WLmax, Cmax = plan
    ent = (jax.device_put(w0),
           jax.device_put(idx.astype(np.int32)), WLmax, Cmax)
    from . import compileaudit
    compileaudit.record_h2d("pplan",
                            int(ent[0].nbytes + ent[1].nbytes))
    if cache is not None:
        # a tuple has no .nbytes, so put() stakes a 64-byte
        # placeholder — reprice with the real device footprint,
        # mirrored into the HBM ledger (ops/hbm.py)
        cache.put(key, ent)
        cache.reprice(key, int(ent[0].nbytes + ent[1].nbytes))
    return ent


def file_aggregate(slabs: list[BlockStack], gids: np.ndarray,
                   t_lo, t_hi, start: int, interval: int, W: int,
                   num_segments: int, want: tuple, scalars=None,
                   gids_dev=None, route: str | None = None):
    """Launch the kernel per slab and combine on device — ONE packed
    plane array per file stays on device (the caller batches the pull
    and unpacks with unpack_planes). Window width picks the kernel:
    masked-pass unroll up to MASK_W_MAX, the scatter-free prefix
    kernel for wider grids (min/max shapes keep the scatter
    fallback — extrema are not prefix-decomposable)."""
    import jax
    K = slabs[0].limbs.shape[-1]
    if scalars is None:
        scalars = query_scalars(t_lo, t_hi, start, interval)
    if gids_dev is None:
        # content-keyed + booked upload (oglint R10): warm repeats of
        # the same grouping re-use the resident vector, cold ones book
        # their bytes into the transfer manifest
        gids_dev = cached_gids(np.asarray(gids, dtype=np.int64))
    # int32 limb cumsums stay exact while SEG·(2^18-1) < 2^31.
    # `route` is the PLAN's windowing-family choice (WindowKernelRule:
    # "mask" unrolls masked passes, "prefix" takes the scatter-free
    # cumsum kernels); without a plan the W threshold decides locally
    wide = (W > MASK_W_MAX) if route is None else (route == "prefix")
    use_prefix = (wide and interval > 0
                  and not ({"min", "max", "sumsq"} & set(want))
                  and slabs[0].seg_rows <= (1 << 13)
                  and slabs[0].t_min is not None)
    out = None
    comb = _pairwise_combine(want, K)
    for st in slabs:
        g = gids_dev[st.block0:st.block0 + st.n_blocks]
        o = None
        if use_prefix:
            G = num_segments // W
            # B <= 4096 keeps the digit-split matmul partial sums
            # under 2^24 (f32-exact); bigger slabs (OG_BLOCK_SLAB
            # override) take the searchsorted/gather-plan kernel.
            # G is capped: the one-hot einsum is P·B·G·W flops —
            # fine for per-query group counts, catastrophic for
            # per-host grids (G=16k measured ~12s/slab); wide-G
            # shapes route to the gather-plan kernel instead
            if (st.all_const and st.t0_dev is not None
                    and st.n_blocks <= 4096
                    and G <= ARITH_G_MAX
                    and G * W == num_segments):
                fn = _kernel_prefix_arith(num_segments, want, W, K,
                                          st.seg_rows, G)
                o = fn(st.valid, st.times, st.limbs, st.bad, g,
                       scalars, st.t0_dev, st.step_dev, st.rows_dev)
            if o is None:
                plan = _prefix_dev_plan(
                    st,
                    np.asarray(gids[st.block0:st.block0 + st.n_blocks],
                               dtype=np.int64),
                    int(start), int(interval), W, num_segments)
                if plan is not None:
                    w0_dev, idx_dev, WLmax, Cmax = plan
                    fn = _kernel_prefix(num_segments, want, W, K,
                                        st.seg_rows, WLmax, Cmax)
                    o = fn(st.values, st.valid, st.times, st.limbs,
                           st.bad, g, scalars, w0_dev, idx_dev)
        if o is None:
            fn = _kernel(num_segments, want, W, K, st.seg_rows)
            o = fn(st.values, st.valid, st.times, st.limbs, st.bad, g,
                   st.block0_dev, scalars)
        from . import devstats
        devstats.bump("kernel_launches")
        out = o if out is None else comb(out, o)
    return out


def gather_exact_values(slabs: list[BlockStack], reader,
                        flat_idx: np.ndarray):
    """Vectorized exact gather: (C,) global flat indices (sentinel
    I64MAX = empty) → ((C,) f64 values, (C,) has mask). Cells grouped
    by block so each segment decodes once (readcache-hot)."""
    seg_rows = slabs[0].seg_rows
    total_blocks = slabs[-1].block0 + slabs[-1].n_blocks
    n = total_blocks * seg_rows
    idx = np.asarray(flat_idx, dtype=np.int64)
    has = (idx >= 0) & (idx < n)
    out = np.zeros(len(idx), dtype=np.float64)
    if not has.any():
        return out, has
    sel = np.nonzero(has)[0]
    b = idx[sel] // seg_rows
    off = idx[sel] % seg_rows
    offsets = [s.block0 for s in slabs]
    for blk in np.unique(b):
        si = int(np.searchsorted(offsets, blk, side="right")) - 1
        st = slabs[si]
        colm, seg = st.seg_refs[int(blk) - st.block0]
        cv = reader.read_segment(colm, seg)
        m = b == blk
        out[sel[m]] = cv.values[off[m]]
    return out, has


# ----------------------- device order-statistic (sketch) finalize


def device_sketch_on() -> bool:
    """Gate for the device order-statistic finalize of raw-slice
    aggregates (percentile/median/mode) over HBM-resident sorted-
    sample planes (OG_DEVICE_SKETCH, default on). Selection-based
    finalizers return INPUT values — backend-independent — but the
    even-length median averages the two midpoints in one IEEE f64
    add+halve, which drifts on f32-pair-emulated backends: the gate
    rides the same real-f64 allowlist as the finalize epilogue
    (OG_DEVICE_FINALIZE=force overrides it for verified hardware),
    and OG_DEVICE_FINALIZE=0 switches this path off together with the
    epilogue — ONE escape hatch restores the whole legacy transport."""
    v = knobs.get_raw("OG_DEVICE_FINALIZE")
    if v == "0" or not bool(knobs.get("OG_DEVICE_SKETCH")):
        return False
    return True if v == "force" else _backend_real_f64()


def device_topk_on() -> bool:
    """Gate for the device ORDER BY/LIMIT cut over finalized answer
    planes (OG_DEVICE_TOPK, default on; 0 = byte-identical full-grid
    pull + host slicing). Pure selection over planes the finalize
    epilogue already produced, so it needs no extra backend gate —
    it can only engage where device_finalize_on() already did."""
    return bool(knobs.get("OG_DEVICE_TOPK"))


def _kernel_cellsort(num_segments: int, N: int):
    """jit: flat scan rows → cell-sorted sample planes. Rows that are
    invalid or outside the cell grid collapse into the trash segment
    (sorted last). The (sv, sid) pair IS the device-resident 'sketch'
    state: every order-statistic finalizer below is a gather over it,
    and the lexsort matches np.lexsort bit for bit (stable, NaN-last,
    ±0.0 order-preserving) so host/device selections cannot skew."""
    key = ("cs", num_segments, N)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn
    import jax.numpy as jnp

    ns = num_segments

    def _f(vals, valid, seg):
        sid = jnp.where(valid & (seg >= 0) & (seg < ns), seg,
                        ns).astype(jnp.int32)
        order = jnp.lexsort((vals, sid))
        return vals[order], sid[order]

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def sketch_sorted_planes(vals, valid, seg, num_segments: int,
                         cache_key: tuple | None = None):
    """Device-resident sorted-sample planes for one field's scan rows
    — (sv_dev, sid_dev), cell-sorted. Content lives in the HBM sketch
    tier (devicecache.sketch_cache, ledger tier "sketch", evicted by
    the OOM relief ladder before the block slabs) keyed by the scan
    plan identity, so a warm dashboard repeat skips the upload AND the
    sort. The upload books H2D site "sketch" (oglint R10)."""
    import jax

    from . import compileaudit, devstats
    cache = None
    if cache_key is not None and devicecache.sketch_capacity_bytes() > 0:
        cache = devicecache.sketch_cache()
        got = cache.get(("sksort",) + cache_key)
        if got is not None:
            devstats.bump("sketch_plane_hits")
            return got
    failpoint.inject("blockagg.sketch_fill")
    v = np.ascontiguousarray(vals, dtype=np.float64)
    m = np.ascontiguousarray(valid, dtype=np.bool_)
    s = np.ascontiguousarray(seg, dtype=np.int64)
    dv = jax.device_put(v)
    dm = jax.device_put(m)
    ds = jax.device_put(s)
    compileaudit.record_h2d("sketch",
                            int(dv.nbytes + dm.nbytes + ds.nbytes))
    fn = _kernel_cellsort(num_segments, len(v))
    sv, sid = fn(dv, dm, ds)
    devstats.bump("kernel_launches")
    devstats.bump("sketch_dev_rows", len(v))
    if cache is not None:
        cache.put_sized(("sksort",) + cache_key, (sv, sid),
                        int(sv.nbytes + sid.nbytes))
    return sv, sid


def _kernel_rawfin(num_segments: int, n_pct: int, with_median: bool,
                   with_mode: bool, N: int):
    """jit order-statistic finalize over cell-sorted planes → stacked
    (n_ops, S) answer grids (NaN = empty cell). Mirrors the host
    finalize_raw_agg formulas operand for operand:
      percentile: value at floor(len·p/100 + 0.5) − 1, clamped;
      median: midpoint value (odd) or the IEEE mean of the two
        middles (even — why this path needs real f64);
      mode: smallest value among the equal-value runs reaching the
        cell's max run length (the host 'first run' rule — runs are
        value-sorted, so first ≡ smallest)."""
    key = ("rf", num_segments, n_pct, with_median, with_mode, N)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    ns = num_segments

    def _f(sv, sid, ps):
        starts = jnp.searchsorted(sid, jnp.arange(ns, dtype=sid.dtype),
                                  side="left")
        ends = jnp.searchsorted(sid, jnp.arange(ns, dtype=sid.dtype),
                                side="right")
        lens = (ends - starts).astype(jnp.int64)
        has = lens > 0
        grids = []

        def at(idx):
            return sv[jnp.clip(starts + idx, 0, N - 1)]

        for j in range(n_pct):
            idx = jnp.floor(lens.astype(jnp.float64) * ps[j] / 100.0
                            + 0.5).astype(jnp.int64) - 1
            idx = jnp.clip(idx, 0, jnp.maximum(lens - 1, 0))
            grids.append(jnp.where(has, at(idx), jnp.nan))
        if with_median:
            hi = at(lens // 2)
            lo = at(jnp.maximum(lens // 2 - 1, 0))
            med = jnp.where(lens % 2 == 1, hi, (lo + hi) / 2.0)
            grids.append(jnp.where(has, med, jnp.nan))
        if with_mode:
            pos = jnp.arange(N, dtype=jnp.int64)
            newrun = jnp.concatenate([
                jnp.ones(1, dtype=bool),
                (sv[1:] != sv[:-1]) | (sid[1:] != sid[:-1])])
            rs = jax.lax.cummax(jnp.where(newrun, pos, 0))
            nxt = jnp.concatenate([
                jnp.where(newrun, pos, N)[1:],
                jnp.full(1, N, dtype=jnp.int64)])
            ne = jax.lax.cummin(nxt[::-1])[::-1]
            rcnt = ne - rs
            maxc = jax.ops.segment_max(rcnt, sid, ns + 1,
                                       indices_are_sorted=True)
            win = rcnt == maxc[sid]
            winner = jax.ops.segment_min(
                jnp.where(win, sv, jnp.inf), sid, ns + 1,
                indices_are_sorted=True)[:ns]
            grids.append(jnp.where(has, winner, jnp.nan))
        return jnp.stack(grids)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def rawfin_grids(sv_dev, sid_dev, num_segments: int,
                 pcts: list, with_median: bool, with_mode: bool):
    """Launch the order-statistic finalize over resident sorted-sample
    planes. Returns the DEVICE (n_ops, S) grid stack (answer-sized —
    the caller pulls it batched); row order is pcts..., median?,
    mode?. Percentile args travel as a traced vector so one compiled
    kernel serves every p."""
    from . import devstats
    ps = np.asarray(pcts if pcts else [0.0], dtype=np.float64)
    fn = _kernel_rawfin(num_segments, len(pcts), with_median,
                        with_mode, int(sv_dev.shape[0]))
    out = fn(sv_dev, sid_dev, ps)
    devstats.bump("kernel_launches")
    devstats.bump("sketch_dev_grids")
    return out


# ------------------------------------ device ORDER BY / LIMIT cut


def _unbits_of(bits, S: int):
    """Traced inverse of _bits_of → bool (S,)."""
    import jax.numpy as jnp
    lanes = ((bits[:, None] >> jnp.arange(32, dtype=jnp.uint32)[None, :])
             & 1)
    return lanes.reshape(-1)[:S].astype(bool)


def _topk_stage(u32, pres_bits, flag_bits, f64, *, G: int, W: int,
                kk: int, desc: bool, offset: int, null_fill: bool,
                need_count: bool, has_flag: bool, n_f64: int):
    """Trace-composable body of _kernel_topk (round 17): a pure
    function of traced operands + static keyword config that the
    fused program tracer (ops/fused.py) inlines into one jit
    body; the staged factory jit-wraps exactly this call — one
    definition, bit-identical on both routes."""
    import jax.numpy as jnp

    S = G * W
    BIG = W + kk + 2
    wdt = jnp.uint16 if W <= 0xFFFF else jnp.int32
    if need_count:
        cnt = u32[0].astype(jnp.int64)
        present = (cnt > 0).reshape(G, W)
    else:
        present = _unbits_of(pres_bits, S).reshape(G, W)
    emit = jnp.ones((G, W), dtype=bool) if null_fill else present
    if desc:
        # suffix count: the highest emitting window ranks 1
        rank = jnp.cumsum(emit[:, ::-1], axis=1)[:, ::-1]
        rank = jnp.where(emit, rank, 0)
    else:
        rank = jnp.where(emit, jnp.cumsum(emit, axis=1), 0)
    keyv = jnp.where(emit & (rank > offset)
                     & (rank <= offset + kk),
                     rank - offset, BIG).astype(jnp.int32)
    order = jnp.argsort(keyv, axis=1, stable=True)[:, :kk]
    kw = jnp.take_along_axis(keyv, order, axis=1)
    win = kw <= kk                       # rank prefix per group
    widx = jnp.where(win, order, 0).astype(wdt)
    safe = jnp.maximum(order, 0)
    nwin = win.sum(axis=1).astype(jnp.int32)
    wpres = jnp.take_along_axis(present, safe, axis=1) & win
    outs = [widx, nwin]
    if null_fill:
        # fill=null emits rows for empty windows, so winner
        # presence and the group-has-any-data gate must ship
        # (fill=none winners are present by construction)
        outs.append(_bits_of(wpres.reshape(-1), G * kk))
        outs.append(_bits_of(present.any(axis=1), G))
    if need_count:
        outs.append(jnp.where(
            wpres, jnp.take_along_axis(cnt.reshape(G, W), safe,
                                       axis=1), 0)
            .astype(jnp.uint32))
    if has_flag:
        flags = _unbits_of(flag_bits, S).reshape(G, W)
        wf = jnp.take_along_axis(flags, safe, axis=1) & wpres
        outs.append(_bits_of(wf.reshape(-1), G * kk))
    if n_f64:
        fw = [jnp.take_along_axis(f64[i].reshape(G, W), safe,
                                  axis=1) for i in range(n_f64)]
        outs.append(jnp.stack(fw))
    return tuple(outs)


def _kernel_topk(G: int, W: int, kk: int, desc: bool, offset: int,
                 null_fill: bool, need_count: bool, has_flag: bool,
                 n_f64: int):
    """jit segmented top-k over a finalized answer grid: per group,
    select the first ``kk`` ROW-EMITTING windows in output order
    (ascending, or descending under ORDER BY time DESC) after
    skipping ``offset`` — exactly the native build_group_rows walk —
    and compact every shipped plane to the (G, kk) winner cells.

    fill=none ranks only PRESENT windows (count > 0); fill=null emits
    a row per window, so the cut is a static slice with per-winner
    presence shipped for the None cells. The transport is winner-
    sized AND winner-shaped: window ids ship as uint16 when W fits,
    presence/flag/group-has masks bit-pack 32 cells per word, and the
    winner mask itself is never shipped (winners are a rank prefix —
    row j of group g is live iff j < nwin[g])."""
    key = ("tk", G, W, kk, desc, offset, null_fill, need_count,
           has_flag, n_f64)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn

    def _f(u32, pres_bits, flag_bits, f64):
        return _topk_stage(u32, pres_bits, flag_bits, f64, G=G,
                           W=W, kk=kk, desc=desc, offset=offset,
                           null_fill=null_fill,
                           need_count=need_count,
                           has_flag=has_flag, n_f64=n_f64)

    _f = _named_jit(_f, key)
    _JITTED[key] = _f
    return _f


def topk_cut(fin_arrs, G: int, W: int, kk: int, desc: bool,
             offset: int, null_fill: bool):
    """Run the segmented top-k kernel over a finalize-epilogue
    transport tuple (u32, pres_bits, flag_bits, f64 — finalize_grid's
    device outputs). Returns the device winner tuple for _emit; the
    host inverse is unpack_topk."""
    from . import devstats
    u32, pres, flag, f64 = fin_arrs
    need_count = u32 is not None
    has_flag = flag is not None
    n_f64 = 0 if f64 is None else int(f64.shape[0])
    fn = _kernel_topk(G, W, kk, desc, offset, null_fill, need_count,
                      has_flag, n_f64)
    devstats.bump("kernel_launches")
    devstats.bump("topk_grids")
    return fn(u32, pres, flag, f64)


def unpack_topk(arrs, planes_dev, K: int, k0: int, E: int,
                dev_mean: bool, ship_sum: bool, need_count: bool,
                G: int, W: int, kk: int,
                null_fill: bool) -> dict:
    """Pulled winner tuple → the topk bo the executor threads into the
    partial: widx/nwin (winners are the rank prefix j < nwin[g]) plus
    per-op winner planes, presence expanded from the bit transport.
    Flagged winner cells (finalize hazard ∪ limb residue) repair here
    exactly like unpack_finalized — ONE sparse gather of the
    still-resident pre-finalize rows, restricted to winners (the only
    cells that will ever be read)."""
    import time as _time
    arrs = [None if a is None else np.asarray(a) for a in arrs]
    i = 0
    widx = arrs[i].astype(np.int64); i += 1
    nwin = arrs[i].astype(np.int64); i += 1
    win = (np.arange(kk)[None, :] < nwin[:, None])
    if null_fill:
        wpres = expand_bits(arrs[i], G * kk).reshape(G, kk) & win
        i += 1
        group_has = expand_bits(arrs[i], G)[:G]
        i += 1
    else:
        wpres = win
        group_has = nwin > 0
    bo: dict = {"widx": widx, "nwin": nwin, "group_has": group_has,
                "pres": wpres}
    wflag = None
    if need_count:
        bo["count"] = arrs[i].astype(np.int64); i += 1
    sum_p = mean_p = None
    if ship_sum or dev_mean:
        # a sum-bearing recipe always ships the hazard/residue flag
        # bits and then the f64 answer planes (finalize kernel layout)
        wflag = expand_bits(arrs[i], G * kk).reshape(G, kk)
        i += 1
        f64w = arrs[i]
        j = 0
        if ship_sum:
            sum_p = np.array(f64w[j], dtype=np.float64); j += 1
        if dev_mean:
            mean_p = np.array(f64w[j], dtype=np.float64)
    if wflag is not None:
        hit = np.nonzero(win & wflag)
        if len(hit[0]):
            from . import compileaudit, devstats
            t0 = _time.perf_counter_ns()
            cells = (hit[0] * W + widx[hit]).astype(np.int64)
            # sparse winner repair — manifest-booked below, exempt
            # from the R1 transport rule like the finalize repair
            sub = np.asarray(planes_dev[:, cells])  # oglint: disable=R103
            compileaudit.record_d2h("repair", int(sub.nbytes))
            bo["_repair_nbytes"] = int(sub.nbytes)
            full = np.zeros((len(cells), exactsum.K_LIMBS))
            full[:, k0:k0 + K] = sub[1:1 + K].T
            sums = exactsum.finalize_exact(full, E)
            if sum_p is not None:
                sum_p[hit] = sums
            if mean_p is not None:
                cnt_f = sub[0].astype(np.int64)
                mean_p[hit] = sums / np.maximum(cnt_f, 1)
            devstats.bump_phase("device_topk",
                                _time.perf_counter_ns() - t0)
    if sum_p is not None:
        bo["sum"] = sum_p
    if mean_p is not None:
        bo["mean"] = mean_p
    return {"topk": bo}
