"""Device-resident block aggregation: the HBM tier of the storage engine.

The round-1 verdict's core critique was TPU paths living as leaves no
query reaches. This module is the opposite design point: a TSSP file's
column segments are staked into HBM ONCE — values, validity, times, and
the exact-sum limb planes (ops/exactsum.py) — and then ANY aggregate
query shape (different windows, time ranges, tag filters, groupings)
reduces ON DEVICE with only a tiny per-query gid vector uploaded and a
result grid pulled.

Why this fits the hardware (measured on the axon-attached v5e):
- H2D ≈ 0.7 GB/s but D2H ≈ 30 MB/s: ship raw data up once, pull only
  result grids. The dispatcher (executor) uses this path when
  rows/cells is large enough that the device reduction beats host
  numpy AND the result grid is small enough to pull.
- f64 is emulated as float32 pairs: float sums would drift, so the
  AUTHORITATIVE sums are int32 limb-plane reductions — exact integer
  arithmetic, bit-identical with every other path. min/max return row
  INDICES; exact values gather host-side from the readcache.
- Stacks are SLABBED (OG_BLOCK_SLAB blocks per kernel launch) to bound
  the scatter temporaries; slab results combine on device and ONE grid
  crosses D2H.

Reference roles covered: lib/readcache/blockcache.go (block cache, HBM
tier), engine/immutable/reader.go decode + series_agg_func reduce
kernels (fused here), aggregateCursor windowing (in-kernel window ids).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..utils import get_logger
from . import devicecache, exactsum

log = get_logger(__name__)

I64MAX = np.iinfo(np.int64).max
I64MIN = np.iinfo(np.int64).min

# blocks per kernel launch: bounds the flattened row count (and hence
# XLA scatter temporaries) of one launch to SLAB × SEG rows. Each
# launch pays a full dispatch round-trip on tunnel-attached devices, so
# bigger is better until the temporaries stop fitting
SLAB_BLOCKS = int(os.environ.get("OG_BLOCK_SLAB", "4096"))


@dataclass
class BlockStack:
    """One slab of a (file, field)'s segments resident in HBM.

    Device arrays (jax) all shaped (B, SEG) with ragged tails padded
    valid=False:
      values f64 | valid bool | times i64 | limbs i32 (B, SEG, K) | bad
    Host metadata: the block→series map and per-block segment refs for
    exact-value gathers. ``block0`` is this slab's global block offset
    within the file.
    """
    path: str
    field: str
    seg_rows: int                    # SEG (padded block width)
    E: int                           # limb scale (multiple of 18)
    block_sids: np.ndarray           # (B,) int64
    seg_refs: list                   # (B,) [(colmeta, segment)] host
    n_rows: int                      # real rows (un-padded)
    block0: int = 0
    values: object = None            # jax (B, SEG) f64
    valid: object = None             # jax (B, SEG) bool
    times: object = None             # jax (B, SEG) i64
    limbs: object = None             # jax (B, SEG, K) i32
    bad: object = None               # jax (B, SEG) bool (limb residual)

    @property
    def n_blocks(self) -> int:
        return len(self.block_sids)

    @property
    def nbytes(self) -> int:
        return sum(int(getattr(a, "nbytes", 0)) for a in
                   (self.values, self.valid, self.times, self.limbs,
                    self.bad))


def _file_layout(reader, field: str):
    """(metas, SEG, E) — or None when the column can't stack."""
    from ..record import DataType
    metas = []
    for sid in reader.series_ids():
        cm = reader.chunk_meta(sid)
        if cm is None:
            continue
        colm = cm.column(field)
        tm = cm.column("time")
        if colm is None or tm is None:
            continue
        if colm.type != DataType.FLOAT:
            # integers keep their exact typed-int64 host/sparse path
            # (the f64 staking would round above 2^53); strings/bools
            # never stack
            return None
        for si, s in enumerate(colm.segments):
            metas.append((sid, colm, s, tm.segments[si]))
    if not metas:
        return None
    seg = max(s.rows for _sid, _c, s, _t in metas)
    if seg == 0:
        return None
    mx = 0.0
    for _sid, _c, s, _t in metas:
        if s.preagg is not None and s.preagg.count:
            mx = max(mx, abs(s.preagg.min), abs(s.preagg.max))
    return metas, seg, exactsum.pick_scale(mx)


def _build_slab(reader, field: str, metas, seg: int, E: int,
                block0: int) -> BlockStack:
    import jax
    B = len(metas)
    vals = np.zeros((B, seg), dtype=np.float64)
    valid = np.zeros((B, seg), dtype=np.bool_)
    times = np.zeros((B, seg), dtype=np.int64)
    sids = np.empty(B, dtype=np.int64)
    refs: list = []
    n_rows = 0
    for b, (sid, colm, s, tseg) in enumerate(metas):
        cv = reader.read_segment(colm, s)
        tv = reader.read_segment(_TimeCol, tseg)
        r = s.rows
        vals[b, :r] = cv.values.astype(np.float64, copy=False)
        valid[b, :r] = cv.valid
        times[b, :r] = tv.values
        sids[b] = sid
        refs.append((colm, s))
        n_rows += r
    limbs, bad = exactsum.host_limbs(vals, valid, E)
    st = BlockStack(reader.path, field, seg, E, sids, refs, n_rows,
                    block0)
    st.values = jax.device_put(vals)
    st.valid = jax.device_put(valid)
    st.times = jax.device_put(times)
    st.limbs = jax.device_put(limbs.astype(np.int32))
    st.bad = jax.device_put(bad)
    return st


class _TimeColMeta:
    """Minimal ColumnMeta stand-in for decoding time segments (the
    reader only consults .type)."""
    def __init__(self):
        from ..record import DataType
        self.type = DataType.TIME
        self.name = "time"


_TimeCol = _TimeColMeta()


def get_stacks(reader, field: str) -> list[BlockStack] | None:
    """Cached slab list for (file, field); None when the column can't
    stack (missing, non-float) — negative results cache too."""
    if not devicecache.enabled():
        return None
    cache = devicecache.global_cache()
    key = (reader.path, field, "blockslabs")
    got = cache.get(key)
    if got is _NO_STACK:
        return None
    if got is not None:
        return got
    layout = _file_layout(reader, field)
    if layout is None:
        cache.put(key, _NO_STACK)
        return None
    metas, seg, E = layout
    slabs = []
    block0 = 0
    for i in range(0, len(metas), SLAB_BLOCKS):
        sl = _build_slab(reader, field, metas[i:i + SLAB_BLOCKS], seg,
                         E, block0)
        slabs.append(sl)
        block0 += sl.n_blocks
    cache.put(key, slabs)
    with cache._lock:   # account real HBM footprint
        if key in cache._map:
            nb = sum(s.nbytes for s in slabs) + 64
            cache._map[key] = (slabs, nb)
            cache._bytes += nb - 64
    return slabs


class _NoStack:
    nbytes = 0


_NO_STACK = _NoStack()


_JITTED: dict = {}


def _kernel(num_segments: int, want: tuple):
    fn = _JITTED.get(("k", num_segments, want))
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _f(values, valid, times, limbs, bad, gids, block0, t_lo, t_hi,
           start, interval, W):
        B, SEG = values.shape
        n = B * SEG
        v = values.reshape(n)
        m = valid.reshape(n)
        t = times.reshape(n)
        lb = limbs.reshape(n, -1)
        bd = bad.reshape(n)
        g = jnp.repeat(gids, SEG)
        m = m & (g >= 0) & (t >= t_lo) & (t <= t_hi)
        w = (t - start) // interval
        inwin = (w >= 0) & (w < W)
        seg = jnp.where(m & inwin, g * W + w, num_segments)
        seg = seg.astype(jnp.int64)
        ns = num_segments + 1
        out = {}
        out["count"] = jax.ops.segment_sum(
            m.astype(jnp.int64), seg, ns)[:num_segments]
        if "sum" in want:
            # per-limb scatters: no (n, K) int64 temporary (that blew
            # XLA's temp budget at large slabs). The f64 sum is NOT
            # computed on device — the caller derives the fallback from
            # the limb totals (exact when the flag holds, truncated-
            # but-deterministic otherwise)
            out["limbs"] = jnp.stack(
                [jax.ops.segment_sum(
                    jnp.where(m, lb[:, k], 0).astype(jnp.int64), seg,
                    ns)[:num_segments]
                 for k in range(lb.shape[1])], axis=-1)
            out["bad"] = jax.ops.segment_max(
                (m & bd).astype(jnp.int32), seg, ns)[:num_segments] > 0
        if "sumsq" in want:
            vz = jnp.where(m, v, 0.0)
            out["sumsq"] = jax.ops.segment_sum(vz * vz, seg,
                                               ns)[:num_segments]
        # global flat row ids (slab offset folded in); sentinel I64MAX
        gidx = jnp.arange(n, dtype=jnp.int64) + block0 * SEG
        if "min" in want:
            ext = jax.ops.segment_min(jnp.where(m, v, jnp.inf), seg, ns)
            out["min"] = ext[:num_segments]
            at = m & (v == ext[seg])
            out["min_idx"] = jax.ops.segment_min(
                jnp.where(at, gidx, I64MAX), seg, ns)[:num_segments]
        if "max" in want:
            ext = jax.ops.segment_max(jnp.where(m, v, -jnp.inf), seg, ns)
            out["max"] = ext[:num_segments]
            at = m & (v == ext[seg])
            out["max_idx"] = jax.ops.segment_min(
                jnp.where(at, gidx, I64MAX), seg, ns)[:num_segments]
        return out
    _JITTED[("k", num_segments, want)] = _f
    return _f


def _combiner(want: tuple, n_slabs: int):
    fn = _JITTED.get(("c", want, n_slabs))
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _c(outs):
        comb = {"count": sum(o["count"] for o in outs)}
        if "sum" in want:
            # the kernel emits only the exact limb planes for sums (the
            # f64 sum is finalized from limb totals by the caller)
            comb["limbs"] = sum(o["limbs"] for o in outs)
            comb["bad"] = jnp.stack([o["bad"] for o in outs]).any(0)
        if "sumsq" in want:
            comb["sumsq"] = sum(o["sumsq"] for o in outs)
        if "min" in want:
            ms = jnp.stack([o["min"] for o in outs])
            k = jnp.argmin(ms, axis=0)
            comb["min"] = jnp.take_along_axis(ms, k[None], 0)[0]
            comb["min_idx"] = jnp.take_along_axis(
                jnp.stack([o["min_idx"] for o in outs]), k[None], 0)[0]
        if "max" in want:
            ms = jnp.stack([o["max"] for o in outs])
            k = jnp.argmax(ms, axis=0)
            comb["max"] = jnp.take_along_axis(ms, k[None], 0)[0]
            comb["max_idx"] = jnp.take_along_axis(
                jnp.stack([o["max_idx"] for o in outs]), k[None], 0)[0]
        return comb
    _JITTED[("c", want, n_slabs)] = _c
    return _c


def file_aggregate(slabs: list[BlockStack], gids: np.ndarray,
                   t_lo, t_hi, start: int, interval: int, W: int,
                   num_segments: int, want: tuple):
    """Launch the kernel per slab and combine on device — one small
    result dict crosses D2H (the caller batches the pull)."""
    import jax.numpy as jnp
    fn = _kernel(num_segments, want)
    lo = jnp.int64(t_lo if t_lo is not None else I64MIN)
    hi = jnp.int64(t_hi if t_hi is not None else I64MAX)
    outs = []
    for st in slabs:
        g = gids[st.block0:st.block0 + st.n_blocks]
        outs.append(fn(st.values, st.valid, st.times, st.limbs, st.bad,
                       jnp.asarray(g, dtype=jnp.int64),
                       jnp.int64(st.block0), lo, hi, jnp.int64(start),
                       jnp.int64(interval), jnp.int64(W)))
    if len(outs) == 1:
        return outs[0]
    return _combiner(want, len(outs))(outs)


def gather_exact_values(slabs: list[BlockStack], reader,
                        flat_idx: np.ndarray):
    """Vectorized exact gather: (C,) global flat indices (sentinel
    I64MAX = empty) → ((C,) f64 values, (C,) has mask). Cells grouped
    by block so each segment decodes once (readcache-hot)."""
    seg_rows = slabs[0].seg_rows
    total_blocks = slabs[-1].block0 + slabs[-1].n_blocks
    n = total_blocks * seg_rows
    idx = np.asarray(flat_idx, dtype=np.int64)
    has = idx < n
    out = np.zeros(len(idx), dtype=np.float64)
    if not has.any():
        return out, has
    sel = np.nonzero(has)[0]
    b = idx[sel] // seg_rows
    off = idx[sel] % seg_rows
    offsets = [s.block0 for s in slabs]
    for blk in np.unique(b):
        si = int(np.searchsorted(offsets, blk, side="right")) - 1
        st = slabs[si]
        colm, seg = st.seg_refs[int(blk) - st.block0]
        cv = reader.read_segment(colm, seg)
        m = b == blk
        out[sel[m]] = cv.values[off[m]]
    return out, has
