"""Segment-reduction aggregation kernels (the framework's hot loop).

Role of the reference's generated reduce kernels and streaming window cursors:
- engine/series_agg_func.gen.go:48 (floatSumReduce & friends)
- engine/series_agg_reducer.gen.go (cross-record window state machines)
- engine/aggregate_cursor.go:90-142 (window loop)

TPU-first formulation: a query window aggregate over many series is ONE fused
kernel over flat column arrays:

    seg_id[i] = group_id[i] * num_windows + window_id[i]
    out[agg][seg] = segment_reduce(values[i] where valid[i])

Two device paths:
- **sparse**: jax.ops.segment_* with sorted segment ids — fully general
  (irregular sampling, nulls, gaps).
- **dense**: when every (group, window) holds exactly P points (regular
  sampling, the TSBS shape — detected upstream from const-delta time blocks),
  data reshapes to (G*W, P) and reduces on the VPU with zero scatter.

Results for count/sum/min/max/first/last are computed in one jitted call so
XLA fuses the masking, id arithmetic and reductions into a single pass over
HBM. Empty segments are reported via count==0; min/max carry +/-inf there,
first/last carry NaN — callers mask on count.

Shapes are padded to buckets (pad_bucket) so repeated queries hit the jit
cache; padding rows carry valid=False and seg_id=num_segments (a trash
segment sliced off before returning).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_F64 = jnp.float64
_I64 = jnp.int64

# aggregates computed by the fused kernel
ALL_AGGS = ("count", "sum", "sumsq", "min", "max", "first", "last",
            "min_time", "max_time")


class AggSpec(NamedTuple):
    """Which aggregates a query needs (subset → XLA dead-code-eliminates the
    rest after fusion, but being explicit also skips gather setup).
    min_time/max_time track the EARLIEST timestamp achieving the extremum
    (influx selector row times: `SELECT max(v)` returns the max point's
    time)."""
    count: bool = True
    sum: bool = True
    sumsq: bool = False
    min: bool = False
    max: bool = False
    first: bool = False
    last: bool = False
    min_time: bool = False
    max_time: bool = False

    @classmethod
    def of(cls, *names: str) -> "AggSpec":
        names_set = set(names)
        for n in names_set:
            if n not in ALL_AGGS and n not in ("mean", "stddev"):
                raise ValueError(f"unknown aggregate {n}")
        if "mean" in names_set:
            names_set |= {"count", "sum"}
        if "stddev" in names_set:
            # stddev finalizes from the (count, sum, sumsq) mergeable state
            # (the reference's FloatStddevReduce keeps raw slices instead —
            # engine/series_agg_func.gen.go — but moment form is the
            # device-friendly mergeable formulation)
            names_set |= {"count", "sum", "sumsq"}
        if "min_time" in names_set:
            names_set.add("min")
        if "max_time" in names_set:
            names_set.add("max")
        return cls(**{k: (k in names_set) for k in ALL_AGGS})


class SegmentAggResult(NamedTuple):
    """Per-segment aggregate states. Fields are None when not requested.
    This is also the *mergeable partial state* exchanged between devices
    (the analog of the reference's partial-agg chunks sent over spdy):
    two results combine with `merge_seg_results` (sum/count add, min/max
    min/max, first/last pick by time)."""
    count: jax.Array | None = None
    sum: jax.Array | None = None
    sumsq: jax.Array | None = None
    min: jax.Array | None = None
    max: jax.Array | None = None
    first: jax.Array | None = None        # value at earliest valid time
    last: jax.Array | None = None         # value at latest valid time
    first_time: jax.Array | None = None
    last_time: jax.Array | None = None
    min_time: jax.Array | None = None     # earliest time achieving min
    max_time: jax.Array | None = None     # earliest time achieving max

    def mean(self) -> jax.Array:
        cnt = jnp.maximum(self.count, 1)
        return self.sum / cnt.astype(self.sum.dtype)


def pad_bucket(n: int, minimum: int = 1024) -> int:
    """Round row count up to a bucket so jit cache keys recur: next power of
    two below 64k, then next multiple of 64k (keeps waste <~2x small, <2%
    large)."""
    if n <= minimum:
        return minimum
    if n <= 65536:
        return 1 << (n - 1).bit_length()
    step = 65536
    return (n + step - 1) // step * step


@functools.partial(jax.jit, static_argnames=("num_windows",))
def window_ids(times: jax.Array, start_time, interval, num_windows: int):
    """window index per row; rows outside [start, start+W*interval) get
    id == num_windows (trash window). Analog of the reference's window
    detection inNextWindowWithInfo (engine/aggregate_cursor.go)."""
    w = (times - start_time) // interval
    return jnp.where((w >= 0) & (w < num_windows), w, num_windows).astype(_I64)


def _extremum_time_dense(values, valid, times, extremum):
    """Earliest time of a row's extremum point (dense (S, P) layout).
    valid=None means every point valid."""
    at = values == extremum[:, None]
    if valid is not None:
        at = valid & at
    return jnp.where(at, times, jnp.iinfo(_I64).max).min(axis=1)


def _extremum_time_segment(values, valid, times, seg_ids, ns,
                           num_segments, sorted_ids, is_min: bool):
    """Earliest time of each segment's extremum point (sparse layout).
    XLA CSEs the recomputed extremum against the spec.min/max reduction."""
    pos, neg = _minmax_idents(values.dtype)
    ident = pos if is_min else neg
    seg_red = jax.ops.segment_min if is_min else jax.ops.segment_max
    ext = seg_red(jnp.where(valid, values, ident), seg_ids, ns,
                  indices_are_sorted=sorted_ids)
    at = valid & (values == ext[seg_ids])
    return jax.ops.segment_min(
        jnp.where(at, times, jnp.iinfo(_I64).max), seg_ids, ns,
        indices_are_sorted=sorted_ids)[:num_segments]


def _minmax_idents(dt):
    """±identity for min/max masking, dtype-aware: integer columns run
    typed kernels (int64 sums are exact AND order-free — the
    bit-identical path for integers needs no limb machinery)."""
    if jnp.issubdtype(dt, jnp.integer):
        info = jnp.iinfo(dt)
        return jnp.array(info.max, dt), jnp.array(info.min, dt)
    return jnp.array(jnp.inf, dt), jnp.array(-jnp.inf, dt)


def _segment_all(values, valid, seg_ids, num_segments: int,
                 spec: AggSpec, sorted_ids: bool):
    """Shared kernel body; num_segments includes NO trash segment — callers
    pass seg_ids already clipped to [0, num_segments]."""
    ns = num_segments + 1  # +1 trash segment for padding/out-of-range rows
    fdt = values.dtype
    pos_ident, neg_ident = _minmax_idents(fdt)
    res = {}
    vz = jnp.where(valid, values, jnp.zeros((), fdt))
    if spec.count or spec.sum:
        cnt = jax.ops.segment_sum(valid.astype(_I64), seg_ids, ns,
                                  indices_are_sorted=sorted_ids)
        res["count"] = cnt[:num_segments]
    if spec.sum:
        s = jax.ops.segment_sum(vz, seg_ids, ns,
                                indices_are_sorted=sorted_ids)
        res["sum"] = s[:num_segments]
    if spec.sumsq:
        sq = jax.ops.segment_sum(vz * vz, seg_ids, ns,
                                 indices_are_sorted=sorted_ids)
        res["sumsq"] = sq[:num_segments]
    if spec.min:
        vmin = jnp.where(valid, values, pos_ident)
        res["min"] = jax.ops.segment_min(vmin, seg_ids, ns,
                                         indices_are_sorted=sorted_ids)[:num_segments]
    if spec.max:
        vmax = jnp.where(valid, values, neg_ident)
        res["max"] = jax.ops.segment_max(vmax, seg_ids, ns,
                                         indices_are_sorted=sorted_ids)[:num_segments]
    return res


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "spec", "sorted_ids",
                     "host_gather"))
def segment_aggregate(values: jax.Array,
                      valid: jax.Array,
                      seg_ids: jax.Array,
                      times: jax.Array | None,
                      num_segments: int,
                      spec: AggSpec = AggSpec(),
                      sorted_ids: bool = True,
                      host_gather: bool = False) -> SegmentAggResult:
    """Sparse path: fused masked segment reductions.

    values: (N,) float; valid: (N,) bool; seg_ids: (N,) int in
    [0, num_segments] (num_segments = trash); times: (N,) int64, needed only
    for first/last.

    host_gather=True returns ROW INDICES in the first/last/min/max
    fields instead of gathered values (sentinels: n / -1 / n / n for
    empty cells): on platforms whose f64 is emulated as float32 pairs
    (axon), values round-tripped through the device lose low mantissa
    bits — the caller gathers exact values host-side. Times (int64)
    stay exact either way.
    """
    res = _segment_all(values, valid, seg_ids, num_segments, spec, sorted_ids)
    ns = num_segments + 1
    n = values.shape[0]
    min_t = max_t = None
    if spec.min_time or spec.max_time:
        if times is None:
            raise ValueError("min_time/max_time need times")
        if spec.min_time:
            min_t = _extremum_time_segment(
                values, valid, times, seg_ids, ns, num_segments,
                sorted_ids, is_min=True)
        if spec.max_time:
            max_t = _extremum_time_segment(
                values, valid, times, seg_ids, ns, num_segments,
                sorted_ids, is_min=False)
    if host_gather and (spec.min or spec.max):
        # earliest row index achieving the extremum (XLA CSEs the
        # extremum reductions against _segment_all's)
        idx = jnp.arange(n, dtype=_I64)
        pos, neg = _minmax_idents(values.dtype)
        if spec.min:
            ext = jax.ops.segment_min(jnp.where(valid, values, pos),
                                      seg_ids, ns,
                                      indices_are_sorted=sorted_ids)
            at = valid & (values == ext[seg_ids])
            res["min"] = jax.ops.segment_min(
                jnp.where(at, idx, n), seg_ids, ns,
                indices_are_sorted=sorted_ids)[:num_segments]
        if spec.max:
            ext = jax.ops.segment_max(jnp.where(valid, values, neg),
                                      seg_ids, ns,
                                      indices_are_sorted=sorted_ids)
            at = valid & (values == ext[seg_ids])
            res["max"] = jax.ops.segment_min(
                jnp.where(at, idx, n), seg_ids, ns,
                indices_are_sorted=sorted_ids)[:num_segments]
    first = last = first_t = last_t = None
    if spec.first or spec.last:
        if times is None:
            raise ValueError("first/last need times")
        idx = jnp.arange(n, dtype=_I64)
        if spec.first:
            fi = jax.ops.segment_min(jnp.where(valid, idx, n), seg_ids, ns,
                                     indices_are_sorted=sorted_ids)[:num_segments]
            safe = jnp.minimum(fi, n - 1)
            has = fi < n
            # first/last stay f64 even for typed integer columns: the
            # merge protocol marks empty cells with NaN
            first = fi if host_gather else \
                jnp.where(has, values[safe].astype(_F64), jnp.nan)
            first_t = jnp.where(has, times[safe], 0)
        if spec.last:
            li = jax.ops.segment_max(jnp.where(valid, idx, -1), seg_ids, ns,
                                     indices_are_sorted=sorted_ids)[:num_segments]
            safe = jnp.maximum(li, 0)
            has = li >= 0
            last = li if host_gather else \
                jnp.where(has, values[safe].astype(_F64), jnp.nan)
            last_t = jnp.where(has, times[safe], 0)
    return SegmentAggResult(
        count=res.get("count"), sum=res.get("sum"), sumsq=res.get("sumsq"),
        min=res.get("min"), max=res.get("max"),
        first=first, last=last, first_time=first_t, last_time=last_t,
        min_time=min_t, max_time=max_t)


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "spec", "sorted_ids",
                     "host_gather"))
def _multi_segment_jit(values_f, valid_f, limbs_f, seg_ids, times,
                       num_segments, spec, sorted_ids, host_gather):
    def one(v, m):
        return segment_aggregate(v, m, seg_ids, times,
                                 num_segments=num_segments, spec=spec,
                                 sorted_ids=sorted_ids,
                                 host_gather=host_gather)

    res = jax.vmap(one)(values_f, valid_f)
    lsum = None
    if limbs_f is not None:
        from .exactsum import exact_segment_sum_traced

        lsum = jax.vmap(
            lambda lb: exact_segment_sum_traced(
                lb, seg_ids, num_segments, sorted_ids))(
                    limbs_f)                  # (F, S, K) int64
    f64s, i64s = [], []
    for k in res._fields:
        v = getattr(res, k)
        if v is None:
            continue
        if v.dtype == jnp.float64:
            f64s.append(v)
        else:
            i64s.append(v.astype(jnp.int64))
    if lsum is not None:
        i64s = i64s + list(jnp.moveaxis(lsum, 2, 0))  # K (F, S) planes
    f64p = jnp.stack(f64s) if f64s else None
    i64p = jnp.stack(i64s) if i64s else None
    return res, lsum, f64p, i64p


def multi_segment_aggregate(values_f, valid_f, limbs_f, seg_ids, times,
                            num_segments: int, spec: AggSpec,
                            sorted_ids: bool = False,
                            host_gather: bool = False):
    """Batched multi-field sparse path: F fields reduce in ONE device
    invocation, and all result states cross D2H in at most TWO packed
    arrays (one per dtype). On remote-attached chips every jit call and
    every pull pays a full round trip (~100-300 ms measured on the
    tunnel-attached v5e), so a 10-field query is launch/pull-count
    bound, not compute bound.

    values_f/valid_f: (F, N); limbs_f: (F, N, K) int32 or None (exact
    sum planes, ops/exactsum.py). Returns (SegmentAggResult of host
    (F, num_segments) arrays, host (F, num_segments, K) int64 limb
    sums or None).
    """
    res, lsum, f64p, i64p = _multi_segment_jit(
        values_f, valid_f, limbs_f, seg_ids, times,
        num_segments=num_segments, spec=spec, sorted_ids=sorted_ids,
        host_gather=host_gather)
    # rebuild the jit's static packing order from leaf dtypes (device
    # arrays expose dtype/shape without a transfer)
    f64_keys = [k for k in res._fields
                if getattr(res, k) is not None
                and getattr(res, k).dtype == jnp.float64]
    i64_keys = [k for k in res._fields
                if getattr(res, k) is not None
                and getattr(res, k).dtype != jnp.float64]
    # ONE readiness wait + ONE parallel chunked fetch for BOTH packed
    # stacks: the old sequential np.asarray pair paid two full
    # round-trips on the tunnel link (the second blocked on the first's
    # completion before its transfer even started)
    if f64p is not None or i64p is not None:
        import jax

        from .pipeline import device_get_parallel
        try:
            jax.block_until_ready((f64p, i64p))
        except Exception as e:
            # the readiness wait is only an optimization (the fetch
            # below re-synchronizes) — but a device-classified failure
            # (OOM mid-reduce, backend death) must surface so the
            # fault ladder can retry/fall back instead of the fetch
            # hitting the same corpse with a worse error
            from . import devicefault as _df
            if _df.classify(e) is not None:
                raise
        f64h, i64h = device_get_parallel((f64p, i64p),
                                         site="segagg")
    else:
        f64h = i64h = None
    rep: dict = {}
    if f64h is not None:
        for i, k in enumerate(f64_keys):
            rep[k] = f64h[i]
    lsum_np = None
    if i64h is not None:
        arr = i64h
        for i, k in enumerate(i64_keys):
            rep[k] = arr[i]
        if lsum is not None:
            planes = arr[len(i64_keys):]      # (K, F, S)
            lsum_np = np.ascontiguousarray(
                np.moveaxis(planes, 0, 2))    # (F, S, K)
    out = SegmentAggResult(**{k: rep.get(k) for k in
                              SegmentAggResult._fields})
    return out, lsum_np


@functools.partial(jax.jit, static_argnames=("spec",))
def dense_window_aggregate(values: jax.Array,
                           valid: jax.Array | None,
                           times: jax.Array | None,
                           spec: AggSpec = AggSpec()) -> SegmentAggResult:
    """Dense path: values/valid shaped (S, P) — S = G*W segments of exactly
    P points each (regular sampling). Pure axis reductions, no scatter:
    this is the TSBS fast path and maps straight onto the VPU.

    valid=None declares every point valid (the decoder knows — a column
    block with no null bitmap): skips reading a (S, P) mask from HBM and
    all the masking selects, leaving pure reductions. On the bench shape
    that is ~1/9 of the HBM traffic removed from a bandwidth-bound kernel.
    """
    fdt = values.dtype
    if valid is None:
        S, P = values.shape
        out = {"count": jnp.full((S,), P, dtype=_I64),
               "sum": values.sum(axis=1)}
        if spec.sumsq:
            out["sumsq"] = (values * values).sum(axis=1)
        if spec.min:
            out["min"] = values.min(axis=1)
        if spec.max:
            out["max"] = values.max(axis=1)
        first = last = first_t = last_t = None
        if spec.first:
            first = values[:, 0]
            if times is not None:
                first_t = times[:, 0]
        if spec.last:
            last = values[:, -1]
            if times is not None:
                last_t = times[:, -1]
        if (spec.min_time or spec.max_time) and times is None:
            raise ValueError("min_time/max_time need times")
        min_t = _extremum_time_dense(values, None, times, out["min"]) \
            if spec.min_time else None
        max_t = _extremum_time_dense(values, None, times, out["max"]) \
            if spec.max_time else None
        return SegmentAggResult(
            count=out["count"], sum=out["sum"], sumsq=out.get("sumsq"),
            min=out.get("min"), max=out.get("max"),
            first=first, last=last, first_time=first_t, last_time=last_t,
            min_time=min_t, max_time=max_t)
    vz = jnp.where(valid, values, jnp.zeros((), fdt))
    out = {"count": valid.sum(axis=1, dtype=_I64), "sum": vz.sum(axis=1)}
    if spec.sumsq:
        out["sumsq"] = (vz * vz).sum(axis=1)
    if spec.min:
        out["min"] = jnp.where(valid, values, jnp.array(jnp.inf, fdt)).min(axis=1)
    if spec.max:
        out["max"] = jnp.where(valid, values, jnp.array(-jnp.inf, fdt)).max(axis=1)
    first = last = first_t = last_t = None
    if spec.first or spec.last:
        S, P = values.shape
        pidx = jnp.arange(P, dtype=_I64)[None, :]
        if spec.first:
            fi = jnp.where(valid, pidx, P).min(axis=1)
            has = fi < P
            safe = jnp.minimum(fi, P - 1)
            first = jnp.where(has, jnp.take_along_axis(
                values, safe[:, None], axis=1)[:, 0], jnp.nan)
            if times is not None:
                first_t = jnp.where(has, jnp.take_along_axis(
                    times, safe[:, None], axis=1)[:, 0], 0)
        if spec.last:
            li = jnp.where(valid, pidx, -1).max(axis=1)
            has = li >= 0
            safe = jnp.maximum(li, 0)
            last = jnp.where(has, jnp.take_along_axis(
                values, safe[:, None], axis=1)[:, 0], jnp.nan)
            if times is not None:
                last_t = jnp.where(has, jnp.take_along_axis(
                    times, safe[:, None], axis=1)[:, 0], 0)
    if (spec.min_time or spec.max_time) and times is None:
        raise ValueError("min_time/max_time need times")
    min_t = _extremum_time_dense(values, valid, times, out["min"]) \
        if spec.min_time else None
    max_t = _extremum_time_dense(values, valid, times, out["max"]) \
        if spec.max_time else None
    return SegmentAggResult(
        count=out["count"], sum=out["sum"], sumsq=out.get("sumsq"),
        min=out.get("min"), max=out.get("max"),
        first=first, last=last, first_time=first_t, last_time=last_t,
        min_time=min_t, max_time=max_t)


def merge_seg_results(a: SegmentAggResult,
                      b: SegmentAggResult) -> SegmentAggResult:
    """Combine two partial aggregate states (same segment space). This is the
    exchange-merge operator: the analog of the reference's reducer Merge()
    phase (engine/series_agg_reducer.gen.go) and of final aggregation at the
    sql node; across devices it runs as psum/all_gather of these fields."""
    def m(fa, fb, how):
        if fa is None or fb is None:
            return None
        return how(fa, fb)
    first = last = first_t = last_t = None
    if a.first is not None:
        a_has = ~jnp.isnan(a.first)
        b_has = ~jnp.isnan(b.first)
        take_a = a_has & (~b_has | (a.first_time <= jnp.where(b_has, b.first_time, jnp.iinfo(jnp.int64).max)))
        first = jnp.where(take_a, a.first, b.first)
        first_t = jnp.where(take_a, a.first_time, b.first_time)
    if a.last is not None:
        a_has = ~jnp.isnan(a.last)
        b_has = ~jnp.isnan(b.last)
        take_b = b_has & (~a_has | (b.last_time >= jnp.where(a_has, a.last_time, jnp.iinfo(jnp.int64).min)))
        last = jnp.where(take_b, b.last, a.last)
        last_t = jnp.where(take_b, b.last_time, a.last_time)
    return SegmentAggResult(
        count=m(a.count, b.count, jnp.add),
        sum=m(a.sum, b.sum, jnp.add),
        sumsq=m(a.sumsq, b.sumsq, jnp.add),
        min=m(a.min, b.min, jnp.minimum),
        max=m(a.max, b.max, jnp.maximum),
        first=first, last=last, first_time=first_t, last_time=last_t,
        # extremum times: winner's time; ties pick the earlier point
        min_time=None if a.min_time is None else jnp.where(
            a.min < b.min, a.min_time,
            jnp.where(b.min < a.min, b.min_time,
                      jnp.minimum(a.min_time, b.min_time))),
        max_time=None if a.max_time is None else jnp.where(
            a.max > b.max, a.max_time,
            jnp.where(b.max > a.max, b.max_time,
                      jnp.minimum(a.max_time, b.max_time))))


def dense_window_aggregate_host(values: np.ndarray,
                                valid: np.ndarray,
                                spec: AggSpec = AggSpec()
                                ) -> SegmentAggResult:
    """Numpy mirror of the dense (S, P) reductions for the scan's dense
    groups. On remote-attached, f64-emulated TPUs this is the right
    home for them: P is small (points per window), the result grid is
    large (D2H at tens of MB/s), and emulated-f64 compare/gather loses
    low mantissa bits — host numpy is faster AND exact. The device
    dense kernel remains for device-resident pipelines (bench kernel
    ceiling, block-resident path)."""
    is_int = np.issubdtype(values.dtype, np.integer)
    vz = np.where(valid, values, 0)
    res: dict[str, np.ndarray | None] = {}
    res["count"] = valid.sum(axis=1, dtype=np.int64)
    if spec.sum:
        res["sum"] = vz.sum(axis=1,
                            dtype=np.int64 if is_int else np.float64)
    if spec.sumsq:
        vf = vz.astype(np.float64, copy=False)
        res["sumsq"] = (vf * vf).sum(axis=1)
    if spec.min:
        ident = np.iinfo(np.int64).max if is_int else np.inf
        res["min"] = np.where(valid, values, ident).min(axis=1)
    if spec.max:
        ident = np.iinfo(np.int64).min if is_int else -np.inf
        res["max"] = np.where(valid, values, ident).max(axis=1)
    return SegmentAggResult(
        count=res.get("count"), sum=res.get("sum"),
        sumsq=res.get("sumsq"), min=res.get("min"), max=res.get("max"))


@functools.partial(jax.jit, static_argnames=("spec", "with_limbs"))
def dense_device_reduce(values: jax.Array, valid: jax.Array,
                        limbs: jax.Array | None, spec: AggSpec,
                        with_limbs: bool) -> dict:
    """Device dense (S, P) reduction of the EXACT-representable states
    only — the decoded-plane-cache path (ops/devicecache.py decoded
    tier, OG_DENSE_DEVICE). The f64 value sum is deliberately ABSENT:
    XLA's reduction order differs from numpy's pairwise order, so a
    device f64 sum would diverge from the host/CPU-baseline bit
    pattern. What this kernel returns is order-free:
      * count — integer sum of the valid mask;
      * min/max — comparisons never round;
      * lsum — (S, K) int64 limb-plane sums (exact integer adds; the
        executor derives the f64 fallback sum from these with
        finalize_exact, deterministic regardless of platform).
    """
    out = {"count": valid.sum(axis=1, dtype=_I64)}
    # dense blocks assemble as f64 today, but identities stay
    # dtype-aware (as in the host mirror) so a future typed-int plane
    # cannot trace jnp.inf into an integer dtype
    pos_ident, neg_ident = _minmax_idents(values.dtype)
    if spec.min:
        out["min"] = jnp.where(valid, values, pos_ident).min(axis=1)
    if spec.max:
        out["max"] = jnp.where(valid, values, neg_ident).max(axis=1)
    if with_limbs:
        lz = jnp.where(valid[:, :, None], limbs, 0)
        out["lsum"] = lz.astype(_I64).sum(axis=1)
    return out


def segment_aggregate_host(values: np.ndarray,
                           valid: np.ndarray,
                           seg_ids: np.ndarray,
                           times: np.ndarray | None,
                           num_segments: int,
                           spec: AggSpec = AggSpec()) -> SegmentAggResult:
    """Numpy mirror of segment_aggregate for SMALL row counts: when the
    sparse rows are a handful of window-edge leftovers (the dense/pre-agg
    paths took the bulk), two device round-trips cost more than the
    reduction itself — on a remote-attached TPU each call pays the full
    tunnel latency. Same semantics, same state layout, numpy arrays."""
    S = num_segments
    keep = valid & (seg_ids < S)
    s = seg_ids[keep]
    v = values[keep]
    n = len(values)
    is_int = np.issubdtype(values.dtype, np.integer)
    res: dict[str, np.ndarray | None] = {}
    if spec.count or spec.sum:
        res["count"] = np.bincount(s, minlength=S).astype(np.int64)
    if spec.sum:
        if is_int:
            acc = np.zeros(S, dtype=np.int64)
            np.add.at(acc, s, v)
            res["sum"] = acc
        else:
            # bincount degenerates to int64 on EMPTY weights — force the
            # device kernel's float64 state dtype or downstream merges
            # would truncate
            res["sum"] = np.bincount(s, weights=v, minlength=S).astype(
                np.float64, copy=False)
    if spec.sumsq:
        vf = v.astype(np.float64, copy=False)   # square AFTER the cast:
        res["sumsq"] = np.bincount(             # int64 squares wrap
            s, weights=vf * vf,
            minlength=S).astype(np.float64, copy=False)
    if spec.min:
        mn = np.full(S, np.iinfo(np.int64).max, dtype=np.int64) \
            if is_int else np.full(S, np.inf)
        np.minimum.at(mn, s, v)
        res["min"] = mn
    if spec.max:
        mx = np.full(S, np.iinfo(np.int64).min, dtype=np.int64) \
            if is_int else np.full(S, -np.inf)
        np.maximum.at(mx, s, v)
        res["max"] = mx
    min_t = max_t = None
    if spec.min_time or spec.max_time:
        if times is None:
            raise ValueError("min_time/max_time need times")
        t = times[keep]
        imax = np.iinfo(np.int64).max
        if spec.min_time:
            at = v == res["min"][s]
            min_t = np.full(S, imax, dtype=np.int64)
            np.minimum.at(min_t, s[at], t[at])
        if spec.max_time:
            at = v == res["max"][s]
            max_t = np.full(S, imax, dtype=np.int64)
            np.minimum.at(max_t, s[at], t[at])
    first = last = first_t = last_t = None
    if spec.first or spec.last:
        if times is None:
            raise ValueError("first/last need times")
        idx = np.nonzero(keep)[0]
        if spec.first:
            fi = np.full(S, n, dtype=np.int64)
            np.minimum.at(fi, s, idx)
            has = fi < n
            safe = np.minimum(fi, max(n - 1, 0))
            first = np.where(has, values[safe].astype(np.float64)
                             if n else np.nan, np.nan)
            first_t = np.where(has, times[safe] if n else 0, 0)
        if spec.last:
            li = np.full(S, -1, dtype=np.int64)
            np.maximum.at(li, s, idx)
            has = li >= 0
            safe = np.maximum(li, 0)
            last = np.where(has, values[safe].astype(np.float64)
                            if n else np.nan, np.nan)
            last_t = np.where(has, times[safe] if n else 0, 0)
    return SegmentAggResult(
        count=res.get("count"), sum=res.get("sum"),
        sumsq=res.get("sumsq"), min=res.get("min"), max=res.get("max"),
        first=first, last=last, first_time=first_t, last_time=last_t,
        min_time=min_t, max_time=max_t)


# ----------------------------------------------------------------- helpers

def pad_rows(arrays: Sequence[np.ndarray], n_padded: int,
             seg_fill: int) -> list[np.ndarray]:
    """Host-side helper: pad row-aligned arrays to n_padded. The first array
    must be seg_ids (padded with seg_fill = trash segment); bool arrays pad
    False; others pad 0."""
    out = []
    n = len(arrays[0])
    pad = n_padded - n
    for k, a in enumerate(arrays):
        if pad == 0:
            out.append(a)
            continue
        if k == 0:
            fill = np.full(pad, seg_fill, dtype=a.dtype)
        elif a.dtype == np.bool_:
            fill = np.zeros(pad, dtype=np.bool_)
        else:
            fill = np.zeros(pad, dtype=a.dtype)
        out.append(np.concatenate([a, fill]))
    return out
