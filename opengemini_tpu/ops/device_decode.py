"""Device-side decode of block codecs — the H2D diet.

SURVEY.md §7 hard parts: "Host↔device bandwidth: decode-on-CPU then DMA
can starve the TPU; … decompress cheap codecs (RLE/delta) *in-kernel*."
Round 1 of this module covered the codecs whose decode is pure
arithmetic (CONST, RLE, CONST_DELTA — encoding/blocks.py): the host
ships the SMALL compressed payload and the expansion to a dense block
happens on device, fused by XLA into whatever kernel consumes it.

Round 14 extends the family to the bit-packed byte tier: DFOR
(encoding/dfor.py) lays numeric blocks out as one reference + one bit
width + fixed-width little-endian u32 lanes, and ``dfor_expand`` here
unpacks them with shifts+masks — a Pallas kernel walks the ≤32-bit
lanes (one program per block row, VMEM-resident words; interpret mode
off-TPU like ops/pallas_agg), the wide residuals (XOR'd full-mantissa
floats) take the same 3-word gather math in vectorized jnp u64. The
inverse transforms (zigzag-delta, XOR-vs-reference, prefix-XOR scan,
decimal-scaled integer divide) are elementwise/associative and trace
straight into the consuming reducer. ops/blockagg's slab build batches
same-(width, rows) segments into ONE kernel launch, so compressed
bytes — not dense f64 planes — are what crosses H2D (manifest sites
``dfor``/``payload``, ops/compileaudit.py).

Shape-class hygiene: every kernel here compiles per a STATIC
(rows, width, transform, batch-bucket) key — widths quantize to
multiples of 2 at ENCODE time (encoding/dfor._round_width) and batch
counts pad to power-of-two buckets (``pad_pow2``) — so the PR 11
compile auditor's warm-window gate stays at exactly 0.

The decimal-scaled and limb-decompose paths divide in f64, so the
device stage only engages on real-f64 backends
(ops/blockagg._backend_real_f64); f32-pair-emulated backends (TPU
today) keep the host decode stage — see query/decodestage.py for the
planner rules.
"""

from __future__ import annotations

import functools
import struct

import jax
import jax.numpy as jnp
import numpy as np

from ..encoding.blocks import CONST, CONST_DELTA, DFOR, RLE, \
    parse_rle_payload
from ..encoding import dfor as _dfor
from ..utils import knobs
from ..utils.stats import register_counters

__all__ = ["rle_expand", "const_expand", "const_delta_expand",
           "device_decode_float_block", "device_decode_time_block",
           "device_decode_int_block", "dfor_expand", "pad_pow2",
           "times_expand_batch", "validity_expand_batch",
           "const_expand_batch", "limbs_decompose", "permute_blocks",
           "device_decode_on", "DECODE_STATS", "dfor_expand_pred",
           "plane_mask", "k_mask", "and_planes", "rle_expand_batch",
           "int_limbs_batch", "const_limbs_batch",
           "mask_limbs_batch"]

I64MAX = np.iinfo(np.int64).max

# counter group (oglint R6: registered declaration, bumps must name
# declared keys). The per-byte H2D split lives in the transfer
# manifest (sites dfor/payload); these count the DECODE work itself.
DECODE_STATS: dict = register_counters("device_decode", {
    "dfor_blocks": 0,        # segments expanded on device from DFOR
    "const_blocks": 0,       # CONST value segments expanded on device
    "time_blocks": 0,        # CONST_DELTA time segments expanded
    "batches": 0,            # batched expansion kernel launches
    "host_heals": 0,         # per-block host-decode heals (fault path)
    "slabs_device_decoded": 0,
    "compressed_hits": 0,    # slab rebuilds served from the HBM
    "compressed_rebuilds": 0,  # compressed tier (zero H2D)
    "rle_blocks": 0,         # RLE segments expanded on device
    "int_limb_slabs": 0,     # slabs limb-decomposed in int space
    "dense_fills_compressed": 0,  # dense-group plane fills served
                                  # straight from compressed payloads
    # packed-space predicate pushdown (ops/pushdown.py, round 18)
    "pushdown_segments_skipped": 0,  # envelope-skipped, never expand
    "pushdown_rows_skipped": 0,      # rows inside skipped segments
    "pushdown_blocks_masked": 0,     # partial blocks (row masks)
    "pushdown_lanes_expanded": 0,    # rows expanded under a pred build
    "pushdown_heals": 0,             # mask launches healed to host
})


def _bump(key: str, n: int = 1) -> None:
    from ..utils.stats import bump as _b
    _b(DECODE_STATS, key, n)


def device_decode_on() -> bool:
    """OG_DEVICE_DECODE gate (default on; 0 = host decode + dense
    plane upload everywhere — the byte-identical escape hatch)."""
    return bool(knobs.get("OG_DEVICE_DECODE"))


@functools.partial(jax.jit, static_argnames=("n",))
def rle_expand(values: jax.Array, lengths: jax.Array, n: int) -> jax.Array:
    """Expand run-length pairs to a dense (n,) block on device. The runs
    arrays are padded with zero-length runs to a fixed size by the caller
    so the jit cache keys recur."""
    return jnp.repeat(values, lengths, total_repeat_length=n)


@functools.partial(jax.jit, static_argnames=("n",))
def const_expand(value: jax.Array, n: int) -> jax.Array:
    return jnp.full((n,), value)


@functools.partial(jax.jit, static_argnames=("n",))
def const_delta_expand(t0: jax.Array, step: jax.Array, n: int) -> jax.Array:
    return t0 + step * jnp.arange(n, dtype=jnp.int64)


def pad_pow2(r: int, floor: int = 256) -> int:
    """Power-of-two bucket for a dynamic count ``r`` (minimum
    ``floor``): the jit-cache-key discipline every dynamic batch/run
    axis in this module rides. Monotone, and exact powers of two map
    to themselves — tested in tests/test_device_decode.py."""
    return max(floor, 1 << (r - 1).bit_length()) if r else floor


def _pad_runs(vals: np.ndarray, lens: np.ndarray,
              bucket: int = 256) -> tuple[np.ndarray, np.ndarray]:
    """Pad run arrays to a bucketed length so repeated decodes share
    one compiled kernel (zero-length runs expand to nothing).

    Bucketing contract (the jit-cache-key claim, pinned by
    tests/test_device_decode.py): run counts ≤ ``bucket`` (256) all
    share the single ``bucket``-wide class; ABOVE the bucket the
    padded length grows by powers of two (257→512, 1025→2048, …), so
    a file whose segments carry anywhere from 1 to 64k runs compiles
    at most log2(64k/256) ≈ 8 extra kernel classes, never one per
    distinct run count."""
    r = len(vals)
    padded = pad_pow2(r, bucket)
    if r == padded:
        return vals, lens
    pv = np.zeros(padded, dtype=vals.dtype)
    pl = np.zeros(padded, dtype=np.int64)
    pv[:r] = vals
    pl[:r] = lens
    return pv, pl


# ------------------------------------------------- DFOR bit-unpack

_JITTED: dict = {}


def _named_jit(fn, key: tuple, **jit_kw):
    """jit under a stable og_* name derived from the cache key, so the
    compile auditor (ops/compileaudit.py) attributes every shape class
    to its kernel variant (same contract as ops/blockagg._named_jit)."""
    name = "og_" + "_".join(str(p) for p in key).replace(" ", "")
    fn.__name__ = name
    fn.__qualname__ = name
    return jax.jit(fn, **jit_kw)


def _unpack_index(n: int, width: int):
    """Static gather plan of the little-endian bit stream: value i
    starts at bit i*width → word index + lane offset."""
    pos = np.arange(n, dtype=np.int64) * width
    iw = (pos >> 5).astype(np.int32)
    off = (pos & 31).astype(np.uint32)
    return iw, off


def _mk_unpack_kernel(width: int):
    """Kernel FACTORY for the Pallas ≤32-bit lane unpack: one program
    unpacks one block row's words from VMEM with two gathers + shifts
    over the uploaded unpack plan (word index / lane offset / spill
    shift+mask per value — Pallas kernels may not capture array
    constants, so the plan rides as operands, cached on device per
    (rows, width) class by ``_unpack_plan``). The compiled body is
    pure shift/mask/or — the bit-twiddly loop the module docstring
    promised would never run on host again. (lint/jitwalk.py roots
    pallas_call kernels built through factories like this one, so
    R5/R9 trace-purity coverage extends into the body.)"""
    mask = np.uint32((1 << width) - 1) if width < 32 \
        else np.uint32(0xFFFFFFFF)

    def _dfor_unpack_kernel(w_ref, iw_ref, off_ref, sh_ref, hm_ref,
                            out_ref):
        w = w_ref[0, :]
        iw = iw_ref[...]
        lo = jnp.take(w, iw) >> off_ref[...]
        hi = (jnp.take(w, iw + 1) << sh_ref[...]) & hm_ref[...]
        out_ref[0, :] = (lo | hi) & mask

    return _dfor_unpack_kernel


@functools.lru_cache(maxsize=None)
def _unpack_plan(n: int, width: int):
    """Device-resident unpack plan per (rows, width) shape class: the
    static gather/shift tables the Pallas kernel reads. Uploaded ONCE
    per class (booked to the ``payload`` manifest site)."""
    from . import compileaudit
    iw, off = _unpack_index(n, width)
    hi_sh = np.where(off > 0, (32 - off) & 31, 0).astype(np.uint32)
    hi_live = (off > 0) & (width > 32 - off.astype(np.int64))
    hi_mask = np.where(hi_live, np.uint32(0xFFFFFFFF),
                       np.uint32(0)).astype(np.uint32)
    plan = tuple(jax.device_put(a)
                 for a in (iw, off, hi_sh, hi_mask))
    compileaudit.record_h2d("payload",
                            sum(int(a.nbytes) for a in plan))
    return plan


@functools.lru_cache(maxsize=None)
def _unpack_fn(nb: int, nw: int, n: int, width: int, interpret: bool):
    """Memoized pallas_call per (batch, words, rows, width) shape
    class (the ops/pallas_agg._rowagg_fn discipline: a fresh
    pallas_call per invocation would recompile on every warm call)."""
    from jax.experimental import pallas as pl
    out = jax.ShapeDtypeStruct((nb, n), jnp.uint32)
    full = pl.BlockSpec((n,), lambda i: (0,))
    return pl.pallas_call(
        _mk_unpack_kernel(width),
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, nw), lambda i: (i, 0)),
                  full, full, full, full],
        out_specs=pl.BlockSpec((1, n), lambda i: (i, 0)),
        out_shape=out,
        interpret=interpret,
    )


def _pallas_unpack(words_dev, n: int, width: int,
                   interpret: bool | None):
    """(nb, nw) u32 packed lanes → (nb, n) u32 residuals (width ≤ 32).
    Runs under x64-off like every pallas call in this repo (Mosaic
    x64-index lowering); inputs/outputs are u32 either way."""
    from jax.experimental import enable_x64
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    nb, nw = words_dev.shape
    plan = _unpack_plan(n, width)
    with enable_x64(False):
        return _unpack_fn(nb, nw, n, width, interpret)(
            words_dev, *plan)


_U64 = jnp.uint64


def _traced_unpack_wide(words, n: int, width: int):
    """In-trace u64 unpack for 33..64-bit residuals — the same 3-word
    gather+shift arithmetic as encoding/dfor.unpack_words, so parity
    with the host decoder is by construction."""
    iw, off_np = _unpack_index(n, width)
    off = off_np.astype(np.uint64)
    w64 = words.astype(_U64)
    lo = jnp.take(w64, iw, axis=-1)
    mid = jnp.take(w64, iw + 1, axis=-1)
    hi = jnp.take(w64, iw + 2, axis=-1)
    r = (lo >> off) | (mid << (np.uint64(32) - off))
    s3 = ((np.uint64(64) - off) % np.uint64(64))
    r = r | jnp.where(off > 0, hi << s3, _U64(0))
    if width < 64:
        r = r & np.uint64((1 << width) - 1)
    return r


def _traced_inverse(r, refs, scale, transform: int, kind: str):
    """Traced twin of encoding/dfor.inverse_transform_batch. ``scale``
    is the T_SCALED divisor as a TRACED f64 operand — were it a trace
    constant, XLA would strength-reduce the divide into a reciprocal
    multiply and drift the low ulp off the host decoder (measured:
    14% of cells 1 ulp off on the 2-decimal bench data)."""
    refs = refs.astype(_U64)[:, None]
    if transform in (_dfor.T_INT, _dfor.T_SCALED):
        u = (r >> _U64(1)) ^ (_U64(0) - (r & _U64(1)))   # un-zigzag
        k = jax.lax.bitcast_convert_type(u + refs, jnp.int64)
        if transform == _dfor.T_INT:
            return k if kind == "i64" else k.astype(jnp.float64)
        return k.astype(jnp.float64) / scale
    if transform == _dfor.T_XORREF:
        u = r ^ refs
    else:                                            # T_XORPRED
        u = jax.lax.associative_scan(jnp.bitwise_xor, r, axis=1) ^ refs
    return jax.lax.bitcast_convert_type(
        u, jnp.float64 if kind == "f64" else jnp.int64)


def dfor_finish_stage(r32, refs, scale, *, transform: int, kind: str):
    """Trace-composable inverse-transform epilogue over Pallas-
    unpacked u32 residuals (round 17): pure traced-operand function
    the fused program tracer (ops/fused.py) can inline; _finish_fn
    jit-wraps exactly this call."""
    return _traced_inverse(r32.astype(_U64), refs, scale,
                           transform, kind)


def _finish_fn(transform: int, kind: str, n: int):
    """jit inverse-transform epilogue over Pallas-unpacked u32
    residuals (the decimal scale rides as a traced operand, so one
    compiled class serves every dscale)."""
    key = ("dforfin", transform, kind, n)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(r32, refs, scale):
            return dfor_finish_stage(r32, refs, scale,
                                     transform=transform, kind=kind)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn


def dfor_wide_stage(words, refs, scale, *, n: int, width: int,
                    transform: int, kind: str):
    """Trace-composable u64 unpack + inverse transform (round 17):
    the _wide_fn body as a pure traced-operand function the fused
    program tracer can inline."""
    if width == 0:
        nb = words.shape[0]
        r = jnp.zeros((nb, n), dtype=_U64)
    else:
        r = _traced_unpack_wide(words, n, width)
    return _traced_inverse(r, refs, scale, transform, kind)


def _wide_fn(transform: int, kind: str, n: int, width: int):
    """jit u64 unpack + inverse transform (widths > 32, and the
    width-0 fast case: residuals are all zero)."""
    key = ("dforwide", transform, kind, n, width)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(words, refs, scale):
            return dfor_wide_stage(words, refs, scale, n=n,
                                   width=width, transform=transform,
                                   kind=kind)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn


@functools.lru_cache(maxsize=None)
def _scale_dev(dscale: int):
    """Device-resident 10^dscale divisor, uploaded once per decimal
    class (it rides as a traced operand — see _traced_inverse)."""
    from . import compileaudit
    s = jax.device_put(np.float64(10.0 ** dscale))
    compileaudit.record_h2d("payload", int(s.nbytes))
    return s


@functools.lru_cache(maxsize=None)
def limb_scale_dev(E: int):
    """Device-resident 2^(E - LIMB_BITS) scale for limbs_decompose,
    uploaded once per limb scale."""
    from . import compileaudit, exactsum
    s = jax.device_put(np.float64(2.0 ** (E - exactsum.LIMB_BITS)))
    compileaudit.record_h2d("payload", int(s.nbytes))
    return s


def dfor_expand(words_dev, refs_dev, *, n: int, width: int,
                transform: int, dscale: int, kind: str,
                interpret: bool | None = None):
    """Batched device expansion of same-shape DFOR segments:
    ``words_dev`` (nb, nw) u32 packed lanes (nw ≥ words+2 — the caller
    pads the gather guard), ``refs_dev`` (nb,) u64 references →
    (nb, n) f64/i64 decoded values, bit-identical to
    encoding/dfor.decode_batch. ≤32-bit lanes ride the Pallas unpack
    kernel; wider residuals take the vectorized u64 path."""
    _bump("batches")
    scale = _scale_dev(dscale)
    if 0 < width <= 32:
        r32 = _pallas_unpack(words_dev, n, width, interpret)
        return _finish_fn(transform, kind, n)(r32, refs_dev, scale)
    return _wide_fn(transform, kind, n, width)(
        words_dev, refs_dev, scale)


def pred_finish_stage(r, refs, scale, thr, *, transform: int,
                      mode: str, sig: tuple):
    """Trace-composable inverse transform + packed-predicate mask:
    (values f64, mask bool) from the SAME unpacked residuals — the
    pushdown launch never walks the words twice. ``mode`` "int"
    compares the un-zigzagged integer k against traced int64
    thresholds (exact, ops/pushdown.translate); "f64" compares the
    decoded plane (XOR fallback — the identical IEEE compares the
    host residual would run).

    The decimal divide stays the TRACED-operand divide from
    _traced_inverse on this survivor-masked path too — a trace-
    constant scale would let XLA strength-reduce to a reciprocal
    multiply and re-open the PR 13 1-ulp drift (pinned by
    tests/test_pushdown.py::test_masked_expand_bit_identity)."""
    from . import pushdown as _pd
    v = _traced_inverse(r, refs, scale, transform, "f64")
    if mode == "int":
        refs_u = refs.astype(_U64)[:, None]
        u = (r >> _U64(1)) ^ (_U64(0) - (r & _U64(1)))
        k = jax.lax.bitcast_convert_type(u + refs_u, jnp.int64)
        m = _pd.mask_from_k_stage(k, thr, sig=sig)
    else:
        m = _pd.mask_from_values_stage(v, thr, sig=sig)
    return v, m


def dfor_expand_pred(words_dev, refs_dev, thr_dev, *, n: int,
                     width: int, transform: int, dscale: int,
                     mode: str, sig: tuple,
                     interpret: bool | None = None):
    """Batched expand WITH packed-predicate mask in one launch:
    (nb, n) f64 values + (nb, n) bool survivor mask. Thresholds ride
    as TRACED operands, so one compiled class per interned
    (mode, ops-signature) serves every literal
    (query/plancache.intern_pred_class names the class for the
    compile auditor)."""
    from ..query import plancache
    _bump("batches")
    scale = _scale_dev(dscale)
    pid, _name = plancache.intern_pred_class((mode, sig))
    if 0 < width <= 32:
        r32 = _pallas_unpack(words_dev, n, width, interpret)
        key = ("dforpred", transform, mode, pid, n)
        fn = _JITTED.get(key)
        if fn is None:
            def _f(r32, refs, scale, thr):
                return pred_finish_stage(
                    r32.astype(_U64), refs, scale, thr,
                    transform=transform, mode=mode, sig=sig)
            fn = _JITTED[key] = _named_jit(_f, key)
        return fn(r32, refs_dev, scale, thr_dev)
    key = ("dforpredwide", transform, mode, pid, n, width)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(words, refs, scale, thr):
            if width == 0:
                r = jnp.zeros((words.shape[0], n), dtype=_U64)
            else:
                r = _traced_unpack_wide(words, n, width)
            return pred_finish_stage(r, refs, scale, thr,
                                     transform=transform, mode=mode,
                                     sig=sig)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(words_dev, refs_dev, scale, thr_dev)


def plane_mask(values_dev, thr_dev, *, sig: tuple):
    """Post-expand predicate mask over an already-decoded (nb, seg)
    f64 plane (CONST-batch / RLE-partial / host-plane pushdown): the
    same traced f64 compares as pred_finish_stage mode "f64"."""
    from ..query import plancache
    from . import pushdown as _pd
    pid, _name = plancache.intern_pred_class(("f64", sig))
    key = ("planemask", pid)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(v, thr):
            return _pd.mask_from_values_stage(v, thr, sig=sig)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(values_dev, thr_dev)


def k_mask(k_dev, thr_dev, *, sig: tuple):
    """Int-mode packed-predicate mask over an (nb, seg) i64 k plane
    (the limb-decomposition input): exact int64 compares against the
    translated thresholds."""
    from ..query import plancache
    from . import pushdown as _pd
    pid, _name = plancache.intern_pred_class(("int", sig))
    key = ("kmask", pid)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(k, thr):
            return _pd.mask_from_k_stage(k, thr, sig=sig)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(k_dev, thr_dev)


def and_planes(a_dev, b_dev):
    """valid ∧ survivor-mask combine (both (B, seg) bool, meta
    order) — the point where the packed predicate lands on the valid
    plane every downstream kernel masks by."""
    key = ("andplane",)
    fn = _JITTED.get(key)
    if fn is None:
        fn = _JITTED[key] = _named_jit(lambda a, b: a & b, key)
    return fn(a_dev, b_dev)


# ------------------------------------------- RLE batched expansion

def rle_expand_batch(vals_dev, lens_dev, rows_dev, seg: int):
    """Batched device RLE expansion (the decode-frontier holdout at
    device_decode_float_block's single-block path): (nb, R) run
    values + run lengths → (nb, seg) dense f64 rows, zero beyond the
    real rows. cumsum over run lengths + a per-row searchsorted
    reproduces np.repeat exactly (host decoder parity is pinned under
    jax.transfer_guard("disallow") in tests/test_device_decode.py);
    run counts bucket through _pad_runs so jit cache keys recur."""
    R = int(vals_dev.shape[1])
    key = ("rlebatch", R, seg)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(vals, lens, rows):
            return rle_stage(vals, lens, rows, R=R, seg=seg)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(vals_dev, lens_dev, rows_dev)


def rle_stage(vals, lens, rows, *, R: int, seg: int):
    """Trace-composable body of rle_expand_batch."""
    cum = jnp.cumsum(lens, axis=1)
    i = jnp.arange(seg, dtype=jnp.int64)
    idx = jax.vmap(
        lambda c: jnp.searchsorted(c, i, side="right"))(cum)
    out = jnp.take_along_axis(vals, jnp.clip(idx, 0, R - 1), axis=1)
    return jnp.where(i[None, :] < rows[:, None], out, 0.0)


# ------------------------------------- int-space limb decomposition

def int_limbs_batch(k_dev, *, E: int):
    """Integer-space twin of limbs_decompose for T_INT segments
    (round 18 — the real-f64 gate's escape route): (nb, seg) i64
    integer values → (nb, seg, K) i32 limb planes via STATIC binary
    shifts only. Every op is integer → exact on f32-pair-emulated
    backends where the f64 floor/divide cascade drifts. The caller
    guarantees |k| < 2^E (ops/blockagg checks the segment envelope at
    build; over-range blocks host-stage), so the host clamp cascade
    never engages and the base-2^18 digits are pure bit windows —
    bit-identical to exactsum.host_limbs on f64(k) by construction."""
    from . import exactsum
    K = exactsum.K_LIMBS
    key = ("intlimbs", E, K)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(k):
            return int_limbs_stage(k, E=E, K=K)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(k_dev)


def int_limbs_stage(k, *, E: int, K: int):
    """Trace-composable body of int_limbs_batch: limb j is the 18-bit
    window of |k| at bit position E - 18*(j+1), times sign. Windows
    below the binary point (negative shift) are zero for integers;
    E ≤ 72 for int64-representable magnitudes, so E - 108 < 0 and the
    residue (hence bad) is identically zero."""
    neg = k < 0
    a = jax.lax.bitcast_convert_type(jnp.where(neg, -k, k), _U64)
    sign = jnp.where(neg, -1, 1).astype(jnp.int32)
    limbs = []
    for j in range(K):
        s = E - 18 * (j + 1)
        if 0 <= s < 64:
            d = ((a >> _U64(s)) & _U64(0x3FFFF)).astype(jnp.int32)
        else:
            d = jnp.zeros(k.shape, dtype=jnp.int32)
        limbs.append(sign * d)
    return jnp.stack(limbs, axis=-1)


def const_limbs_batch(vecs_dev, bad_dev, seg: int):
    """CONST int-mode batch: per-block HOST-computed limb vectors
    (exactsum.host_limbs on one value — f64 host math, exact)
    broadcast to (nb, seg, K) plane rows + (nb, seg) bad rows; the
    final valid mask (mask_limbs_batch) zeroes the padding."""
    K = int(vecs_dev.shape[1])
    key = ("constlimbs", K, seg)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(vecs, bad):
            nb = vecs.shape[0]
            lb = jnp.broadcast_to(vecs[:, None, :], (nb, seg, K))
            bd = jnp.broadcast_to(bad[:, None], (nb, seg))
            return lb, bd
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(vecs_dev, bad_dev)


def mask_limbs_batch(limbs_dev, bad_dev, valid_dev):
    """Assembled int-mode limb planes → valid-masked planes +
    activity flags (the exact tail of limbs_stage: limbs zero where
    invalid, bad only where valid)."""
    K = int(limbs_dev.shape[-1])
    key = ("limbmaskb", K)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(lb, bd, valid):
            lb = jnp.where(valid[..., None], lb, 0)
            bd = bd & valid
            act = (lb != 0).any(axis=(0, 1))
            return lb, bd, act
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(limbs_dev, bad_dev, valid_dev)


# ------------------------------------ batched slab-plane expanders

def times_expand_batch(t0s_dev, steps_dev, rows_dev, seg: int):
    """CONST_DELTA time batch → (nb, seg) i64 plane rows: affine times
    for the first ``rows`` rows of each block, I64MAX padding beyond
    (the slab layout's monotone-tail contract,
    ops/blockagg._build_slab)."""
    key = ("dfortimes", seg)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(t0s, steps, rows):
            return times_stage(t0s, steps, rows, seg=seg)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(t0s_dev, steps_dev, rows_dev)


def times_stage(t0s, steps, rows, *, seg: int):
    """Trace-composable body of times_expand_batch (round 17)."""
    i = jnp.arange(seg, dtype=jnp.int64)[None, :]
    t = t0s[:, None] + steps[:, None] * i
    return jnp.where(i < rows[:, None], t, I64MAX)


def validity_expand_batch(bits_dev, const_dev, rows_dev, seg: int):
    """Validity batch → (nb, seg) bool plane rows. ``bits_dev``
    (nb, ceil(seg/8)) u8 big-endian packbits lanes (all-zero rows for
    CONST all-valid blocks), ``const_dev`` (nb,) bool flags,
    ``rows_dev`` (nb,) real row counts: CONST rows expand to
    arange < rows, BITPACK rows unpack their bits (encode already
    zero-pads beyond the real rows)."""
    key = ("dforvalid", seg)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(bits, const, rows):
            return validity_stage(bits, const, rows, seg=seg)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(bits_dev, const_dev, rows_dev)


def validity_stage(bits, const, rows, *, seg: int):
    """Trace-composable body of validity_expand_batch (round 17)."""
    i = jnp.arange(seg, dtype=jnp.int32)[None, :]
    byte = jnp.take(bits, np.arange(seg, dtype=np.int32) >> 3,
                    axis=1)
    sh = (7 - (np.arange(seg, dtype=np.int32) & 7)).astype(
        np.uint8)
    unpacked = ((byte >> sh[None, :]) & 1).astype(jnp.bool_)
    from_const = i < rows[:, None]
    return jnp.where(const[:, None], from_const, unpacked)


def const_expand_batch(vals_dev, rows_dev, seg: int):
    """CONST float batch → (nb, seg) f64 plane rows (zero padding
    beyond the real rows — the host slab assembly's np.zeros init)."""
    key = ("dforconst", seg)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(vals, rows):
            return const_stage(vals, rows, seg=seg)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(vals_dev, rows_dev)


def const_stage(vals, rows, *, seg: int):
    """Trace-composable body of const_expand_batch (round 17)."""
    i = jnp.arange(seg, dtype=jnp.int64)[None, :]
    return jnp.where(i < rows[:, None], vals[:, None], 0.0)


def fit_rows(plane_dev, seg: int, fill=None):
    """(nb, r) batch → (nb, seg) plane rows, zero-padded (values) or
    ``fill``-padded beyond r. No-op when r == seg."""
    r = int(plane_dev.shape[1])
    if r == seg:
        return plane_dev
    key = ("dforfit", r, seg, str(fill))
    fn = _JITTED.get(key)
    if fn is None:
        def _f(x):
            return fit_stage(x, r=r, seg=seg, fill=fill)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(plane_dev)


def fit_stage(x, *, r: int, seg: int, fill=None):
    """Trace-composable body of fit_rows (round 17)."""
    return jnp.pad(x, ((0, 0), (0, seg - r)),
                   constant_values=0 if fill is None else fill)


def permute_blocks(plane_dev, perm_dev):
    """Order-restoring gather along the block axis: batched expansion
    groups blocks by shape class, this puts them back in meta order."""
    key = ("dforperm", plane_dev.ndim)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(p, idx):
            return permute_stage(p, idx)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(plane_dev, perm_dev)


def permute_stage(p, idx):
    """Trace-composable body of permute_blocks (round 17)."""
    return jnp.take(p, idx, axis=0)


def limbs_decompose(values_dev, valid_dev, scale0):
    """Traced twin of ops/exactsum.host_limbs: (B, SEG) f64 values →
    ((B, SEG, K) i32 limb planes, (B, SEG) bool residue flags,
    (K,) bool plane-activity flags). ``scale0`` is 2^(E - LIMB_BITS)
    as a TRACED f64 scalar, so one compiled kernel serves every limb
    scale (all per-limb scale steps are exact power-of-two factors).

    Bit-identity: the same IEEE f64 floor/divide/subtract sequence as
    the host decompose — which is why the device decode stage is
    gated to real-f64 backends (query/decodestage.py); on f32-pair
    emulation the floor/divide drift and the limb invariant breaks."""
    from . import exactsum
    K = exactsum.K_LIMBS
    key = ("dforlimbs", K)
    fn = _JITTED.get(key)
    if fn is None:
        def _f(v, valid, s0):
            return limbs_stage(v, valid, s0, K=K)
        fn = _JITTED[key] = _named_jit(_f, key)
    return fn(values_dev, valid_dev, scale0)


def limbs_stage(v, valid, s0, *, K: int):
    """Trace-composable body of limbs_decompose (round 17): the
    traced twin of ops/exactsum.host_limbs as a pure stage function
    the fused program tracer can inline."""
    from . import exactsum
    finite = jnp.isfinite(v)
    a = jnp.abs(jnp.where(finite, v, 0.0))
    sign = jnp.where(v < 0, -1.0, 1.0)
    limbs = []
    s = s0
    for _k in range(K):
        b = jnp.floor(a / s)
        b = jnp.minimum(b, float(exactsum._RADIX - 1))
        a = a - b * s
        limbs.append(sign * b)
        s = s * (1.0 / exactsum._RADIX)
    res = jnp.where(finite, sign * a, jnp.nan)
    bad = (res != 0.0) | ~jnp.isfinite(res)
    lb = jnp.stack(limbs, axis=-1)
    lb = jnp.where(valid[..., None], lb, 0.0)
    bad = bad & valid
    lb32 = lb.astype(jnp.int32)
    act = (lb32 != 0).any(axis=(0, 1))
    return lb32, bad, act


# --------------------------------------------- single-block decode

def device_decode_float_block(buf, n: int) -> jax.Array | None:
    """Decode a float block ON DEVICE when its codec is device-
    expandable (CONST / RLE arithmetic payloads, DFOR bit-packed
    lanes); returns None otherwise (caller falls back to the CPU
    decoder, encoding/blocks.decode_float_block). The compressed
    payload is the only H2D traffic — booked per upload into the
    transfer manifest (ops/compileaudit.py)."""
    from . import compileaudit
    codec = buf[0]
    payload = memoryview(buf)[1:]
    if codec == CONST:
        v = np.frombuffer(payload[:8], dtype=np.float64)[0]
        vd = jnp.asarray(v)
        compileaudit.record_h2d("decode", int(vd.nbytes))
        return const_expand(vd, n)
    if codec == RLE:
        vals, lens = parse_rle_payload(payload)
        pv, pl = _pad_runs(vals, lens)
        # ship ~runs*12 bytes instead of n*8
        pvd, pld = jnp.asarray(pv), jnp.asarray(pl)
        compileaudit.record_h2d("decode",
                                int(pvd.nbytes + pld.nbytes))
        return rle_expand(pvd, pld, n)
    if codec == DFOR and device_decode_on():
        return _dfor_single(payload, n, "f64")
    return None


def device_decode_int_block(buf, n: int) -> jax.Array | None:
    """Int64 twin of device_decode_float_block (DFOR only — the other
    int codecs are host-sequential)."""
    if buf[0] == DFOR and device_decode_on():
        return _dfor_single(memoryview(buf)[1:], n, "i64")
    return None


def _dfor_single(payload, n: int, kind: str) -> jax.Array:
    """One DFOR segment expanded on device (nb == 1 batch)."""
    from . import compileaudit
    transform, width, dscale, n_hdr, ref = _dfor.parse_header(payload)
    if n_hdr != n:
        raise ValueError(f"DFOR row-count mismatch: header {n_hdr}, "
                         f"caller {n}")
    words = _dfor.payload_words(payload, n, width)
    wpad = np.zeros((1, len(words) + 2), dtype=np.uint32)
    wpad[0, :len(words)] = words
    wd = jax.device_put(wpad)
    rd = jax.device_put(np.array([ref], dtype=np.uint64))
    compileaudit.record_h2d("dfor", int(wd.nbytes))
    compileaudit.record_h2d("payload", int(rd.nbytes))
    _bump("dfor_blocks")
    out = dfor_expand(wd, rd, n=n, width=width, transform=transform,
                      dscale=dscale, kind=kind)
    return out[0]


def device_decode_time_block(buf, n: int) -> jax.Array | None:
    """Decode a time block on device: CONST_DELTA (regular sampling —
    the overwhelmingly common case — costs 16 bytes of transfer) or a
    DFOR-packed irregular block."""
    from . import compileaudit
    if buf[0] == DFOR and device_decode_on():
        return _dfor_single(memoryview(buf)[1:], n, "i64")
    if buf[0] != CONST_DELTA:
        return None
    t0, step = struct.unpack("<qq", memoryview(buf)[1:17])
    t0d = jnp.asarray(t0, dtype=jnp.int64)
    stepd = jnp.asarray(step, dtype=jnp.int64)
    compileaudit.record_h2d("decode", int(t0d.nbytes + stepd.nbytes))
    return const_delta_expand(t0d, stepd, n)
