"""Device-side decode of cheap block codecs.

SURVEY.md §7 hard parts: "Host↔device bandwidth: decode-on-CPU then DMA
can starve the TPU; … decompress cheap codecs (RLE/delta) *in-kernel*."
This module is that path: for the codecs whose decode is pure arithmetic
(CONST, RLE, CONST_DELTA — encoding/blocks.py), the host ships the SMALL
compressed payload (run values + lengths, or start + stride) and the
expansion to a dense block happens on device, fused by XLA into whatever
kernel consumes it. A run-heavy block of 64k floats moves a few hundred
bytes over PCIe/DMA instead of 512KB.

Expansion uses static output lengths (`total_repeat_length` /
`jnp.arange(n)`) so everything stays jit-compatible; block sizes are
already padded to fixed tiers by the TSSP layout (SEGMENT_SIZE), so the
jit cache hits.

Byte-codec blocks (gorilla/zstd/simple8b) stay CPU-decoded — bit-twiddly
sequential decoders don't map to the VPU; `device_decode_float_block`
returns None for them and the caller falls back to the numpy decoder.
"""

from __future__ import annotations

import functools
import struct

import jax
import jax.numpy as jnp
import numpy as np

from ..encoding.blocks import CONST, CONST_DELTA, RLE, parse_rle_payload

__all__ = ["rle_expand", "const_expand", "const_delta_expand",
           "device_decode_float_block", "device_decode_time_block"]


@functools.partial(jax.jit, static_argnames=("n",))
def rle_expand(values: jax.Array, lengths: jax.Array, n: int) -> jax.Array:
    """Expand run-length pairs to a dense (n,) block on device. The runs
    arrays are padded with zero-length runs to a fixed size by the caller
    so the jit cache keys recur."""
    return jnp.repeat(values, lengths, total_repeat_length=n)


@functools.partial(jax.jit, static_argnames=("n",))
def const_expand(value: jax.Array, n: int) -> jax.Array:
    return jnp.full((n,), value)


@functools.partial(jax.jit, static_argnames=("n",))
def const_delta_expand(t0: jax.Array, step: jax.Array, n: int) -> jax.Array:
    return t0 + step * jnp.arange(n, dtype=jnp.int64)


def _pad_runs(vals: np.ndarray, lens: np.ndarray,
              bucket: int = 256) -> tuple[np.ndarray, np.ndarray]:
    """Pad run arrays to a bucketed length (zero-length runs expand to
    nothing) so repeated decodes share one compiled kernel."""
    r = len(vals)
    padded = max(bucket, 1 << (r - 1).bit_length()) if r else bucket
    if r == padded:
        return vals, lens
    pv = np.zeros(padded, dtype=vals.dtype)
    pl = np.zeros(padded, dtype=np.int64)
    pv[:r] = vals
    pl[:r] = lens
    return pv, pl


def device_decode_float_block(buf, n: int) -> jax.Array | None:
    """Decode a float block ON DEVICE when its codec is arithmetic;
    returns None for byte codecs (caller falls back to the CPU decoder,
    encoding/blocks.decode_float_block). The compressed payload is the
    only H2D traffic — booked per upload into the transfer manifest
    (ops/compileaudit.py, site ``decode``)."""
    from . import compileaudit
    codec = buf[0]
    payload = memoryview(buf)[1:]
    if codec == CONST:
        v = np.frombuffer(payload[:8], dtype=np.float64)[0]
        vd = jnp.asarray(v)
        compileaudit.record_h2d("decode", int(vd.nbytes))
        return const_expand(vd, n)
    if codec == RLE:
        vals, lens = parse_rle_payload(payload)
        pv, pl = _pad_runs(vals, lens)
        # ship ~runs*12 bytes instead of n*8
        pvd, pld = jnp.asarray(pv), jnp.asarray(pl)
        compileaudit.record_h2d("decode",
                                int(pvd.nbytes + pld.nbytes))
        return rle_expand(pvd, pld, n)
    return None


def device_decode_time_block(buf, n: int) -> jax.Array | None:
    """Decode a CONST_DELTA time block on device (regular sampling — the
    overwhelmingly common case — costs 16 bytes of transfer)."""
    from . import compileaudit
    if buf[0] != CONST_DELTA:
        return None
    t0, step = struct.unpack("<qq", memoryview(buf)[1:17])
    t0d = jnp.asarray(t0, dtype=jnp.int64)
    stepd = jnp.asarray(step, dtype=jnp.int64)
    compileaudit.record_h2d("decode", int(t0d.nbytes + stepd.nbytes))
    return const_delta_expand(t0d, stepd, n)
