"""PromQL range/instant vector kernels.

Role of the reference's prom cursors (engine/prom_range_vector_cursor.go:34
window logic :92-167, engine/prom_instant_vector_cursor.go, reduce funcs
engine/prom_functions.go, series_agg_func_prom.go).

TPU-first formulation of overlapping range windows: a range query evaluates
rate(x[R]) at steps t_0, t_0+step, ... — windows overlap whenever R > step.
Instead of replicating rows into every window they touch (R/step× blowup),
we compute **disjoint per-(series, step-bucket) partial states** with one
segment reduction, then merge k = R/step consecutive bucket states per eval
point with a fold over k shifted state arrays (bucket states form a monoid:
first/last pick, count/sum/increase add with boundary reset correction).
O(rows) + O(series × buckets × k) vector ops, no scatter blowup.

Alignment: eval timestamps and bucket edges share the step grid; R must be
a multiple of step (common dashboard case). Non-aligned R is rounded up to
the next step multiple (documented deviation; exactness restored when
step | R).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_I64 = jnp.int64


class BucketState(NamedTuple):
    """Partial state of one (series, step-bucket): a monoid under
    chronological merge."""
    count: jax.Array        # valid samples
    first: jax.Array        # value at earliest sample
    last: jax.Array         # value at latest sample
    first_t: jax.Array      # ns
    last_t: jax.Array       # ns
    sum: jax.Array
    min: jax.Array
    max: jax.Array
    inc: jax.Array          # reset-corrected increase WITHIN the bucket
    sumsq: jax.Array        # sum of squares (stddev/stdvar_over_time)
    resets: jax.Array       # counter resets WITHIN the bucket
    changes: jax.Array      # value changes WITHIN the bucket
    sum_t: jax.Array        # sum of times (seconds, origin-relative)
    sum_tv: jax.Array       # sum of time*value (deriv/predict_linear)
    sum_t2: jax.Array       # sum of time^2


@functools.partial(jax.jit, static_argnames=("num_segments",))
def bucket_states(values, valid, times, seg_ids, series_ids,
                  num_segments: int, origin_t=0,
                  value_anchor=0.0) -> BucketState:
    """One fused pass: rows (sorted by series, then time) → per-segment
    BucketState. seg_ids = series_index * num_buckets + bucket. series_ids
    identify series-change boundaries for the reset correction. origin_t:
    ns origin the regression time sums are taken relative to (keeps t^2
    magnitudes small — epoch-relative seconds squared would eat half the
    float64 mantissa). value_anchor: per-row value shift (typically each
    series' first sample) applied to the second-order sums (sumsq,
    sum_tv) for the same cancellation reason — a 1.7e9-magnitude gauge
    has sumsq ulp ≈ 512, so un-anchored variance is rounding noise.
    First-order state (sum/min/max/first/last/inc) stays unshifted."""
    ns = num_segments + 1
    n = values.shape[0]
    fdt = values.dtype
    idx = jnp.arange(n, dtype=_I64)

    def seg_sum(x):
        return jax.ops.segment_sum(x, seg_ids, ns)[:num_segments]

    cnt = seg_sum(valid.astype(_I64))
    vz = jnp.where(valid, values, jnp.zeros((), fdt))
    va = jnp.where(valid, values - value_anchor, jnp.zeros((), fdt))
    ssum = seg_sum(vz)
    ssumsq = seg_sum(va * va)
    smin = jax.ops.segment_min(
        jnp.where(valid, values, jnp.array(jnp.inf, fdt)), seg_ids,
        ns)[:num_segments]
    smax = jax.ops.segment_max(
        jnp.where(valid, values, jnp.array(-jnp.inf, fdt)), seg_ids,
        ns)[:num_segments]
    fi = jax.ops.segment_min(jnp.where(valid, idx, n), seg_ids,
                             ns)[:num_segments]
    li = jax.ops.segment_max(jnp.where(valid, idx, -1), seg_ids,
                             ns)[:num_segments]
    fsafe = jnp.minimum(fi, n - 1)
    lsafe = jnp.maximum(li, 0)
    has_f = fi < n
    first = jnp.where(has_f, values[fsafe], jnp.nan)
    first_t = jnp.where(has_f, times[fsafe], 0)
    last = jnp.where(li >= 0, values[lsafe], jnp.nan)
    last_t = jnp.where(li >= 0, times[lsafe], 0)

    # linear-regression moments over origin-relative seconds and
    # anchor-relative values
    t_rel = jnp.where(valid, (times - origin_t).astype(fdt) / 1e9,
                      jnp.zeros((), fdt))
    sum_t = seg_sum(t_rel)
    sum_tv = seg_sum(t_rel * va)
    sum_t2 = seg_sum(t_rel * t_rel)

    # pairwise stats over consecutive valid samples of the SAME series and
    # bucket: reset-corrected increase, counter resets, value changes
    prev_v = jnp.roll(values, 1)
    same = (jnp.roll(seg_ids, 1) == seg_ids) & valid & jnp.roll(valid, 1)
    same = same.at[0].set(False)
    step_inc = jnp.where(values >= prev_v, values - prev_v, values)
    inc = seg_sum(jnp.where(same, step_inc, jnp.zeros((), fdt)))
    resets = seg_sum((same & (values < prev_v)).astype(_I64))
    changes = seg_sum((same & (values != prev_v)).astype(_I64))

    return BucketState(cnt, first, last, first_t, last_t, ssum, smin, smax,
                       inc, ssumsq, resets, changes, sum_t, sum_tv, sum_t2)


def _merge(a: BucketState, b: BucketState, xp=jnp) -> BucketState:
    """Merge chronologically adjacent states (a earlier than b).
    ``xp`` picks the array module: jnp inside the jitted device fold,
    np for the host fold — one body, no drift."""
    a_has = a.count > 0
    b_has = b.count > 0
    first = xp.where(a_has, a.first, b.first)
    first_t = xp.where(a_has, a.first_t, b.first_t)
    last = xp.where(b_has, b.last, a.last)
    last_t = xp.where(b_has, b.last_t, a.last_t)
    # boundary corrections between a.last and b.first
    both = a_has & b_has
    boundary = xp.where(
        both,
        xp.where(b.first >= a.last, b.first - a.last, b.first),
        0.0)
    inc = (xp.where(a_has, a.inc, 0.0) + xp.where(b_has, b.inc, 0.0)
           + boundary)
    resets = (a.resets + b.resets
              + (both & (b.first < a.last)).astype(a.resets.dtype))
    changes = (a.changes + b.changes
               + (both & (b.first != a.last)).astype(a.changes.dtype))

    def add(x, y):
        return xp.where(a_has, x, 0.0) + xp.where(b_has, y, 0.0)

    return BucketState(
        count=a.count + b.count,
        first=first, last=last, first_t=first_t, last_t=last_t,
        sum=add(a.sum, b.sum),
        min=xp.minimum(a.min, b.min),
        max=xp.maximum(a.max, b.max),
        inc=inc,
        sumsq=add(a.sumsq, b.sumsq),
        resets=resets, changes=changes,
        sum_t=add(a.sum_t, b.sum_t),
        sum_tv=add(a.sum_tv, b.sum_tv),
        sum_t2=add(a.sum_t2, b.sum_t2))


def _shift_right(s: BucketState, by: int, xp=jnp) -> BucketState:
    """Shift bucket axis (last axis) right by `by` (earlier buckets move
    toward the eval position); vacated slots become empty states."""
    def sh(x, fill):
        y = xp.roll(x, by, axis=-1)
        mask_idx = xp.arange(x.shape[-1]) < by
        return xp.where(mask_idx, xp.asarray(fill).astype(y.dtype), y)
    return BucketState(
        count=sh(s.count, 0), first=sh(s.first, xp.nan),
        last=sh(s.last, xp.nan), first_t=sh(s.first_t, 0),
        last_t=sh(s.last_t, 0), sum=sh(s.sum, 0.0),
        min=sh(s.min, xp.inf), max=sh(s.max, -xp.inf),
        inc=sh(s.inc, 0.0), sumsq=sh(s.sumsq, 0.0),
        resets=sh(s.resets, 0), changes=sh(s.changes, 0),
        sum_t=sh(s.sum_t, 0.0), sum_tv=sh(s.sum_tv, 0.0),
        sum_t2=sh(s.sum_t2, 0.0))


def _fold_windows_body(states: BucketState, k: int, xp) -> BucketState:
    acc = _shift_right(states, k - 1, xp)
    for i in range(k - 2, -1, -1):
        acc = _merge(acc, _shift_right(states, i, xp), xp)
    return acc


@functools.partial(jax.jit, static_argnames=("k",))
def fold_windows(states: BucketState, k: int) -> BucketState:
    """states: (G, B) per-bucket; returns (G, B) where slot b holds the
    merged state of buckets (b-k, b] — the range window ending at bucket b.
    Fold over k shifted copies, earliest first (log(k) merges possible;
    linear fold keeps the reset-correction order exact)."""
    return _fold_windows_body(states, k, jnp)


def fold_windows_host(states: BucketState, k: int) -> BucketState:
    """Host fold over numpy states — same body as the jitted fold."""
    return _fold_windows_body(states, k, np)


def _seg_reduce_sorted(seg, n_out, arrays_min, arrays_max):
    """Sorted-run reduceat helper: seg must be nondecreasing. Returns
    per-output (min…, max…) arrays with identity fills for empty
    segments. arrays_* are (values, identity) pairs."""
    starts = np.flatnonzero(np.diff(seg, prepend=-1))
    run_seg = seg[starts]
    keep = run_seg < n_out
    outs = []
    for vals, ident in arrays_min:
        o = np.full(n_out, ident, dtype=vals.dtype)
        if starts.size:
            r = np.minimum.reduceat(vals, starts)
            o[run_seg[keep]] = r[keep]
        outs.append(o)
    for vals, ident in arrays_max:
        o = np.full(n_out, ident, dtype=vals.dtype)
        if starts.size:
            r = np.maximum.reduceat(vals, starts)
            o[run_seg[keep]] = r[keep]
        outs.append(o)
    return outs


def bucket_states_host(values, valid, times, seg_ids, series_ids,
                       num_segments: int, origin_t=0,
                       value_anchor=0.0) -> BucketState:
    """Host mirror of bucket_states: numpy bincount/reduceat instead of
    device segment ops. On tunnel-attached TPUs the device kernel pays
    a ~0.1-0.25s transfer per pulled state array (15 of them), so
    realistic prom shapes (millions of rows, huge series counts) fold
    faster on host; the engine routes by size (PROM_DEVICE_MIN_ROWS).
    Semantics mirror the jitted kernel field for field."""
    ns = num_segments + 1
    n = len(values)
    values = np.asarray(values, dtype=np.float64)
    valid = np.asarray(valid, dtype=bool)
    times = np.asarray(times, dtype=np.int64)
    seg_ids = np.minimum(np.asarray(seg_ids, dtype=np.int64),
                         num_segments)
    fdt = values.dtype
    idx = np.arange(n, dtype=np.int64)

    def seg_sum(x):
        return np.bincount(seg_ids, weights=x,
                           minlength=ns)[:num_segments]

    cnt = seg_sum(valid.astype(np.float64)).astype(np.int64)
    vz = np.where(valid, values, 0.0)
    va = np.where(valid, vz - value_anchor, 0.0)
    ssum = seg_sum(vz)
    ssumsq = seg_sum(va * va)
    # min/max/first/last need ordered runs: one stable sort by segment
    if n and not (np.diff(seg_ids) >= 0).all():
        order = np.argsort(seg_ids, kind="stable")
        seg_s = seg_ids[order]
        val_s, valid_s, idx_s = values[order], valid[order], idx[order]
    else:
        seg_s, val_s, valid_s, idx_s = seg_ids, values, valid, idx
    smin, fi, smax, li = _seg_reduce_sorted(
        seg_s, num_segments,
        [(np.where(valid_s, val_s, np.inf), np.inf),
         (np.where(valid_s, idx_s, n), n)],
        [(np.where(valid_s, val_s, -np.inf), -np.inf),
         (np.where(valid_s, idx_s, -1), -1)])
    fsafe = np.minimum(fi, n - 1) if n else np.zeros_like(fi)
    lsafe = np.maximum(li, 0)
    has_f = fi < n
    first = np.where(has_f, values[fsafe] if n else np.nan, np.nan)
    first_t = np.where(has_f, times[fsafe] if n else 0, 0)
    last = np.where(li >= 0, values[lsafe] if n else np.nan, np.nan)
    last_t = np.where(li >= 0, times[lsafe] if n else 0, 0)

    t_rel = np.where(valid, (times - origin_t).astype(fdt) / 1e9, 0.0)
    sum_t = seg_sum(t_rel)
    sum_tv = seg_sum(t_rel * va)
    sum_t2 = seg_sum(t_rel * t_rel)

    # mask BEFORE the subtract: invalid lanes can hold non-finite
    # placeholders, and adjacent Inf lanes make the unmasked
    # `values - prev_v` compute inf-inf (RuntimeWarning); `same` gates
    # the RESULT but not the arithmetic, so use the zeroed vz here
    prev_v = np.roll(vz, 1)
    same = (np.roll(seg_ids, 1) == seg_ids) & valid & np.roll(valid, 1)
    if n:
        same[0] = False
    step_inc = np.where(vz >= prev_v, vz - prev_v, vz)
    inc = seg_sum(np.where(same, step_inc, 0.0))
    resets = seg_sum((same & (vz < prev_v)).astype(
        np.float64)).astype(np.int64)
    changes = seg_sum((same & (vz != prev_v)).astype(
        np.float64)).astype(np.int64)

    return BucketState(cnt, first, last, first_t, last_t, ssum, smin,
                       smax, inc, ssumsq, resets, changes, sum_t,
                       sum_tv, sum_t2)


def irate_states_host(values, valid, times, seg_ids,
                      num_segments: int):
    """Host mirror of irate_states (last two samples per segment)."""
    n = len(values)
    values = np.asarray(values, dtype=np.float64)
    valid = np.asarray(valid, dtype=bool)
    times = np.asarray(times, dtype=np.int64)
    seg_ids = np.minimum(np.asarray(seg_ids, dtype=np.int64),
                         num_segments)
    idx = np.arange(n, dtype=np.int64)
    if n and not (np.diff(seg_ids) >= 0).all():
        order = np.argsort(seg_ids, kind="stable")
        seg_s, valid_s, idx_s = (seg_ids[order], valid[order],
                                 idx[order])
    else:
        seg_s, valid_s, idx_s = seg_ids, valid, idx
    # reduce over ns = num_segments+1 so rows routed to the pad
    # segment stay indexable through li_full[seg_ids] (the device
    # kernel trims AFTER the gather for the same reason)
    (li_full,) = _seg_reduce_sorted(
        seg_s, num_segments + 1, [],
        [(np.where(valid_s, idx_s, -1), -1)])
    li = li_full[:num_segments]
    is_last = valid & (li_full[seg_ids] == idx) if n else valid
    masked = np.where(valid_s & ~is_last[idx_s], idx_s, -1) \
        if n else idx_s
    (pi_full,) = _seg_reduce_sorted(seg_s, num_segments + 1, [],
                                    [(masked, -1)])
    pi = pi_full[:num_segments]
    lsafe = np.maximum(li, 0)
    psafe = np.maximum(pi, 0)
    cnt = (li >= 0).astype(np.int64) + (pi >= 0).astype(np.int64)
    return (np.where(li >= 0, values[lsafe] if n else np.nan, np.nan),
            np.where(pi >= 0, values[psafe] if n else np.nan, np.nan),
            np.where(li >= 0, times[lsafe] if n else 0, 0),
            np.where(pi >= 0, times[psafe] if n else 0, 0),
            cnt)


# ---------------------------------------------------------------- functions

def _xp_of(x):
    """np for host (numpy) states, jnp for device arrays — the finalize
    functions below are not jitted, so eager jnp on numpy inputs would
    bounce every op through the (possibly tunnel-attached) device."""
    return np if isinstance(x, np.ndarray) else jnp


def prom_rate(win: BucketState, window_end_t, range_ns: int,
              kind: str = "rate"):
    """Prometheus extrapolated rate/increase/delta over merged window
    states (promql extrapolatedRate semantics: extrapolate the sampled
    slope to the window boundaries, limited to half a sample interval /
    zero-crossing)."""
    jnp = _xp_of(win.count)  # noqa: shadows module alias on purpose
    cnt = win.count
    ok = cnt >= 2
    dur = (win.last_t - win.first_t).astype(jnp.float64) / 1e9
    dur = jnp.maximum(dur, 1e-12)
    if kind == "delta":
        delta = win.last - win.first
    else:
        delta = win.inc
    rng_s = range_ns / 1e9
    # extrapolation (prom extrapolatedRate): window is (end-range, end]
    start_gap = (win.first_t - (window_end_t - range_ns)).astype(
        jnp.float64) / 1e9
    end_gap = (window_end_t - win.last_t).astype(jnp.float64) / 1e9
    avg_interval = dur / jnp.maximum(cnt - 1, 1).astype(jnp.float64)
    # upstream extrapolatedRate: a boundary gap under 1.1×avg_interval is
    # bridged completely (the series plausibly extends to the boundary);
    # larger gaps extend by only half a sample interval
    threshold = avg_interval * 1.1
    # counters can't go below zero: limit start extrapolation
    with np.errstate(divide="ignore", invalid="ignore"):
        zero_limit = jnp.where(
            (kind != "delta") & (delta > 0) & (win.first >= 0),
            win.first / jnp.maximum(delta / dur, 1e-30), jnp.inf)
    start_gap = jnp.minimum(start_gap, zero_limit)
    extra_start = jnp.where(start_gap < threshold, start_gap,
                            avg_interval / 2)
    extra_end = jnp.where(end_gap < threshold, end_gap,
                          avg_interval / 2)
    factor = (dur + extra_start + extra_end) / dur
    ext_delta = delta * factor
    if kind == "rate":
        out = ext_delta / rng_s
    else:  # increase / delta
        out = ext_delta
    return jnp.where(ok, out, jnp.nan)


def prom_irate(win: BucketState, kind: str = "irate"):
    """irate/idelta need the last TWO samples — approximated from bucket
    granularity is wrong, so the caller computes them with a dedicated
    per-row pass (see irate_states)."""
    raise NotImplementedError


@functools.partial(jax.jit, static_argnames=("num_segments",))
def irate_states(values, valid, times, seg_ids, num_segments: int):
    """Last two samples per segment: returns (last, prev, last_t, prev_t,
    count). One pass: last via segment_max on index; prev via segment_max
    on index masked below last."""
    ns = num_segments + 1
    n = values.shape[0]
    idx = jnp.arange(n, dtype=_I64)
    li = jax.ops.segment_max(jnp.where(valid, idx, -1), seg_ids, ns)
    li_seg = li[:num_segments]
    # mask out the last sample, find the new max index = prev sample
    is_last = valid & (li[seg_ids] == idx)
    pi = jax.ops.segment_max(jnp.where(valid & ~is_last, idx, -1), seg_ids,
                             ns)[:num_segments]
    lsafe = jnp.maximum(li_seg, 0)
    psafe = jnp.maximum(pi, 0)
    cnt = (li_seg >= 0).astype(_I64) + (pi >= 0).astype(_I64)
    return (jnp.where(li_seg >= 0, values[lsafe], jnp.nan),
            jnp.where(pi >= 0, values[psafe], jnp.nan),
            jnp.where(li_seg >= 0, times[lsafe], 0),
            jnp.where(pi >= 0, times[psafe], 0),
            cnt)


def prom_irate_value(last, prev, last_t, prev_t, cnt, kind: str = "irate"):
    jnp = _xp_of(cnt)
    ok = cnt >= 2
    dt = (last_t - prev_t).astype(jnp.float64) / 1e9
    dt = jnp.maximum(dt, 1e-12)
    if kind == "idelta":
        v = last - prev
    else:
        d = jnp.where(last >= prev, last - prev, last)  # reset
        v = d / dt
    return jnp.where(ok, v, jnp.nan)


# over_time family: direct from merged window states
def over_time_value(win: BucketState, func: str, value_anchor=0.0):
    """value_anchor: the per-series shift bucket_states applied to the
    second-order sums — needed to reconstruct variance (shape must
    broadcast against win arrays, e.g. (S, 1))."""
    jnp = _xp_of(win.count)
    has = win.count > 0
    if func == "avg_over_time":
        v = win.sum / jnp.maximum(win.count, 1)
    elif func == "sum_over_time":
        v = win.sum
    elif func == "min_over_time":
        v = win.min
    elif func == "max_over_time":
        v = win.max
    elif func == "count_over_time":
        v = win.count.astype(jnp.float64)
    elif func == "last_over_time":
        v = win.last
    elif func == "first_over_time":
        v = win.first
    elif func == "present_over_time":
        v = jnp.ones_like(win.sum)
    elif func in ("stddev_over_time", "stdvar_over_time"):
        n = jnp.maximum(win.count, 1).astype(jnp.float64)
        # sumsq is anchor-relative; var is shift-invariant
        mean_a = win.sum / n - value_anchor
        v = jnp.maximum(win.sumsq / n - mean_a * mean_a, 0.0)
        if func == "stddev_over_time":
            v = jnp.sqrt(v)
    elif func == "resets":
        v = win.resets.astype(jnp.float64)
    elif func == "changes":
        v = win.changes.astype(jnp.float64)
    else:
        raise ValueError(f"unsupported over_time func {func}")
    return jnp.where(has, v, jnp.nan)


def prom_linreg(win: BucketState, end_rel_s, value_anchor=0.0):
    """Least-squares fit over the window's samples (prom linearRegression,
    promql/functions.go): returns (slope, intercept at the window end
    time). end_rel_s: window end times in seconds relative to the same
    origin bucket_states used for its regression moments; value_anchor:
    the per-series value shift it applied to sum_tv (slope is
    shift-invariant, the intercept un-shifts)."""
    jnp = _xp_of(win.count)
    ok = win.count >= 2
    n = jnp.maximum(win.count, 1).astype(jnp.float64)
    mean_t = win.sum_t / n
    mean_va = win.sum / n - value_anchor
    # covariance/variance from raw moments (n-weighted, factors cancel)
    cov = win.sum_tv - win.sum_t * mean_va
    var = win.sum_t2 - win.sum_t * mean_t
    # all samples at one timestamp → var 0 → undefined slope
    ok = ok & (var > 0)
    slope = cov / jnp.where(var > 0, var, 1.0)
    intercept = mean_va + value_anchor + slope * (end_rel_s - mean_t)
    return (jnp.where(ok, slope, jnp.nan),
            jnp.where(ok, intercept, jnp.nan))
