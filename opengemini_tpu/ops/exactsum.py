"""Reproducible (bit-identical) float64 summation via binned integer limbs.

The north star demands bit-identical aggregation across topologies: one
shard, a multi-store cluster, and the host reference must produce the
SAME f64 bits for sum/mean over the same data. Floating-point addition
is not associative, so no ordering discipline survives distribution —
the reference merges per-store partials in arrival order and silently
accepts last-ulp drift. The TPU-native fix is to make the accumulation
EXACT and therefore order-free (the Demmel–Nguyen reproducible-sum idea,
specialised to integer limbs):

    v  =  Σ_k  b_k · 2^(E - B(k+1))   + residual,   0 ≤ |b_k| < 2^B

Each value decomposes into K=6 signed limbs of B=18 bits below a scale
2^E (E a multiple of B, chosen per store from max|v|). Limb sums are
exact integers (n·2^18 < 2^48 keeps them exact even in the TPU's
float32-pair f64 emulation), so ANY summation order — per-segment
scatter on device, bincount on host, cross-store merge — yields the
same limb totals. A cell whose every contributing value decomposed with
residual 0 is flagged EXACT: its final value is the correctly-rounded
f64 of the exact integer total, identical in every topology and equal
to math.fsum. Cells with >2^56 dynamic range (or non-finite values)
fall back to the ordinary f64 state, flagged inexact.

Partials with different E rebase by whole-limb shifts (exact integer
shifts; dropped nonzero low limbs clear the exact flag).

Selector values (first/last/min/max) never round-trip through the
emulated-f64 device, so they keep full f64 precision everywhere:
the sparse device path returns ROW INDICES (host_gather in
query/executor.py) and gathers the exact values host-side; the
block-resident path ships min/max row-index planes
(ops/blockagg.py plane_layout) with the same host gather; dense
groups reduce on host in real IEEE f64 (dense_window_aggregate_host).
Remaining caveat: the multi-device mesh merge (parallel/meshquery.py)
carries min/max through pmin/pmax as VALUES — exact on real-f64
meshes (CPU/GPU/TPU-f64), ~48-bit on f32-pair-emulated single-chip
setups, where the executor's host-gather paths are used instead.

No counterpart in the reference — it has no reproducible-sum machinery
(engine/series_agg_reducer.gen.go merges f64 partials directly).
"""

from __future__ import annotations

import functools

import numpy as np

LIMB_BITS = 18
K_LIMBS = 6
_RADIX = 1 << LIMB_BITS            # 262144
SPAN_BITS = LIMB_BITS * K_LIMBS    # 108 bits captured below 2^E


def pick_scale(max_abs: float) -> int:
    """Smallest E (multiple of LIMB_BITS) with max_abs < 2^E."""
    if not np.isfinite(max_abs) or max_abs <= 0:
        return 0
    e = int(np.ceil(np.log2(max_abs))) + 1
    return int(np.ceil(e / LIMB_BITS)) * LIMB_BITS


def limb_scales(E: int) -> np.ndarray:
    """(K,) f64 powers 2^(E - B(k+1)) — exact (powers of two)."""
    exps = E - LIMB_BITS * (np.arange(K_LIMBS) + 1)
    return np.exp2(exps.astype(np.float64))


def decompose(values: np.ndarray, E: int):
    """values (N,) f64 → (limbs (N, K) f64-integers, residual (N,)).
    Exact: Σ_k limbs[:,k]·scale_k + residual == values, bit for bit.
    Non-finite values yield limbs 0 and residual NaN (→ inexact)."""
    scales = limb_scales(E)
    finite = np.isfinite(values)
    a = np.abs(np.where(finite, values, 0.0))
    sign = np.where(values < 0, -1.0, 1.0)
    limbs = np.empty(values.shape + (K_LIMBS,), dtype=np.float64)
    for k in range(K_LIMBS):
        b = np.floor(a / scales[k])
        # a may equal 2^E only through caller error; clamp defensively
        np.minimum(b, float(_RADIX - 1), out=b)
        a = a - b * scales[k]
        limbs[..., k] = sign * b
    residual = np.where(finite, sign * a, np.nan)
    return limbs, residual


def exact_segment_sum_host(values: np.ndarray, valid: np.ndarray,
                           seg_ids: np.ndarray, num_segments: int,
                           E: int):
    """Host path: (limb sums (S, K) f64, inexact flags (S,) bool)."""
    S = num_segments
    keep = valid & (seg_ids < S)
    v = values[keep]
    s = seg_ids[keep]
    limbs, res = decompose(v, E)
    out = np.zeros((S, K_LIMBS), dtype=np.float64)
    if len(v) * 8 < S:
        # sparse residue into a huge grid: scattered adds touch only
        # the live cells; K bincounts would each alloc+walk S
        np.add.at(out, s, limbs)
    else:
        for k in range(K_LIMBS):
            out[:, k] = np.bincount(s, weights=limbs[:, k],
                                    minlength=S)
    bad = res != 0.0
    bad |= ~np.isfinite(res)
    inexact = np.zeros(S, dtype=bool)
    np.logical_or.at(inexact, s[bad], True)
    return out, inexact


def host_limbs(values: np.ndarray, valid: np.ndarray | None, E: int):
    """Decompose on HOST into int32 limb planes + per-row bad flags.

    The decomposition MUST run in real IEEE f64: on TPU, f64 is emulated
    as float32 pairs whose floor/divide are not exact, which silently
    breaks the integer-limb invariant (measured: ~1e-16 relative drift).
    Integer ADDS on device are exact, so the device path ships int32
    limbs and reduces in int64."""
    limbs, res = decompose(values, E)
    bad = (res != 0.0) | ~np.isfinite(res)
    if valid is not None:
        limbs = np.where(valid[..., None], limbs, 0.0)
        bad = bad & valid
    return limbs.astype(np.int32), bad


_JITTED: dict = {}


def exact_segment_sum_traced(limbs_i32, seg_ids, num_segments: int,
                             sorted_ids: bool):
    """Traceable body of the device limb reduction — the bit-identical
    invariant lives HERE, shared by the jitted single-field path below
    and the vmapped multi-field kernel (segment_agg._multi_segment_jit)
    so the two can never drift apart."""
    import jax
    import jax.numpy as jnp
    ns = num_segments + 1
    sums = jax.ops.segment_sum(limbs_i32.astype(jnp.int64),
                               seg_ids, ns,
                               indices_are_sorted=sorted_ids)
    return sums[:num_segments]


def exact_segment_sum(limbs_i32, seg_ids, num_segments: int,
                      sorted_ids: bool = False):
    """Device sparse path: int64 segment sums of host-decomposed int32
    limb planes — exact integer arithmetic on the device. (jit built
    lazily so importing this module never initializes a backend.)"""
    fn = _JITTED.get("seg")
    if fn is None:
        import jax

        _JITTED["seg"] = fn = functools.partial(
            jax.jit, static_argnames=("num_segments", "sorted_ids"))(
                exact_segment_sum_traced)
    return fn(limbs_i32, seg_ids, num_segments=num_segments,
              sorted_ids=sorted_ids)


def exact_dense_sum(limbs_i32):
    """Device dense path: (S, P, K) int32 limbs → (S, K) int64 sums."""
    fn = _JITTED.get("dense")
    if fn is None:
        import jax
        import jax.numpy as jnp
        _JITTED["dense"] = fn = jax.jit(
            lambda x: x.astype(jnp.int64).sum(axis=1))
    return fn(limbs_i32)


def segment_bad_flags(bad: np.ndarray, seg_ids: np.ndarray,
                      num_segments: int) -> np.ndarray:
    """Host reduction of per-row inexact flags (cheap — bools)."""
    out = np.zeros(num_segments, dtype=bool)
    sel = bad & (seg_ids < num_segments)
    np.logical_or.at(out, seg_ids[sel], True)
    return out


def canonicalize(limbs: np.ndarray) -> np.ndarray:
    """Carry-normalize limb planes to the canonical representation:
    digits in [0, 2^18) with the signed top carry folded into the high
    limb. Value-preserving (exact integer arithmetic). Needed wherever
    a decision depends on limb MAGNITUDES rather than the represented
    value — different but equal-valued representations (e.g. the packed
    device transport vs raw kernel sums) must decide identically."""
    d = limbs.astype(np.int64)
    for k in range(K_LIMBS - 1, 0, -1):
        c = d[..., k] >> LIMB_BITS          # floor (sign-safe)
        d[..., k] -= c << LIMB_BITS
        d[..., k - 1] += c
    return d.astype(np.float64)


def rebase(limbs: np.ndarray, inexact: np.ndarray, e_from: int,
           e_to: int):
    """Shift limb grids from scale e_from to e_to ≥ e_from (whole-limb
    shifts — exact). Dropped nonzero low limbs clear exactness; the
    drop check runs on the canonical representation so equal-valued
    limb encodings rebase identically."""
    if e_to == e_from:
        return limbs, inexact
    shift = (e_to - e_from) // LIMB_BITS
    if shift < 0:
        raise ValueError("rebase target must be ≥ source scale")
    limbs = canonicalize(limbs)
    out = np.zeros_like(limbs)
    if shift < K_LIMBS:
        out[..., shift:] = limbs[..., :K_LIMBS - shift]
        dropped = limbs[..., K_LIMBS - shift:]
    else:
        dropped = limbs
    inexact = inexact | (dropped != 0.0).any(axis=-1)
    return out, inexact


def merge_limbs(a_limbs, a_inexact, a_e, b_limbs, b_inexact, b_e):
    """Combine two partial limb states → (limbs, inexact, E). Addition
    of exact integers — order-free."""
    E = max(a_e, b_e)
    a_limbs, a_inexact = rebase(a_limbs, a_inexact, a_e, E)
    b_limbs, b_inexact = rebase(b_limbs, b_inexact, b_e, E)
    return a_limbs + b_limbs, a_inexact | b_inexact, E


def finalize_exact(limbs: np.ndarray, E: int) -> np.ndarray:
    """Correctly-rounded f64 of the exact integer totals — equals
    math.fsum of the original values wherever the exact flag held.

    Vectorized path: carry-normalize the signed limb sums into base-2^18
    digits (int64, exact), pack them into three NON-OVERLAPPING exact
    f64 components, and sum high→low with a TwoSum error track. Cells
    whose residual error could straddle a rounding boundary (double-
    rounding hazard) fall back to the per-cell big-int path — measured
    ~0 cells on real data, but the guarantee needs the check."""
    scale_lo = 2.0 ** float(E - SPAN_BITS)
    n = int(np.prod(limbs.shape[:-1], dtype=np.int64))
    if n == 0:
        return np.zeros(limbs.shape[:-1])
    # native single-pass path (same IEEE sequence — bit-identical);
    # hazard cells fall through to the shared big-int loop below
    from .. import native as _native
    nf = _native.finalize_exact_fast(limbs, LIMB_BITS, E)
    if nf is not None:
        out, sus = nf
        if len(sus):
            flat_h = limbs.reshape(-1, K_LIMBS)
            for i in sus.tolist():
                out[i] = _bigint_cell(flat_h, i, scale_lo)
        return out.reshape(limbs.shape[:-1])
    flat = limbs.reshape(-1, K_LIMBS).astype(np.int64)
    # signed carry-normalization: digits in [0, R), top carry signed
    d = flat.copy()
    for k in range(K_LIMBS - 1, 0, -1):
        c = d[:, k] >> LIMB_BITS          # floor division (sign-safe)
        d[:, k] -= c << LIMB_BITS
        d[:, k - 1] += c
    top = d[:, 0] >> LIMB_BITS
    d0 = d[:, 0] - (top << LIMB_BITS)
    # three exact, non-overlapping f64 components (each < 2^53):
    #   P0 = top·2^36 + d0·2^18 + d1   scaled 2^(E-108+72)
    #   P1 = d2·2^18 + d3              scaled 2^(E-108+36)
    #   P2 = d4·2^18 + d5              scaled 2^(E-108)
    p0_i = (top * _RADIX + d0) * _RADIX + d[:, 1]
    p0 = p0_i.astype(np.float64)
    p1 = (d[:, 2] * _RADIX + d[:, 3]).astype(np.float64)
    p2 = (d[:, 4] * _RADIX + d[:, 5]).astype(np.float64)
    t0 = p0 * (scale_lo * float(1 << 72))
    t1 = p1 * (scale_lo * float(1 << 36))
    t2 = p2 * scale_lo
    # TwoSum cascade: r = fl(t0+t1+t2) with tracked errors. Full Knuth
    # TwoSum (magnitude-order-free — negative totals cancel t0 against
    # t1/t2, so the Fast2Sum precondition does not hold)
    def two_sum(a, b):
        s = a + b
        bv = s - a
        return s, (a - (s - bv)) + (b - bv)

    r1, e1 = two_sum(t0, t1)             # exact error terms
    r2, e2 = two_sum(r1, t2)
    err, ee = two_sum(e1, e2)
    out = r2 + err
    # hazard detection — re-do any cell the fast path can't PROVE
    # correctly rounded:
    #   * |top| ≥ 2^17 ⇒ p0_i may exceed 2^53 (inexact f64 conversion)
    #     or even wrap int64 — checked on `top` BEFORE packing so an
    #     int64 wraparound can't hide under the threshold
    #   * e1+e2 itself rounded (ee ≠ 0) — then r2+err ≠ exact total and
    #     the final rounding may land wrong.
    # With ee == 0, r2 + err IS the exact total, so out = fl(total) is
    # correctly rounded by construction.
    sus = np.nonzero((np.abs(top) >= (1 << 17)) | (ee != 0.0))[0]
    for i in sus.tolist():
        out[i] = _bigint_cell(flat, i, scale_lo)
    return out.reshape(limbs.shape[:-1])


def finalize_exact_traced(limb_planes: list, scale_lo):
    """Traceable (jnp) twin of finalize_exact's vectorized fast path —
    the device half of the finalize epilogue (ops/blockagg.py
    ``_finalize_kernel``). ``limb_planes`` is a list of K_LIMBS int64
    (S,) arrays (dead planes as zeros); ``scale_lo`` is 2^(E −
    SPAN_BITS) as an f64 scalar — passed as a TRACED operand so one
    compiled kernel serves every limb scale (all the scale products
    below are power-of-two multiplies: exact whether constant-folded
    or computed on device). Returns ``(out, hazard)``:

    - ``out`` is the SAME IEEE f64 sequence as the host fast path
      (carry-normalize → three exact components → full-Knuth TwoSum
      cascade), so on a real-f64 backend every non-hazard cell is
      bit-identical to finalize_exact by construction;
    - ``hazard`` mirrors the host's suspicion test (|top| ≥ 2^17 or a
      rounded error track) — flagged cells must be repaired on HOST
      (the big-int backstop); the caller pulls their limb rows
      sparsely. On f32-pair-emulated-f64 backends the fast path itself
      drifts, which is why the epilogue stays host-gated there (see
      blockagg.device_finalize_on)."""
    import jax.numpy as jnp
    R = _RADIX
    d = [p.astype(jnp.int64) for p in limb_planes]
    for k in range(K_LIMBS - 1, 0, -1):
        c = d[k] >> LIMB_BITS              # arithmetic shift = floor
        d[k] = d[k] - (c << LIMB_BITS)
        d[k - 1] = d[k - 1] + c
    top = d[0] >> LIMB_BITS
    d0 = d[0] - (top << LIMB_BITS)
    # hazard on `top` BEFORE packing, exactly as the host path: an
    # int64 wraparound in p0 can't hide under the threshold
    p0 = ((top * R + d0) * R + d[1]).astype(jnp.float64)
    p1 = (d[2] * R + d[3]).astype(jnp.float64)
    p2 = (d[4] * R + d[5]).astype(jnp.float64)
    t0 = p0 * (scale_lo * float(1 << 72))
    t1 = p1 * (scale_lo * float(1 << 36))
    t2 = p2 * scale_lo

    def two_sum(a, b):
        s = a + b
        bv = s - a
        return s, (a - (s - bv)) + (b - bv)

    r1, e1 = two_sum(t0, t1)
    r2, e2 = two_sum(r1, t2)
    err, ee = two_sum(e1, e2)
    out = r2 + err
    hazard = (jnp.abs(top) >= (1 << 17)) | (ee != 0.0)
    return out, hazard


def _bigint_cell(flat: np.ndarray, i: int, scale_lo: float) -> float:
    """Exact big-int evaluation of one cell's limb row — the shared
    hazard backstop for the native and numpy finalize paths (Python
    ints are arbitrary precision; float() is correctly rounded)."""
    total = int(flat[i, 0])
    for k in range(1, K_LIMBS):
        total = total * _RADIX + int(flat[i, k])
    return float(total) * scale_lo
