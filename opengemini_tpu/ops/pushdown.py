"""Packed-space predicate pushdown (round 18) — filter BEFORE decode.

PR 13 put compressed DFOR bytes on the device and PR 17 fused the
whole lattice plan, but WHERE residuals still evaluated on fully
EXPANDED planes: every segment paid bit-unpack + inverse-transform
even when 99% of its rows were about to be filtered out. This module
is the planner + translation layer that moves the filter into packed
space ("GPU Acceleration of SQL Analytics on Compressed Data",
PAPERS.md):

* ``plan_residual`` classifies a WHERE residual as packed-translatable
  — an AND of ``field op numeric-literal`` comparisons on ONE field —
  and normalizes it into a :class:`PackedPredicate`.
* ``translate`` turns each conjunct into an EXACT integer-space
  constraint on the un-zigzagged DFOR residual ``k`` (``v op c`` ⇔
  ``k op' K``): for zigzag-delta ints the stored f64 is the integer
  ``k`` bit-for-bit, so a Fraction-exact floor/ceil of the literal is
  the whole translation; for decimal-scaled ints the stored value is
  ``fl(k / 10^d)`` — the threshold search walks the few candidate
  ``k`` around the rational boundary with REAL np.float64 arithmetic,
  so the integer compare reproduces the rounded float compare
  bit-for-bit. Equality on decimal-scaled ints becomes a single
  packed ``k == K`` that never decodes.
* ``classify`` evaluates the predicate against a segment's
  frame-of-reference envelope ``[ref - 2^(w-1), ref + 2^(w-1) - 1]``
  (Python bignums — int64 wrap disables the skip, never the row
  compare): segments wholly outside skip ALL per-row work (they are
  dropped before the slab even batches), segments wholly inside pay
  no mask.
* Non-translatable transforms (prefix-XOR floats) fall back to
  expand-then-filter: the SAME f64 compare numpy would run, traced —
  byte-identical by construction (mode "f64").

The masks land on the slab VALID plane before limb decomposition, so
every downstream route (staged lattice, fused whole-plan, min/max
mask kernel, count) late-materializes only surviving lanes without
knowing pushdown exists. ``OG_PACKED_PREDICATE=0`` keeps the classic
expand-then-residual path — byte-identical escape hatch. Mask
launches ride breaker route ``block`` at the ``device.pushdown.eval``
failpoint and heal per batch to host expand-then-filter
(ops/blockagg._heal_mask) under the PR 9 ladder.
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..encoding import dfor as _dfor
from ..utils import knobs

_CMP_OPS = ("<", "<=", ">", ">=", "=", "!=")

# literal-first leaves normalize field-first (mirrors
# query/condition._walk_and's flip map)
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
         "=": "=", "!=": "!="}


def packed_predicate_on() -> bool:
    """OG_PACKED_PREDICATE gate, read per query (perf_smoke diffs the
    packed and expand-then-filter routes digest-for-digest)."""
    return bool(knobs.get("OG_PACKED_PREDICATE"))


class PackedPredicate:
    """Normalized AND-of-comparisons on one field.

    ``conjs`` is a tuple of ``(op, c)`` with ``op`` field-first in
    ``_CMP_OPS`` and ``c`` a python float (the np.float64 the numpy
    residual compare would coerce the literal to — int literals ride
    NEP-50 weak promotion to f64, so this IS the compared value).
    ``key`` is the full value identity (cache key for pred-masked
    slabs); ``sig`` is the threshold-free ops signature (compile
    class — thresholds ride as traced operands)."""

    __slots__ = ("field", "conjs")

    def __init__(self, field: str, conjs: tuple):
        self.field = field
        self.conjs = conjs

    @property
    def key(self) -> tuple:
        return (self.field, self.conjs)

    @property
    def sig(self) -> tuple:
        return tuple(op for op, _c in self.conjs)

    def __repr__(self):
        body = " and ".join(f"{self.field} {op} {c!r}"
                            for op, c in self.conjs)
        return f"PackedPredicate({body})"


def plan_residual(residual, tag_keys=()) -> PackedPredicate | None:
    """Classify a residual AST as packed-translatable → normalized
    PackedPredicate, or None (stays on the post-expand path). Only
    AND-trees of ``field op numeric-literal`` over ONE non-tag field
    qualify; regex/string ops, OR trees, arithmetic and multi-field
    residuals all stay behind."""
    from ..query.ast import BinaryExpr, FieldRef, Literal
    if residual is None:
        return None
    leaves: list = []

    def walk(e) -> bool:
        if isinstance(e, BinaryExpr) and e.op == "and":
            return walk(e.lhs) and walk(e.rhs)
        if not isinstance(e, BinaryExpr) or e.op not in _CMP_OPS:
            return False
        lhs, rhs, op = e.lhs, e.rhs, e.op
        if isinstance(lhs, Literal) and isinstance(rhs, FieldRef):
            lhs, rhs, op = rhs, lhs, _FLIP[op]
        if not (isinstance(lhs, FieldRef) and isinstance(rhs, Literal)):
            return False
        v = rhs.value
        # bool is an int subclass — numpy compares it as 0/1 but the
        # intent is almost surely a typo'd tag filter; stay safe
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return False
        leaves.append((lhs.name, op, float(np.float64(v))))
        return True

    if not walk(residual) or not leaves:
        return None
    fields = {f for f, _o, _c in leaves}
    if len(fields) != 1:
        return None
    field = next(iter(fields))
    if field == "time" or field in set(tag_keys):
        return None
    return PackedPredicate(field,
                           tuple((op, c) for _f, op, c in leaves))


# ------------------------------------------------ exact translation
#
# Integer-space constraint forms on the decoded integer k:
#   ("ge", K) ("le", K) ("eq", K) ("ne", K) ("true",) ("false",)

def _int_constraint(op: str, c: float) -> tuple:
    """T_INT: stored value v == f64(k) EXACTLY (slabs stack FLOAT
    columns only, so k came FROM an f64 — conversion is lossless at
    any magnitude). Both sides of the numpy compare are exact reals
    → Fraction floor/ceil of the literal is the exact translation."""
    if np.isnan(c):
        return ("true",) if op == "!=" else ("false",)
    if np.isinf(c):
        pos = c > 0
        if op in ("<", "<="):
            return ("true",) if pos else ("false",)
        if op in (">", ">="):
            return ("false",) if pos else ("true",)
        return ("true",) if op == "!=" else ("false",)
    f = Fraction(c)
    integral = f.denominator == 1
    if op == "<":
        return ("le", (f.numerator - 1) if integral else _ffloor(f))
    if op == "<=":
        return ("le", _ffloor(f))
    if op == ">":
        return ("ge", (f.numerator + 1) if integral else _fceil(f))
    if op == ">=":
        return ("ge", _fceil(f))
    if op == "=":
        return ("eq", f.numerator) if integral else ("false",)
    return ("ne", f.numerator) if integral else ("true",)


def _ffloor(f: Fraction) -> int:
    return f.numerator // f.denominator


def _fceil(f: Fraction) -> int:
    return -((-f.numerator) // f.denominator)


def _scaled_constraint(op: str, c: float, ds: int) -> tuple:
    """T_SCALED: stored value v == fl(k / 10^ds) — the f64 DIVISION
    ROUNDS, so the exact rational boundary can sit one k off the
    float-compare boundary. Start from the Fraction boundary and walk
    ±2 candidates with the same np.float64 divide the decoder runs
    (monotone in k), landing on thresholds that reproduce the rounded
    compare bit-for-bit. |k| < 2^51 (encoding/dfor._try_scaled), so
    f64(k) is exact and fl is strictly monotone over distinct k."""
    if np.isnan(c) or np.isinf(c):
        return _int_constraint(op, c)      # same whole-line semantics
    S = 10 ** ds
    Sf = np.float64(10.0 ** ds)

    def val(k: int) -> np.float64:
        return np.float64(k) / Sf

    f = Fraction(c) * S
    if op in ("<", "<="):
        # K = max{k : fl(k/S) op c} — rounding shifts the boundary by
        # at most one k (0.5 ulp < half a k-unit at |k| < 2^51), the
        # ±4 window is pure paranoia; an unexpectedly empty window
        # falls back to the f64 row compare (None)
        ok = (lambda x: x < c) if op == "<" else (lambda x: x <= c)
        for k in range(_ffloor(f) + 4, _ffloor(f) - 5, -1):
            if ok(val(k)):
                return ("le", k)
        return None
    if op in (">", ">="):
        ok = (lambda x: x > c) if op == ">" else (lambda x: x >= c)
        for k in range(_fceil(f) - 4, _fceil(f) + 5):
            if ok(val(k)):
                return ("ge", k)
        return None
    # =, != : distinct k give distinct floats (spacing 10^-ds beats
    # ulp at |k| < 2^51), so at most one k matches
    k0 = _ffloor(f)
    hit = [k for k in range(k0 - 2, k0 + 3) if val(k) == c]
    if op == "=":
        return ("eq", hit[0]) if hit else ("false",)
    return ("ne", hit[0]) if hit else ("true",)


def translate(pred: PackedPredicate, transform: int,
              dscale: int) -> list | None:
    """Integer-space constraint list for one (transform, dscale)
    class, or None when the transform is not packed-translatable
    (zigzag is monotone-decodable; the XOR transforms are not).
    ``("false",)`` anywhere means the whole class is empty."""
    if transform not in (_dfor.T_INT, _dfor.T_SCALED):
        return None
    out = []
    for op, c in pred.conjs:
        if transform == _dfor.T_INT:
            con = _int_constraint(op, c)
        else:
            con = _scaled_constraint(op, c, dscale)
        if con is None:
            return None
        if con[0] == "false":
            return [("false",)]
        if con[0] != "true":
            out.append(con)
    return out


_I64_LO, _I64_HI = -(1 << 63), (1 << 63) - 1


def clamp_constraints(cons: list) -> list | None:
    """Saturate thresholds into int64 (device compare operands).
    Returns None when saturation makes the class empty ("none")."""
    out = []
    for con in cons:
        if con[0] == "false":
            return None
        kind, K = con
        if kind == "ge":
            if K > _I64_HI:
                return None
            out.append(("ge", max(K, _I64_LO)))
        elif kind == "le":
            if K < _I64_LO:
                return None
            out.append(("le", min(K, _I64_HI)))
        elif kind == "eq":
            if not (_I64_LO <= K <= _I64_HI):
                return None
            out.append(con)
        else:                                   # ne
            if _I64_LO <= K <= _I64_HI:
                out.append(con)
    return out


# -------------------------------------------- envelope classification

def envelope_k(w: int, ref: int) -> tuple | None:
    """Exact k-interval [klo, khi] of a DFOR int-space segment from
    its header (Python bignums), or None when the un-zigzagged delta
    can wrap int64 (the interval would be a torus arc — the per-row
    compare stays exact, only the SKIP is disabled)."""
    if w >= 64:
        return None
    ref_i = ref - (1 << 64) if ref >= (1 << 63) else ref
    if w == 0:
        return (ref_i, ref_i)
    half = 1 << (w - 1)
    klo, khi = ref_i - half, ref_i + half - 1
    if klo < _I64_LO or khi > _I64_HI:
        return None
    return (klo, khi)


def classify_interval(cons: list, klo: int, khi: int) -> str:
    """\"all\" | \"none\" | \"partial\" of the AND of int-space
    constraints over k ∈ [klo, khi]."""
    if cons and cons[0][0] == "false":
        return "none"
    all_ok = True
    for kind, K in cons:
        if kind == "ge":
            if khi < K:
                return "none"
            if klo < K:
                all_ok = False
        elif kind == "le":
            if klo > K:
                return "none"
            if khi > K:
                all_ok = False
        elif kind == "eq":
            if K < klo or K > khi:
                return "none"
            if klo != khi:
                all_ok = False
        else:                                   # ne
            if klo == khi == K:
                return "none"
            if klo <= K <= khi:
                all_ok = False
    return "all" if all_ok else "partial"


def classify_dfor(pred: PackedPredicate, transform: int, w: int,
                  ds: int, ref: int) -> str:
    """Per-segment envelope decision from the DFOR header alone:
    \"none\" → the segment is DROPPED before any device work;
    \"all\" → no mask needed; \"partial\" → packed row mask;
    \"fallback\" → post-expand f64 row mask (XOR transforms, or an
    envelope the int space can't bound)."""
    cons = translate(pred, transform, ds)
    if cons is None:
        return "fallback"
    if cons and cons[0][0] == "false":
        return "none"
    env = envelope_k(w, ref)
    if env is None:
        return "partial"
    return classify_interval(cons, env[0], env[1])


def eval_numpy(pred: PackedPredicate, values: np.ndarray) -> np.ndarray:
    """Host mask over raw f64 values — EXACTLY the compares
    query/condition.eval_residual would run leaf-by-leaf (the caller
    ANDs validity, same as the leaf's ``& valid``). This is the
    ground truth every device mask is pinned against, and the heal
    target when the pushdown launch faults."""
    m = np.ones(values.shape, dtype=bool)
    with np.errstate(invalid="ignore"):
        for op, c in pred.conjs:
            if op == "<":
                m &= values < c
            elif op == "<=":
                m &= values <= c
            elif op == ">":
                m &= values > c
            elif op == ">=":
                m &= values >= c
            elif op == "=":
                m &= values == c
            else:
                m &= values != c
    return m


def classify_const(pred: PackedPredicate, val: float) -> str:
    """CONST segments carry one value — the envelope IS the value
    (numpy f64 compare semantics, NaN-aware)."""
    return "all" if bool(eval_numpy(pred, np.array([val]))[0]) \
        else "none"


def classify_runs(pred: PackedPredicate, run_vals: np.ndarray) -> str:
    """RLE segments: the run values are the (tiny) host-parsed
    payload — evaluate them directly (exact, NaN-aware; no envelope
    approximation needed)."""
    m = eval_numpy(pred, run_vals)
    if m.all():
        return "all"
    if not m.any():
        return "none"
    return "partial"


# ---------------------------------------------- device mask recipes

def batch_mask_plan(pred: PackedPredicate, transform: int, w: int,
                    ds: int, classes: list):
    """Mask plan for ONE same-(w, transform, ds) expand batch whose
    per-block classes are ``classes`` (never \"none\" — those blocks
    were dropped before batching). Returns None (all \"all\": no mask
    work at all) or (mode, sig, thr_host):

    * ("int", sig, (m,) i64) — packed compare on the un-zigzagged k
      inside the SAME launch that expands values (never decodes when
      the values themselves aren't wanted).
    * ("f64", sig, (m,) f64) — post-expand compare on the decoded
      plane, bit-identical to the escape hatch by construction.

    Thresholds are TRACED operands — one compiled class per ops
    signature serves every literal (query/plancache.intern_pred_class
    names the class for the compile auditor)."""
    if all(cl == "all" for cl in classes):
        return None
    cons = translate(pred, transform, ds)
    if cons is not None and "fallback" not in classes:
        cons = clamp_constraints(cons)
        if cons is not None:
            sig = tuple(kind for kind, _K in cons)
            thr = np.array([K for _kind, K in cons], dtype=np.int64)
            if not sig:                # all-true after clamping
                return None
            return ("int", sig, thr)
    sig = pred.sig
    thr = np.array([c for _op, c in pred.conjs], dtype=np.float64)
    return ("f64", sig, thr)


def mask_from_k_stage(k, thr, *, sig: tuple):
    """Traced packed-space mask: AND of int64 compares of the decoded
    integer k against traced thresholds. Pure trace-composable stage
    (round-17 discipline) — ops/device_decode fuses it into the
    expand launch."""
    m = None
    for j, kind in enumerate(sig):
        t = thr[j]
        if kind == "ge":
            c = k >= t
        elif kind == "le":
            c = k <= t
        elif kind == "eq":
            c = k == t
        else:
            c = k != t
        m = c if m is None else (m & c)
    return m


def mask_from_values_stage(v, thr, *, sig: tuple):
    """Traced post-expand mask: the SAME f64 compares numpy's
    eval_residual runs, over the decoded plane (XOR-transform
    fallback; NaN compares false, != true — IEEE == numpy == jnp)."""
    m = None
    for j, op in enumerate(sig):
        t = thr[j]
        if op == "<":
            c = v < t
        elif op == "<=":
            c = v <= t
        elif op == ">":
            c = v > t
        elif op == ">=":
            c = v >= t
        elif op == "=":
            c = v == t
        else:
            c = v != t
        m = c if m is None else (m & c)
    return m
