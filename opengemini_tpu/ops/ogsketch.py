"""OGSketch — mergeable quantile sketch for approximate percentiles.

Role of the reference's `engine/executor/ogsketch.go` (NewOGSketchImpl :125,
processInsert :270, Percentile :188, Rank :213, delete path :323-430,
EquiHeightHistogram :446, DemarcationHistogram :490): a t-digest-style
centroid sketch on an arcsin scale function, supporting batch insert,
sketch merge (the distributed partial-agg combine), decremental delete
(sliding windows), interpolated percentile/rank, and the two histogram
modes the SQL surface exposes.

Design differences from the reference (which is pointer/sort.Sort based):
centroids live in flat numpy arrays; inserts buffer in a list and compress
via one vectorized sort + a bounded greedy merge pass (the merge loop is
inherently sequential — the q-limit advances at cluster boundaries — but
runs over at most sketch_size + buffer_size ≈ 10·c centroids, so it is
O(c) per compression and amortized O(1) per point).

The sketch is the partial state for `percentile_approx(field, p[, c])`:
store nodes build per-(group, window) sketches (ogsketch_insert), the sql
node merges them (ogsketch_merge) and finalizes with Percentile
(ogsketch_percentile) — the three-phase split named in the reference's
call_processor.go:37-41.
"""

from __future__ import annotations

import math

import numpy as np

DEFAULT_CLUSTERS = 100.0


class OGSketch:
    """Arcsin-scale centroid sketch. `clusters` bounds the compressed
    sketch size (larger → more accurate, linearly more state)."""

    __slots__ = ("c", "sketch_size", "buffer_size", "means", "weights",
                 "all_weight", "delete_weight", "min_value", "max_value",
                 "_buf_m", "_buf_w", "_acc", "_del")

    def __init__(self, clusters: float = DEFAULT_CLUSTERS):
        self.c = max(float(clusters), 1.0)
        self.sketch_size = int(2 * math.ceil(self.c))
        self.buffer_size = int(8 * math.ceil(self.c))
        self.means = np.empty(0, dtype=np.float64)
        self.weights = np.empty(0, dtype=np.float64)
        self.all_weight = 0.0
        self.delete_weight = 0.0
        self.min_value = math.inf
        self.max_value = -math.inf
        self._buf_m: list = []
        self._buf_w: list = []
        self._acc: np.ndarray | None = None
        self._del: dict[float, float] = {}

    # ------------------------------------------------------------ insert

    def insert(self, values, weights=None) -> None:
        """Batch insert points (weights default 1). NaN values and
        non-positive/NaN/inf weights are dropped, as in the reference."""
        v = np.atleast_1d(np.asarray(values, dtype=np.float64))
        if weights is None:
            w = np.ones_like(v)
        else:
            w = np.broadcast_to(
                np.asarray(weights, dtype=np.float64), v.shape)
        keep = ~np.isnan(v) & (w > 0) & np.isfinite(w)
        if not keep.all():
            v, w = v[keep], w[keep]
        if v.size == 0:
            return
        self.all_weight += float(w.sum())
        self._buf_m.append(v)
        self._buf_w.append(w)
        if sum(b.size for b in self._buf_m) > self.buffer_size:
            self._compress()

    # ---------------------------------------------------------- compress

    def _ruler(self, q: float) -> float:
        return self.c * (math.asin(2.0 * q - 1.0) + math.pi / 2.0) / math.pi

    def _reverse_ruler(self, k: float) -> float:
        return (math.sin(min(k, self.c) * math.pi / self.c - math.pi / 2.0)
                + 1.0) / 2.0

    def _compress(self) -> None:
        if not self._buf_m and len(self.means) <= self.sketch_size:
            return
        m = np.concatenate([self.means] + self._buf_m)
        w = np.concatenate([self.weights] + self._buf_w)
        self._buf_m, self._buf_w = [], []
        order = np.argsort(m, kind="stable")
        m, w = m[order], w[order]
        if m.size == 0:
            return
        self.min_value = min(self.min_value, float(m[0]))
        self.max_value = max(self.max_value, float(m[-1]))
        if m.size < self.sketch_size:
            self.means, self.weights = m, w
            self._acc = None
            return
        # greedy scale-bounded merge (reference processInsert step2)
        out_m = np.empty(m.size, dtype=np.float64)
        out_w = np.empty(m.size, dtype=np.float64)
        n_out = 0
        total = self.all_weight
        q0 = 0.0
        qlimit = self._reverse_ruler(self._ruler(q0) + 1.0)
        cur_m, cur_w = float(m[0]), float(w[0])
        for i in range(1, m.size):
            q = q0 + (cur_w + w[i]) / total
            if q <= qlimit:
                cur_m = (cur_m * cur_w + m[i] * w[i]) / (cur_w + w[i])
                cur_w += w[i]
            else:
                out_m[n_out], out_w[n_out] = cur_m, cur_w
                n_out += 1
                q0 += cur_w / total
                qlimit = self._reverse_ruler(self._ruler(q0) + 1.0)
                cur_m, cur_w = float(m[i]), float(w[i])
        out_m[n_out], out_w[n_out] = cur_m, cur_w
        n_out += 1
        self.means = out_m[:n_out].copy()
        self.weights = out_w[:n_out].copy()
        self._acc = None

    def _settle(self) -> None:
        self._compress()
        self._process_delete()
        if self._acc is None and len(self.means):
            # accumulative half-weight midpoints (updateAccumulativeSum)
            w = self.weights
            acc = np.empty(len(w), dtype=np.float64)
            acc[0] = w[0] / 2
            if len(w) > 1:
                acc[1:] = (w[1:] + w[:-1]) / 2
                np.cumsum(acc, out=acc)
            self._acc = acc

    # ------------------------------------------------------------ delete

    def delete(self, values, weights=None) -> None:
        """Decremental delete (sliding-window support): deletions buffer
        and are applied by carving weight out of the nearest centroids."""
        v = np.atleast_1d(np.asarray(values, dtype=np.float64))
        if weights is None:
            w = np.ones_like(v)
        else:
            w = np.broadcast_to(
                np.asarray(weights, dtype=np.float64), v.shape)
        for m, ww in zip(v, w):
            if np.isnan(m) or ww <= 0:
                continue
            self._del[float(m)] = self._del.get(float(m), 0.0) + float(ww)
            self.delete_weight += float(ww)
        if self.delete_weight >= self.all_weight:
            self.reset()
            return
        if self.delete_weight > self.all_weight / 2:
            self._compress()
            self._process_delete()

    def _process_delete(self) -> None:
        if not self._del:
            return
        for key, val in self._del.items():
            if not len(self.means):
                break
            if key <= self.means[0]:
                self._delete_from(0, val, forward=True)
            elif key >= self.means[-1]:
                self._delete_from(len(self.means) - 1, val, forward=False)
            else:
                self._delete_between(key, val)
        self.all_weight = max(self.all_weight - self.delete_weight, 0.0)
        self.delete_weight = 0.0
        self._del = {}
        keep = self.weights > 0
        self.means, self.weights = self.means[keep], self.weights[keep]
        if len(self.means) == 0:
            self.reset()
        self._acc = None

    def _delete_from(self, loc: int, val: float, forward: bool) -> float:
        step = 1 if forward else -1
        while 0 <= loc < len(self.weights) and val > 0:
            if self.weights[loc] > val:
                self.weights[loc] -= val
                return 0.0
            val -= float(self.weights[loc])
            self.weights[loc] = 0.0
            loc += step
        return val

    def _delete_between(self, key: float, val: float) -> None:
        locr = int(np.searchsorted(self.means, key, side="left"))
        locl = locr - 1
        span = self.means[locr] - self.means[locl]
        wr = val * (key - self.means[locl]) / span
        wl = val * (self.means[locr] - key) / span
        wl = self._delete_from(locl, wl, forward=False)
        wr = self._delete_from(locr, wr, forward=True)
        if wl > 0:
            self._delete_from(locr, wl, forward=True)
        if wr > 0:
            self._delete_from(locl, wr, forward=False)

    # ------------------------------------------------------------- merge

    def merge(self, other: "OGSketch") -> None:
        other._settle()
        if other.all_weight <= 0:
            return
        self._buf_m.append(other.means.copy())
        self._buf_w.append(other.weights.copy())
        self.all_weight += other.all_weight
        self.min_value = min(self.min_value, other.min_value)
        self.max_value = max(self.max_value, other.max_value)
        self._compress()

    # ----------------------------------------------------------- queries

    def __len__(self) -> int:
        return len(self.means) + sum(b.size for b in self._buf_m)

    def percentile(self, q: float) -> float:
        """Interpolated quantile, q in [0, 1] (reference Percentile :188):
        linear between min_value, centroid midpoints, and max_value."""
        self._settle()
        n = len(self.means)
        if n == 0 or q < 0 or q > 1 or self.all_weight <= 0:
            return math.nan
        rank = q * self.all_weight
        first_half = self.weights[0] / 2
        last_half = self.weights[-1] / 2
        if rank < first_half:
            return self.min_value + rank / first_half * (
                self.means[0] - self.min_value)
        if rank >= self.all_weight - last_half:
            return self.max_value - (self.all_weight - rank) / last_half * (
                self.max_value - self.means[-1])
        idx = int(np.searchsorted(self._acc, rank, side="right"))
        idx = min(max(idx, 1), n - 1)
        return float(self.means[idx - 1]
                     + 2 * (rank - self._acc[idx - 1])
                     / (self.weights[idx - 1] + self.weights[idx])
                     * (self.means[idx] - self.means[idx - 1]))

    def rank(self, x: float) -> int:
        """Approximate count of points ≤ x (reference Rank :213)."""
        self._settle()
        n = len(self.means)
        if n == 0:
            return 0
        if x >= self.max_value:
            return int(self.all_weight)
        if x <= self.min_value:
            return 0
        first_half = self.weights[0] / 2
        last_half = self.weights[-1] / 2
        if x < self.means[0]:
            return int(first_half * (x - self.min_value)
                       / (self.means[0] - self.min_value))
        if x >= self.means[-1]:
            return int(self.all_weight - (self.max_value - x)
                       / (self.max_value - self.means[-1]) * last_half)
        idx = int(np.searchsorted(self.means, x, side="right"))
        return int(self._acc[idx]
                   - (self.means[idx] - x)
                   / (self.means[idx] - self.means[idx - 1])
                   * (self.weights[idx] + self.weights[idx - 1]) / 2)

    def equi_height_histogram(self, bins: int, begin: float,
                              end: float) -> np.ndarray:
        """bins+1 quantile boundaries splitting [begin, end] into bins of
        equal weight (reference EquiHeightHistogram :446)."""
        self._settle()
        if self.all_weight <= 0:
            return np.full(bins + 1, math.nan)
        p = self.rank(begin) / self.all_weight
        step = (self.rank(end) - self.rank(begin)) / (
            self.all_weight * bins)
        return np.array([self.percentile(p + i * step)
                         for i in range(bins + 1)])

    def demarcation_histogram(self, begin: float, width: float,
                              bins: int, bins_type: int = 0) -> np.ndarray:
        """Per-bin counts over linear (bins_type 0) or exponential (1)
        boundaries, with under/overflow bins at the ends (reference
        DemarcationHistogram :490)."""
        edges = [begin]
        b, base = begin, width
        for _ in range(bins):
            if bins_type == 0:
                b += width
            else:
                b += base
                base *= width
            edges.append(b)
        ranks = [self.rank(e) for e in edges]
        counts = [ranks[0]]
        counts += [ranks[i] - ranks[i - 1] for i in range(1, len(ranks))]
        counts.append(int(self.all_weight) - ranks[-1])
        return np.array(counts, dtype=np.int64)

    # ------------------------------------------------------------- state

    def reset(self) -> None:
        self.means = np.empty(0, dtype=np.float64)
        self.weights = np.empty(0, dtype=np.float64)
        self._buf_m, self._buf_w = [], []
        self.all_weight = 0.0
        self.delete_weight = 0.0
        self.min_value = math.inf
        self.max_value = -math.inf
        self._acc = None
        self._del = {}

    def to_state(self) -> dict:
        """Serializable partial-agg state (ships store → sql)."""
        self._settle()
        return {"c": self.c, "means": self.means.tolist(),
                "weights": self.weights.tolist(),
                "all_weight": self.all_weight,
                "min": self.min_value, "max": self.max_value}

    @classmethod
    def from_state(cls, st: dict) -> "OGSketch":
        s = cls(st["c"])
        s.means = np.asarray(st["means"], dtype=np.float64)
        s.weights = np.asarray(st["weights"], dtype=np.float64)
        s.all_weight = float(st["all_weight"])
        s.min_value = float(st["min"])
        s.max_value = float(st["max"])
        return s

    @classmethod
    def of(cls, values, clusters: float = DEFAULT_CLUSTERS) -> "OGSketch":
        s = cls(clusters)
        s.insert(values)
        return s


def batch_percentile(states: list, q: float) -> np.ndarray:
    """Vectorized `OGSketch.from_state(st).percentile(q)` over a flat
    list of state dicts (None entries → NaN). One padded (N, L) pass
    replaces N per-cell object constructions + settles — the
    ogsketch_percentile finalize at high cardinality (G·W cells) was a
    literal per-cell Python loop. Bit-identical to the scalar path:
    the accumulative-midpoint cumsum runs in the same order per lane,
    and every interpolation formula is applied elementwise with the
    same operand order. Cells whose serialized sketch would trigger a
    re-compression in _settle (means longer than sketch_size — not
    produced by to_state, but tolerated) fall back to the scalar
    object path."""
    N = len(states)
    out = np.full(N, np.nan)
    live: list[int] = []
    for i, st in enumerate(states):
        if st is None:
            continue
        n_m = len(st["means"])
        if n_m == 0 or float(st["all_weight"]) <= 0:
            continue
        if n_m > int(2 * math.ceil(max(float(st["c"]), 1.0))):
            # would re-compress in _settle: keep scalar semantics
            out[i] = OGSketch.from_state(st).percentile(q)
            continue
        live.append(i)
    if not live or q < 0 or q > 1:
        return out
    L = max(len(states[i]["means"]) for i in live)
    n_live = len(live)
    m = np.zeros((n_live, L))
    w = np.zeros((n_live, L))
    n_arr = np.empty(n_live, dtype=np.int64)
    aw = np.empty(n_live)
    mn = np.empty(n_live)
    mx = np.empty(n_live)
    for j, i in enumerate(live):
        st = states[i]
        k = len(st["means"])
        n_arr[j] = k
        m[j, :k] = st["means"]
        w[j, :k] = st["weights"]
        aw[j] = float(st["all_weight"])
        mn[j] = float(st["min"])
        mx[j] = float(st["max"])
    last = n_arr - 1
    cols = np.arange(L)[None, :]
    # accumulative half-weight midpoints (same add order as _settle)
    acc = np.empty_like(w)
    acc[:, 0] = w[:, 0] / 2
    if L > 1:
        acc[:, 1:] = (w[:, 1:] + w[:, :-1]) / 2
        np.cumsum(acc, axis=1, out=acc)
    rank = q * aw
    m0 = m[:, 0]
    w0h = w[:, 0] / 2
    mlast = np.take_along_axis(m, last[:, None], axis=1)[:, 0]
    wlasth = np.take_along_axis(w, last[:, None], axis=1)[:, 0] / 2
    with np.errstate(divide="ignore", invalid="ignore"):
        low = mn + rank / w0h * (m0 - mn)
        high = mx - (aw - rank) / wlasth * (mx - mlast)
        # searchsorted(acc[:n], rank, side="right") per lane: count of
        # acc entries <= rank among the first n (acc is nondecreasing)
        idx = ((acc <= rank[:, None]) & (cols < n_arr[:, None])).sum(
            axis=1)
        idx = np.minimum(np.maximum(idx, 1), np.maximum(last, 1))
        ilo = np.minimum(idx - 1, last)[:, None]
        ihi = np.minimum(idx, last)[:, None]
        m_lo = np.take_along_axis(m, ilo, axis=1)[:, 0]
        m_hi = np.take_along_axis(m, ihi, axis=1)[:, 0]
        w_lo = np.take_along_axis(w, ilo, axis=1)[:, 0]
        w_hi = np.take_along_axis(w, ihi, axis=1)[:, 0]
        a_lo = np.take_along_axis(acc, ilo, axis=1)[:, 0]
        mid = m_lo + 2 * (rank - a_lo) / (w_lo + w_hi) * (m_hi - m_lo)
        # single-centroid lanes: the scalar path's clamped index wraps
        # to the sole centroid and the slope term vanishes → exactly m0
        mid = np.where(last == 0, m0, mid)
        vals = np.where(rank < w0h, low,
                        np.where(rank >= aw - wlasth, high, mid))
    out[np.asarray(live, dtype=np.int64)] = vals
    return out


def batch_of_states(sv: np.ndarray, starts: np.ndarray,
                    lens: np.ndarray,
                    clusters: float) -> list[dict]:
    """``OGSketch.of(cell_values).to_state()`` over many cells at
    once, given one NaN-free value stream sorted by (cell, value):
    cell i's values are ``sv[starts[i]:starts[i]+lens[i]]``.

    Bit-identical to the per-cell object path by construction: a cell
    whose count stays under ``sketch_size`` never runs the greedy
    merge — ``_compress`` stable-sorts the buffer (the identity on a
    pre-sorted stream, and equal values are interchangeable) and
    keeps it verbatim, so its state IS the sorted values with unit
    weights. Bigger cells fall back to the scalar object on the
    sorted slice, which ``_compress``'s own stable argsort makes
    order-equivalent to the row-order insert. Replaces the
    G·W-object construction loop that dominated high-cardinality
    ``percentile_approx`` partials (one OGSketch + compress per cell
    at 11.5M cells)."""
    c_eff = max(float(clusters), 1.0)
    sk_size = int(2 * math.ceil(c_eff))
    out: list[dict] = []
    svl = sv.tolist()
    for st, ln in zip(starts.tolist(), lens.tolist()):
        if ln == 0:
            out.append({"c": c_eff, "means": [], "weights": [],
                        "all_weight": 0.0, "min": math.inf,
                        "max": -math.inf})
        elif ln < sk_size:
            vals = svl[st:st + ln]
            out.append({"c": c_eff, "means": vals,
                        "weights": [1.0] * ln,
                        "all_weight": float(ln),
                        "min": vals[0], "max": vals[-1]})
        else:
            out.append(OGSketch.of(sv[st:st + ln],
                                   clusters).to_state())
    return out
