from .schema import DataType, Field, Schema, TIME_FIELD
from .record import ColVal, Record, merge_sorted_records
