"""Schema / field types for the columnar record format.

Analog of the reference's ``lib/record`` field schema (record.Field /
record.Schemas, /root/reference/lib/record/record.go) and influx field type
constants. The canonical column ordering convention is preserved: field
columns sorted by name, with the ``time`` column LAST (the reference relies on
this invariant throughout the engine).

TPU-first deviations:
- numeric dtypes are explicit numpy dtypes so columns map 1:1 onto device
  arrays (int64/float64 natively; the TPU kernel layer may downcast to
  float32/bfloat16 per query precision mode).
- tags are dictionary-encoded to int32 ids on CPU before anything reaches the
  device; strings never go to TPU.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class DataType(enum.IntEnum):
    """Column data types (reference influx.Field_Type_* constants)."""

    UNKNOWN = 0
    INTEGER = 1   # int64
    FLOAT = 2     # float64
    BOOLEAN = 3
    STRING = 4
    TAG = 5       # dictionary-encoded string (tag key column)
    TIME = 6      # int64 nanoseconds since epoch

    @property
    def numpy_dtype(self) -> np.dtype | None:
        return _NUMPY_DTYPES.get(self)

    @property
    def is_numeric(self) -> bool:
        return self in (DataType.INTEGER, DataType.FLOAT, DataType.BOOLEAN,
                        DataType.TIME)


_NUMPY_DTYPES = {
    DataType.INTEGER: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float64),
    DataType.BOOLEAN: np.dtype(np.bool_),
    DataType.TIME: np.dtype(np.int64),
}

TIME_COL_NAME = "time"


@dataclass(frozen=True)
class Field:
    name: str
    type: DataType

    def __repr__(self) -> str:
        return f"Field({self.name}:{self.type.name})"


TIME_FIELD = Field(TIME_COL_NAME, DataType.TIME)


class Schema:
    """Ordered list of fields; time column last when present.

    Mirrors record.Schemas (/root/reference/lib/record/record.go): sorted
    field columns + trailing time column. Provides O(1) name lookup.
    """

    __slots__ = ("fields", "_index")

    def __init__(self, fields: list[Field]):
        self.fields = list(fields)
        self._index = {f.name: i for i, f in enumerate(self.fields)}
        if len(self._index) != len(self.fields):
            raise ValueError("duplicate field names in schema")

    @classmethod
    def from_pairs(cls, pairs: list[tuple[str, DataType]],
                   add_time: bool = True) -> "Schema":
        """Build a canonical schema: fields sorted by name, time last."""
        fields = sorted((Field(n, t) for n, t in pairs), key=lambda f: f.name)
        if add_time:
            fields.append(TIME_FIELD)
        return cls(fields)

    def field_index(self, name: str) -> int:
        return self._index.get(name, -1)

    def field(self, name: str) -> Field | None:
        i = self._index.get(name)
        return self.fields[i] if i is not None else None

    @property
    def has_time(self) -> bool:
        return bool(self.fields) and self.fields[-1].name == TIME_COL_NAME

    @property
    def time_index(self) -> int:
        return len(self.fields) - 1 if self.has_time else -1

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __repr__(self) -> str:
        return f"Schema({', '.join(f'{f.name}:{f.type.name}' for f in self.fields)})"
