"""Columnar Record / ColVal — the lingua franca of the whole framework.

Analog of the reference's record.Record / record.ColVal
(/root/reference/lib/record/record.go, /root/reference/lib/record/column.go):
a batch of rows for one measurement as per-column value buffers plus validity
bitmaps.

TPU-first design notes:
- Numeric columns are contiguous numpy arrays (int64/float64/bool) + a bool
  validity mask; these upload to device with zero copies beyond the DMA.
- String columns are arrow-style (offsets int32[n+1] + utf-8 byte buffer);
  they stay host-side. Tag columns are dictionary-encoded upstream.
- All mutation is append-at-end; records are sorted by time before flush
  (the reference keeps the same invariant: rows within a record sorted by
  timestamp; out-of-order data handled one level up by the merge cursors).
"""

from __future__ import annotations

import numpy as np

from .schema import DataType, Field, Schema, TIME_COL_NAME

__all__ = ["ColVal", "Record"]


class ColVal:
    """One column of values + validity.

    - numeric/bool/time: ``values`` numpy array of the schema dtype,
      ``valid`` bool array of the same length. Invalid slots hold a zero
      value (never NaN — aggregation kernels rely on masks, not NaN).
    - string/tag: ``offsets`` int32[n+1] + ``data`` bytes, plus ``valid``.
    """

    __slots__ = ("type", "values", "valid", "offsets", "data")

    def __init__(self, type_: DataType, values=None, valid=None,
                 offsets=None, data=b""):
        self.type = type_
        if type_.is_numeric:
            dt = type_.numpy_dtype
            self.values = (np.asarray(values, dtype=dt) if values is not None
                           else np.empty(0, dtype=dt))
            n = len(self.values)
            self.valid = (np.asarray(valid, dtype=np.bool_) if valid is not None
                          else np.ones(n, dtype=np.bool_))
            if len(self.valid) != n:
                raise ValueError("valid length mismatch")
            self.offsets = None
            self.data = b""
        else:
            self.offsets = (np.asarray(offsets, dtype=np.int32)
                            if offsets is not None
                            else np.zeros(1, dtype=np.int32))
            self.data = bytes(data)
            n = len(self.offsets) - 1
            self.valid = (np.asarray(valid, dtype=np.bool_) if valid is not None
                          else np.ones(n, dtype=np.bool_))
            if len(self.valid) != n:
                raise ValueError("valid length mismatch")
            self.values = None

    # ---- construction helpers -------------------------------------------

    @classmethod
    def nulls(cls, type_: DataType, n: int) -> "ColVal":
        """All-null column of length n (invalid slots hold zero, never NaN)."""
        if type_.is_numeric:
            return cls(type_, np.zeros(n, type_.numpy_dtype),
                       np.zeros(n, np.bool_))
        return cls(type_, valid=np.zeros(n, np.bool_),
                   offsets=np.zeros(n + 1, np.int32), data=b"")

    @classmethod
    def from_strings(cls, strs: list[str | None],
                     type_: DataType = DataType.STRING) -> "ColVal":
        offsets = np.zeros(len(strs) + 1, dtype=np.int32)
        valid = np.ones(len(strs), dtype=np.bool_)
        chunks = []
        pos = 0
        for i, s in enumerate(strs):
            if s is None:
                valid[i] = False
            else:
                b = s.encode("utf-8")
                chunks.append(b)
                pos += len(b)
            offsets[i + 1] = pos
        return cls(type_, valid=valid, offsets=offsets, data=b"".join(chunks))

    # ---- basic info ------------------------------------------------------

    def __len__(self) -> int:
        if self.values is not None:
            return len(self.values)
        return len(self.offsets) - 1

    @property
    def null_count(self) -> int:
        return int(len(self.valid) - np.count_nonzero(self.valid))

    def is_string_like(self) -> bool:
        return self.values is None

    # ---- accessors -------------------------------------------------------

    def get_string(self, i: int) -> str | None:
        if not self.valid[i]:
            return None
        return self.data[self.offsets[i]:self.offsets[i + 1]].decode("utf-8")

    def to_strings(self) -> list[str | None]:
        return [self.get_string(i) for i in range(len(self))]

    def get(self, i: int):
        if not self.valid[i]:
            return None
        if self.values is not None:
            v = self.values[i]
            if self.type == DataType.BOOLEAN:
                return bool(v)
            if self.type == DataType.FLOAT:
                return float(v)
            return int(v)
        return self.get_string(i)

    # ---- mutation --------------------------------------------------------

    def append(self, other: "ColVal") -> None:
        if other.type != self.type:
            raise ValueError(f"type mismatch: {self.type} vs {other.type}")
        if self.values is not None:
            self.values = np.concatenate([self.values, other.values])
            self.valid = np.concatenate([self.valid, other.valid])
        else:
            base = self.offsets[-1]
            self.offsets = np.concatenate(
                [self.offsets, other.offsets[1:] + base])
            self.data = self.data + other.data
            self.valid = np.concatenate([self.valid, other.valid])

    # ---- slicing / permutation ------------------------------------------

    def slice(self, start: int, stop: int) -> "ColVal":
        if self.values is not None:
            return ColVal(self.type, self.values[start:stop],
                          self.valid[start:stop])
        offs = self.offsets[start:stop + 1]
        lo, hi = int(offs[0]), int(offs[-1])
        return ColVal(self.type, valid=self.valid[start:stop],
                      offsets=offs - lo, data=self.data[lo:hi])

    def take(self, idx: np.ndarray) -> "ColVal":
        """Row gather (used for time-sorting and merge)."""
        if self.values is not None:
            return ColVal(self.type, self.values[idx], self.valid[idx])
        lens = (self.offsets[1:] - self.offsets[:-1])[idx]
        offsets = np.zeros(len(idx) + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        mv = memoryview(self.data)
        data = b"".join(
            mv[self.offsets[j]:self.offsets[j + 1]] for j in idx)
        return ColVal(self.type, valid=self.valid[idx], offsets=offsets,
                      data=data)

    def __eq__(self, other) -> bool:
        if not isinstance(other, ColVal) or other.type != self.type:
            return False
        if not np.array_equal(self.valid, other.valid):
            return False
        if self.values is not None:
            m = self.valid
            return np.array_equal(self.values[m], other.values[m])
        return (np.array_equal(self.offsets, other.offsets)
                and self.data == other.data)

    def __repr__(self) -> str:
        return f"ColVal({self.type.name}, n={len(self)}, nulls={self.null_count})"


class Record:
    """A columnar batch of rows for one measurement.

    schema: Schema (fields sorted by name, time last)
    cols:   list[ColVal] aligned with schema
    """

    __slots__ = ("schema", "cols")

    def __init__(self, schema: Schema, cols: list[ColVal] | None = None):
        self.schema = schema
        if cols is None:
            cols = [_empty_col(f.type) for f in schema]
        if len(cols) != len(schema):
            raise ValueError("cols/schema length mismatch")
        if cols:
            n = len(cols[0])
            for f, c in zip(schema, cols):
                if len(c) != n:
                    raise ValueError(
                        f"column length mismatch: {f.name} has {len(c)} "
                        f"rows, expected {n}")
        self.cols = cols

    # ---- info ------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return len(self.cols[-1]) if self.cols else 0

    def __len__(self) -> int:
        return self.num_rows

    @property
    def times(self) -> np.ndarray:
        ti = self.schema.time_index
        if ti < 0:
            raise ValueError("record has no time column")
        return self.cols[ti].values

    def column(self, name: str) -> ColVal | None:
        i = self.schema.field_index(name)
        return self.cols[i] if i >= 0 else None

    @property
    def min_time(self) -> int:
        return int(self.times[0]) if self.num_rows else 0

    @property
    def max_time(self) -> int:
        return int(self.times[-1]) if self.num_rows else 0

    # ---- transforms ------------------------------------------------------

    def sort_by_time(self, kind: str = "stable") -> "Record":
        """Return a record sorted by timestamp (stable: preserves write order
        for duplicate timestamps, matching the reference's dedup semantics)."""
        t = self.times
        if len(t) <= 1 or bool(np.all(t[:-1] <= t[1:])):
            # deep-copy buffers so both paths hand back fully independent
            # records (the take() branch below already copies via fancy
            # indexing)
            return Record(self.schema, [_copy_col(c) for c in self.cols])
        idx = np.argsort(t, kind=kind)
        return Record(self.schema, [c.take(idx) for c in self.cols])

    def slice(self, start: int, stop: int) -> "Record":
        return Record(self.schema, [c.slice(start, stop) for c in self.cols])

    def take(self, idx: np.ndarray) -> "Record":
        return Record(self.schema, [c.take(idx) for c in self.cols])

    def append(self, other: "Record") -> None:
        if other.schema != self.schema:
            raise ValueError("schema mismatch on append")
        for c, oc in zip(self.cols, other.cols):
            c.append(oc)

    def time_slice(self, t_min: int, t_max: int) -> "Record":
        """Rows with t_min <= time <= t_max; assumes sorted by time."""
        t = self.times
        lo = int(np.searchsorted(t, t_min, side="left"))
        hi = int(np.searchsorted(t, t_max, side="right"))
        return self.slice(lo, hi)

    # ---- interop ---------------------------------------------------------

    def to_rows(self) -> list[dict]:
        """Debug/HTTP helper: rows as dicts (None for nulls)."""
        out = []
        for i in range(self.num_rows):
            out.append({f.name: c.get(i)
                        for f, c in zip(self.schema, self.cols)})
        return out

    @classmethod
    def from_columns(cls, schema: Schema, **arrays) -> "Record":
        """Build from dense numpy arrays / string lists keyed by field name."""
        cols = []
        for f in schema:
            a = arrays.get(f.name)
            if a is None:
                raise ValueError(f"missing column {f.name}")
            if f.type.is_numeric:
                cols.append(ColVal(f.type, a))
            else:
                cols.append(ColVal.from_strings(list(a), f.type))
        return cls(schema, cols)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Record) and other.schema == self.schema
                and all(a == b for a, b in zip(self.cols, other.cols)))

    def __repr__(self) -> str:
        return f"Record({self.schema}, rows={self.num_rows})"


def _empty_col(t: DataType) -> ColVal:
    return ColVal(t)


def _copy_col(c: ColVal) -> ColVal:
    if c.values is not None:
        return ColVal(c.type, c.values.copy(), c.valid.copy())
    return ColVal(c.type, valid=c.valid.copy(), offsets=c.offsets.copy(),
                  data=c.data)


def merge_sorted_records(a: Record, b: Record, dedup: str = "last") -> Record:
    """Merge two time-sorted records of the same schema into one sorted
    record, deduplicating identical timestamps field-wise: the later write
    wins per field, but a null field in the later row does NOT erase an
    older non-null value (matching the reference's MergeSameTime semantics,
    /root/reference/lib/record/meger.go; ordered-merge analog of
    /root/reference/engine/tsm_merge_cursor.go)."""
    if a.schema != b.schema:
        raise ValueError("schema mismatch in merge_sorted_records")
    if a.num_rows == 0:
        return Record(b.schema, [c.slice(0, len(c)) for c in b.cols])
    if b.num_rows == 0:
        return Record(a.schema, [c.slice(0, len(c)) for c in a.cols])
    ta, tb = a.times, b.times
    t = np.concatenate([ta, tb])
    # stable sort with b after a: for equal timestamps, b's rows come later
    order = np.argsort(t, kind="stable")
    # build concatenated columns then gather into sorted order
    cols = []
    for ca, cb in zip(a.cols, b.cols):
        # append() replaces buffers via concatenate, so no defensive copies
        cc = ColVal(ca.type, ca.values, ca.valid, ca.offsets, ca.data)
        cc.append(cb)
        cols.append(cc.take(order))
    rec = Record(a.schema, cols)
    ts = rec.times
    if dedup and len(ts) > 1:
        dup = ts[1:] == ts[:-1]
        if dup.any():
            rec = _dedup_same_time(rec, dup, newest_wins=(dedup == "last"))
    return rec


def _dedup_same_time(rec: Record, dup: np.ndarray, newest_wins: bool) -> Record:
    """Collapse runs of equal timestamps into one row, merging field-wise:
    among duplicate rows the preferred (newest for last-wins) VALID value is
    kept per column; nulls never overwrite values."""
    n = rec.num_rows
    keep = np.ones(n, dtype=np.bool_)
    if newest_wins:
        keep[:-1][dup] = False      # keep last row of each run
    else:
        keep[1:][dup] = False       # keep first row of each run
    keep_idx = np.nonzero(keep)[0]
    out = rec.take(keep_idx)
    # field-wise backfill: walk each duplicate run (rare path, python loop ok)
    ts = rec.times
    i = 0
    oi = 0
    while i < n:
        j = i
        while j + 1 < n and ts[j + 1] == ts[i]:
            j += 1
        if j > i:  # duplicate run [i..j]
            rows = range(j, i - 1, -1) if newest_wins else range(i, j + 1)
            for ci, col in enumerate(rec.cols):
                ocol = out.cols[ci]
                if ocol.valid[oi]:
                    continue
                for r in rows:
                    if col.valid[r]:
                        _copy_cell(col, r, ocol, oi)
                        break
        i = j + 1
        oi += 1
    return out


def _copy_cell(src: ColVal, si: int, dst: ColVal, di: int) -> None:
    """Copy one valid cell src[si] → dst[di] (numeric only; string columns
    are rebuilt). Used only on the rare duplicate-timestamp backfill path."""
    if dst.values is not None:
        dst.values[di] = src.values[si]
        dst.valid[di] = True
    else:
        strs = dst.to_strings()
        strs[di] = src.get_string(si)
        repl = ColVal.from_strings(strs, dst.type)
        dst.offsets, dst.data, dst.valid = repl.offsets, repl.data, repl.valid
