"""opengemini_tpu — a TPU-native distributed time-series database framework.

A from-scratch rebuild of the capabilities of openGemini (reference:
/root/reference, an MPP shared-nothing time-series DB in Go) designed
TPU-first:

- Columnar storage (record format, encodings, TSSP-like immutable files with
  per-segment pre-aggregation) lives on CPU with fixed-size, padded segments
  sized for TPU device blocks.
- The query compute plane (windowed group-by aggregation, PromQL range/instant
  vector functions) runs on TPU as JAX segment reductions / Pallas kernels.
- Distribution is jax.sharding/pjit over a device Mesh (ICI/DCN collectives)
  in place of the reference's custom spdy RPC exchange; CPU-side meta/raft
  stays on the host control plane.

Package layout (layer map mirrors SURVEY.md §1):
- ``record/``    L1 columnar record format (lib/record analog)
- ``encoding/``  L2 encodings & compression (lib/encoding analog)
- ``storage/``   L3 storage engine: WAL, memtable, immutable TSSP, shard, engine
- ``index/``     tsi-style inverted series index, bloom filters
- ``ops/``       TPU kernels: segment window aggregation, prom functions
- ``query/``     InfluxQL parser, logical plan, optimizer, pipeline executor
- ``promql/``    PromQL parser + transpiler
- ``meta/``      catalog: databases, retention policies, shard groups, nodes
- ``parallel/``  device mesh, sharding, distributed exchange (psum merges)
- ``services/``  retention, downsample, continuous queries, stream compute
- ``http/``      InfluxDB-1.x-compatible HTTP API + Prom endpoints
- ``models/``    flagship end-to-end query pipelines (jittable entry points)
- ``utils/``     logger, errors, line protocol, misc
"""

__version__ = "0.1.0"
